"""Pallas TPU kernel for the proving scan — the label-stream hot loop.

Proving sweeps every stored label against a group of nonces
(ops/proving.py:proving_scan_jit). That op is pure streaming: for each
(label lane, nonce) pair one Salsa20/8 application and a threshold
compare — no cross-lane dataflow. This kernel keeps a label tile resident
in VMEM and unrolls the nonce group over it, so each label crosses
HBM->VMEM once per group instead of once per nonce (the XLA version
re-materializes the broadcast state per nonce).

Compaction epilogue (streaming prover): alongside the mask the kernel
reduces each HIT_SEGMENT-lane span to its hit count while the tile is
still in VMEM, and masks pad lanes (``lane >= valid``) so a ragged tail
batch shares the full-batch compiled shape. The surrounding jit
(``prove_scan_step_pallas``) turns those segment counts into packed
(nonce, index) hit pairs merged into a donated device carry — the mask
never crosses PCIe; the only per-batch D2H is the (n_nonces,) count
vector (ops/proving.py compact_hits/merge_hits).

Layout (matching ops/scrypt.py): lane-minor u32 tiles. Inputs:
  base  (12, B)  rows: challenge words 0..7 (broadcast), idx_lo, idx_hi,
                 zeros, spare
  lw    (4, B)   little-endian label words
  nonce_base, threshold, valid: SMEM scalars
Outputs:
  mask  (n_nonces, B) int8 qualification
  seg   (n_nonces, B // HIT_SEGMENT) i32 per-segment hit counts

Grid: lane tiles of LANE_TILE. Set ``interpret=True`` to run/verify on CPU
(the test path); on TPU the same call compiles via Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import proving

try:  # pltpu only resolves on TPU builds; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
except Exception:  # pragma: no cover - non-TPU jaxlib
    pltpu = None
    _SMEM = None

LANE_TILE = 512


def _quarter(x, a, b, c, d):
    def rotl(v, n):
        return (v << jnp.uint32(n)) | (v >> jnp.uint32(32 - n))

    x[b] = x[b] ^ rotl(x[a] + x[d], 7)
    x[c] = x[c] ^ rotl(x[b] + x[a], 9)
    x[d] = x[d] ^ rotl(x[c] + x[b], 13)
    x[a] = x[a] ^ rotl(x[d] + x[c], 18)


def _kernel(nonce_ref, thr_ref, valid_ref, base_ref, lw_ref, out_ref,
            seg_ref, *, n_nonces: int):
    base = base_ref[...]          # (12, T) u32
    lw = lw_ref[...]              # (4, T) u32
    thr = thr_ref[0]
    nonce0 = nonce_ref[0]
    valid = valid_ref[0]
    t = base.shape[1]
    nseg = t // proving.HIT_SEGMENT
    # global lane index of each tile lane (2-D iota: TPU-safe)
    lane = jax.lax.broadcasted_iota(jnp.uint32, (t, 1), 0).reshape(t)
    alive = (jnp.uint32(pl.program_id(0)) * jnp.uint32(t) + lane) < valid
    zeros = jnp.zeros((t,), jnp.uint32)
    for k in range(n_nonces):     # static unroll over the nonce group
        x = [base[i] for i in range(8)]          # challenge rows
        x.append(zeros + (nonce0 + jnp.uint32(k)))
        x.append(base[8])                         # idx_lo
        x.append(base[9])                         # idx_hi
        x.append(base[10])                        # zeros row
        x.extend(lw[i] for i in range(4))
        in0 = x[0]
        for _ in range(4):        # Salsa20/8 = 4 double rounds
            _quarter(x, 0, 4, 8, 12)
            _quarter(x, 5, 9, 13, 1)
            _quarter(x, 10, 14, 2, 6)
            _quarter(x, 15, 3, 7, 11)
            _quarter(x, 0, 1, 2, 3)
            _quarter(x, 5, 6, 7, 4)
            _quarter(x, 10, 11, 8, 9)
            _quarter(x, 15, 12, 13, 14)
        word0 = x[0] + in0
        hit = (word0 < thr) & alive
        out_ref[k, :] = hit.astype(jnp.int8)
        # compaction epilogue: per-segment popcounts while the tile is in
        # VMEM, so the host-side hit extraction never touches the mask
        seg_ref[k, :] = jnp.sum(
            hit.reshape(nseg, proving.HIT_SEGMENT).astype(jnp.int32),
            axis=1)


@functools.partial(jax.jit,
                   static_argnames=("n_nonces", "interpret", "lane_tile"))
def _scan_pallas(challenge_words, nonce_base, idx_lo, idx_hi, label_words,
                 threshold, valid, *, n_nonces: int, interpret: bool = False,
                 lane_tile: int = LANE_TILE):
    """Mask + per-segment hit counts; batch must divide by ``lane_tile``."""
    b = idx_lo.shape[0]
    if b % lane_tile:
        raise ValueError(f"batch {b} not a multiple of lane tile {lane_tile}")
    ch = jnp.broadcast_to(challenge_words.astype(jnp.uint32)[:, None], (8, b))
    base = jnp.concatenate([
        ch, idx_lo[None].astype(jnp.uint32), idx_hi[None].astype(jnp.uint32),
        jnp.zeros((2, b), jnp.uint32),
    ])
    grid = (b // lane_tile,)
    kernel = functools.partial(_kernel, n_nonces=n_nonces)
    scalar_spec = (pl.BlockSpec(memory_space=_SMEM) if _SMEM is not None
                   else pl.BlockSpec(memory_space=pl.ANY))
    seg_tile = lane_tile // proving.HIT_SEGMENT
    mask, seg = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n_nonces, b), jnp.int8),
            jax.ShapeDtypeStruct((n_nonces, b // proving.HIT_SEGMENT),
                                 jnp.int32),
        ),
        grid=grid,
        in_specs=[
            scalar_spec,
            scalar_spec,
            scalar_spec,
            pl.BlockSpec((12, lane_tile), lambda i: (0, i)),
            pl.BlockSpec((4, lane_tile), lambda i: (0, i)),
        ],
        out_specs=(
            pl.BlockSpec((n_nonces, lane_tile), lambda i: (0, i)),
            pl.BlockSpec((n_nonces, seg_tile), lambda i: (0, i)),
        ),
        interpret=interpret,
    )(jnp.asarray([nonce_base], jnp.uint32),
      jnp.asarray([threshold], jnp.uint32),
      jnp.asarray([valid], jnp.uint32), base,
      label_words.astype(jnp.uint32))
    return mask, seg


@functools.partial(jax.jit,
                   static_argnames=("n_nonces", "interpret", "lane_tile"))
def proving_scan_pallas(challenge_words, nonce_base, idx_lo, idx_hi,
                        label_words, threshold, *, n_nonces: int,
                        interpret: bool = False, lane_tile: int = LANE_TILE):
    """Drop-in for ops.proving.proving_scan_jit (returns int8 mask).

    Batch size must be a multiple of ``lane_tile``.
    """
    b = idx_lo.shape[0]
    mask, _ = _scan_pallas(challenge_words, nonce_base, idx_lo, idx_hi,
                           label_words, threshold, jnp.uint32(b),
                           n_nonces=n_nonces, interpret=interpret,
                           lane_tile=lane_tile)
    return mask


@functools.partial(jax.jit,
                   static_argnames=("n_nonces", "max_hits", "interpret",
                                    "lane_tile"),
                   donate_argnums=(6, 7))
def prove_scan_step_pallas(challenge_words, nonce_base, idx_lo, idx_hi,
                           label_words, threshold, hit_counts, hit_carry,
                           valid, start_lo, start_hi, *, n_nonces: int,
                           max_hits: int, interpret: bool = False,
                           lane_tile: int = LANE_TILE):
    """Pallas-backed twin of ops.proving.prove_scan_step_jit.

    Same contract: donated (hit_counts, hit_carry) device state, per-batch
    D2H limited to the (n_nonces,) batch count vector.
    """
    mask, seg = _scan_pallas(challenge_words, nonce_base, idx_lo, idx_hi,
                             label_words, threshold, valid,
                             n_nonces=n_nonces, interpret=interpret,
                             lane_tile=lane_tile)
    counts, pos, ok = proving.compact_hits(mask.astype(bool), seg_sum=seg,
                                           max_hits=max_hits)
    return proving.merge_hits(hit_counts, hit_carry, counts, pos, ok,
                              start_lo, start_hi)


def proving_scan(challenge: bytes, nonce_base: int, indices, labels: np.ndarray,
                 threshold: int, n_nonces: int,
                 interpret: bool | None = None) -> np.ndarray:
    """Host wrapper mirroring ops.proving host entries. Pads the batch to
    the lane tile. Returns (n_nonces, B) bool."""
    from .proving import challenge_words
    from .scrypt import labels_to_words, split_indices

    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    idx = np.atleast_1d(np.asarray(indices, dtype=np.uint64)).ravel()
    b = idx.shape[0]
    pad = (-b) % LANE_TILE
    if pad:
        idx = np.concatenate([idx, np.zeros(pad, np.uint64)])
        labels = np.concatenate(
            [labels, np.zeros((pad, labels.shape[1]), labels.dtype)])
    lo, hi = split_indices(idx)
    mask = proving_scan_pallas(
        jnp.asarray(challenge_words(challenge)), jnp.uint32(nonce_base),
        jnp.asarray(lo), jnp.asarray(hi),
        jnp.asarray(labels_to_words(labels)), jnp.uint32(threshold),
        n_nonces=n_nonces, interpret=interpret)
    return np.asarray(mask)[:, :b].astype(bool)
