"""Pallas ROMix variant: contiguous-row (N, T, 32) V + async-copy gathers.

The race candidate recorded in docs/ROUND2_NOTES.md ("Pallas ROMix:
analysis"): the XLA path (ops/scrypt.py romix_r1) stores V as (N, 32, B)
and gathers a (32, B) slab per iteration with a per-lane random row —
one fused XLA gather.  This kernel flips the layout to (N, T, 32) so ONE
LANE'S ROW IS 128 CONTIGUOUS BYTES, then:

* phase 1 (fill): V rows stream VMEM->HBM with double-buffered async
  copies — the write of row i overlaps the BlockMix that produces row
  i+1;
* phase 2 (mix): per-lane gathers are explicit 128-byte DMAs, all T
  in flight together before the single wait-loop (the iteration's
  BlockMix depends on the gathered rows, so cross-iteration overlap is
  impossible — the overlap is across LANES within an iteration).

The Salsa20/8 core is kept fully in registers: the (T, 32) block is
split into 32 per-word (T,) columns once per phase and every quarter
round is elementwise column arithmetic — no per-round ``stack`` /
``concatenate`` relayouts for Mosaic to shuffle through VMEM.  The
block is only materialized as a (T, 32) tile at the DMA boundaries
(fill-buffer stores, Integerify staging, final output).

Whether this beats XLA's gather is an empirical, per-platform question:
ops/autotune.py races the two implementations on a tiny calibration
workload and persists the winner (docs/ROMIX_KERNEL.md).  The flag
``SPACEMESH_ROMIX=pallas`` forces this path.  Interpret mode verifies
bit-exactness on CPU (tests/test_romix_pallas.py — the autotune sweep
in tests/test_romix_autotune.py covers unaligned batches through the
lane-padding wrapper).

Reference workload: activation/post.go:27-61 (labels per unit),
config/mainnet.go:184-190 (N=8192, r=1, p=1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu resolves on TPU builds; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - non-TPU jaxlib
    pltpu = None

LANE_TILE = 128


def _rotl(x, n: int):
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


def _quarter(x, a: int, b: int, c: int, d: int):
    x[b] = x[b] ^ _rotl(x[a] + x[d], 7)
    x[c] = x[c] ^ _rotl(x[b] + x[a], 9)
    x[d] = x[d] ^ _rotl(x[c] + x[b], 13)
    x[a] = x[a] ^ _rotl(x[d] + x[c], 18)


def _salsa20_8_cols(block):
    """Salsa20/8 over 16 per-word (T,) columns, fully unrolled in registers."""
    x = list(block)
    for _ in range(4):  # 4 double-rounds = 8 rounds
        _quarter(x, 0, 4, 8, 12)
        _quarter(x, 5, 9, 13, 1)
        _quarter(x, 10, 14, 2, 6)
        _quarter(x, 15, 3, 7, 11)
        _quarter(x, 0, 1, 2, 3)
        _quarter(x, 5, 6, 7, 4)
        _quarter(x, 10, 11, 8, 9)
        _quarter(x, 15, 12, 13, 14)
    return [x[i] + block[i] for i in range(16)]


def _blockmix_cols(cols):
    """scrypt BlockMix r=1 over 32 (T,) u32 columns, lanes major."""
    y0 = _salsa20_8_cols([cols[i] ^ cols[16 + i] for i in range(16)])
    y1 = _salsa20_8_cols([cols[16 + i] ^ y0[i] for i in range(16)])
    return tuple(y0 + y1)


def _to_cols(block):
    """(T, 32) tile -> tuple of 32 (T,) columns (the in-register layout)."""
    return tuple(block[:, i] for i in range(32))


def _to_block(cols):
    """32 (T,) columns -> (T, 32) tile, materialized for a DMA boundary."""
    return jnp.stack(cols, axis=1)


def _romix_kernel(x_ref, o_ref, v_ref, fill_buf, gather_buf, jsm,
                  fill_sem, jsem, gsem, *, n: int, tile: int,
                  mix_phase: bool):
    # ---- phase 1: fill V[i] = x_i, double-buffered writes ----
    def fill(i, cols):
        slot = i % 2

        @pl.when(i >= 2)
        def _():
            # retire the copy that used this slot two iterations ago
            # (same shape/size, so the reconstructed handle's wait
            # matches the outstanding transfer)
            pltpu.make_async_copy(fill_buf.at[slot], v_ref.at[0],
                                  fill_sem.at[slot]).wait()

        fill_buf[slot] = _to_block(cols)
        pltpu.make_async_copy(fill_buf.at[slot], v_ref.at[i],
                              fill_sem.at[slot]).start()
        return _blockmix_cols(cols)

    cols = lax.fori_loop(0, n, fill, _to_cols(x_ref[...]))
    # drain the last two in-flight writes
    for slot in (0, 1):
        pltpu.make_async_copy(fill_buf.at[slot], v_ref.at[0],
                              fill_sem.at[slot]).wait()

    if not mix_phase:  # profiler fill/mix split (tools/profiler.py --romix)
        o_ref[...] = _to_block(cols)
        return

    # ---- phase 2: x = BlockMix(x ^ V[Integerify(x)]), per-lane DMAs ----
    def mix(_, cols):
        # Integerify indices must become SMEM scalars: stage the word-16
        # column through a DMA (vector stores to SMEM don't lower)
        fill_buf[0, :, 16:17] = cols[16][:, None]
        stage = pltpu.make_async_copy(
            fill_buf.at[0, :, 16:17], jsm, jsem)
        stage.start()
        stage.wait()

        def start_lane(lane, _):
            row = (jsm[lane, 0] % jnp.uint32(n)).astype(jnp.int32)
            pltpu.make_async_copy(v_ref.at[row, lane],
                                  gather_buf.at[lane], gsem).start()
            return 0

        lax.fori_loop(0, tile, start_lane, 0)

        def wait_lane(lane, _):
            pltpu.make_async_copy(v_ref.at[0, 0], gather_buf.at[0],
                                  gsem).wait()
            return 0

        lax.fori_loop(0, tile, wait_lane, 0)
        g = gather_buf[...]
        return _blockmix_cols(tuple(cols[k] ^ g[:, k] for k in range(32)))

    o_ref[...] = _to_block(lax.fori_loop(0, n, mix, cols))


def romix_pallas(x, *, n: int, lane_tile: int = LANE_TILE,
                 interpret: bool = False, mix_phase: bool = True):
    """Drop-in for ops.scrypt.romix_r1: x is (32, B) u32; returns same.

    B must be a multiple of ``lane_tile`` (``romix_pallas_padded`` lifts
    that).  ``mix_phase=False`` stops after the fill phase — only the
    profiler's stage-split view uses it.
    """
    if pltpu is None:
        raise RuntimeError("pltpu unavailable: Pallas TPU support missing "
                           "from this jaxlib")
    b = x.shape[1]
    if b % lane_tile:
        raise ValueError(f"batch {b} not a multiple of tile {lane_tile}")
    xt = x.T  # (B, 32) lanes major: one lane's row is contiguous

    # scratch declarations use the current callable-memory-space form
    # (pltpu.ANY(shape, dtype); the pl.ANY(...) call form was removed —
    # pl.ANY is now the backend-neutral MemorySpace enum member, only
    # valid as pl.BlockSpec(memory_space=pl.ANY))
    scratch = [
        pltpu.ANY((n, lane_tile, 32), jnp.uint32),    # V (HBM)
        pltpu.VMEM((2, lane_tile, 32), jnp.uint32),   # fill double-buffer
        pltpu.VMEM((lane_tile, 32), jnp.uint32),      # gathered rows
        pltpu.SMEM((lane_tile, 1), jnp.uint32),       # per-lane j
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA(()),
    ]
    out = pl.pallas_call(
        functools.partial(_romix_kernel, n=n, tile=lane_tile,
                          mix_phase=mix_phase),
        grid=(b // lane_tile,),
        in_specs=[pl.BlockSpec((lane_tile, 32), lambda g: (g, 0))],
        out_specs=pl.BlockSpec((lane_tile, 32), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 32), jnp.uint32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(xt)
    return out.T


_romix_pallas_jit = jax.jit(
    romix_pallas, static_argnames=("n", "lane_tile", "interpret",
                                   "mix_phase"))


def romix_pallas_padded(x, *, n: int, lane_tile: int = LANE_TILE,
                        interpret: bool = False, mix_phase: bool = True):
    """``romix_pallas`` for ANY batch size: pads lanes up to the tile.

    The pad lanes run real (wasted) ROMix work — at most ``lane_tile-1``
    extra lanes per call, so callers with steady batch shapes should
    still size batches as tile multiples.  Traceable (jit-safe): the pad
    amount depends only on the static lane count.
    """
    b = x.shape[1]
    pad = -b % lane_tile
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((32, pad), dtype=jnp.uint32)], axis=1)
    out = romix_pallas(x, n=n, lane_tile=lane_tile, interpret=interpret,
                       mix_phase=mix_phase)
    return out[:, :b] if pad else out
