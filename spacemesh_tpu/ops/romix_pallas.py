"""Pallas ROMix variant: contiguous-row (N, T, 32) V + async-copy gathers.

The race candidate recorded in docs/ROUND2_NOTES.md ("Pallas ROMix:
analysis"): the XLA path (ops/scrypt.py romix_r1) stores V as (N, 32, B)
and gathers a (32, B) slab per iteration with a per-lane random row —
one fused XLA gather.  This kernel flips the layout to (N, T, 32) so ONE
LANE'S ROW IS 128 CONTIGUOUS BYTES, then:

* phase 1 (fill): V rows stream VMEM->HBM with double-buffered async
  copies — the write of row i overlaps the BlockMix that produces row
  i+1;
* phase 2 (mix): per-lane gathers are explicit 128-byte DMAs, all T
  in flight together before the single wait-loop (the iteration's
  BlockMix depends on the gathered rows, so cross-iteration overlap is
  impossible — the overlap is across LANES within an iteration).

Which candidate wins is an empirical question the round-2 analysis could
not settle without hardware (per-lane DMA latency vs. XLA's gather); the
flag `SPACEMESH_ROMIX=pallas` (or romix_impl="pallas") races them on the
same test vectors.  Interpret mode verifies bit-exactness on CPU.

Reference workload: activation/post.go:27-61 (labels per unit),
config/mainnet.go:184-190 (N=8192, r=1, p=1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu resolves on TPU builds; interpret mode works without it
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - non-TPU jaxlib
    pltpu = None

LANE_TILE = 128


def _rotl(x, n: int):
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


def _quarter(x, a: int, b: int, c: int, d: int):
    x[b] = x[b] ^ _rotl(x[a] + x[d], 7)
    x[c] = x[c] ^ _rotl(x[b] + x[a], 9)
    x[d] = x[d] ^ _rotl(x[c] + x[b], 13)
    x[a] = x[a] ^ _rotl(x[d] + x[c], 18)


def _salsa20_8_rows(block):
    """Salsa20/8 over (T, 16) u32 (lanes MAJOR — rows are labels)."""
    x = [block[:, i] for i in range(16)]
    for _ in range(4):
        _quarter(x, 0, 4, 8, 12)
        _quarter(x, 5, 9, 13, 1)
        _quarter(x, 10, 14, 2, 6)
        _quarter(x, 15, 3, 7, 11)
        _quarter(x, 0, 1, 2, 3)
        _quarter(x, 5, 6, 7, 4)
        _quarter(x, 10, 11, 8, 9)
        _quarter(x, 15, 12, 13, 14)
    return jnp.stack([x[i] for i in range(16)], axis=1) + block


def _blockmix_rows(x):
    """scrypt BlockMix r=1 over (T, 32) u32, lanes major."""
    y0 = _salsa20_8_rows(x[:, 0:16] ^ x[:, 16:32])
    y1 = _salsa20_8_rows(x[:, 16:32] ^ y0)
    return jnp.concatenate([y0, y1], axis=1)


def _romix_kernel(x_ref, o_ref, v_ref, fill_buf, gather_buf, jsm,
                  fill_sem, jsem, gsem, *, n: int, tile: int):
    # ---- phase 1: fill V[i] = x_i, double-buffered writes ----
    def fill(i, x):
        slot = i % 2

        @pl.when(i >= 2)
        def _():
            # retire the copy that used this slot two iterations ago
            # (same shape/size, so the reconstructed handle's wait
            # matches the outstanding transfer)
            pltpu.make_async_copy(fill_buf.at[slot], v_ref.at[0],
                                  fill_sem.at[slot]).wait()

        fill_buf[slot] = x
        pltpu.make_async_copy(fill_buf.at[slot], v_ref.at[i],
                              fill_sem.at[slot]).start()
        return _blockmix_rows(x)

    x = lax.fori_loop(0, n, fill, x_ref[...])
    # drain the last two in-flight writes
    for slot in (0, 1):
        pltpu.make_async_copy(fill_buf.at[slot], v_ref.at[0],
                              fill_sem.at[slot]).wait()

    # ---- phase 2: x = BlockMix(x ^ V[Integerify(x)]), per-lane DMAs ----
    def mix(_, x):
        # Integerify indices must become SMEM scalars: stage the word-16
        # column through a DMA (vector stores to SMEM don't lower)
        fill_buf[0] = x  # reuse slot 0 as the staging source
        stage = pltpu.make_async_copy(
            fill_buf.at[0, :, 16:17], jsm, jsem)
        stage.start()
        stage.wait()

        def start_lane(lane, _):
            row = (jsm[lane, 0] % jnp.uint32(n)).astype(jnp.int32)
            pltpu.make_async_copy(v_ref.at[row, lane],
                                  gather_buf.at[lane], gsem).start()
            return 0

        lax.fori_loop(0, tile, start_lane, 0)

        def wait_lane(lane, _):
            pltpu.make_async_copy(v_ref.at[0, 0], gather_buf.at[0],
                                  gsem).wait()
            return 0

        lax.fori_loop(0, tile, wait_lane, 0)
        return _blockmix_rows(x ^ gather_buf[...])

    o_ref[...] = lax.fori_loop(0, n, mix, x)


def romix_pallas(x, *, n: int, lane_tile: int = LANE_TILE,
                 interpret: bool = False):
    """Drop-in for ops.scrypt.romix_r1: x is (32, B) u32; returns same.

    B must be a multiple of ``lane_tile``.
    """
    if pltpu is None and not interpret:
        raise RuntimeError("pltpu unavailable: TPU build required "
                           "(use interpret=True on CPU)")
    b = x.shape[1]
    if b % lane_tile:
        raise ValueError(f"batch {b} not a multiple of tile {lane_tile}")
    xt = x.T  # (B, 32) lanes major: one lane's row is contiguous

    scratch = [
        pl.ANY((n, lane_tile, 32), jnp.uint32),       # V (HBM)
        pltpu.VMEM((2, lane_tile, 32), jnp.uint32),   # fill double-buffer
        pltpu.VMEM((lane_tile, 32), jnp.uint32),      # gathered rows
        pltpu.SMEM((lane_tile, 1), jnp.uint32),       # per-lane j
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA(()),
    ]
    out = pl.pallas_call(
        functools.partial(_romix_kernel, n=n, tile=lane_tile),
        grid=(b // lane_tile,),
        in_specs=[pl.BlockSpec((lane_tile, 32), lambda g: (g, 0))],
        out_specs=pl.BlockSpec((lane_tile, 32), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 32), jnp.uint32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(xt)
    return out.T


_romix_pallas_jit = jax.jit(
    romix_pallas, static_argnames=("n", "lane_tile", "interpret"))
