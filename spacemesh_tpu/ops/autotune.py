"""ROMix kernel autotuner: race the candidates once, persist the winner.

Which label-kernel variant is fastest is a per-host question (SURVEY.md
§7; the ASIC-crypto playbook of arxiv 2604.17808 / 2505.14657): the XLA
gather path with a VMEM/LLC-sized lane chunk wins where the working set
must be kept hot, the contiguous-row variant wins where the gather's
read amplification dominates, and the Pallas DMA kernel is only worth
compiling on a real TPU.  Rather than hardcode that table, first use
races the candidates on a tiny calibration workload and persists the
winner per ``(platform, N, batch)`` next to the persistent XLA compile
cache (utils/accel.py), so every entry point — post/initializer.py,
post/prover.py's scan, parallel/mesh.py, bench.py, tools/profiler.py —
picks up the tuned kernel with zero configuration, and a second process
on the same host skips the race entirely.

Decision precedence (highest first):

1. env overrides — ``SPACEMESH_ROMIX`` (``xla`` | ``xla-rows`` |
   ``pallas``) forces the implementation, ``SPACEMESH_ROMIX_CHUNK``
   (lanes per sequential V chunk; ``0``/``off`` = unchunked) forces the
   chunk; either beats a cached winner;
2. the persisted winner for ``(platform, N, batch)``;
3. a race (disable with ``SPACEMESH_ROMIX_AUTOTUNE=off``, e.g. in
   latency-sensitive tests), whose result is persisted;
4. a static heuristic default (race disabled or impossible).

Cache file: ``<cache root>/romix_autotune.json`` (cache root is the
parent of accel.DEFAULT_CACHE_DIR, i.e. ``~/.cache/spacemesh_tpu``;
``SPACEMESH_ROMIX_CACHE`` overrides the file path, ``SPACEMESH_JAX_CACHE``
moves the whole cache root).  A corrupt or unreadable file is treated as
empty — the race re-runs and rewrites it.  See docs/ROMIX_KERNEL.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

SCHEMA = 1
IMPLS = ("xla", "xla-rows", "pallas")

ENV_IMPL = "SPACEMESH_ROMIX"
ENV_CHUNK = "SPACEMESH_ROMIX_CHUNK"
ENV_AUTOTUNE = "SPACEMESH_ROMIX_AUTOTUNE"
ENV_CACHE = "SPACEMESH_ROMIX_CACHE"

# calibration workload: CAL_BATCH lanes bound the race cost independently
# of the production batch (chunk locality is a per-lane property, so the
# winner transfers to wider batches — docs/ROMIX_KERNEL.md discusses the
# one approximation this makes for the unchunked candidate)
CAL_BATCH = 512
CAL_REPS = 2

_OFF = ("0", "off", "none", "false")


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


@dataclasses.dataclass(frozen=True)
class Decision:
    """A resolved kernel choice for one (platform, N, batch) shape."""

    impl: str                 # "xla" | "xla-rows" | "pallas"
    chunk: int | None         # lanes per sequential V chunk; None = whole batch
    source: str               # "env" | "cache" | "race" | "default" | "untuned"
    labels_per_sec: float | None = None  # calibration rate, when raced
    explicit_impl: bool = False  # impl came from SPACEMESH_ROMIX (never
    #                              silently fall back from it — ops/scrypt.py)

    def as_json(self) -> dict:
        return {"impl": self.impl, "chunk": self.chunk,
                "source": self.source,
                "labels_per_sec": self.labels_per_sec}


def cache_path() -> str:
    """The autotune winners file, colocated with the XLA compile cache."""
    explicit = os.environ.get(ENV_CACHE)
    if explicit:
        return os.path.expanduser(explicit)
    from ..utils import accel

    jax_cache = os.environ.get("SPACEMESH_JAX_CACHE")
    if not jax_cache or jax_cache in _OFF:
        jax_cache = accel.DEFAULT_CACHE_DIR
    root = os.path.dirname(os.path.expanduser(jax_cache))
    return os.path.join(root, "romix_autotune.json")


def _key(platform: str, n: int, batch: int) -> str:
    return f"v{SCHEMA}:{platform}:n{n}:b{batch}"


def _load_cache(path: str | None = None) -> dict:
    path = path or cache_path()
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError("autotune cache root is not an object")
        return doc
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        # a corrupt winners file must never break labeling — re-race
        _log(f"romix autotune: ignoring unreadable cache {path} ({e})")
        return {}


def _store(key: str, entry: dict) -> None:
    path = cache_path()
    doc = _load_cache(path)
    doc[key] = entry
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)  # atomic: concurrent racers lose, not corrupt
    except OSError as e:
        # persistence is an optimization (read-only HOME, sandboxed CI)
        _log(f"romix autotune: cannot persist winner ({e})")


def _entry_decision(entry: dict, batch: int, source: str) -> Decision | None:
    impl = entry.get("impl")
    chunk = entry.get("chunk")
    if impl not in IMPLS:
        return None
    if chunk is not None and (not isinstance(chunk, int) or chunk < 1):
        return None
    if chunk is not None and chunk >= batch:
        chunk = None
    rate = entry.get("labels_per_sec")
    return Decision(impl, chunk, source,
                    rate if isinstance(rate, (int, float)) else None)


def read_env() -> tuple[str | None, int | None, bool, bool]:
    """-> (impl override, chunk override, chunk was set, race disabled)."""
    impl = os.environ.get(ENV_IMPL) or None
    if impl is not None and impl not in IMPLS:
        raise ValueError(
            f"{ENV_IMPL}={impl!r}: expected one of {', '.join(IMPLS)}")
    chunk_raw = os.environ.get(ENV_CHUNK)
    chunk_set = chunk_raw is not None and chunk_raw != ""
    chunk: int | None = None
    if chunk_set and chunk_raw.lower() not in _OFF:
        chunk = int(chunk_raw)
        if chunk < 1:
            raise ValueError(f"{ENV_CHUNK}={chunk_raw!r}: must be >= 1")
    no_race = (os.environ.get(ENV_AUTOTUNE) or "").lower() in _OFF
    return impl, chunk, chunk_set, no_race


def chunk_candidates(n: int, batch: int,
                     targets: tuple[int, ...] = (256 << 20,)
                     ) -> list[int]:
    """Power-of-two lane chunks whose V working set (n * 128 bytes per
    lane) lands near each cache-capacity target, clipped to the batch."""
    row_bytes = 128  # one lane's (32,) u32 V row
    out = set()
    for t in targets:
        c = max(t // (n * row_bytes), 8)
        c = 1 << (int(c).bit_length() - 1)
        if c < batch:
            out.add(int(c))
    return sorted(out)


def default_decision(platform: str, n: int, batch: int) -> Decision:
    """Static heuristic when racing is disabled or impossible: the
    word-major XLA gather over the whole batch. Measured on CPU hosts the
    diagonal-vector Salsa is op-dispatch-bound, so sequential lane chunks
    only subtract lane width (docs/ROMIX_KERNEL.md) — chunking has to
    EARN its place through the race."""
    return Decision("xla", None, "default")


def candidates(platform: str, n: int, batch: int) -> list[tuple[str, int | None]]:
    """The (impl, chunk) grid raced for one shape."""
    chunks: list[int | None] = [None, *chunk_candidates(n, batch)]
    if platform == "cpu":
        # interpret-mode Pallas executes every DMA in Python — never a
        # contender, so never raced (force it with SPACEMESH_ROMIX=pallas)
        return [(impl, c) for impl in ("xla", "xla-rows") for c in chunks]
    out: list[tuple[str, int | None]] = [("xla", c) for c in chunks]
    if platform == "tpu":
        # the Pallas kernel tiles lanes at LANE_TILE internally (its V
        # scratch is per-tile), so an outer chunk adds nothing
        out.append(("pallas", None))
    return out


def calibration_block(batch: int = CAL_BATCH, seed: int = 7) -> np.ndarray:
    """Deterministic (32, batch) u32 ROMix input, shared by the race and
    tools/profiler.py --romix so both measure the same workload."""
    rng = np.random.RandomState(seed)
    return rng.randint(0, 2**32, size=(32, batch),
                       dtype=np.uint64).astype(np.uint32)


# in-process memos. Race measurements are per (platform, n) — the
# calibration workload is FIXED at CAL_BATCH lanes, so one measurement
# serves every production batch size (bench sweeps, init tail batches,
# the verifier's variable-count label recomputes) — and are additionally
# persisted, so a new process deriving a winner for a new batch size
# never re-compiles. Resolved decisions are memoized per call signature
# (env included) so the steady dispatch path costs dict lookups, not a
# cache-file parse per batch.
_race_memo: dict[tuple, list[dict]] = {}
_decision_memo: dict[tuple, Decision] = {}


def reset_memo() -> None:
    """Drop in-process memos (tests simulating fresh processes)."""
    _race_memo.clear()
    _decision_memo.clear()


def _meas_key(platform: str, n: int) -> str:
    return f"v{SCHEMA}:meas:{platform}:n{n}:cal{CAL_BATCH}"


def _valid_rows(rows) -> list[dict]:
    out = []
    if not isinstance(rows, list):
        return out
    for r in rows:
        if (isinstance(r, dict) and r.get("impl") in IMPLS
                and (r.get("chunk") is None
                     or (isinstance(r.get("chunk"), int) and r["chunk"] >= 1))
                and isinstance(r.get("labels_per_sec"), (int, float))):
            out.append(r)
    return out


def _race_measurements(platform: str, n: int) -> list[dict]:
    memo_key = (platform, n)
    got = _race_memo.get(memo_key)
    if got is not None:
        return got
    persisted = _valid_rows(
        _load_cache().get(_meas_key(platform, n), {}).get("raced"))
    if persisted:
        _race_memo[memo_key] = persisted
        return persisted
    from ..utils import metrics, tracing

    metrics.post_romix_autotune_races.inc()
    race_sp = tracing.span("romix.race", {"platform": platform, "n": n}
                           if tracing.is_enabled() else None)
    race_sp.__enter__()
    try:
        rows = _race_candidates(platform, n)
    finally:
        race_sp.__exit__(None, None, None)
    _race_memo[memo_key] = rows
    if rows:
        _store(_meas_key(platform, n),
               {"raced": rows, "cal_batch": CAL_BATCH,
                "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())})
    return rows


def _race_candidates(platform: str, n: int) -> list[dict]:
    import jax.numpy as jnp

    from ..utils import tracing
    from . import scrypt

    x = jnp.asarray(calibration_block(CAL_BATCH))
    rows = []
    for impl, chunk in candidates(platform, n, CAL_BATCH):
        if chunk is not None and chunk >= CAL_BATCH:
            continue  # indistinguishable from unchunked at this workload
        # non-pallas candidates never interpret — the SAME static jit key
        # production uses, so the race's compile is reused, not repaid
        interpret = impl == "pallas" and platform != "tpu"
        label = f"{impl}" + (f"/chunk={chunk}" if chunk else "")
        csp = tracing.span("romix.race_candidate",
                           {"impl": impl, "chunk": chunk}
                           if tracing.is_enabled() else None)
        csp.__enter__()
        try:
            t0 = time.perf_counter()
            scrypt.romix_tuned(x, n=n, impl=impl, chunk=chunk,
                               interpret=interpret).block_until_ready()
            compile_s = time.perf_counter() - t0
            best = float("inf")
            for _ in range(CAL_REPS):
                t0 = time.perf_counter()
                scrypt.romix_tuned(x, n=n, impl=impl, chunk=chunk,
                                   interpret=interpret).block_until_ready()
                best = min(best, time.perf_counter() - t0)
            rate = CAL_BATCH / best
            _log(f"romix autotune: {label}: {rate:,.0f} labels/s "
                 f"(compile+first {compile_s:.1f}s)")
            csp.set(labels_per_sec=round(rate, 1),
                    compile_s=round(compile_s, 3))
            rows.append({"impl": impl, "chunk": chunk,
                         "labels_per_sec": round(rate, 1)})
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # compile on this host simply loses the race
            _log(f"romix autotune: {label} failed "
                 f"({type(e).__name__}: {e})")
            csp.set(failed=type(e).__name__)
        finally:
            csp.__exit__(None, None, None)
    return rows


def race(platform: str, n: int, batch: int) -> Decision:
    """Race (or reuse the measured race of) the candidate kernels on the
    fixed calibration workload, then persist and return the winner for
    ``(platform, n, batch)``."""
    rows = _race_measurements(platform, n)
    usable = [r for r in rows
              if r["chunk"] is None or r["chunk"] < batch]
    if not usable:
        return default_decision(platform, n, batch)
    win = max(usable, key=lambda r: r["labels_per_sec"])
    chunk = win["chunk"]
    entry = {"impl": win["impl"], "chunk": chunk,
             "labels_per_sec": win["labels_per_sec"],
             "cal_batch": CAL_BATCH, "raced": rows,
             "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    _store(_key(platform, n, batch), entry)
    _log(f"romix autotune: winner for {platform} n={n} b={batch}: "
         f"{win['impl']}" + (f"/chunk={chunk}" if chunk else "") +
         f" ({win['labels_per_sec']:,.0f} labels/s, persisted)")
    return Decision(win["impl"], chunk, "race", win["labels_per_sec"])


def decide(n: int, batch: int, *, platform: str | None = None,
           allow_race: bool = True) -> Decision:
    """Resolve the kernel choice for one shape (precedence in the module
    docstring). The steady dispatch path — one call per label batch from
    post/initializer.py — is a memoized dict lookup; the env values are
    part of the memo key so override changes always take effect."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    memo_key = (platform, n, batch, allow_race,
                os.environ.get(ENV_IMPL), os.environ.get(ENV_CHUNK),
                os.environ.get(ENV_AUTOTUNE), os.environ.get(ENV_CACHE))
    hit = _decision_memo.get(memo_key)
    if hit is not None:
        return hit
    d = _decide(n, batch, platform, allow_race)
    _decision_memo[memo_key] = d
    return d


def _decide(n: int, batch: int, platform: str, allow_race: bool) -> Decision:
    impl_env, chunk_env, chunk_set, no_race = read_env()
    cached = _entry_decision(
        _load_cache().get(_key(platform, n, batch), {}), batch, "cache")
    if impl_env is not None:
        # explicit impl: env chunk > cached chunk (same impl) > heuristic
        if chunk_set:
            chunk = chunk_env
        elif cached is not None and cached.impl == impl_env:
            chunk = cached.chunk
        elif impl_env == "pallas":
            chunk = None
        else:
            chunk = default_decision(platform, n, batch).chunk
        if chunk is not None and chunk >= batch:
            chunk = None
        return Decision(impl_env, chunk, "env", explicit_impl=True)
    if chunk_set:
        base = cached or default_decision(platform, n, batch)
        chunk = chunk_env if (chunk_env is None or chunk_env < batch) else None
        return Decision(base.impl, chunk, "env")
    if cached is not None:
        return cached
    if no_race or not allow_race:
        return default_decision(platform, n, batch)
    return race(platform, n, batch)
