"""ROMix kernel autotuner: race the candidates once, persist the winner.

Which label-kernel variant is fastest is a per-host question (SURVEY.md
§7; the ASIC-crypto playbook of arxiv 2604.17808 / 2505.14657): the XLA
gather path with a VMEM/LLC-sized lane chunk wins where the working set
must be kept hot, the contiguous-row variant wins where the gather's
read amplification dominates, and the Pallas DMA kernel is only worth
compiling on a real TPU.  Rather than hardcode that table, first use
races the candidates on a tiny calibration workload and persists the
winner per ``(platform, N, batch)`` next to the persistent XLA compile
cache (utils/accel.py), so every entry point — post/initializer.py,
post/prover.py's scan, parallel/mesh.py, bench.py, tools/profiler.py —
picks up the tuned kernel with zero configuration, and a second process
on the same host skips the race entirely.

The grid has a MESH dimension (docs/ROMIX_KERNEL.md): on hosts exposing
more than one device — notably the CPU fallback's virtual host devices
(``--xla_force_host_platform_device_count``, which every test/driver
entry point already forces to 8) — the race also times the label kernel
lane-sharded over {2, 4, 8} devices via parallel/mesh.py. The
diagonal-vector Salsa program is op-dispatch-bound on XLA:CPU, so N
sequential per-device streams routinely beat one device's intra-op
parallelism (measured 3.2x at mainnet N on a 2-core host); whether and
at how many devices that trade wins is exactly what the race persists.
Mesh-aware callers (post/initializer.py, post/prover.py, bench.py) pass
``max_devices=None`` and route batches through the mesh when the winner
says so; shape-bound callers keep the default ``max_devices=1`` and are
served the best single-device row of the same measurements.

Decision precedence (highest first):

1. env overrides — ``SPACEMESH_ROMIX`` (``xla`` | ``xla-rows`` |
   ``pallas``) forces the implementation, ``SPACEMESH_ROMIX_CHUNK``
   (lanes per sequential V chunk; ``0``/``off`` = unchunked) forces the
   chunk, ``SPACEMESH_MESH`` forces the device count (``0``/``off`` = 1,
   ``1``/``on`` = every visible device, an integer >= 2 = exactly that
   many); any of them beats a cached winner;
2. the persisted winner for ``(platform, N, batch, device cap)``;
3. a race (disable with ``SPACEMESH_ROMIX_AUTOTUNE=off``, e.g. in
   latency-sensitive tests), whose result is persisted;
4. a static heuristic default (race disabled or impossible): the plain
   single-device XLA kernel.

Cache file: ``<cache root>/romix_autotune.json`` (cache root is the
parent of accel.DEFAULT_CACHE_DIR, i.e. ``~/.cache/spacemesh_tpu``;
``SPACEMESH_ROMIX_CACHE`` overrides the file path, ``SPACEMESH_JAX_CACHE``
moves the whole cache root).  A corrupt or unreadable file is treated as
empty — the race re-runs and rewrites it.  See docs/ROMIX_KERNEL.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import numpy as np

SCHEMA = 2  # v2: rows/winners carry a "devices" mesh dimension
IMPLS = ("xla", "xla-rows", "pallas")
MAX_MESH_DEVICES = 8  # the raced device-count grid is {1, 2, 4, 8}

# The two mesh SHAPES a sharded label batch can take over the topology's
# ``data`` axis (docs/ROMIX_KERNEL.md):
#   lane   — the word-major kernel: arrays stay (words, B), the lane axis
#            shards, V is gathered word-major per shard;
#   vshard — the contiguous-row kernel: V lives as per-lane (32,) rows,
#            so sharding the lanes shards each device's V scratch with
#            them (the row-sharded ROMix layout).
# Rows and winners are tagged with their shape, and race() additionally
# persists the best row PER shape — tools/warmcache.py warms both so a
# later SPACEMESH_ROMIX flip or a re-race that flips the winner still
# hits the persistent compile cache.
MESH_SHAPES = ("lane", "vshard")


def shape_of(impl: str) -> str:
    """The mesh shape an impl uses when its lanes shard over ``data``."""
    return "vshard" if impl == "xla-rows" else "lane"

ENV_IMPL = "SPACEMESH_ROMIX"
ENV_CHUNK = "SPACEMESH_ROMIX_CHUNK"
ENV_AUTOTUNE = "SPACEMESH_ROMIX_AUTOTUNE"
ENV_CACHE = "SPACEMESH_ROMIX_CACHE"
ENV_MESH = "SPACEMESH_MESH"  # shared with post/initializer.py + prover

# calibration workload: CAL_BATCH lanes bound the race cost independently
# of the production batch (chunk locality is a per-lane property, so the
# winner transfers to wider batches — docs/ROMIX_KERNEL.md discusses the
# one approximation this makes for the unchunked candidate)
CAL_BATCH = 512
CAL_REPS = 2

_OFF = ("0", "off", "none", "false")


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


@dataclasses.dataclass(frozen=True)
class Decision:
    """A resolved kernel choice for one (platform, N, batch) shape."""

    impl: str                 # "xla" | "xla-rows" | "pallas"
    chunk: int | None         # lanes per sequential V chunk; None = whole batch
    source: str               # "env" | "cache" | "race" | "default" | "untuned"
    labels_per_sec: float | None = None  # calibration rate, when raced
    explicit_impl: bool = False  # impl came from SPACEMESH_ROMIX (never
    #                              silently fall back from it — ops/scrypt.py)
    devices: int = 1          # lane-shard the batch over this many devices
    #                           (parallel/mesh.py; 1 = single-device dispatch)
    mesh_shape: str = "lane"  # which MESH_SHAPES layout the sharded
    #                           dispatch uses (meaningful when devices > 1)

    def as_json(self) -> dict:
        return {"impl": self.impl, "chunk": self.chunk,
                "source": self.source, "devices": self.devices,
                "shape": self.mesh_shape,
                "labels_per_sec": self.labels_per_sec}


def cache_path() -> str:
    """The autotune winners file, colocated with the XLA compile cache."""
    explicit = os.environ.get(ENV_CACHE)
    if explicit:
        return os.path.expanduser(explicit)
    from ..utils import accel

    jax_cache = os.environ.get("SPACEMESH_JAX_CACHE")
    if not jax_cache or jax_cache in _OFF:
        jax_cache = accel.DEFAULT_CACHE_DIR
    root = os.path.dirname(os.path.expanduser(jax_cache))
    return os.path.join(root, "romix_autotune.json")


def _key(platform: str, n: int, batch: int, dev_cap: int = 1) -> str:
    # dev_cap: the device budget the winner was selected under. A shape
    # has (at most) two persisted winners — the best single-device row
    # (d1, what ops/scrypt.py's per-call dispatch consumes) and the best
    # row under the host's mesh cap (what the mesh-aware init/prove/bench
    # callers consume) — so the two lookups never overwrite each other.
    return f"v{SCHEMA}:{platform}:n{n}:b{batch}:d{dev_cap}"


def _shape_key(platform: str, n: int, batch: int, dev_cap: int,
               shape: str) -> str:
    # the best row PER mesh shape under the same budget — what
    # shape_winner() serves warmcache and the sharded entry points so
    # both layouts' executables land in the persistent compile cache
    return _key(platform, n, batch, dev_cap) + f":s{shape}"


def _load_cache(path: str | None = None) -> dict:
    path = path or cache_path()
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError("autotune cache root is not an object")
        return doc
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        # a corrupt winners file must never break labeling — re-race
        _log(f"romix autotune: ignoring unreadable cache {path} ({e})")
        return {}


def _store(key: str, entry: dict) -> None:
    _store_many({key: entry})


def _store_many(entries: dict) -> None:
    path = cache_path()
    doc = _load_cache(path)
    doc.update(entries)
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # durable write (tmp + fsync + rename + dir-fsync): a power cut
        # mid-save must never leave a half-written winners file that the
        # corrupt-cache-ignored path above silently re-races away
        from ..utils import fsio

        fsio.atomic_write_text(
            path, json.dumps(doc, indent=1, sort_keys=True))
    except OSError as e:
        # persistence is an optimization (read-only HOME, sandboxed CI)
        _log(f"romix autotune: cannot persist winner ({e})")


def _entry_decision(entry: dict, batch: int, source: str) -> Decision | None:
    impl = entry.get("impl")
    chunk = entry.get("chunk")
    devices = entry.get("devices", 1)
    if impl not in IMPLS:
        return None
    if chunk is not None and (not isinstance(chunk, int) or chunk < 1):
        return None
    if not isinstance(devices, int) or isinstance(devices, bool) \
            or devices < 1:
        return None
    if chunk is not None and chunk >= batch:
        chunk = None
    shape = entry.get("shape") or shape_of(impl)
    if shape not in MESH_SHAPES:
        return None
    rate = entry.get("labels_per_sec")
    return Decision(impl, chunk, source,
                    rate if isinstance(rate, (int, float)) else None,
                    devices=devices, mesh_shape=shape)


def read_env() -> tuple[str | None, int | None, bool, bool]:
    """-> (impl override, chunk override, chunk was set, race disabled)."""
    impl = os.environ.get(ENV_IMPL) or None
    if impl is not None and impl not in IMPLS:
        raise ValueError(
            f"{ENV_IMPL}={impl!r}: expected one of {', '.join(IMPLS)}")
    chunk_raw = os.environ.get(ENV_CHUNK)
    chunk_set = chunk_raw is not None and chunk_raw != ""
    chunk: int | None = None
    if chunk_set and chunk_raw.lower() not in _OFF:
        chunk = int(chunk_raw)
        if chunk < 1:
            raise ValueError(f"{ENV_CHUNK}={chunk_raw!r}: must be >= 1")
    no_race = (os.environ.get(ENV_AUTOTUNE) or "").lower() in _OFF
    return impl, chunk, chunk_set, no_race


def read_mesh_env() -> int | None:
    """``SPACEMESH_MESH`` as a device count: None = auto (tuned),
    ``0``/``off`` = 1 (never shard), ``1``/``on`` = every visible device
    (the historical force-the-mesh switch), an integer >= 2 = exactly
    that many devices."""
    raw = os.environ.get(ENV_MESH)
    if raw is None:
        return None
    v = raw.strip().lower()
    if v in ("", "auto"):
        return None
    if v in _OFF:
        return 1
    if v in ("1", "on"):
        return _device_count()
    try:
        count = int(v)
    except ValueError:
        raise ValueError(
            f"{ENV_MESH}={raw!r}: expected off/on/auto or a device count")
    if count < 1:
        raise ValueError(f"{ENV_MESH}={raw!r}: device count must be >= 1")
    return count


def _device_count() -> int:
    import jax

    return jax.device_count()


def resolve_auto_mesh(n: int, batch: int):
    """-> (device list | None, Decision) for a mesh-aware caller in
    ``auto`` mode — ONE definition of the routing post/initializer.py
    and post/prover.py share (hand-rolled twins of this logic have
    already diverged once on knob parsing; see read_mesh_env).

    On the CPU fallback the tuned mesh winner decides (devices > 1 only
    when the raced row says so and the host still exposes that many).
    On real multi-device hardware the historical whole-mesh default
    holds. SPACEMESH_MESH forces either way (off -> always None; the
    CPU path honors it inside decide(), which collapses a forced count
    into the returned decision). Callers build the parallel/mesh.py
    Mesh from the returned device list; None means stay single-device.
    """
    import jax

    if jax.default_backend() != "cpu":
        forced = read_mesh_env()
        count = _device_count()
        d = decide(n, batch)
        if forced == 1 or count <= 1:
            return None, d
        return jax.devices()[:min(forced or count, count)], d
    d = decide(n, batch, max_devices=None)
    if d.devices > 1 and _device_count() >= d.devices:
        return jax.devices()[:d.devices], d
    return None, d


def _device_cap(max_devices: int | None) -> int:
    """The device budget for one decide() call: the caller's cap clipped
    to the host and the raced grid. ``max_devices=1`` short-circuits
    without touching the backend (the per-call dispatch path in
    ops/scrypt.py must not pay a device enumeration)."""
    if max_devices == 1:
        return 1
    cap = min(_device_count(), MAX_MESH_DEVICES)
    if max_devices is not None:
        cap = min(cap, max_devices)
    return max(cap, 1)


def chunk_candidates(n: int, batch: int,
                     targets: tuple[int, ...] = (256 << 20,)
                     ) -> list[int]:
    """Power-of-two lane chunks whose V working set (n * 128 bytes per
    lane) lands near each cache-capacity target, clipped to the batch."""
    row_bytes = 128  # one lane's (32,) u32 V row
    out = set()
    for t in targets:
        c = max(t // (n * row_bytes), 8)
        c = 1 << (int(c).bit_length() - 1)
        if c < batch:
            out.add(int(c))
    return sorted(out)


def default_decision(platform: str, n: int, batch: int) -> Decision:
    """Static heuristic when racing is disabled or impossible: the
    word-major XLA gather over the whole batch. Measured on CPU hosts the
    diagonal-vector Salsa is op-dispatch-bound, so sequential lane chunks
    only subtract lane width (docs/ROMIX_KERNEL.md) — chunking has to
    EARN its place through the race."""
    return Decision("xla", None, "default")


def mesh_candidates(device_count: int, cap: int = MAX_MESH_DEVICES
                    ) -> list[int]:
    """Power-of-two device counts to race the lane-sharded kernel over:
    {2, 4, 8} clipped to the visible devices and ``cap``."""
    out, d = [], 2
    while d <= min(device_count, cap):
        out.append(d)
        d *= 2
    return out


def candidates(platform: str, n: int, batch: int, mesh_cap: int = 1
                ) -> list[tuple[str, int | None, int]]:
    """The (impl, chunk, devices) grid raced for one shape."""
    chunks: list[int | None] = [None, *chunk_candidates(n, batch)]
    if platform == "cpu":
        # interpret-mode Pallas executes every DMA in Python — never a
        # contender, so never raced (force it with SPACEMESH_ROMIX=pallas)
        out = [(impl, c, 1) for impl in ("xla", "xla-rows") for c in chunks]
    else:
        out = [("xla", c, 1) for c in chunks]
        if platform == "tpu":
            # the Pallas kernel tiles lanes at LANE_TILE internally (its V
            # scratch is per-tile), so an outer chunk adds nothing
            out.append(("pallas", None, 1))
    if mesh_cap > 1:
        # mesh rows: both XLA layouts on CPU (the contiguous-row variant's
        # win condition — gather read amplification — is per-device, so it
        # can flip under sharding too), plain xla elsewhere. No chunk: a
        # sequential lane chunk inside a shard fights GSPMD partitioning
        # (ops/scrypt.py _tunable), and the Pallas kernel is raced
        # single-device only (its per-tile V scratch already bounds the
        # working set).
        impls = ("xla", "xla-rows") if platform == "cpu" else ("xla",)
        for d in mesh_candidates(_device_count(), mesh_cap):
            out.extend((impl, None, d) for impl in impls)
    return out


def calibration_block(batch: int = CAL_BATCH, seed: int = 7) -> np.ndarray:
    """Deterministic (32, batch) u32 ROMix input, shared by the race and
    tools/profiler.py --romix so both measure the same workload."""
    rng = np.random.RandomState(seed)
    return rng.randint(0, 2**32, size=(32, batch),
                       dtype=np.uint64).astype(np.uint32)


# in-process memos. Race measurements are per (platform, n) — the
# calibration workload is FIXED at CAL_BATCH lanes, so one measurement
# serves every production batch size (bench sweeps, init tail batches,
# the verifier's variable-count label recomputes) — and are additionally
# persisted, so a new process deriving a winner for a new batch size
# never re-compiles. Resolved decisions are memoized per call signature
# (env included) so the steady dispatch path costs dict lookups, not a
# cache-file parse per batch.
_race_memo: dict[tuple, list[dict]] = {}
_decision_memo: dict[tuple, Decision] = {}


def reset_memo() -> None:
    """Drop in-process memos (tests simulating fresh processes)."""
    _race_memo.clear()
    _decision_memo.clear()


def _meas_key(platform: str, n: int) -> str:
    return f"v{SCHEMA}:meas:{platform}:n{n}:cal{CAL_BATCH}"


def _valid_rows(rows) -> list[dict]:
    out = []
    if not isinstance(rows, list):
        return out
    for r in rows:
        if (isinstance(r, dict) and r.get("impl") in IMPLS
                and (r.get("chunk") is None
                     or (isinstance(r.get("chunk"), int) and r["chunk"] >= 1))
                and isinstance(r.get("devices", 1), int)
                and not isinstance(r.get("devices", 1), bool)
                and r.get("devices", 1) >= 1
                and isinstance(r.get("labels_per_sec"), (int, float))):
            r.setdefault("devices", 1)
            # pre-shape rows (written by an older process) tag by impl
            if r.setdefault("shape", shape_of(r["impl"])) not in MESH_SHAPES:
                continue
            out.append(r)
    return out


def _race_measurements(platform: str, n: int, mesh_cap: int = 1
                       ) -> list[dict]:
    """All calibration measurements for (platform, n), raced lazily: the
    single-device grid on first use, mesh rows the first time a caller
    with a device budget > 1 asks. Rows persist incrementally, so a
    winners file written on a 1-device host grows mesh rows when it is
    first read on (or shipped to, via the CI cache) a multi-device one."""
    memo_key = (platform, n)
    rows = _race_memo.get(memo_key)
    if rows is None:
        rows = _valid_rows(
            _load_cache().get(_meas_key(platform, n), {}).get("raced"))
    missing = [c for c in candidates(platform, n, CAL_BATCH, mesh_cap)
               if (c[1] is None or c[1] < CAL_BATCH)
               and not any(r["impl"] == c[0] and r["chunk"] == c[1]
                           and r["devices"] == c[2] for r in rows)]
    if not missing:
        _race_memo[memo_key] = rows
        return rows
    from ..utils import metrics, tracing

    metrics.post_romix_autotune_races.inc()
    race_sp = tracing.span("romix.race",
                           {"platform": platform, "n": n,
                            "mesh_cap": mesh_cap}
                           if tracing.is_enabled() else None)
    race_sp.__enter__()
    try:
        rows = rows + _race_rows(platform, n, missing)
    finally:
        race_sp.__exit__(None, None, None)
    _race_memo[memo_key] = rows
    if rows:
        _store(_meas_key(platform, n),
               {"raced": rows, "cal_batch": CAL_BATCH,
                "tuned_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime())})
    return rows


def _race_rows(platform: str, n: int,
               combos: list[tuple[str, int | None, int]]) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from ..utils import tracing
    from . import scrypt

    x_host = jnp.asarray(calibration_block(CAL_BATCH))
    rows = []
    for impl, chunk, devices in combos:
        # non-pallas candidates never interpret — the SAME static jit key
        # production uses, so the race's compile is reused, not repaid
        interpret = impl == "pallas" and platform != "tpu"
        label = f"{impl}" + (f"/chunk={chunk}" if chunk else "") + (
            f"/devices={devices}" if devices > 1 else "")
        csp = tracing.span("romix.race_candidate",
                           {"impl": impl, "chunk": chunk,
                            "devices": devices}
                           if tracing.is_enabled() else None)
        csp.__enter__()
        try:
            if devices > 1:
                from ..parallel import mesh as pmesh

                mesh = pmesh.data_mesh(jax.devices()[:devices])
                x = jax.device_put(x_host, pmesh.lane_sharding(mesh))
            else:
                x = x_host

            def run():
                return scrypt.romix_tuned(x, n=n, impl=impl, chunk=chunk,
                                          interpret=interpret)

            t0 = time.perf_counter()
            run().block_until_ready()
            compile_s = time.perf_counter() - t0
            best = float("inf")
            for _ in range(CAL_REPS):
                t0 = time.perf_counter()
                run().block_until_ready()
                best = min(best, time.perf_counter() - t0)
            rate = CAL_BATCH / best
            _log(f"romix autotune: {label}: {rate:,.0f} labels/s "
                 f"(compile+first {compile_s:.1f}s)")
            csp.set(labels_per_sec=round(rate, 1),
                    compile_s=round(compile_s, 3))
            rows.append({"impl": impl, "chunk": chunk, "devices": devices,
                         "shape": shape_of(impl),
                         "labels_per_sec": round(rate, 1)})
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # compile on this host simply loses the race. Persisted as a
            # 0-rate row so the next process does NOT see it as missing
            # and re-pay the failing attempt at every startup (delete the
            # winners file to retry after fixing the host).
            _log(f"romix autotune: {label} failed "
                 f"({type(e).__name__}: {e})")
            csp.set(failed=type(e).__name__)
            rows.append({"impl": impl, "chunk": chunk, "devices": devices,
                         "shape": shape_of(impl), "labels_per_sec": 0.0,
                         "failed": type(e).__name__})
        finally:
            csp.__exit__(None, None, None)
    return rows


NOISE_BAND = 0.95  # rows within 5% of the best rate count as tied


def _select_winner(usable: list[dict]) -> dict:
    """The fastest row — except that among rows within the calibration
    noise band of the best rate, the one sharded over the FEWEST devices
    wins. Sharding overhead (SPMD rendezvous, per-shard D2H) grows with
    the production batch while the fixed 512-lane calibration slightly
    flatters wide meshes, so a near-tie at calibration is a real win for
    the narrower mesh at production shapes."""
    best = max(r["labels_per_sec"] for r in usable)
    near = [r for r in usable if r["labels_per_sec"] >= NOISE_BAND * best]
    return min(near, key=lambda r: (r["devices"], -r["labels_per_sec"]))


def race(platform: str, n: int, batch: int, dev_cap: int = 1,
         pin_devices: int | None = None) -> Decision | None:
    """Race (or reuse the measured race of) the candidate kernels on the
    fixed calibration workload, then persist and return the winner for
    ``(platform, n, batch)`` under a ``dev_cap`` device budget.

    ``pin_devices`` restricts selection to rows at exactly that device
    count (the SPACEMESH_MESH=<k> override); pinned selections are NOT
    persisted as winners — unsetting the override must fall back to the
    full-grid winner, not a pinned one — and return None when no row at
    that count survived."""
    rows = _race_measurements(platform, n, mesh_cap=dev_cap)
    usable = [r for r in rows
              if (r["chunk"] is None or r["chunk"] < batch)
              and r["devices"] <= dev_cap
              and r["devices"] <= batch
              and not r.get("failed") and r["labels_per_sec"] > 0]
    if pin_devices is not None:
        usable = [r for r in usable if r["devices"] == pin_devices]
        if not usable:
            return None
    if not usable:
        return default_decision(platform, n, batch)
    win = _select_winner(usable)
    chunk = win["chunk"]
    d = Decision(win["impl"], chunk, "race", win["labels_per_sec"],
                 devices=win["devices"], mesh_shape=win["shape"])
    if pin_devices is not None:
        return dataclasses.replace(d, source="env")
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    entries = {_key(platform, n, batch, dev_cap): {
        "impl": win["impl"], "chunk": chunk,
        "devices": win["devices"], "shape": win["shape"],
        "labels_per_sec": win["labels_per_sec"],
        "cal_batch": CAL_BATCH, "raced": rows, "tuned_at": stamp}}
    # the best row per mesh SHAPE, under the same budget: warmcache
    # compiles both layouts into the persistent cache, so a later winner
    # flip (re-race, SPACEMESH_ROMIX override) never cold-compiles
    for shape in MESH_SHAPES:
        srows = [r for r in usable if r["shape"] == shape]
        if not srows:
            continue
        sw = _select_winner(srows)
        entries[_shape_key(platform, n, batch, dev_cap, shape)] = {
            "impl": sw["impl"], "chunk": sw["chunk"],
            "devices": sw["devices"], "shape": shape,
            "labels_per_sec": sw["labels_per_sec"],
            "cal_batch": CAL_BATCH, "tuned_at": stamp}
    _store_many(entries)
    _log(f"romix autotune: winner for {platform} n={n} b={batch} "
         f"(<= {dev_cap} devices): {win['impl']}"
         + (f"/chunk={chunk}" if chunk else "")
         + (f"/devices={win['devices']}" if win["devices"] > 1 else "")
         + f" ({win['labels_per_sec']:,.0f} labels/s, persisted)")
    return d


def shape_winner(n: int, batch: int, shape: str, *,
                 platform: str | None = None,
                 max_devices: int | None = None) -> Decision | None:
    """The persisted winner for one mesh *shape* under the caller's
    device budget, or None when no race has measured that shape yet (or
    every candidate of that shape failed on this host). A pure cache
    read — never races — so warmcache and tests can enumerate both
    layouts' winners without re-paying measurement."""
    if shape not in MESH_SHAPES:
        raise ValueError(
            f"mesh shape {shape!r}: expected one of {', '.join(MESH_SHAPES)}")
    if platform is None:
        import jax

        platform = jax.default_backend()
    dev_cap = _device_cap(max_devices)
    entry = _load_cache().get(
        _shape_key(platform, n, batch, dev_cap, shape), {})
    d = _entry_decision(entry, batch, "cache")
    if d is not None and d.mesh_shape != shape:
        return None  # entry corrupted by hand-editing: shape key disagrees
    return d


def decide(n: int, batch: int, *, platform: str | None = None,
           allow_race: bool = True, max_devices: int | None = 1
           ) -> Decision:
    """Resolve the kernel choice for one shape (precedence in the module
    docstring). The steady dispatch path — one call per label batch from
    post/initializer.py — is a memoized dict lookup; the env values are
    part of the memo key so override changes always take effect.

    ``max_devices``: the caller's device budget. The default (1) serves
    shape-bound callers — ops/scrypt.py's per-call dispatch, the
    profiler's stage views — the best single-device row. Mesh-aware
    callers (post/initializer.py, post/prover.py, bench.py) pass None
    (= up to min(visible devices, 8)) and route through parallel/mesh.py
    when the winning row says ``devices > 1``."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    dev_cap = _device_cap(max_devices)
    memo_key = (platform, n, batch, allow_race, dev_cap,
                os.environ.get(ENV_IMPL), os.environ.get(ENV_CHUNK),
                os.environ.get(ENV_AUTOTUNE), os.environ.get(ENV_CACHE),
                os.environ.get(ENV_MESH))
    hit = _decision_memo.get(memo_key)
    if hit is not None:
        return hit
    d = _decide(n, batch, platform, allow_race, dev_cap)
    _decision_memo[memo_key] = d
    return d


def _decide(n: int, batch: int, platform: str, allow_race: bool,
            dev_cap: int) -> Decision:
    impl_env, chunk_env, chunk_set, no_race = read_env()
    mesh_env = read_mesh_env() if dev_cap > 1 else None
    if mesh_env is not None:
        mesh_env = max(1, min(mesh_env, dev_cap, batch))
    if mesh_env == 1:
        # SPACEMESH_MESH=off: the whole decision collapses to the
        # single-device budget — lookups, races, and persisted winners
        # all use the :d1 key, so the kill-switch also holds through the
        # race fall-through at the bottom
        dev_cap, mesh_env = 1, None
    cached = _entry_decision(
        _load_cache().get(_key(platform, n, batch, dev_cap), {}), batch,
        "cache")
    if cached is not None and cached.devices > min(dev_cap, batch):
        cached = None  # raced under a wider device budget than this call's
    if cached is not None and mesh_env is not None \
            and cached.devices != mesh_env:
        cached = None  # forced device count: the cached winner is moot
    if impl_env is not None:
        # explicit impl: env chunk > cached chunk (same impl) > heuristic
        if chunk_set:
            chunk = chunk_env
        elif cached is not None and cached.impl == impl_env:
            chunk = cached.chunk
        elif impl_env == "pallas":
            chunk = None
        else:
            chunk = default_decision(platform, n, batch).chunk
        if chunk is not None and chunk >= batch:
            chunk = None
        devices = mesh_env if mesh_env is not None else (
            cached.devices if cached is not None else 1)
        return Decision(impl_env, chunk, "env", explicit_impl=True,
                        devices=devices)
    if chunk_set:
        base = cached or default_decision(platform, n, batch)
        chunk = chunk_env if (chunk_env is None or chunk_env < batch) else None
        devices = mesh_env if mesh_env is not None else base.devices
        return Decision(base.impl, chunk, "env", devices=devices)
    if cached is not None:
        return cached
    if mesh_env is not None and mesh_env > 1:
        # forced device count: best raced row at that count when racing
        # is allowed, the plain XLA kernel otherwise (the historical
        # SPACEMESH_MESH=1 behavior)
        if allow_race and not no_race:
            pinned = race(platform, n, batch, dev_cap,
                          pin_devices=mesh_env)
            if pinned is not None:
                return pinned
        return Decision("xla", None, "env", devices=mesh_env)
    if no_race or not allow_race:
        return default_decision(platform, n, batch)
    return race(platform, n, batch, dev_cap)
