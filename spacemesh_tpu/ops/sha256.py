"""SHA-256 as pure JAX on uint32 words.

Used by the scrypt labeler (PBKDF2-HMAC-SHA256 envelope; see ops/scrypt.py)
and by k2pow. The reference computes these inside post-rs (Rust `scrypt`
crate); here they are expressed as branch-free uint32 arithmetic so a single
definition serves:

- per-label scalar form (word vectors of shape ``(n,)``), which `jax.vmap`
  batches across labels, and
- direct batched use with a leading lane dimension.

All words are big-endian packed per FIPS 180-4. Conversions to scrypt's
little-endian layout happen in ops/scrypt.py via `byteswap32`.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)

IV = np.array(
    [0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
     0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19],
    dtype=np.uint32,
)


def rotr(x, n: int):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def byteswap32(x):
    """Reverse byte order of each uint32 lane (BE <-> LE repacking)."""
    x = x.astype(jnp.uint32)
    return (
        (x << jnp.uint32(24))
        | ((x & jnp.uint32(0xFF00)) << jnp.uint32(8))
        | ((x >> jnp.uint32(8)) & jnp.uint32(0xFF00))
        | (x >> jnp.uint32(24))
    )


def sha256_compress(state, block):
    """One SHA-256 compression. ``state``: (8, ...) u32, ``block``: (16, ...) u32.

    Trailing dims are lanes (label batch). The schedule and round loops are
    `lax.fori_loop`s rather than unrolled: the fully unrolled 64-round u32
    graph sends XLA:CPU's algebraic simplifier into a circular-rewrite spin
    (hang at compile time), and rolled loops also keep compiles fast.
    SHA-256 is the envelope, not the hot path — ROMix dominates runtime.
    """
    state = jnp.asarray(state)
    block = jnp.asarray(block)
    tail = block.shape[1:]
    if state.ndim < block.ndim:  # add lane axes: words are ALWAYS axis 0
        state = state.reshape(state.shape + (1,) * (block.ndim - state.ndim))
    if state.shape[1:] != tail:  # broadcast lanes eagerly: fori_loop carries
        state = jnp.broadcast_to(state, (8,) + tail)  # must be shape-stable

    w0 = jnp.concatenate(
        [block, jnp.zeros((48,) + tail, jnp.uint32)], axis=0)

    def extend(i, w):
        a = lax.dynamic_index_in_dim(w, i - 15, keepdims=False)
        b = lax.dynamic_index_in_dim(w, i - 2, keepdims=False)
        s0 = rotr(a, 7) ^ rotr(a, 18) ^ (a >> jnp.uint32(3))
        s1 = rotr(b, 17) ^ rotr(b, 19) ^ (b >> jnp.uint32(10))
        wi = (lax.dynamic_index_in_dim(w, i - 16, keepdims=False) + s0
              + lax.dynamic_index_in_dim(w, i - 7, keepdims=False) + s1)
        return lax.dynamic_update_index_in_dim(w, wi, i, axis=0)

    w = lax.fori_loop(16, 64, extend, w0)
    k = jnp.asarray(_K)

    def round_(i, carry):
        a, b, c, d, e, f, g, h = carry
        s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + lax.dynamic_index_in_dim(k, i, keepdims=False)
              + lax.dynamic_index_in_dim(w, i, keepdims=False))
        s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    init = tuple(state[i] for i in range(8))
    out = lax.fori_loop(0, 64, round_, init)
    return jnp.stack([state[i] + out[i] for i in range(8)])


def sha256_words(blocks):
    """SHA-256 over pre-padded message ``blocks`` of shape (nblocks, 16) u32."""
    state = jnp.asarray(IV)
    nblocks = blocks.shape[0]
    if nblocks <= 4:  # unroll short messages (the common case here)
        for i in range(nblocks):
            state = sha256_compress(state, blocks[i])
        return state
    def body(i, st):
        return sha256_compress(st, lax.dynamic_index_in_dim(blocks, i, keepdims=False))
    return lax.fori_loop(0, nblocks, body, state)


def hmac_midstates(key_words):
    """Midstates of HMAC-SHA256 for a 32-byte key given as (8,) u32 BE words.

    Returns (inner, outer) compression states after absorbing key^ipad /
    key^opad — shared across every PBKDF2 block and every label.
    """
    key_words = jnp.asarray(key_words, jnp.uint32)
    kw = jnp.concatenate([key_words, jnp.zeros_like(key_words)])
    ipad = kw ^ jnp.uint32(0x36363636)
    opad = kw ^ jnp.uint32(0x5C5C5C5C)
    iv = jnp.asarray(IV)
    return sha256_compress(iv, ipad), sha256_compress(iv, opad)


def pad_message_np(msg: bytes) -> np.ndarray:
    """Host-side FIPS 180-4 padding -> (nblocks, 16) u32 BE words."""
    ml = len(msg)
    msg = msg + b"\x80"
    msg += b"\x00" * ((-(len(msg) + 8)) % 64)
    msg += (ml * 8).to_bytes(8, "big")
    arr = np.frombuffer(msg, dtype=">u4").astype(np.uint32)
    return arr.reshape(-1, 16)
