"""TPU compute kernels (JAX/XLA/Pallas) for the POST compute plane.

These replace the reference's native stack (post-rs scrypt labeler + OpenCL
kernels + RandomX PoW; see SURVEY.md §2.3): everything here is expressed as
jittable JAX on uint32 lanes so XLA can vectorize across the label/proof
batch dimension, with Pallas variants for the hot loops.
"""
