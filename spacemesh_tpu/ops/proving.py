"""POST proving & verification primitives — TPU-native nonce search.

Reference semantics (post-rs `post-service`, reached through the gRPC seam at
reference api/grpcserver/post_service.go; params reference
activation/post.go:27-61 and config/mainnet.go:187-189):

- A proof over a unit of ``total_labels`` labels is a nonce plus K2 label
  indices whose *proving hash* falls under a difficulty threshold; K1 sets
  the expected number of qualifying labels per nonce, so a nonce "wins"
  with tunable probability. Verification recomputes a K3-subset of the
  submitted indices' labels and re-checks the threshold.

TPU-first redesign (NOT a port): post-rs hashes the label stream with
AES128 keyed by the challenge — fast on CPU AES-NI, hostile on TPU (S-box
table lookups). Our proving hash is one Salsa20/8 application (pure ARX on
u32 lanes, the same core the labeler already uses):

    state = challenge(8 words LE) || nonce || idx_lo || idx_hi || 0
            || label(4 words LE)
    value = salsa20_8(state)[0]          # u32, uniform
    qualifies <=> value < threshold(k1, total_labels)

so proving streams labels through the VPU at full lane width. The
threshold is ``floor(k1 * 2^32 / total_labels)`` giving E[qualifying] = k1
per nonce over the unit.

All functions here are shape-static and jittable; the host-side scheduler
(post/prover.py) feeds label batches and collects qualifying indices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .scrypt import salsa20_8


def threshold_u32(k1: int, total_labels: int) -> int:
    """Qualifying threshold: E[#qualifying labels per nonce] == k1."""
    if total_labels <= 0:
        raise ValueError("total_labels must be positive")
    t = (k1 << 32) // total_labels
    return min(t, (1 << 32) - 1)


def challenge_words(challenge: bytes) -> np.ndarray:
    if len(challenge) != 32:
        raise ValueError("challenge must be 32 bytes")
    return np.frombuffer(challenge, dtype="<u4").astype(np.uint32)


_challenge_words = challenge_words  # compat alias


@functools.partial(jax.jit, static_argnames=())
def proving_hash_jit(challenge_words, nonce, idx_lo, idx_hi, label_words):
    """Proving-hash values for a batch of labels.

    challenge_words: (8,) u32 LE shared, or (8, B) per-lane (batch verify);
    nonce: scalar u32 shared, or (B,) per-lane; idx_lo/idx_hi: (B,) u32;
    label_words: (4, B) u32 LE (batch minor, as produced by the labeler).
    Returns (B,) u32 hash values.
    """
    b = idx_lo.shape[0]
    ch = challenge_words.astype(jnp.uint32)
    if ch.ndim == 1:
        ch = ch[:, None]
    ch = jnp.broadcast_to(ch, (8, b))
    nv = jnp.broadcast_to(jnp.asarray(nonce, jnp.uint32).reshape(-1), (b,))
    state = jnp.concatenate([
        ch,
        nv[None],
        idx_lo[None],
        idx_hi[None],
        jnp.zeros((1, b), jnp.uint32),
        label_words,
    ])
    return salsa20_8(state)[0]


@functools.partial(jax.jit, static_argnames=("n_nonces",))
def proving_scan_jit(challenge_words, nonce_base, idx_lo, idx_hi, label_words,
                     threshold, *, n_nonces: int):
    """Evaluate ``n_nonces`` consecutive nonces over one label batch.

    Returns (n_nonces, B) bool qualification mask. The host accumulates
    per-nonce hit counts/indices across label batches; n_nonces is static
    so the whole sweep is one compiled program.
    """
    def one(k):
        vals = proving_hash_jit(challenge_words, nonce_base + jnp.uint32(k),
                                idx_lo, idx_hi, label_words)
        return vals < threshold.astype(jnp.uint32)
    return jnp.stack([one(k) for k in range(n_nonces)])


def proving_hashes(challenge: bytes, nonce: int, indices, labels: np.ndarray
                   ) -> np.ndarray:
    """Host entry: hash values for (nonce, labels[i]) pairs.

    ``labels``: (B, 16) uint8 as returned by the labeler. Returns (B,) u32.
    """
    from .scrypt import labels_to_words, split_indices

    cw = challenge_words(challenge)
    lo, hi = split_indices(np.atleast_1d(np.asarray(indices)).ravel())
    lw = labels_to_words(labels)
    out = proving_hash_jit(jnp.asarray(cw), jnp.uint32(nonce),
                           jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(lw))
    return np.asarray(out)
