"""POST proving & verification primitives — TPU-native nonce search.

Reference semantics (post-rs `post-service`, reached through the gRPC seam at
reference api/grpcserver/post_service.go; params reference
activation/post.go:27-61 and config/mainnet.go:187-189):

- A proof over a unit of ``total_labels`` labels is a nonce plus K2 label
  indices whose *proving hash* falls under a difficulty threshold; K1 sets
  the expected number of qualifying labels per nonce, so a nonce "wins"
  with tunable probability. Verification recomputes a K3-subset of the
  submitted indices' labels and re-checks the threshold.

TPU-first redesign (NOT a port): post-rs hashes the label stream with
AES128 keyed by the challenge — fast on CPU AES-NI, hostile on TPU (S-box
table lookups). Our proving hash is one Salsa20/8 application (pure ARX on
u32 lanes, the same core the labeler already uses):

    state = challenge(8 words LE) || nonce || idx_lo || idx_hi || 0
            || label(4 words LE)
    value = salsa20_8(state)[0]          # u32, uniform
    qualifies <=> value < threshold(k1, total_labels)

so proving streams labels through the VPU at full lane width. The
threshold is ``floor(k1 * 2^32 / total_labels)`` giving E[qualifying] = k1
per nonce over the unit.

All functions here are shape-static and jittable; the host-side scheduler
(post/prover.py) feeds label batches and collects qualifying indices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .scrypt import salsa20_8


def threshold_u32(k1: int, total_labels: int) -> int:
    """Qualifying threshold: E[#qualifying labels per nonce] == k1."""
    if total_labels <= 0:
        raise ValueError("total_labels must be positive")
    t = (k1 << 32) // total_labels
    return min(t, (1 << 32) - 1)


def challenge_words(challenge: bytes) -> np.ndarray:
    if len(challenge) != 32:
        raise ValueError("challenge must be 32 bytes")
    return np.frombuffer(challenge, dtype="<u4").astype(np.uint32)


_challenge_words = challenge_words  # compat alias


@functools.partial(jax.jit, static_argnames=())
def proving_hash_jit(challenge_words, nonce, idx_lo, idx_hi, label_words):
    """Proving-hash values for a batch of labels.

    challenge_words: (8,) u32 LE shared, or (8, B) per-lane (batch verify);
    nonce: scalar u32 shared, or (B,) per-lane; idx_lo/idx_hi: (B,) u32;
    label_words: (4, B) u32 LE (batch minor, as produced by the labeler).
    Returns (B,) u32 hash values.
    """
    b = idx_lo.shape[0]
    ch = challenge_words.astype(jnp.uint32)
    if ch.ndim == 1:
        ch = ch[:, None]
    ch = jnp.broadcast_to(ch, (8, b))
    nv = jnp.broadcast_to(jnp.asarray(nonce, jnp.uint32).reshape(-1), (b,))
    state = jnp.concatenate([
        ch,
        nv[None],
        idx_lo[None],
        idx_hi[None],
        jnp.zeros((1, b), jnp.uint32),
        label_words,
    ])
    return salsa20_8(state)[0]


def _scan_mask(challenge_words, nonce_base, idx_lo, idx_hi, label_words,
               threshold, *, n_nonces: int):
    """(n_nonces, B) bool qualification mask, traced per-nonce.

    The per-nonce stacking (rather than one fused (16, n*B) state) keeps
    each Salsa20/8 working set L2-resident — measured ~2x faster on CPU
    and neutral on TPU, where the Pallas kernel is the fast path anyway.
    """
    def one(k):
        vals = proving_hash_jit(challenge_words, nonce_base + jnp.uint32(k),
                                idx_lo, idx_hi, label_words)
        return vals < threshold.astype(jnp.uint32)
    return jnp.stack([one(k) for k in range(n_nonces)])


@functools.partial(jax.jit, static_argnames=("n_nonces",))
def proving_scan_jit(challenge_words, nonce_base, idx_lo, idx_hi, label_words,
                     threshold, *, n_nonces: int):
    """Evaluate ``n_nonces`` consecutive nonces over one label batch.

    Returns (n_nonces, B) bool qualification mask. The host accumulates
    per-nonce hit counts/indices across label batches; n_nonces is static
    so the whole sweep is one compiled program.
    """
    return _scan_mask(challenge_words, nonce_base, idx_lo, idx_hi,
                      label_words, threshold, n_nonces=n_nonces)


# --- on-device hit compaction ----------------------------------------------
#
# The streaming prover never copies a qualification mask to the host: each
# batch's hits are compacted on device into ascending (lane, rank) form and
# merged into a *donated* running hit state, so the per-batch D2H is one
# (n_nonces,) count vector (~100-1000x smaller than the mask) and the packed
# (nonce, index) hit pairs cross PCIe once per pass, not once per batch.

HIT_SEGMENT = 64  # lanes per compaction segment; batch must divide by this


def compact_hits(mask, seg_sum=None, *, max_hits: int):
    """Compact a (n_nonces, B) mask into per-nonce hit positions.

    Returns ``(batch_counts, local_pos, hit_valid)``: true per-nonce hit
    counts (i32), the ascending lane indices of each nonce's first
    ``max_hits`` hits (u32, garbage where invalid), and the validity mask.
    Two-level extraction — segment popcounts, then a gather of only the
    ``max_hits`` segments that actually contain the wanted hits — so the
    cost is one reduction pass over the mask, not a (n_nonces, B) sort.

    ``seg_sum`` may be supplied by a kernel that already reduced the mask
    (the Pallas epilogue); otherwise it is computed here.
    """
    n_nonces, b = mask.shape
    nseg = b // HIT_SEGMENT
    m3 = mask.reshape(n_nonces, nseg, HIT_SEGMENT)
    if seg_sum is None:
        seg_sum = jnp.sum(m3, axis=-1, dtype=jnp.int32)
    seg_csum = jnp.cumsum(seg_sum, axis=1)
    batch_counts = seg_csum[:, -1]
    targets = jnp.arange(1, max_hits + 1, dtype=jnp.int32)
    # segment holding each nonce's j-th hit (binary search per row)
    seg = jax.vmap(
        lambda row: jnp.searchsorted(row, targets, side="left"))(seg_csum)
    segc = jnp.minimum(seg, nseg - 1)
    prev = jnp.where(seg > 0,
                     jnp.take_along_axis(seg_csum,
                                         jnp.maximum(seg - 1, 0), axis=1),
                     0)
    rank = targets[None, :] - prev             # 1-based rank within segment
    seg_lanes = jnp.take_along_axis(m3, segc[:, :, None], axis=1)
    within = jnp.cumsum(seg_lanes.astype(jnp.int32), axis=-1)
    lane = jnp.sum((within < rank[:, :, None]).astype(jnp.int32), axis=-1)
    local_pos = (segc * HIT_SEGMENT + lane).astype(jnp.uint32)
    hit_valid = targets[None, :] <= batch_counts[:, None]
    return batch_counts, local_pos, hit_valid


def merge_hits(hit_counts, hit_carry, batch_counts, local_pos, hit_valid,
               start_lo, start_hi):
    """Scatter one batch's compacted hits into the running device state.

    ``hit_carry`` is (2, n_nonces, cap) u32 — lo/hi halves of global label
    indices, slot-ordered (ascending) per nonce. Hits beyond ``cap`` drop:
    the prover sizes cap >= k2, and only the first k2 hits per nonce can
    ever appear in a proof. Returns (new_counts, batch_counts, hit_carry);
    callers donate hit_counts/hit_carry so the state rotates in place.
    """
    n_nonces, max_hits = local_pos.shape
    cap = hit_carry.shape[2]
    glo = (start_lo + local_pos).astype(jnp.uint32)
    ghi = (start_hi + (glo < local_pos).astype(jnp.uint32)).astype(jnp.uint32)
    targets = jnp.arange(max_hits, dtype=jnp.int32)
    rows = jnp.broadcast_to(jnp.arange(n_nonces)[:, None], local_pos.shape)
    slots = jnp.where(hit_valid, hit_counts[:, None] + targets[None, :], cap)
    hit_carry = hit_carry.at[0, rows, slots].set(glo, mode="drop")
    hit_carry = hit_carry.at[1, rows, slots].set(ghi, mode="drop")
    return hit_counts + batch_counts, batch_counts, hit_carry


@functools.partial(jax.jit, static_argnames=("n_nonces", "max_hits"),
                   donate_argnums=(6, 7))
def prove_scan_step_jit(challenge_words, nonce_base, idx_lo, idx_hi,
                        label_words, threshold, hit_counts, hit_carry,
                        valid, start_lo, start_hi, *, n_nonces: int,
                        max_hits: int):
    """One pipelined prove step: scan + compact + merge, all on device.

    ``valid`` masks pad lanes of a ragged tail batch (lane >= valid never
    qualifies), so every batch of a pass shares one compiled shape.
    Returns (hit_counts', batch_counts, hit_carry'); the carries are
    donated and cycle device-side across the pass — the only per-batch
    host fetch is ``batch_counts``.
    """
    b = idx_lo.shape[0]
    mask = _scan_mask(challenge_words, nonce_base, idx_lo, idx_hi,
                      label_words, threshold, n_nonces=n_nonces)
    lane = jnp.arange(b, dtype=jnp.uint32)
    mask = mask & (lane[None, :] < valid)
    counts, pos, ok = compact_hits(mask, max_hits=max_hits)
    return merge_hits(hit_counts, hit_carry, counts, pos, ok,
                      start_lo, start_hi)


def init_hit_state(n_nonces: int, cap: int):
    """Fresh (hit_counts, hit_carry) device state for one prove pass."""
    return (jnp.zeros(n_nonces, jnp.int32),
            jnp.full((2, n_nonces, cap), 0xFFFFFFFF, jnp.uint32))


def decode_hits(hit_counts, hit_carry, nonce_row: int, limit: int
                ) -> list[int]:
    """Host-side: first ``limit`` global label indices of one nonce row."""
    counts = np.asarray(hit_counts)
    carry = np.asarray(hit_carry)
    n = min(int(counts[nonce_row]), carry.shape[2], limit)
    lo = carry[0, nonce_row, :n].astype(np.uint64)
    hi = carry[1, nonce_row, :n].astype(np.uint64)
    return [int(v) for v in (lo | (hi << np.uint64(32)))]


def proving_hashes(challenge: bytes, nonce: int, indices, labels: np.ndarray
                   ) -> np.ndarray:
    """Host entry: hash values for (nonce, labels[i]) pairs.

    ``labels``: (B, 16) uint8 as returned by the labeler. Returns (B,) u32.
    """
    from .scrypt import labels_to_words, split_indices

    cw = challenge_words(challenge)
    lo, hi = split_indices(np.atleast_1d(np.asarray(indices)).ravel())
    lw = labels_to_words(labels)
    out = proving_hash_jit(jnp.asarray(cw), jnp.uint32(nonce),
                           jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(lw))
    return np.asarray(out)
