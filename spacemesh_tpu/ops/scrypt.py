"""scrypt (N, r=1, p=1) as pure JAX — the POST labeling function.

The reference fills 64 GiB Space Units with 16-byte labels computed by the
post-rs native initializer (CGo/OpenCL; SURVEY.md §2.3, reference
activation/post.go:355). A label is scrypt of the smesher's commitment over
the label index. Here the whole pipeline — PBKDF2-HMAC-SHA256 envelope,
Salsa20/8 core, BlockMix, ROMix with its data-dependent gather — is
branch-free uint32 JAX, batched across labels (the embarrassingly parallel
axis: 2^32 labels per Space Unit).

Label definition (bit-exact against `hashlib.scrypt`, which is our CPU
ground truth in tests):

    label(commitment, i) = scrypt(password=commitment, salt=le64(i),
                                  N=n, r=1, p=1, dklen=16)

Kernel structure (docs/ROMIX_KERNEL.md):

* Salsa20/8 runs in the DIAGONAL-VECTOR formulation: the 4x4 word matrix
  is regrouped into four diagonal vectors of shape (4, B) so every
  quarter-round is ONE vector op over all four quarters at once — 4x
  fewer, 4x wider XLA ops than the scalar-word unrolling, which is what
  the op-dispatch-bound XLA:CPU backend needs (measured 6.4x on the
  ROMix stage; the rowround reuses the same dataflow after a lane roll).
* ROMix has two interchangeable, bit-identical V layouts: word-major
  (N, 32, B) — dense u32 tiles on TPU, one fused gather — and
  contiguous-row (N*B, 32) — one lane's row is 128 contiguous bytes, the
  layout the Pallas kernel (ops/romix_pallas.py) uses for its DMAs.
* The batch can be processed in sequential lane CHUNKS (`lax.map`) so the
  V working set (N * 128 bytes per lane) fits a cache/VMEM budget.
* The whole label pipeline — PBKDF2 expand, ROMix, PBKDF2 finish, and
  optionally the VRF min-scan — compiles as ONE jitted program with a
  donated scan carry, so HMAC block state never round-trips through HBM
  between stages. (The historical three-program split guarded against an
  XLA:CPU simplifier loop that the rolled SHA-256 compression loops in
  ops/sha256.py already avoid; the fused pipeline is re-verified against
  hashlib in tests/test_scrypt.py and tests/test_romix_autotune.py.)

Which (implementation, chunk) wins is decided per (platform, N, batch) by
ops/autotune.py — raced once on a calibration workload, persisted next to
the XLA compile cache, overridable via SPACEMESH_ROMIX /
SPACEMESH_ROMIX_CHUNK. Every entry point (post/initializer.py,
post/prover.py, parallel/mesh.py, bench.py, tools/profiler.py) goes
through `scrypt_labels_jit` / `scrypt_labels_with_min` and therefore
picks up the tuned kernel with zero configuration.

TPU layout note: the batch is the MINOR dimension everywhere — block state
is (32, B) — so u32 tiles are fully dense ((8,128) tiling pads a trailing
dim of 32 by 4x; a trailing dim of B%128==0 pads nothing). Every op is
then a (B,)-wide VPU lane op and the data-dependent V[j] read is a
per-lane gather. V costs N*128 bytes per in-flight label (1 MiB at
mainnet N=8192), so batch size trades HBM for throughput; see
post/initializer.py (batch sizing) and bench.py.
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils import sanitize, tracing
from .sha256 import byteswap32, hmac_midstates, sha256_compress

LABEL_BYTES = 16  # reference: 16-byte labels, 2^32 per 64 GiB unit

ENV_BUCKETS = "SPACEMESH_SHAPE_BUCKETS"  # "0"/"off" disables bucketing


def shape_bucket(b: int) -> int:
    """The executable lane-count bucket for a batch of ``b`` labels: the
    next power of two (identity when ``b`` already is one).

    Every jitted program here compiles per (static args, input shape) —
    so without bucketing, an init session's ragged tail batch, the
    verifier's variable-count label recomputes, and every bench sweep
    size each mint a fresh executable (17-26s of XLA compile apiece on a
    cold host). Padding the lane axis up to a power-of-two bucket and
    trimming the output caps the executable population at log2(max
    batch) shapes per N; pad lanes repeat the last label index, which
    the VRF min-scan cannot distinguish from the real last lane (same
    value, first-occurrence lane wins — the identical argument the mesh
    pad in post/initializer.py relies on). ``SPACEMESH_SHAPE_BUCKETS=off``
    disables (tests that measure exact shapes)."""
    if b <= 1:
        return max(b, 1)
    if (os.environ.get(ENV_BUCKETS) or "").lower() in ("0", "off", "none"):
        return b
    return 1 << (b - 1).bit_length()


def _rotl(x, n: int):
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


# Salsa20's 4x4 state regrouped into diagonal vectors: row q of _DIAG
# lists the state words whose quarter-round position is q. In this
# layout the columnround's four quarters are ONE quarter-round over
# (4, B) vectors, and the rowround is the same dataflow after rolling
# each vector q lanes (the standard SIMD salsa trick, cf. the reference
# implementation's core/salsa2012 SSE2 path).
_DIAG = np.array([[0, 5, 10, 15],
                  [4, 9, 14, 3],
                  [8, 13, 2, 7],
                  [12, 1, 6, 11]])
_UNDIAG = np.argsort(_DIAG.ravel())


def salsa20_8(block):
    """Salsa20/8 core. ``block``: (16, ...) u32 LE words (lanes trailing)."""
    a = block[_DIAG[0]]
    b = block[_DIAG[1]]
    c = block[_DIAG[2]]
    d = block[_DIAG[3]]
    for _ in range(4):  # 4 double-rounds = 8 rounds
        # columnround: all four column quarters, one vector quarter-round
        b = b ^ _rotl(a + d, 7)
        c = c ^ _rotl(b + a, 9)
        d = d ^ _rotl(c + b, 13)
        a = a ^ _rotl(d + c, 18)
        # realign diagonals, then the rowround is the same dataflow with
        # the b/d roles mirrored
        b = jnp.roll(b, 1, axis=0)
        c = jnp.roll(c, 2, axis=0)
        d = jnp.roll(d, 3, axis=0)
        d = d ^ _rotl(a + b, 7)
        c = c ^ _rotl(d + a, 9)
        b = b ^ _rotl(c + d, 13)
        a = a ^ _rotl(b + c, 18)
        b = jnp.roll(b, -1, axis=0)
        c = jnp.roll(c, -2, axis=0)
        d = jnp.roll(d, -3, axis=0)
    return jnp.concatenate([a, b, c, d])[_UNDIAG] + block


def blockmix_r1(x):
    """scrypt BlockMix for r=1: x is (32, ...) u32 LE, two 64-byte halves."""
    y0 = salsa20_8(x[0:16] ^ x[16:32])
    y1 = salsa20_8(x[16:32] ^ y0)
    return jnp.concatenate([y0, y1])


def romix_r1(x, n: int, *, mix_phase: bool = True):
    """scrypt ROMix for r=1 over a (32, B) u32 LE block batch. ``n`` static.

    Word-major V layout (n, 32, B): dense u32 tiles on TPU, and the
    data-dependent read is one fused per-lane gather. ``mix_phase=False``
    stops after the fill phase (profiler stage split only).
    """
    b = x.shape[1]
    v0 = jnp.zeros((n, 32, b), dtype=jnp.uint32)

    def fill(i, carry):
        v, xx = carry
        v = lax.dynamic_update_slice_in_dim(v, xx[None], i, axis=0)
        return v, blockmix_r1(xx)

    v, x = lax.fori_loop(0, n, fill, (v0, x))
    if not mix_phase:
        return x

    def mix(_, xx):
        j = xx[16] % jnp.uint32(n)  # Integerify: first word of B_{2r-1}, per lane
        vj = jnp.take_along_axis(
            v, j[None, None, :].astype(jnp.int32), axis=0
        )[0]
        return blockmix_r1(xx ^ vj)

    return lax.fori_loop(0, n, mix, x)


def romix_r1_rows(x, n: int, *, mix_phase: bool = True):
    """ROMix with the contiguous-row V layout: (n*B, 32), one lane's row
    is 128 contiguous bytes (the layout ops/romix_pallas.py DMAs around).

    Bit-identical to :func:`romix_r1`; trades the word-major gather's
    read amplification (32 strided words per lane) for one contiguous
    row read plus a (B, 32) transpose per iteration. Raced against the
    other variants by ops/autotune.py.
    """
    b = x.shape[1]
    v0 = jnp.zeros((n * b, 32), dtype=jnp.uint32)

    def fill(i, carry):
        v, xx = carry
        v = lax.dynamic_update_slice_in_dim(v, xx.T, i * b, axis=0)
        return v, blockmix_r1(xx)

    v, x = lax.fori_loop(0, n, fill, (v0, x))
    if not mix_phase:
        return x
    lanes = jnp.arange(b, dtype=jnp.uint32)

    def mix(_, xx):
        j = xx[16] % jnp.uint32(n)
        rows = (j * jnp.uint32(b) + lanes).astype(jnp.int32)
        vj = jnp.take(v, rows, axis=0)  # (B, 32): contiguous per lane
        return blockmix_r1(xx ^ vj.T)

    return lax.fori_loop(0, n, mix, x)


def _romix_chunked(fn, x, n: int, chunk: int | None, **kw):
    """Run ``fn`` over sequential lane chunks (``lax.map``) so only one
    chunk's V (n * 128 * chunk bytes) is live at a time. Lanes are padded
    to a chunk multiple and trimmed — pad lanes run wasted ROMix work, at
    most chunk-1 of them per call."""
    b = x.shape[1]
    if not chunk or chunk >= b:
        return fn(x, n, **kw)
    pad = -b % chunk
    if pad:
        x = jnp.concatenate([x, jnp.zeros((32, pad), jnp.uint32)], axis=1)
    xc = jnp.moveaxis(x.reshape(32, -1, chunk), 1, 0)
    out = lax.map(lambda c: fn(c, n, **kw), xc)
    out = jnp.moveaxis(out, 0, 1).reshape(32, -1)
    return out[:, :b] if pad else out


def _romix_dispatch(blk, *, n: int, impl: str, chunk: int | None,
                    interpret: bool, mix_phase: bool = True):
    if impl == "pallas":
        from .romix_pallas import romix_pallas_padded

        # the Pallas kernel already tiles lanes (per-tile V scratch), so
        # the outer chunk is meaningless there
        return romix_pallas_padded(blk, n=n, interpret=interpret,
                                   mix_phase=mix_phase)
    fn = romix_r1_rows if impl == "xla-rows" else romix_r1
    return _romix_chunked(fn, blk, n, chunk, mix_phase=mix_phase)


romix_tuned = jax.jit(
    _romix_dispatch,
    static_argnames=("n", "impl", "chunk", "interpret", "mix_phase"))
"""Jitted ROMix with an explicit (impl, chunk) choice — the entry the
autotune race and the profiler's --romix stage view share."""


def _hmac_finish(outer_mid, inner_digest):
    """Outer HMAC compression over a 32-byte inner digest batch (8, B)."""
    b = inner_digest.shape[1]
    tail = np.zeros((8, 1), dtype=np.uint32)
    tail[0, 0] = 0x80000000
    tail[7, 0] = (64 + 32) * 8
    block = jnp.concatenate(
        [inner_digest, jnp.broadcast_to(jnp.asarray(tail), (8, b))])
    return sha256_compress(outer_mid, block)


def _pbkdf2_first(inner_mid, outer_mid, idx_lo, idx_hi):
    """PBKDF2(pw, salt=le64(index), c=1, dklen=128) -> (32, B) u32 LE words."""
    b = idx_lo.shape[0]
    out = []
    for i in (1, 2, 3, 4):
        # message = salt le64(index) || be32(i), then SHA padding to one block
        tail = np.zeros((14, 1), dtype=np.uint32)
        tail[0, 0] = i            # be32(block index)
        tail[1, 0] = 0x80000000   # padding start
        tail[13, 0] = (64 + 12) * 8
        block = jnp.concatenate([
            byteswap32(idx_lo)[None],
            byteswap32(idx_hi)[None],
            jnp.broadcast_to(jnp.asarray(tail), (14, b)),
        ])
        digest = _hmac_finish(outer_mid, sha256_compress(inner_mid, block))
        out.append(digest)
    return byteswap32(jnp.concatenate(out))  # repack BE digests as LE words


def _pbkdf2_second(inner_mid, outer_mid, b_le):
    """PBKDF2(pw, salt=B'||be32(1), c=1) -> 32-byte digests, (8, B) u32 BE."""
    b = b_le.shape[1]
    st = sha256_compress(inner_mid, byteswap32(b_le[0:16]))
    st = sha256_compress(st, byteswap32(b_le[16:32]))
    tail = np.zeros((16, 1), dtype=np.uint32)
    tail[0, 0] = 1            # be32(block index 1)
    tail[1, 0] = 0x80000000   # padding start
    tail[15, 0] = (64 + 132) * 8
    st = sha256_compress(st, jnp.broadcast_to(jnp.asarray(tail), (16, b)))
    return _hmac_finish(outer_mid, st)


def _expand(commitment_words, idx_lo, idx_hi):
    # commitment_words: (8,) shared across the batch, or (8, B) per-lane
    # (the batched verifier recomputes labels of many smeshers at once)
    inner_mid, outer_mid = hmac_midstates(commitment_words)
    if inner_mid.ndim == 1:
        inner_mid = inner_mid[:, None]  # broadcast over lanes
        outer_mid = outer_mid[:, None]
    return inner_mid, outer_mid, _pbkdf2_first(inner_mid, outer_mid,
                                               idx_lo, idx_hi)


# standalone per-stage jits: kept for the profiler's stage-timing view
# and for any caller that wants a single stage; production labeling goes
# through the fused single-program pipelines below
_stage_expand = jax.jit(_expand)

_stage_romix_xla = jax.jit(romix_r1, static_argnames=("n", "mix_phase"))


@jax.jit
def _stage_finish(inner_mid, outer_mid, blk):
    return _pbkdf2_second(inner_mid, outer_mid, blk)[:4]


# --- tuned dispatch -----------------------------------------------------

_fallback_logged = False


def _tunable(*arrays) -> bool:
    """Autotuned chunking/impl selection only applies when the inputs are
    concrete and single-device: under a tracer (parallel/mesh.py jits
    around these wrappers) or a multi-device sharding, the lane-chunk
    reshape would fight GSPMD's batch partitioning, so those callers get
    the plain XLA path unless the env overrides say otherwise."""
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            return False
        s = getattr(a, "sharding", None)
        if s is not None:
            try:
                if len(s.device_set) > 1:
                    return False
            except Exception:  # noqa: BLE001 — exotic array types
                pass
    return True


def _plan(n: int, batch: int, *arrays, impl: str | None = None,
          chunk: int | None = None):
    """-> (autotune.Decision, interpret flag) for one call.

    ``impl``/``chunk`` are caller overrides (the mesh entry points in
    parallel/mesh.py pass the raced mesh winner's layout through here);
    they skip the autotune lookup and are only explicit in the
    SPACEMESH_ROMIX sense when they MATCH an explicit env request — the
    mesh callers forward decision.impl verbatim, so an operator's
    SPACEMESH_ROMIX=pallas must keep its never-silently-fall-back
    contract through the sharded path too."""
    from . import autotune

    platform = jax.default_backend()
    interpret = platform != "tpu"
    if impl is not None:
        if chunk is not None and chunk >= batch:
            chunk = None
        impl_env, _, _, _ = autotune.read_env()
        d = autotune.Decision(impl, chunk, "caller",
                              explicit_impl=impl == impl_env)
    elif not _tunable(*arrays):
        impl_env, chunk_env, chunk_set, _ = autotune.read_env()
        d = autotune.Decision(impl_env or "xla",
                              chunk_env if chunk_set else None,
                              "untuned", explicit_impl=impl_env is not None)
    else:
        d = autotune.decide(n, batch, platform=platform)
    return d, (interpret if d.impl == "pallas" else False)


def _bucket_lanes(commitment_words, idx_lo, idx_hi):
    """Pad the lane axis up to its shape bucket (repeat the last index).
    Returns (cw, lo, hi, valid) with ``valid`` = the caller's lane count
    (trim the output to it), or the inputs unchanged when the batch is
    already bucket-sized."""
    b = int(idx_lo.shape[0])
    bb = shape_bucket(b)
    if bb == b:
        return commitment_words, idx_lo, idx_hi, b
    pad = bb - b
    idx_lo = jnp.concatenate(
        [jnp.asarray(idx_lo), jnp.broadcast_to(jnp.asarray(idx_lo)[-1:],
                                               (pad,))])
    idx_hi = jnp.concatenate(
        [jnp.asarray(idx_hi), jnp.broadcast_to(jnp.asarray(idx_hi)[-1:],
                                               (pad,))])
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:  # per-lane commitments: repeat the last column too
        cw = jnp.concatenate(
            [cw, jnp.broadcast_to(cw[:, -1:], (cw.shape[0], pad))], axis=1)
    return cw, idx_lo, idx_hi, b


def compiled_shape_count() -> int:
    """Executables compiled for the fused label pipelines in this
    process — one per distinct (shape, static args). Tests assert shape
    bucketing keeps this flat across ragged batch sizes."""
    return _labels_fused._cache_size() + _labels_min_fused._cache_size()


def _pallas_failed(d, err: Exception):
    """A Pallas selection failed to import/compile/run: raise when the
    operator explicitly demanded it, otherwise log ONCE, count, and
    return the XLA fallback decision."""
    global _fallback_logged
    from . import autotune
    from ..utils import metrics

    if d.impl != "pallas":
        raise err
    if d.explicit_impl:
        raise RuntimeError(
            f"{autotune.ENV_IMPL}=pallas was explicitly requested but the "
            f"Pallas ROMix kernel failed ({type(err).__name__}: {err}); "
            "refusing to silently degrade to the XLA path") from err
    metrics.post_romix_fallback.inc(reason=type(err).__name__)
    if not _fallback_logged:
        _fallback_logged = True
        print(f"romix: Pallas kernel failed ({type(err).__name__}: {err}); "
              "falling back to XLA (counted in post_romix_fallback_total)",
              file=sys.stderr, flush=True)
    return autotune.Decision("xla", d.chunk, "fallback")


def _stage_romix(blk, *, n: int):
    """ROMix stage dispatch under the autotuned (impl, chunk) decision.

    Kept for callers that run the stages separately; the fused pipelines
    below inline the same dispatch into one program."""
    d, interpret = _plan(n, blk.shape[1], blk)
    try:
        return romix_tuned(blk, n=n, impl=d.impl, chunk=d.chunk,
                           interpret=interpret)
    except Exception as e:  # noqa: BLE001 — pallas-only fallback, re-raised otherwise
        d = _pallas_failed(d, e)
        return romix_tuned(blk, n=n, impl=d.impl, chunk=d.chunk,
                           interpret=False)


# --- fused single-program pipelines -------------------------------------


@functools.partial(jax.jit,
                   static_argnames=("n", "impl", "chunk", "interpret"))
def _labels_fused(commitment_words, idx_lo, idx_hi, *, n: int, impl: str,
                  chunk: int | None, interpret: bool):
    """expand -> ROMix -> finish as ONE XLA program: PBKDF2/HMAC block
    state stays on device between stages instead of round-tripping
    through HBM as three executables' inputs/outputs."""
    inner_mid, outer_mid, blk = _expand(commitment_words, idx_lo, idx_hi)
    blk = _romix_dispatch(blk, n=n, impl=impl, chunk=chunk,
                          interpret=interpret)
    return _pbkdf2_second(inner_mid, outer_mid, blk)[:4]


def scrypt_labels_jit(commitment_words, idx_lo, idx_hi, *, n: int,
                      impl: str | None = None, chunk: int | None = None):
    """Batch of labels. ``idx_lo/idx_hi``: (B,) u32 halves of label indices.

    Returns (4, B) u32 BE words = B 16-byte labels (batch minor). One
    fused program under the autotuned kernel decision (module
    docstring), or under an explicit caller ``impl``/``chunk`` (the mesh
    entry points pass the raced mesh winner through). Ragged batches are
    padded to their power-of-two shape bucket and trimmed, so they reuse
    the bucket's executable instead of compiling their own
    (:func:`shape_bucket`; sharded/traced inputs skip the pad — mesh
    callers pre-bucket on host)."""
    valid = None
    if _tunable(commitment_words, idx_lo, idx_hi):
        commitment_words, idx_lo, idx_hi, valid = _bucket_lanes(
            commitment_words, idx_lo, idx_hi)
    batch = int(idx_lo.shape[0])
    sanitize.on_jit_shape("labels_fused", batch)
    d, interpret = _plan(n, batch, commitment_words, idx_lo, idx_hi,
                         impl=impl, chunk=chunk)
    # the span covers the ENQUEUE (trace+compile on a cache miss, else
    # async dispatch) — device time shows up in the XLA trace, which the
    # SPACEMESH_TRACE_JAX bridge lines these spans up against
    with tracing.span("romix.dispatch",
                      {"impl": d.impl, "chunk": d.chunk, "n": n,
                       "batch": batch}
                      if tracing.is_enabled() else None):
        try:
            words = _labels_fused(commitment_words, idx_lo, idx_hi, n=n,
                                  impl=d.impl, chunk=d.chunk,
                                  interpret=interpret)
        except Exception as e:  # noqa: BLE001 — pallas-only fallback
            d = _pallas_failed(d, e)
            words = _labels_fused(commitment_words, idx_lo, idx_hi, n=n,
                                  impl=d.impl, chunk=d.chunk,
                                  interpret=False)
    return words if valid is None or valid == batch else words[:, :valid]


# --- on-device VRF-nonce scan ----------------------------------------------
#
# The VRF nonce is the index of the numerically smallest LE-u128 label seen
# during init. Doing that scan on host (np.lexsort per batch) forces a full
# device->host round trip before every disk write; here it is a jitted
# argmin reduction that runs right after the label batch, device-side, and
# folds into a tiny running-minimum carry. The carry is donated, so across
# batches the scan is a single rolling (6,) u32 buffer:
#
#   carry = [k3, k2, k1, k0, idx_hi, idx_lo]
#
# where k3..k0 are the u32 limbs of the LE-u128 label key, MOST significant
# first (so lexicographic limb compare == u128 compare), and idx is the u64
# global label index of that minimum. Ties keep the earlier index — same
# first-occurrence semantics as np.lexsort.

VRF_CARRY_WORDS = 6
_U32_MAX = 0xFFFFFFFF


def vrf_carry_init(best: tuple[int, int] | None = None,
                   index: int = 0) -> np.ndarray:
    """Fresh (or resumed) host-side carry. ``best`` is the (hi, lo) u64
    halves of the current minimum label value, as stored in metadata."""
    c = np.full((VRF_CARRY_WORDS,), _U32_MAX, dtype=np.uint32)
    if best is not None:
        hi, lo = best
        c[0] = hi >> 32
        c[1] = hi & _U32_MAX
        c[2] = lo >> 32
        c[3] = lo & _U32_MAX
        c[4] = index >> 32
        c[5] = index & _U32_MAX
    return c


def vrf_carry_decode(carry) -> tuple[int, tuple[int, int]] | None:
    """Carry -> (index, (hi, lo)) or None when no label has been scanned."""
    c = np.asarray(carry)
    hi = int(c[0]) << 32 | int(c[1])
    lo = int(c[2]) << 32 | int(c[3])
    if hi == (_U32_MAX << 32 | _U32_MAX) and lo == hi:
        return None
    return int(c[4]) << 32 | int(c[5]), (hi, lo)


def _minscan(words, idx_lo, idx_hi, carry):
    # LE-u128 key limbs, most significant first (labels are LE bytes; the
    # (4, B) words are BE within each 4-byte group, so byteswap gives the
    # LE u32 limbs and word order gives significance).
    l3 = byteswap32(words[3])
    l2 = byteswap32(words[2])
    l1 = byteswap32(words[1])
    l0 = byteswap32(words[0])
    ff = jnp.uint32(_U32_MAX)
    m3 = jnp.min(l3)
    eq = l3 == m3
    m2 = jnp.min(jnp.where(eq, l2, ff))
    eq = eq & (l2 == m2)
    m1 = jnp.min(jnp.where(eq, l1, ff))
    eq = eq & (l1 == m1)
    m0 = jnp.min(jnp.where(eq, l0, ff))
    eq = eq & (l0 == m0)
    b = l3.shape[0]
    lane = jnp.min(jnp.where(eq, jnp.arange(b, dtype=jnp.int32),
                             jnp.int32(b)))
    batch = jnp.stack([m3, m2, m1, m0, idx_hi[lane], idx_lo[lane]])
    c3, c2, c1, c0 = carry[0], carry[1], carry[2], carry[3]
    lt = ((m3 < c3)
          | ((m3 == c3) & ((m2 < c2)
             | ((m2 == c2) & ((m1 < c1)
                | ((m1 == c1) & (m0 < c0)))))))
    new = jnp.where(lt, batch, carry)
    return new, new + jnp.uint32(0)


@functools.partial(jax.jit, donate_argnums=(3,))
def _stage_minscan(words, idx_lo, idx_hi, carry):
    """Fold one label batch into the running LE-u128 minimum.

    Returns ``(new_carry, snapshot)``: the donated rolling carry plus an
    independently-buffered copy of the same value, so callers can retain a
    per-batch snapshot while the carry buffer keeps rotating.
    """
    return _minscan(words, idx_lo, idx_hi, carry)


@functools.partial(jax.jit,
                   static_argnames=("n", "impl", "chunk", "interpret"),
                   donate_argnums=(3,))
def _labels_min_fused(commitment_words, idx_lo, idx_hi, carry, *, n: int,
                      impl: str, chunk: int | None, interpret: bool):
    inner_mid, outer_mid, blk = _expand(commitment_words, idx_lo, idx_hi)
    blk = _romix_dispatch(blk, n=n, impl=impl, chunk=chunk,
                          interpret=interpret)
    words = _pbkdf2_second(inner_mid, outer_mid, blk)[:4]
    new_carry, snapshot = _minscan(words, idx_lo, idx_hi, carry)
    return words, new_carry, snapshot


def scrypt_labels_with_min(commitment_words, idx_lo, idx_hi, carry, *,
                           n: int, impl: str | None = None,
                           chunk: int | None = None):
    """Label batch + running VRF minimum, fully device-side.

    One host call enqueues ONE fused XLA program (PBKDF2 expand, ROMix,
    finish, min-scan) under the autotuned kernel decision (or a caller
    ``impl``/``chunk`` — see :func:`scrypt_labels_jit`); no data returns
    to host. Returns ``(words, new_carry, snapshot)``; ``carry`` is
    donated. Ragged batches pad to their shape bucket with the last
    index repeated — the min-scan cannot tell the pad lanes from the
    real last lane (same value, first-occurrence lane wins), so the
    carry is exact and only ``words`` is trimmed.
    """
    valid = None
    if _tunable(commitment_words, idx_lo, idx_hi, carry):
        commitment_words, idx_lo, idx_hi, valid = _bucket_lanes(
            commitment_words, idx_lo, idx_hi)
    batch = int(idx_lo.shape[0])
    sanitize.on_jit_shape("labels_min_fused", batch)
    d, interpret = _plan(n, batch, commitment_words, idx_lo, idx_hi, carry,
                         impl=impl, chunk=chunk)
    # a pallas attempt can fail AFTER compile (e.g. HBM exhaustion
    # allocating the per-tile V scratch at dispatch), by which point the
    # donated carry buffer is consumed — keep an independent (6,)-word
    # device copy (async, no host sync: the streaming init keeps batches
    # in flight) so the XLA fallback retry has a live carry to donate
    backup = jnp.asarray(carry) + jnp.uint32(0) if d.impl == "pallas" else None
    with tracing.span("romix.dispatch",
                      {"impl": d.impl, "chunk": d.chunk, "n": n,
                       "batch": batch, "minscan": True}
                      if tracing.is_enabled() else None):
        try:
            words, new_carry, snap = _labels_min_fused(
                commitment_words, idx_lo, idx_hi, carry, n=n, impl=d.impl,
                chunk=d.chunk, interpret=interpret)
        except Exception as e:  # noqa: BLE001 — pallas-only fallback
            d = _pallas_failed(d, e)
            words, new_carry, snap = _labels_min_fused(
                commitment_words, idx_lo, idx_hi, backup, n=n, impl=d.impl,
                chunk=d.chunk, interpret=False)
    if valid is not None and valid != batch:
        words = words[:, :valid]
    return words, new_carry, snap


def commitment_to_words(commitment: bytes) -> np.ndarray:
    if len(commitment) != 32:
        raise ValueError("commitment must be 32 bytes")
    return np.frombuffer(commitment, dtype=">u4").astype(np.uint32)


def split_indices(indices: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    indices = np.asarray(indices, dtype=np.uint64)
    lo = (indices & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (indices >> np.uint64(32)).astype(np.uint32)
    return lo, hi


def labels_to_bytes(words) -> bytes:
    """(4, B) u32 BE word batch -> concatenated 16-byte labels."""
    return np.asarray(words, dtype=np.uint32).T.astype(">u4").tobytes()


def labels_to_words(labels: np.ndarray) -> np.ndarray:
    """(B, 16) uint8 labels -> (4, B) u32 LE words (proving-hash input)."""
    return np.ascontiguousarray(labels).view("<u4").reshape(-1, 4).T.astype(np.uint32)


def _check_n(n: int) -> None:
    # RFC 7914: for r=1, N must be a power of two and < 2^(128*r/8) = 2^16
    if n < 2 or n >= 2**16 or (n & (n - 1)) != 0:
        raise ValueError(f"scrypt n must be a power of 2 in [2, 2^16), got {n}")


def _run(cw: np.ndarray, indices: np.ndarray, n: int) -> np.ndarray:
    """Shared tail: split indices, run the jit pipeline, pack (B,16) bytes."""
    if indices.size == 0:
        return np.zeros((0, LABEL_BYTES), dtype=np.uint8)
    lo, hi = split_indices(indices)
    words = scrypt_labels_jit(jnp.asarray(cw), jnp.asarray(lo), jnp.asarray(hi), n=n)
    out = np.frombuffer(labels_to_bytes(words), dtype=np.uint8)
    return out.reshape(-1, LABEL_BYTES)


def scrypt_labels_multi(commitments: np.ndarray, indices, *, n: int = 8192
                        ) -> np.ndarray:
    """Labels for (commitment[i], index[i]) pairs — one program, many keys.

    ``commitments``: (B, 32) uint8. Used by the batched verifier to
    recompute labels for many smeshers in a single device pass.
    """
    _check_n(n)
    commitments = np.ascontiguousarray(np.asarray(commitments, dtype=np.uint8))
    if commitments.ndim != 2 or commitments.shape[1] != 32:
        raise ValueError("commitments must be (B, 32) bytes")
    indices = np.atleast_1d(np.asarray(indices)).ravel()
    if indices.shape[0] != commitments.shape[0]:
        raise ValueError("commitments and indices must have equal batch size")
    cw = commitments.view(">u4").astype(np.uint32).T  # (8, B)
    return _run(cw, indices, n)


def scrypt_labels(commitment: bytes, indices, *, n: int = 8192) -> np.ndarray:
    """Compute labels for ``indices`` (any u64 array). Returns (B, 16) uint8."""
    _check_n(n)
    indices = np.atleast_1d(np.asarray(indices)).ravel()
    return _run(commitment_to_words(commitment), indices, n)
