"""k2pow — proof-gating proof-of-work as a batched TPU nonce search.

The reference gates NIPoST proof generation behind a RandomX PoW ("k2pow",
reference activation/post.go:71-81, difficulty config/mainnet.go:40-43).
RandomX is *deliberately* CPU-serial (random code execution over a 2 GiB
dataset) and has no sensible TPU mapping, so this framework replaces it —
behind the same validator seam (see post/verifier.py) — with a SHA-256
preimage search under a 256-bit big-endian target, which batches across
nonces on the VPU:

    pow_hash(challenge, node_id, nonce) = SHA256(challenge || node_id
                                                 || le64(nonce))
    valid <=> pow_hash < difficulty     (32-byte big-endian compare)

Difficulty is expressed exactly like the reference's (a 32-byte threshold;
lower = harder) so operator configs translate directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .sha256 import IV, sha256_compress

# Message layout: challenge(32) || node_id(32) || le64(nonce) = 72 bytes
# -> two 64-byte blocks with FIPS padding in the second.
_BIT_LEN = 72 * 8


def _words_be(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype=">u4").astype(np.uint32)


@jax.jit
def pow_hash_batch_jit(prefix_state, nonce_lo, nonce_hi):
    """SHA-256 over the second block for a (B,) batch of nonces.

    ``prefix_state``: (8,) u32 — midstate after the first 64-byte block
    (challenge || first half of node_id). ``nonce_lo/hi``: (B,) u32.
    Returns (8, B) u32 BE digest words.
    """
    from .sha256 import byteswap32

    b = nonce_lo.shape[0]
    # block 1 (in prefix_state): challenge(32) || node_id(32).
    # block 2: le64(nonce) || 0x80 || zeros || be64(bit length) —
    # words: [swap(lo), swap(hi), 0x80000000, 0*12, _BIT_LEN]
    tail = np.zeros((14, 1), dtype=np.uint32)
    tail[0, 0] = 0x80000000
    tail[13, 0] = _BIT_LEN
    block = jnp.concatenate([
        byteswap32(nonce_lo)[None],
        byteswap32(nonce_hi)[None],
        jnp.broadcast_to(jnp.asarray(tail), (14, b)),
    ])
    return sha256_compress(jnp.broadcast_to(prefix_state[:, None], (8, b)), block)


@jax.jit
def below_target_jit(digest_words, target_words):
    """Big-endian 256-bit compare: digest < target, per lane.

    digest_words: (8, B) u32; target_words: (8,) u32. Returns (B,) bool.
    """
    b = digest_words.shape[1]
    t = jnp.broadcast_to(target_words[:, None], (8, b))
    lt = digest_words < t
    eq = digest_words == t
    out = lt[7]
    for i in range(6, -1, -1):
        out = lt[i] | (eq[i] & out)
    return out


def prefix_state(challenge: bytes, node_id: bytes) -> np.ndarray:
    """Midstate after absorbing challenge||node_id (the first block)."""
    if len(challenge) != 32 or len(node_id) != 32:
        raise ValueError("challenge and node_id must be 32 bytes")
    block = jnp.asarray(_words_be(challenge + node_id))
    return np.asarray(sha256_compress(jnp.asarray(IV), block))


def pow_hash(challenge: bytes, node_id: bytes, nonce: int) -> bytes:
    """Single hash on host (verification path: one 2-block SHA-256 is far
    cheaper than a device round-trip; the device path is for search)."""
    import hashlib

    if len(challenge) != 32 or len(node_id) != 32:
        raise ValueError("challenge and node_id must be 32 bytes")
    return hashlib.sha256(
        challenge + node_id + int(nonce).to_bytes(8, "little")).digest()


def _host_scan(challenge: bytes, node_id: bytes, difficulty: bytes,
               base: int, batch: int) -> int | None:
    """Pure-host fallback batch (hashlib): the k2pow gate must survive a
    wedged or failing accelerator — a device dispatch error degrades to
    this, it does not kill the prove."""
    prefix = challenge + node_id
    import hashlib

    for nonce in range(base, base + batch):
        if hashlib.sha256(
                prefix + nonce.to_bytes(8, "little")).digest() < difficulty:
            return nonce
    return None


def search(challenge: bytes, node_id: bytes, difficulty: bytes,
           *, batch: int = 1 << 16, start: int = 0,
           max_batches: int = 1 << 16, inflight: int = 2,
           tenant: str = "-") -> int | None:
    """Find a nonce whose pow_hash is below ``difficulty`` (32B BE target).

    Scans ``batch`` nonces per device program through the shared runtime
    engine (runtime/engine.py): ``inflight`` batches stay enqueued so
    the host-side hit check of one batch overlaps the next batch's
    device compute.  Batches retire in nonce order, so the result — the
    smallest hit in the first batch containing one — is identical to
    the historical serial loop's.  A device dispatch failure falls back
    to a host hashlib scan of that batch (counted in
    ``runtime_fallbacks_total{kind="k2pow"}``); None when exhausted.
    """
    from ..runtime import engine

    if len(difficulty) != 32:
        raise ValueError("difficulty must be 32 bytes")
    st = jnp.asarray(prefix_state(challenge, node_id))
    tgt = jnp.asarray(_words_be(difficulty))

    def dispatch(base):
        nonces = np.arange(base, base + batch, dtype=np.uint64)
        lo = jnp.asarray((nonces & 0xFFFFFFFF).astype(np.uint32))
        hi = jnp.asarray((nonces >> 32).astype(np.uint32))
        # enqueue only: the (B,) hit mask crosses to host at retire
        return base, below_target_jit(pow_hash_batch_jit(st, lo, hi), tgt)

    def fallback(base, exc):
        del exc  # counted by runtime_fallbacks_total{kind="k2pow"}
        return base, None  # marker: retire re-scans this batch on host

    def retire(ticket):
        # a 0 return is a valid winning nonce: the engine's early-exit
        # test is `is not None`, not truthiness
        base, ok = ticket
        if ok is None:
            return _host_scan(challenge, node_id, difficulty, base, batch)
        hits = np.nonzero(np.asarray(ok))[0]
        return int(base + int(hits[0])) if hits.size else None

    pipe = engine.Pipeline(kind="k2pow", tenant=tenant,
                           inflight=inflight, fallback=fallback,
                           span="pow")
    return pipe.run((start + i * batch for i in range(max_batches)),
                    dispatch, retire)


def verify(challenge: bytes, node_id: bytes, difficulty: bytes, nonce: int) -> bool:
    return pow_hash(challenge, node_id, nonce) < difficulty


# --- batched verification (verifyd / the verify farm's "pow" kind) ------
#
# Search batches many NONCES under one (challenge, node_id); verification
# at service scale batches many ITEMS, each with its own prefix and its
# own difficulty. Both 64-byte blocks run on device with per-lane state:
# block 1 is the item's challenge||node_id, block 2 its nonce + padding.


@jax.jit
def below_targets_jit(digest_words, target_words):
    """Per-lane big-endian 256-bit compare: digest < target.

    digest_words, target_words: (8, B) u32. Returns (B,) bool — the
    per-lane-target twin of :func:`below_target_jit`.
    """
    lt = digest_words < target_words
    eq = digest_words == target_words
    out = lt[7]
    for i in range(6, -1, -1):
        out = lt[i] | (eq[i] & out)
    return out


@jax.jit
def pow_verify_batch_jit(block1, nonce_lo, nonce_hi, target_words):
    """Verify a (B,) batch of (challenge, node_id, nonce, difficulty)
    witnesses in one two-block SHA-256 pass.

    ``block1``: (16, B) u32 — each item's challenge||node_id words.
    ``nonce_lo/hi``: (B,) u32. ``target_words``: (8, B) u32 per-item
    difficulty. Returns (B,) bool.
    """
    from .sha256 import byteswap32

    b = nonce_lo.shape[0]
    st = sha256_compress(
        jnp.broadcast_to(jnp.asarray(IV)[:, None], (8, b)), block1)
    tail = np.zeros((14, 1), dtype=np.uint32)
    tail[0, 0] = 0x80000000
    tail[13, 0] = _BIT_LEN
    block2 = jnp.concatenate([
        byteswap32(nonce_lo)[None],
        byteswap32(nonce_hi)[None],
        jnp.broadcast_to(jnp.asarray(tail), (14, b)),
    ])
    return below_targets_jit(sha256_compress(st, block2), target_words)


def _verify_host(items: list) -> list[bool]:
    import hashlib

    out = []
    for challenge, node_id, difficulty, nonce in items:
        out.append(hashlib.sha256(
            challenge + node_id + int(nonce).to_bytes(8, "little")
        ).digest() < difficulty)
    return out


def verify_many(items: list, *, batch: int = 1 << 12,
                inflight: int = 2, min_device: int = 8,
                tenant: str = "-") -> list[bool]:
    """Batched k2pow verification: ``items`` are (challenge, node_id,
    difficulty, nonce) tuples; returns per-item validity, bit-identical
    to :func:`verify` on every item.

    Chunks of ``batch`` items run as one device program each through the
    shared runtime engine (``kind="k2pow_verify"``, ``inflight`` chunks
    enqueued so host packing of one chunk overlaps the previous chunk's
    device compute); ragged chunks pad to their power-of-two shape
    bucket by replicating lane 0, so occupancy changes reuse compiled
    executables. Batches below ``min_device`` items skip the device
    round-trip (two hashlib blocks are cheaper than a dispatch), and a
    device dispatch failure degrades that chunk to the host scan
    (``runtime_fallbacks_total{kind="k2pow_verify"}``) — never a wrong
    or missing verdict.
    """
    n = len(items)
    if n == 0:
        return []
    for challenge, node_id, difficulty, nonce in items:
        if len(challenge) != 32 or len(node_id) != 32:
            raise ValueError("challenge and node_id must be 32 bytes")
        if len(difficulty) != 32:
            raise ValueError("difficulty must be 32 bytes")
        if not 0 <= int(nonce) < 1 << 64:
            # fail fast and clearly: past this point an out-of-range
            # nonce would surface as an OverflowError mid-batch
            raise ValueError("nonce must be an unsigned 64-bit integer")
    if n < min_device:
        return _verify_host(items)
    from ..runtime import engine
    from . import scrypt

    results = np.zeros(n, dtype=bool)

    def dispatch(rng):
        lo_i, hi_i = rng
        chunk = items[lo_i:hi_i]
        count = len(chunk)
        pad = max(scrypt.shape_bucket(count), 1)
        rows = chunk + [chunk[0]] * (pad - count)
        block1 = np.stack([
            np.frombuffer(c + nid, dtype=">u4").astype(np.uint32)
            for c, nid, _d, _n in rows], axis=1)
        targets = np.stack([
            _words_be(d) for _c, _nid, d, _n in rows], axis=1)
        nonces = np.array([x[3] for x in rows], dtype=np.uint64)
        lo = jnp.asarray((nonces & 0xFFFFFFFF).astype(np.uint32))
        hi = jnp.asarray((nonces >> 32).astype(np.uint32))
        return rng, pow_verify_batch_jit(
            jnp.asarray(block1), lo, hi, jnp.asarray(targets))

    def fallback(rng, exc):
        del exc  # counted by runtime_fallbacks_total{kind="k2pow_verify"}
        return rng, None  # marker: retire re-verifies this chunk on host

    def retire(ticket):
        (lo_i, hi_i), ok = ticket
        if ok is None:
            results[lo_i:hi_i] = _verify_host(items[lo_i:hi_i])
        else:
            results[lo_i:hi_i] = np.asarray(ok)[:hi_i - lo_i]
        return None

    pipe = engine.Pipeline(kind="k2pow_verify", tenant=tenant,
                           inflight=inflight, fallback=fallback,
                           span="pow_verify")
    pipe.run(((i, min(i + batch, n)) for i in range(0, n, batch)),
             dispatch, retire)
    return results.tolist()
