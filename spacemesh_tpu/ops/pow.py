"""k2pow — proof-gating proof-of-work as a batched TPU nonce search.

The reference gates NIPoST proof generation behind a RandomX PoW ("k2pow",
reference activation/post.go:71-81, difficulty config/mainnet.go:40-43).
RandomX is *deliberately* CPU-serial (random code execution over a 2 GiB
dataset) and has no sensible TPU mapping, so this framework replaces it —
behind the same validator seam (see post/verifier.py) — with a SHA-256
preimage search under a 256-bit big-endian target, which batches across
nonces on the VPU:

    pow_hash(challenge, node_id, nonce) = SHA256(challenge || node_id
                                                 || le64(nonce))
    valid <=> pow_hash < difficulty     (32-byte big-endian compare)

Difficulty is expressed exactly like the reference's (a 32-byte threshold;
lower = harder) so operator configs translate directly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .sha256 import IV, sha256_compress

# Message layout: challenge(32) || node_id(32) || le64(nonce) = 72 bytes
# -> two 64-byte blocks with FIPS padding in the second.
_BIT_LEN = 72 * 8


def _words_be(data: bytes) -> np.ndarray:
    return np.frombuffer(data, dtype=">u4").astype(np.uint32)


@jax.jit
def pow_hash_batch_jit(prefix_state, nonce_lo, nonce_hi):
    """SHA-256 over the second block for a (B,) batch of nonces.

    ``prefix_state``: (8,) u32 — midstate after the first 64-byte block
    (challenge || first half of node_id). ``nonce_lo/hi``: (B,) u32.
    Returns (8, B) u32 BE digest words.
    """
    from .sha256 import byteswap32

    b = nonce_lo.shape[0]
    # block 1 (in prefix_state): challenge(32) || node_id(32).
    # block 2: le64(nonce) || 0x80 || zeros || be64(bit length) —
    # words: [swap(lo), swap(hi), 0x80000000, 0*12, _BIT_LEN]
    tail = np.zeros((14, 1), dtype=np.uint32)
    tail[0, 0] = 0x80000000
    tail[13, 0] = _BIT_LEN
    block = jnp.concatenate([
        byteswap32(nonce_lo)[None],
        byteswap32(nonce_hi)[None],
        jnp.broadcast_to(jnp.asarray(tail), (14, b)),
    ])
    return sha256_compress(jnp.broadcast_to(prefix_state[:, None], (8, b)), block)


@jax.jit
def below_target_jit(digest_words, target_words):
    """Big-endian 256-bit compare: digest < target, per lane.

    digest_words: (8, B) u32; target_words: (8,) u32. Returns (B,) bool.
    """
    b = digest_words.shape[1]
    t = jnp.broadcast_to(target_words[:, None], (8, b))
    lt = digest_words < t
    eq = digest_words == t
    out = lt[7]
    for i in range(6, -1, -1):
        out = lt[i] | (eq[i] & out)
    return out


def prefix_state(challenge: bytes, node_id: bytes) -> np.ndarray:
    """Midstate after absorbing challenge||node_id (the first block)."""
    if len(challenge) != 32 or len(node_id) != 32:
        raise ValueError("challenge and node_id must be 32 bytes")
    block = jnp.asarray(_words_be(challenge + node_id))
    return np.asarray(sha256_compress(jnp.asarray(IV), block))


def pow_hash(challenge: bytes, node_id: bytes, nonce: int) -> bytes:
    """Single hash on host (verification path: one 2-block SHA-256 is far
    cheaper than a device round-trip; the device path is for search)."""
    import hashlib

    if len(challenge) != 32 or len(node_id) != 32:
        raise ValueError("challenge and node_id must be 32 bytes")
    return hashlib.sha256(
        challenge + node_id + int(nonce).to_bytes(8, "little")).digest()


def _host_scan(challenge: bytes, node_id: bytes, difficulty: bytes,
               base: int, batch: int) -> int | None:
    """Pure-host fallback batch (hashlib): the k2pow gate must survive a
    wedged or failing accelerator — a device dispatch error degrades to
    this, it does not kill the prove."""
    prefix = challenge + node_id
    import hashlib

    for nonce in range(base, base + batch):
        if hashlib.sha256(
                prefix + nonce.to_bytes(8, "little")).digest() < difficulty:
            return nonce
    return None


def search(challenge: bytes, node_id: bytes, difficulty: bytes,
           *, batch: int = 1 << 16, start: int = 0,
           max_batches: int = 1 << 16, inflight: int = 2,
           tenant: str = "-") -> int | None:
    """Find a nonce whose pow_hash is below ``difficulty`` (32B BE target).

    Scans ``batch`` nonces per device program through the shared runtime
    engine (runtime/engine.py): ``inflight`` batches stay enqueued so
    the host-side hit check of one batch overlaps the next batch's
    device compute.  Batches retire in nonce order, so the result — the
    smallest hit in the first batch containing one — is identical to
    the historical serial loop's.  A device dispatch failure falls back
    to a host hashlib scan of that batch (counted in
    ``runtime_fallbacks_total{kind="k2pow"}``); None when exhausted.
    """
    from ..runtime import engine

    if len(difficulty) != 32:
        raise ValueError("difficulty must be 32 bytes")
    st = jnp.asarray(prefix_state(challenge, node_id))
    tgt = jnp.asarray(_words_be(difficulty))

    def dispatch(base):
        nonces = np.arange(base, base + batch, dtype=np.uint64)
        lo = jnp.asarray((nonces & 0xFFFFFFFF).astype(np.uint32))
        hi = jnp.asarray((nonces >> 32).astype(np.uint32))
        # enqueue only: the (B,) hit mask crosses to host at retire
        return base, below_target_jit(pow_hash_batch_jit(st, lo, hi), tgt)

    def fallback(base, exc):
        del exc  # counted by runtime_fallbacks_total{kind="k2pow"}
        return base, None  # marker: retire re-scans this batch on host

    def retire(ticket):
        # a 0 return is a valid winning nonce: the engine's early-exit
        # test is `is not None`, not truthiness
        base, ok = ticket
        if ok is None:
            return _host_scan(challenge, node_id, difficulty, base, batch)
        hits = np.nonzero(np.asarray(ok))[0]
        return int(base + int(hits[0])) if hits.size else None

    pipe = engine.Pipeline(kind="k2pow", tenant=tenant,
                           inflight=inflight, fallback=fallback,
                           span="pow")
    return pipe.run((start + i * batch for i in range(max_batches)),
                    dispatch, retire)


def verify(challenge: bytes, node_id: bytes, difficulty: bytes, nonce: int) -> bool:
    return pow_hash(challenge, node_id, nonce) < difficulty
