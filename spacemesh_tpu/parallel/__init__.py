"""Device-mesh sharding for the POST compute plane.

The reference scales by (a) running many identities on one machine
(multi-smesher, reference activation/activation.go:218 Register) and
(b) per-device OpenCL providers (provider id selects a GPU). The TPU-native
equivalent is SPMD over a `jax.sharding.Mesh`: the label index space —
across one identity's unit range or across many identities — is the data
axis, sharded over devices; XLA inserts the (few) collectives, which ride
ICI. See mesh.py.
"""

from . import topology  # noqa: F401
from .mesh import (  # noqa: F401
    data_mesh,
    init_step_sharded,
    labels_with_min_sharded,
    replicate,
    scrypt_labels_sharded,
)
