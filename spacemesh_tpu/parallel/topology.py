"""Process-wide device topology: ONE mesh, named axes, persistent layouts.

The GSPMD pattern (SNIPPETS.md [1]/[3]; SZKP and CRYPTONITE both locate
throughput in keeping the accelerator saturated across many small
proofs): build the device mesh ONCE with named axes, annotate arrays
with persistent ``NamedSharding`` layouts, and let jit insert the
collectives — so the identical code path serves a 1-chip dev box, an
8-chip v5e, and a multi-host pod slice.

Before this module, every sharded entry point re-derived
``NamedSharding(mesh, P(...))`` per dispatch (and re-``device_put`` the
replicated VRF carry per batch, evicting a donated buffer that was
already resident). This module is the one place sharding objects are
constructed; everything else — parallel/mesh.py's entry points, the
autotuner's mesh race, the multi-tenant packer, the prover's window
scans, the verify farm's batch recompute — consumes the catalog.
spacecheck rule SC010 holds the line: ``Mesh(`` / ``NamedSharding(``
construction inside functions of the hot-path modules is a finding.

Axes:

* ``data``  — the lane/batch axis every label, prove and verify batch
  shards over (SURVEY.md §2.4: everything is lane arithmetic with no
  cross-lane dataflow except reductions, which XLA lowers to ICI
  all-reduces).
* ``model`` — reserved for V-sharded ROMix (splitting one lane's V
  scratch across devices); size 1 today, so every ``P(...)`` spec that
  does not name it replicates over it for free, and growing it later
  is a topology-only change.

The topology is built lazily on first use from the devices visible at
that moment — entry points that want the virtual host devices call
``accel.ensure_host_devices()`` BEFORE first backend use, exactly as
they already do (tests' conftest, tools/warmcache.py, bench.py probes).
``SPACEMESH_MESH`` routing stays where it was (ops/autotune.py: the
grammar is unchanged and decides HOW MANY devices a dispatch uses); the
topology only answers WHICH mesh/layout objects serve that count, and
guarantees each count maps to one Mesh object per process.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import sanitize

DATA_AXIS = "data"
MODEL_AXIS = "model"


class MeshLayouts:
    """The persistent sharding catalog for one device-count submesh.

    One instance per device count per process (DeviceTopology caches
    them); every field is constructed once and reused by every dispatch,
    so steady-state sharded dispatch allocates no sharding objects.
    """

    def __init__(self, devices) -> None:
        dev = np.asarray(devices, dtype=object).reshape(-1, 1)
        # the one construction site for the process (SC010 polices the
        # hot-path modules; this module is the exemption)
        # spacecheck: ok=SC010 the topology IS the construction site
        self.mesh = Mesh(dev, (DATA_AXIS, MODEL_AXIS))
        # spacecheck: ok=SC010 persistent catalog, built once per count
        self.batch = NamedSharding(self.mesh, P(DATA_AXIS))
        # word-major (words, B) arrays: shard the minor/lane axis
        # spacecheck: ok=SC010 persistent catalog, built once per count
        self.lane = NamedSharding(self.mesh, P(None, DATA_AXIS))
        # row-major (B, words) arrays (the contiguous-row ROMix layout)
        # spacecheck: ok=SC010 persistent catalog, built once per count
        self.row = NamedSharding(self.mesh, P(DATA_AXIS, None))
        # spacecheck: ok=SC010 persistent catalog, built once per count
        self.replicated = NamedSharding(self.mesh, P())

    @property
    def devices(self) -> int:
        return self.mesh.size

    # --- placement helpers (the per-dispatch hot path) -----------------

    def put_batch(self, value) -> jax.Array:
        """Place a (B, ...) per-lane array sharded over ``data``."""
        return jax.device_put(jnp.asarray(value), self.batch)

    def put_lane(self, value) -> jax.Array:
        """Place a word-major (words, B) array with the lane axis
        sharded over ``data``."""
        return jax.device_put(jnp.asarray(value), self.lane)

    def replicate(self, value) -> jax.Array:
        """Place ``value`` replicated across the mesh — a NO-OP when it
        already lives there.

        The VRF min-scan carry (and the prover's donated hit state) is
        replicated once at the start of a pass and then DONATED through
        every batch; the jit output comes back resident with this same
        layout. Re-``device_put``-ing it per batch (the pre-topology
        behavior) minted a fresh buffer each call and threw the donated
        residency away; detecting the already-placed case keeps the
        carry on device across the whole pass.
        """
        if isinstance(value, jax.Array) \
                and not isinstance(value, jax.core.Tracer):
            try:
                s = value.sharding
                if s == self.replicated or s.is_equivalent_to(
                        self.replicated, value.ndim):
                    return value
            except Exception:  # noqa: BLE001 — exotic array types: re-place
                pass
        return jax.device_put(jnp.asarray(value), self.replicated)


class DeviceTopology:
    """One mesh family per process, built once, layouts cached forever.

    ``layouts(k)`` returns the catalog for the first ``k`` visible
    devices (``None`` = all of them). Each distinct count constructs its
    Mesh exactly once; repeated calls return the identical objects, so
    jit caches key on stable shardings and executables are reused across
    sessions, tenants and entry points.
    """

    def __init__(self) -> None:
        self._devices = tuple(jax.devices())
        self._layouts: dict[int, MeshLayouts] = {}
        self._foreign: dict[tuple, MeshLayouts] = {}
        self._lock = sanitize.lock("parallel.topology")

    @property
    def device_count(self) -> int:
        return len(self._devices)

    def layouts(self, devices: int | None = None) -> MeshLayouts:
        k = self.device_count if devices is None else int(devices)
        k = max(1, min(k, self.device_count))
        with self._lock:
            lay = self._layouts.get(k)
            if lay is None:
                lay = self._layouts[k] = MeshLayouts(self._devices[:k])
            return lay

    def layouts_for_devices(self, devices) -> MeshLayouts:
        """The catalog covering exactly ``devices`` (a list of jax
        Devices). The common case — a prefix of the visible devices, what
        every auto-routing caller passes — hits the per-count cache; a
        non-prefix selection (an operator pinning specific chips) gets
        its own catalog, still built once per distinct device set."""
        devs = tuple(devices)
        if devs == self._devices[:len(devs)]:
            return self.layouts(len(devs))
        key = tuple(id(d) for d in devs)
        with self._lock:
            lay = self._foreign.get(key)
            if lay is None:
                lay = self._foreign[key] = MeshLayouts(devs)
            return lay

    def layouts_for(self, mesh: Mesh) -> MeshLayouts:
        """The catalog whose layouts place onto ``mesh``'s devices.

        When ``mesh`` came from this topology the lookup returns the
        catalog that owns it; a foreign mesh (built by hand in a test or
        an operator script) resolves by its device set — the returned
        layouts place onto the same devices, which is all a sharding
        is."""
        return self.layouts_for_devices(mesh.devices.flatten().tolist())


_TOPOLOGY: DeviceTopology | None = None
_TOPOLOGY_LOCK = sanitize.lock("parallel.topology.init")


def get() -> DeviceTopology:
    """The process-wide topology, built on first use."""
    global _TOPOLOGY
    t = _TOPOLOGY
    if t is None:
        with _TOPOLOGY_LOCK:
            if _TOPOLOGY is None:
                _TOPOLOGY = DeviceTopology()
            t = _TOPOLOGY
    return t


def reset() -> None:
    """Drop the topology (tests simulating a fresh process ONLY — a live
    process must never rebuild its mesh mid-flight: executables cache on
    the old sharding objects and every one would recompile)."""
    global _TOPOLOGY
    with _TOPOLOGY_LOCK:
        _TOPOLOGY = None
