"""SPMD sharding of the POST pipeline over a device mesh.

One parallelism axis matters for this workload (SURVEY.md §2.4): the label
batch — spanning one identity's index range, or many identities' ranges
concatenated (multi-smesher DP; per-lane commitments). Everything is lane
arithmetic with no cross-lane dataflow except reductions (init stats, VRF
scan), so: shard the batch axis over the mesh, let XLA all-reduce the
scalar stats over ICI.

Mesh axis name: "data". Mainnet-scale example (BASELINE config 5): 16
smeshers x 4 SU on a v5e-8 = batch lanes striped across 8 chips; each chip
labels its stripe and the host shards disk writes per smesher.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import proving, scrypt
from ..ops.sha256 import byteswap32

DATA_AXIS = "data"


def data_mesh(devices=None) -> Mesh:
    """A 1-D data mesh over all (or the given) devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices.reshape(-1), (DATA_AXIS,))


def _batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(DATA_AXIS))


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for word-major arrays: (words, B) — shard the minor/lane
    axis (the autotuner's mesh race places its calibration block with
    this, the same placement the sharded label entry points use)."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


_lane_sharding = lane_sharding  # historical private alias


def replicate(mesh: Mesh, value) -> jax.Array:
    """Place ``value`` replicated across every device in the mesh (the
    VRF-scan carry lives like this between sharded batches)."""
    return jax.device_put(jnp.asarray(value), NamedSharding(mesh, P()))


def labels_with_min_sharded(mesh: Mesh, commitment_words, idx_lo, idx_hi,
                            carry, *, n: int, impl: str | None = None):
    """Sharded label batch chained to the on-device VRF min-scan.

    Lane axis sharded over the mesh; the (6,) running-minimum carry is
    replicated and donated, and the argmin reduction lowers to ICI
    all-reduces under GSPMD. Returns ``(words, new_carry, snapshot)`` like
    scrypt.scrypt_labels_with_min, with ``words`` lane-sharded so the host
    can fetch and stripe each device's shard to disk independently.

    Kernel choice: ``impl`` carries the autotuned mesh winner's layout
    (ops/autotune.py races both XLA layouts per device count); when None,
    multi-device shardings pin the ROMix dispatch to the plain word-major
    XLA kernel (a sequential lane-chunk would fight GSPMD's batch
    partitioning — ops/scrypt.py ``_tunable``). The SPACEMESH_ROMIX /
    SPACEMESH_ROMIX_CHUNK overrides still win for operators who have
    measured their mesh (docs/ROMIX_KERNEL.md).
    """
    bs = _batch_sharding(mesh)
    idx_lo = jax.device_put(jnp.asarray(idx_lo), bs)
    idx_hi = jax.device_put(jnp.asarray(idx_hi), bs)
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:
        cw = jax.device_put(cw, lane_sharding(mesh))
    return scrypt.scrypt_labels_with_min(cw, idx_lo, idx_hi,
                                         replicate(mesh, carry), n=n,
                                         impl=impl)


def scrypt_labels_sharded(mesh: Mesh, commitment_words, idx_lo, idx_hi,
                          *, n: int, impl: str | None = None):
    """Label batch sharded over the mesh. Batch size must divide evenly.

    ``commitment_words``: (8,) shared or (8, B) per-lane (multi-identity).
    Returns (4, B) u32 BE words with the lane axis sharded. ``impl`` as
    in :func:`labels_with_min_sharded`.
    """
    bs = _batch_sharding(mesh)
    idx_lo = jax.device_put(jnp.asarray(idx_lo), bs)
    idx_hi = jax.device_put(jnp.asarray(idx_hi), bs)
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:
        cw = jax.device_put(cw, lane_sharding(mesh))
    return scrypt.scrypt_labels_jit(cw, idx_lo, idx_hi, n=n, impl=impl)


def prove_step_sharded(mesh: Mesh, challenge_words, nonce_base, idx_lo,
                       idx_hi, label_words, threshold, hit_counts, hit_carry,
                       valid, start_lo, start_hi, *, n_nonces: int,
                       max_hits: int):
    """One sharded streaming-prove step (the multichip prove path).

    Label lanes are striped over the mesh exactly like
    ``labels_with_min_sharded`` stripes init batches; the Salsa20/8 sweep
    is embarrassingly parallel per lane, and GSPMD lowers the compaction
    epilogue's small reductions/gathers to ICI collectives. The donated
    (hit_counts, hit_carry) state stays replicated (see
    ops/proving.py merge_hits); the prover replicates it via
    ``replicate()`` before the first batch of a pass. Batch size must
    divide by the mesh size — the prover's pad-and-trim already makes
    every batch the full ``batch_labels``.
    """
    bs = _batch_sharding(mesh)
    idx_lo = jax.device_put(jnp.asarray(idx_lo), bs)
    idx_hi = jax.device_put(jnp.asarray(idx_hi), bs)
    lw = jax.device_put(jnp.asarray(label_words), _lane_sharding(mesh))
    return proving.prove_scan_step_jit(
        jnp.asarray(challenge_words), nonce_base, idx_lo, idx_hi, lw,
        threshold, hit_counts, hit_carry, valid, start_lo, start_hi,
        n_nonces=n_nonces, max_hits=max_hits)


@functools.partial(jax.jit, static_argnames=("n",))
def _init_step(commitment_words, idx_lo, idx_hi, threshold, *, n: int):
    words = scrypt.scrypt_labels_jit(commitment_words, idx_lo, idx_hi, n=n)
    # init statistics, all-reduced across the mesh by XLA:
    #  - how many labels fall under the proving threshold (K1 calibration)
    #  - running minimum of the labels' top-64-bit keys (coarse scan; the
    #    exact LE-u128 argmin is the device carry in ops/scrypt.py
    #    _stage_minscan, used by labels_with_min_sharded above)
    k_hi = byteswap32(words[3]).astype(jnp.uint32)
    k_lo = byteswap32(words[2]).astype(jnp.uint32)
    qualifying = jnp.sum((words[0] < threshold).astype(jnp.int32))
    min_hi = jnp.min(k_hi)
    is_min = k_hi == min_hi
    min_lo = jnp.min(jnp.where(is_min, k_lo, jnp.uint32(0xFFFFFFFF)))
    return words, qualifying, min_hi, min_lo


def init_step_sharded(mesh: Mesh, commitment_words, idx_lo, idx_hi,
                      threshold: int, *, n: int):
    """One sharded init step: labels + global stats (the multichip path).

    The label computation is embarrassingly parallel over lanes; the three
    scalar stats are cross-device reductions XLA lowers to ICI all-reduces.
    """
    bs = _batch_sharding(mesh)
    idx_lo = jax.device_put(jnp.asarray(idx_lo), bs)
    idx_hi = jax.device_put(jnp.asarray(idx_hi), bs)
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:
        cw = jax.device_put(cw, _lane_sharding(mesh))
    return _init_step(cw, idx_lo, idx_hi, jnp.uint32(threshold), n=n)
