"""SPMD sharding of the POST pipeline over the process-wide topology.

One parallelism axis matters for this workload (SURVEY.md §2.4): the label
batch — spanning one identity's index range, or many identities' ranges
concatenated (multi-smesher DP; per-lane commitments). Everything is lane
arithmetic with no cross-lane dataflow except reductions (init stats, VRF
scan), so: shard the batch axis over the mesh, let XLA all-reduce the
scalar stats over ICI.

Mesh axis names: ``data`` (the lane/batch axis) and ``model`` (reserved
for V-sharded ROMix; size 1). The mesh and its ``NamedSharding`` layouts
are NOT built here — parallel/topology.py constructs them once per
process and this module's entry points consume the persistent catalog
(spacecheck SC010 keeps per-call construction from growing back).
Mainnet-scale example (BASELINE config 5): 16 smeshers x 4 SU on a
v5e-8 = batch lanes striped across 8 chips; each chip labels its stripe
and the host shards disk writes per smesher.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding

from ..ops import proving, scrypt
from ..ops.sha256 import byteswap32
from . import topology

DATA_AXIS = topology.DATA_AXIS


def data_mesh(devices=None) -> Mesh:
    """The process topology's mesh over all (or the given) devices.

    Same Mesh OBJECT on every call for a given device count — the
    topology builds each count once, so jit caches key on a stable mesh
    and sharded executables are reused across sessions and tenants."""
    if devices is None:
        return topology.get().layouts().mesh
    return topology.get().layouts_for_devices(list(devices)).mesh


def _layouts(mesh: Mesh) -> topology.MeshLayouts:
    return topology.get().layouts_for(mesh)


def _batch_sharding(mesh: Mesh) -> NamedSharding:
    return _layouts(mesh).batch


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for word-major arrays: (words, B) — shard the minor/lane
    axis (the autotuner's mesh race places its calibration block with
    this, the same placement the sharded label entry points use). Served
    from the topology catalog, never constructed per call."""
    return _layouts(mesh).lane


_lane_sharding = lane_sharding  # historical private alias


def replicate(mesh: Mesh, value) -> jax.Array:
    """Place ``value`` replicated across every device in the mesh (the
    VRF-scan carry lives like this between sharded batches). A no-op
    when ``value`` is already resident with this layout — donated
    carries stay on device across a whole pass instead of paying a
    fresh ``device_put`` per batch (topology.MeshLayouts.replicate)."""
    return _layouts(mesh).replicate(value)


def labels_with_min_sharded(mesh: Mesh, commitment_words, idx_lo, idx_hi,
                            carry, *, n: int, impl: str | None = None):
    """Sharded label batch chained to the on-device VRF min-scan.

    Lane axis sharded over the mesh; the (6,) running-minimum carry is
    replicated and donated, and the argmin reduction lowers to ICI
    all-reduces under GSPMD. Returns ``(words, new_carry, snapshot)`` like
    scrypt.scrypt_labels_with_min, with ``words`` lane-sharded so the host
    can fetch and stripe each device's shard to disk independently.

    Kernel choice: ``impl`` carries the autotuned mesh winner's layout
    (ops/autotune.py races both mesh shapes per device count); when None,
    multi-device shardings pin the ROMix dispatch to the plain word-major
    XLA kernel (a sequential lane-chunk would fight GSPMD's batch
    partitioning — ops/scrypt.py ``_tunable``). The SPACEMESH_ROMIX /
    SPACEMESH_ROMIX_CHUNK overrides still win for operators who have
    measured their mesh (docs/ROMIX_KERNEL.md).
    """
    lay = _layouts(mesh)
    idx_lo = lay.put_batch(idx_lo)
    idx_hi = lay.put_batch(idx_hi)
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:
        cw = lay.put_lane(cw)
    return scrypt.scrypt_labels_with_min(cw, idx_lo, idx_hi,
                                         lay.replicate(carry), n=n,
                                         impl=impl)


def scrypt_labels_sharded(mesh: Mesh, commitment_words, idx_lo, idx_hi,
                          *, n: int, impl: str | None = None):
    """Label batch sharded over the mesh. Batch size must divide evenly.

    ``commitment_words``: (8,) shared or (8, B) per-lane (multi-identity).
    Returns (4, B) u32 BE words with the lane axis sharded. ``impl`` as
    in :func:`labels_with_min_sharded`.
    """
    lay = _layouts(mesh)
    idx_lo = lay.put_batch(idx_lo)
    idx_hi = lay.put_batch(idx_hi)
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:
        cw = lay.put_lane(cw)
    return scrypt.scrypt_labels_jit(cw, idx_lo, idx_hi, n=n, impl=impl)


@jax.jit
def words_to_le(words):
    """(4, B) BE label words -> LE proving-hash words, on device.

    The device-side twin of the host ``labels_to_bytes`` ->
    ``labels_to_words`` round trip: sharded verify feeds label words
    straight into the proving hash without a host bytes detour, so the
    endianness flip the host path performs for free must happen here."""
    return byteswap32(words)


def prove_step_sharded(mesh: Mesh, challenge_words, nonce_base, idx_lo,
                       idx_hi, label_words, threshold, hit_counts, hit_carry,
                       valid, start_lo, start_hi, *, n_nonces: int,
                       max_hits: int):
    """One sharded streaming-prove step (the multichip prove path).

    Label lanes are striped over the mesh exactly like
    ``labels_with_min_sharded`` stripes init batches; the Salsa20/8 sweep
    is embarrassingly parallel per lane, and GSPMD lowers the compaction
    epilogue's small reductions/gathers to ICI collectives. The donated
    (hit_counts, hit_carry) state stays replicated (see
    ops/proving.py merge_hits); the prover replicates it via
    ``replicate()`` before the first batch of a pass. Batch size must
    divide by the mesh size — the prover's pad-and-trim already makes
    every batch the full ``batch_labels``.
    """
    lay = _layouts(mesh)
    idx_lo = lay.put_batch(idx_lo)
    idx_hi = lay.put_batch(idx_hi)
    lw = lay.put_lane(label_words)
    return proving.prove_scan_step_jit(
        jnp.asarray(challenge_words), nonce_base, idx_lo, idx_hi, lw,
        threshold, hit_counts, hit_carry, valid, start_lo, start_hi,
        n_nonces=n_nonces, max_hits=max_hits)


@functools.partial(jax.jit, static_argnames=("n",))
def _init_step(commitment_words, idx_lo, idx_hi, threshold, *, n: int):
    words = scrypt.scrypt_labels_jit(commitment_words, idx_lo, idx_hi, n=n)
    # init statistics, all-reduced across the mesh by XLA:
    #  - how many labels fall under the proving threshold (K1 calibration)
    #  - running minimum of the labels' top-64-bit keys (coarse scan; the
    #    exact LE-u128 argmin is the device carry in ops/scrypt.py
    #    _stage_minscan, used by labels_with_min_sharded above)
    k_hi = byteswap32(words[3]).astype(jnp.uint32)
    k_lo = byteswap32(words[2]).astype(jnp.uint32)
    qualifying = jnp.sum((words[0] < threshold).astype(jnp.int32))
    min_hi = jnp.min(k_hi)
    is_min = k_hi == min_hi
    min_lo = jnp.min(jnp.where(is_min, k_lo, jnp.uint32(0xFFFFFFFF)))
    return words, qualifying, min_hi, min_lo


def init_step_sharded(mesh: Mesh, commitment_words, idx_lo, idx_hi,
                      threshold: int, *, n: int):
    """One sharded init step: labels + global stats (the multichip path).

    The label computation is embarrassingly parallel over lanes; the three
    scalar stats are cross-device reductions XLA lowers to ICI all-reduces.
    """
    lay = _layouts(mesh)
    idx_lo = lay.put_batch(idx_lo)
    idx_hi = lay.put_batch(idx_hi)
    cw = jnp.asarray(commitment_words)
    if cw.ndim == 2:
        cw = lay.put_lane(cw)
    return _init_step(cw, idx_lo, idx_hi, jnp.uint32(threshold), n=n)
