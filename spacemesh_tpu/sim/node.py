"""Sim node factories: hundreds of light relays + a few full Apps.

All nodes share ONE event loop (a VirtualClockLoop — sim/scenario.py)
and one process. Two weights:

* :class:`LightNode` — a PubSub endpoint on the MeshHub: it relays
  every topic (an empty handler set accepts) and counts what it saw.
  Hundreds of these give partitions/storms a real multi-hop fabric at
  ~zero cost per node.
* :class:`FullNode` — a real :class:`node.app.App` (consensus, mesh,
  tortoise, verify farm, health engine) with DETERMINISTIC identities
  derived from the scenario seed, its clock driven by the injected
  virtual time source. These carry the consensus assertions.

Identity seeds, data dirs, and genesis are all functions of the
scenario seed and the node's logical name — never of wall time — so the
same seed boots byte-identical networks.
"""

from __future__ import annotations

import hashlib
import pathlib
from typing import Callable

from ..core.hashing import sum256
from ..core.signing import EdSigner
from ..node import clock as clock_mod
from ..node.app import App
from ..node.config import load
from ..p2p.pubsub import PubSub
from .net import MeshHub, SimNet

# ONE fixed genesis placeholder: genesis_id (signature prefix, golden
# ATX) derives from it, so per-run values would put every run on a
# different network. The LayerClock is rebased onto virtual time at
# scenario start.
GENESIS_PLACEHOLDER = 1_700_000_900.0

STORM_TOPIC = "storm"


def light_name(seed: int, index: int) -> bytes:
    return hashlib.sha256(f"sim-{seed}-light-{index}".encode()).digest()


class LightNode:
    """PubSub relay endpoint; observes (and counts) what it sees."""

    def __init__(self, seed: int, index: int, hub: MeshHub):
        self.index = index
        self.name = light_name(seed, index)
        self.pubsub = PubSub(node_name=self.name, deliver_self=False)
        self.storm_seen = 0

        async def on_storm(peer: bytes, data: bytes) -> bool:
            self.storm_seen += 1
            return True

        self.pubsub.register(STORM_TOPIC, on_storm)
        # light=True: on the event fabric the node runs no gossipsub
        # control plane, just the sparse relay set (legacy hub ignores it)
        hub.join(self.pubsub, light=True)


def _full_config(data_dir: pathlib.Path, *, layer_sec: float, lpe: int,
                 num_identities: int, hdist: int = 4,
                 smeshing: bool = True):
    return load("standalone", overrides={
        "data_dir": str(data_dir),
        "layer_duration": layer_sec,
        "layers_per_epoch": lpe,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": smeshing, "num_units": 1, "init_batch": 128,
                     "num_identities": num_identities},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": hdist, "zdist": 2, "window_size": 50},
    })


class FullNode:
    """One real App on the sim fabric, deterministically seeded."""

    def __init__(self, seed: int, index: int, *, tmp: pathlib.Path,
                 hub: MeshHub, simnet: SimNet,
                 loop_time: Callable[[], float],
                 layer_sec: float, lpe: int, num_identities: int = 1,
                 smeshing: bool = True):
        self.index = index
        self.seed = seed
        self.layer_sec = layer_sec
        self.skew = 0.0     # timeskew fault: virtual seconds of offset
        self._loop_time = loop_time
        self.alive = True
        cfg = _full_config(tmp / f"full{index:03d}", layer_sec=layer_sec,
                           lpe=lpe, num_identities=num_identities,
                           smeshing=smeshing)
        # deterministic identities (the reference pins test keys the
        # same way): every VRF roll — eligibility, leaders, weak coins —
        # replays identically from the scenario seed
        key_dir = pathlib.Path(cfg.data_dir) / "identities"
        key_dir.mkdir(parents=True, exist_ok=True)
        signers = []
        for i in range(num_identities):
            kseed = hashlib.sha256(
                f"sim-{seed}-full-{index}-{i}".encode()).digest()
            s = EdSigner(seed=kseed, prefix=cfg.genesis.genesis_id)
            fname = "local.key" if i == 0 else f"local_{i:02d}.key"
            (key_dir / fname).write_text(s.private_bytes().hex())
            signers.append(s)
        self.signer = signers[0]
        self.name = self.signer.node_id
        self._cfg = cfg
        self.pubsub = PubSub(node_name=self.name)
        hub.join(self.pubsub)
        self.hub = hub
        self.simnet = simnet
        self.app = App(cfg, signer=self.signer, pubsub=self.pubsub,
                       time_source=self._time)
        # the scenario engine owns SLI sampling and SLO verdicts
        # (obs/sli.py over the shared registry); per-App tick loops
        # would only burn wall clock spooling flight bundles mid-fault
        # (breaching by design) and add thread-completion jitter
        self.app.health_engine.close()
        self.app.connect_network(simnet)
        self._tasks: list = []

    def _time(self) -> float:
        return self._loop_time() + self.skew

    # --- lifecycle -----------------------------------------------------

    async def prepare(self) -> None:
        await self.app.prepare()

    def rebase_clock(self, genesis: float) -> None:
        self.genesis = genesis
        self.app.clock = clock_mod.LayerClock(
            genesis, self.layer_sec, time_source=self._time)

    def start(self, until_layer: int, *, sync_interval: float = 2.0):
        import asyncio

        self._tasks = [
            asyncio.ensure_future(self.app.run(until_layer=until_layer)),
            asyncio.ensure_future(self.app.syncer.run(sync_interval)),
        ]
        return self._tasks[0]

    @property
    def run_task(self):
        return self._tasks[0] if self._tasks else None

    def kill(self) -> None:
        """SIGKILL analogue: drop off the fabric, cancel everything.
        Storage is left on disk (a later restart recovers from it)."""
        self.alive = False
        self.hub.suspend(self.name)
        self.app.syncer.stop()
        for t in self._tasks:
            t.cancel()
        for t in self.app._tasks:
            t.cancel()
        self.close()

    async def restart(self, until_layer: int, *,
                      sync_interval: float = 2.0) -> None:
        """Crash recovery: rebuild the App over the surviving on-disk
        stores (the PR-13 faultfs recovery path), rejoin the fabric,
        and resume consensus. A FRESH PubSub is built — register()
        appends, so reusing the crashed App's handler table would
        double-deliver every topic."""
        assert not self.alive, "restart() follows kill()"
        self._closed = False
        self.pubsub = PubSub(node_name=self.name)
        self.hub.join(self.pubsub)
        self.app = App(self._cfg, signer=self.signer, pubsub=self.pubsub,
                       time_source=self._time)
        self.app.health_engine.close()
        self.app.connect_network(self.simnet)
        await self.app.prepare()
        self.app.clock = clock_mod.LayerClock(
            self.genesis, self.layer_sec, time_source=self._time)
        self.alive = True
        self.hub.resume(self.name)
        self.start(until_layer, sync_interval=sync_interval)

    async def stop(self) -> None:
        """Graceful stop: cancel the run loop, close the app."""
        import asyncio

        self.app.syncer.stop()
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self.close()

    def close(self) -> None:
        if not getattr(self, "_closed", False):
            self._closed = True
            try:
                self.app.close()
            except Exception:  # noqa: BLE001 — teardown must not mask results
                pass

    # --- state inspection (assertions) ---------------------------------

    def applied_record(self, lo: int, hi: int) -> list[tuple[int, bytes]]:
        """(layer, applied block id or EMPTY) over [lo, hi] — the
        consensus record the event digest covers."""
        from ..storage import layers as layerstore

        out = []
        for lyr in range(lo, hi + 1):
            block = layerstore.applied_block(self.app.state, lyr)
            out.append((lyr, block or bytes(32)))
        return out

    def state_root(self, layer: int) -> bytes | None:
        from ..storage import layers as layerstore

        return layerstore.state_hash(self.app.state, layer)

    def last_applied(self) -> int:
        from ..storage import layers as layerstore

        return layerstore.last_applied(self.app.state)


def storm_payload(seed: int, index: int, size: int = 200) -> bytes:
    """Deterministic storm traffic body."""
    base = sum256(b"storm", seed.to_bytes(8, "little"),
                  index.to_bytes(8, "little"))
    reps = (size + 31) // 32
    return (base * reps)[:size]
