"""Scripted fault vocabulary + adversarial payload builders.

Each fault is a dict ``{"kind": ..., ...}`` in a scenario phase; the
engine calls :func:`apply_fault` at phase entry. Kinds:

  partition   {"islands": [[full idx, ...], ...]} — listed islands get
              their own partition groups; light nodes split round-robin
              across the islands by index. The in-proc analogue of
              systest/chaos/partition.go.
  heal        {} — clear partitions, eclipses, blocked links.
  eclipse     {"victim": ("full"|"light", i),
               "attackers": [("light", j), ...]} — the victim may only
              talk to its attackers.
  clear_eclipse {"victim": (...)}
  churn       {"light": [i, ...]} — suspend light nodes (frames lost).
  resume      {"light": [i, ...]}
  kill        {"full": i} — SIGKILL analogue for one full node.
  timeskew    {"full": i, "offset": seconds} — skew one node's clock
              (systest/chaos/timeskew.go); 0 resets.
  link_policy {"loss": p, "delay": s, "jitter": s, "dup": p,
               "reorder": p} — network default link degradation.
  adversary   {"what": "malformed_atx"|"torsion_sig"|"dup_flood",
               "count": n, "via": light idx} — hostile payload
              injection from a light node.

Adversarial payloads:

* ``malformed_atx`` — garbage and truncated blobs on the ATX topic:
  every full node must reject without crashing its handler loop.
* ``torsion_sig`` — a wire-valid hare message whose ed25519 signature
  carries a small-order torsion component in R (the PR-2 consensus
  divergence class): cofactored verification must treat it IDENTICALLY
  on every node — farm batch or inline — so no divergence results.
* ``dup_flood`` — the same frame republished over and over (sub-flood
  duplication): the hubs' seen-caches must absorb it.
"""

from __future__ import annotations

import dataclasses
import hashlib

from ..core.signing import Domain


def torsion_point():
    """A nonzero small-order (torsion) point on edwards25519."""
    from ..core import signing

    i = 0
    while True:
        pt = signing._pt_decode(
            hashlib.sha256(b"sim-torsion%d" % i).digest())
        i += 1
        if pt is None:
            continue
        cand = signing._pt_mul(signing._Q, pt)
        if not signing._pt_eq(cand, signing._ID):
            return cand


def torsion_hare_message(layer: int, seed: int) -> bytes:
    """A well-formed PREROUND hare message whose signature is the
    honest (r, s) with ``R' = R + T`` for a small-order T: the ZIP-215
    cofactored check accepts the signature on every path, and the
    message then dies deterministically on eligibility (the identity
    holds no ATX). The pre-PR-2 split — inline reject, batch accept
    ~7/8 of the time — would make nodes diverge on exactly this input.
    """
    from ..consensus.hare import PREROUND, HareMessage
    from ..core import signing

    t8 = torsion_point()
    kseed = hashlib.sha256(b"sim-torsion-key-%d" % seed).digest()
    scalar, nonce_prefix = signing._expand_key(kseed)
    pub = signing._pt_encode(signing._pt_mul_base(scalar))
    msg = HareMessage(
        layer=layer, iteration=0, round=PREROUND,
        values=[hashlib.sha256(b"sim-torsion-val-%d" % seed).digest()],
        eligibility_proof=bytes(80), eligibility_count=1,
        atx_id=hashlib.sha256(b"sim-torsion-atx-%d" % seed).digest(),
        node_id=pub, cert_msgs=[], signature=bytes(64))
    data = bytes([int(Domain.HARE)]) + msg.signed_bytes()
    r = int.from_bytes(hashlib.sha512(nonce_prefix + data).digest(),
                       "little") % signing._Q
    r_enc = signing._pt_encode(
        signing._pt_add(signing._pt_mul_base(r), t8))
    k = int.from_bytes(hashlib.sha512(r_enc + pub + data).digest(),
                       "little") % signing._Q
    s = (r + k * scalar) % signing._Q
    forged = dataclasses.replace(msg, signature=r_enc
                                 + s.to_bytes(32, "little"))
    return forged.to_bytes()


def malformed_atx_blobs(seed: int, count: int) -> list[bytes]:
    """Garbage + truncated blobs for the ATX topic."""
    out = []
    for i in range(count):
        body = hashlib.sha256(b"sim-bad-atx-%d-%d"
                              % (seed, i)).digest() * 8
        out.append(body if i % 2 == 0 else body[: 16 + i % 48])
    return out


class FaultError(ValueError):
    pass


def _resolve(engine, sel):
    kind, idx = sel
    if kind == "full":
        return engine.fulls[idx].name
    if kind == "light":
        return engine.lights[idx].name
    raise FaultError(f"unknown node selector {sel!r}")


def apply_fault(engine, spec: dict) -> str:
    """Apply one fault spec; returns the canonical line the event
    digest records (no timestamps — content only, replay-stable)."""
    net = engine.network
    kind = spec["kind"]
    if kind == "partition":
        islands = spec["islands"]
        groups = [[engine.fulls[i].name for i in isl] for isl in islands]
        for j, ln in enumerate(engine.lights):
            groups[j % len(groups)].append(ln.name)
        net.partition(groups)
        return "partition islands=%s lights=round-robin" % (
            ",".join("|".join(str(i) for i in isl) for isl in islands))
    if kind == "heal":
        net.heal()
        return "heal"
    if kind == "eclipse":
        victim = _resolve(engine, tuple(spec["victim"]))
        attackers = [_resolve(engine, tuple(a))
                     for a in spec["attackers"]]
        net.eclipse(victim, attackers)
        return "eclipse victim=%s attackers=%d" % (
            victim.hex()[:8], len(attackers))
    if kind == "clear_eclipse":
        net.clear_eclipse(_resolve(engine, tuple(spec["victim"])))
        return "clear_eclipse"
    if kind == "churn":
        for i in spec["light"]:
            engine.hub.suspend(engine.lights[i].name)
        return "churn light=%s" % ",".join(str(i) for i in spec["light"])
    if kind == "resume":
        for i in spec["light"]:
            engine.hub.resume(engine.lights[i].name)
        return "resume light=%s" % ",".join(str(i) for i in spec["light"])
    if kind == "kill":
        node = engine.fulls[spec["full"]]
        node.kill()
        return "kill full=%d" % spec["full"]
    if kind == "timeskew":
        node = engine.fulls[spec["full"]]
        node.skew = float(spec["offset"])
        return "timeskew full=%d offset=%s" % (spec["full"],
                                               spec["offset"])
    if kind == "link_policy":
        from .net import LinkPolicy

        fields = {k: float(spec[k]) for k in
                  ("loss", "delay", "jitter", "dup", "reorder",
                   "reorder_delay") if k in spec}
        net.set_link_policy(LinkPolicy(**fields))
        return "link_policy " + ",".join(
            f"{k}={v}" for k, v in sorted(fields.items()))
    raise FaultError(f"unknown fault kind {kind!r}")
