"""Built-in scenario scripts.

Each builder returns a plain script dict (sim/scenario.py documents the
schema); ``builtin(name, **overrides)`` is the registry the CLI and CI
use. All numbers are DETERMINISTIC functions of the seed — nothing here
reads wall time.

* ``partition-heal`` — the bread-and-butter robustness drill (and the
  CI scenario-smoke workload): majority/minority islands, storm + tx
  traffic, malformed-ATX adversary, heal, SLI/SLO + convergence
  assertions. Replaces the wall-clock partition half of the old
  subprocess chaos suite with a seeded, replayable run.
* ``storm-256`` — the 256-node acceptance scenario: gossip storm at
  production fan-out, a 3-way partition (no island holds a certifying
  majority for part of it), link degradation, light-node churn, the
  full adversarial payload set, heal + Tortoise re-convergence with
  zero consensus divergence.
* ``timeskew-kill`` — ports the assertions of the randomly-seeded
  multi-process cluster chaos test (tests/test_cluster_chaos.py —
  systest timeskew.go + fail.go): one node's clock skews ahead and
  returns, another dies for good; the survivors keep applying layers
  and agree on applied blocks and state roots.
* ``smoke`` — tiny engine self-test (2 full, 8 light, one storm).
* ``verifyd-load`` — the verification SERVICE under seeded open-loop
  multi-client load (``"engine": "verifyd"`` dispatches to
  sim/verifyd_load.py): three light clients + one heavy client over
  capacity, typed rate sheds on the heavy client only, zero wrong
  verdicts, replay-stable outcome digest.
* ``crash-recovery`` — the POST storage plane under deterministic
  disk faults (``"engine": "crashrec"`` dispatches to
  sim/crash_recovery.py): power-cut and torn-write crashes swept over
  the write-path op sites of a tiny init, each reboot recovered to a
  bit-identical store, plus an ENOSPC hold that must degrade (not
  kill) the pipeline and release cleanly (docs/CRASH_SAFETY.md).
* ``verifyd-outage`` — the self-healing drill (``"engine":
  "failover"`` dispatches to sim/failover.py): verifyd killed
  mid-load, the node keeps verifying on the local farm with zero
  verdict divergence and a green BLOCK-lane SLO, the breaker stops
  re-paying the dead service, and traffic fails back to remote after
  recovery (docs/SELF_HEALING.md).
* ``runtime-degrade`` — the device-decay drill (same engine): a
  seeded device-dispatch fault plan; the runtime breaker opens after
  its failure budget (N device attempts for an M≫N-batch outage, not
  M), the host fallback carries the load bit-identically, and device
  recovery re-closes the breaker.
* ``storm-1024`` — the thousand-node acceptance drill on the event
  fabric (sim/net.py EventMeshHub): 1024 nodes, mostly light relays,
  through storm, a 3-way partition, churn, three concurrent
  adversaries, and heal — converged with a byte-identical replay
  digest inside the tier-1 wall budget (the storm-smoke CI job).
* ``storm-512-bench`` — the pure-fabric bench shape behind
  ``sim_fabric_events_per_sec`` (bench.py): smeshing and tracing off,
  sparse heartbeats, a long quiet tail, so the wall clock measures hub
  idle+relay cost — the axis the event fabric rebuilt — instead of
  the consensus/crypto floor both fabrics share. Digest-identical
  across fabrics (clean links draw nothing from the net RNG).
* ``crash-store`` — composed crash + netsplit: a full node is
  partitioned into its own island, SIGKILLed, and after heal restarts
  over its surviving on-disk stores (the ``restart`` fault), re-syncing
  into byte-identical consensus with the majority.
* ``storm-4096`` — storm-1024's geometry at 4x the relay population,
  only affordable on the SHARDED fabric (sim/shard.py): the light
  wheels spread over host cores with conservative virtual-time
  windows (``"shards": "auto"``).
* ``eclipse-campaign`` — eclipse a minority full across an epoch
  boundary while attacker lights feed it malformed ATXs; typed
  rejections only, victim re-syncs to zero divergence after heal.
* ``soak-epochs`` — 3.5 epochs of continuous storm + VM tx traffic on
  the sharded fabric with state-root equality asserted at EVERY epoch
  boundary (the slow-divergence drift detector).
* ``byzantine-verifyd`` — one fleet replica keeps a healthy transport
  but flips every verdict (``"engine": "fleet"``): the FleetVerifier's
  verdict audit must detect it, trip only that replica's breaker, and
  let zero wrong verdicts reach a caller.
"""

from __future__ import annotations


def smoke(seed: int = 1, light: int = 8) -> dict:
    return {
        "name": "smoke", "seed": seed,
        "nodes": {"full": 2, "light": light},
        "layer_sec": 2.0, "lpe": 3, "until_layer": 6,
        "digest_frontier": 5,
        "phases": [
            {"name": "run", "until_layer": 5,
             "traffic": {"storm": {"publishers": 3, "messages": 8,
                                   "interval": 0.2}}},
            {"name": "end",
             "converge": {"frontier": 5, "deadline": 180.0},
             "asserts": [
                 {"kind": "converged", "frontier": 5},
                 {"kind": "storm_coverage", "min_fraction": 0.9},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "slo_green"},
             ]},
        ],
    }


def partition_heal(seed: int = 7, light: int = 60) -> dict:
    """Majority island (4/6 identities) keeps deciding layers through
    the split; the minority islands coast and must re-converge after
    the merge. Healing has BOTH reference paths available: validated
    certificate adoption where the island's certifier hit threshold,
    and tortoise vote weight once the divergent layers leave the hdist
    window — which is why the run continues well past the merge
    (test_partition.healed3 uses the same geometry)."""
    return {
        "name": "partition-heal", "seed": seed,
        "nodes": {"full": 4, "light": light,
                  "identities": [3, 1, 1, 1]},
        "layer_sec": 2.0, "lpe": 8, "until_layer": 20,
        "digest_frontier": 12,
        "phases": [
            {"name": "warmup", "until_layer": 10,
             "traffic": {"storm": {"publishers": 6, "messages": 16,
                                   "interval": 0.3},
                         "tx_spawn": {}},
             "asserts": [
                 {"kind": "storm_coverage", "min_fraction": 0.9},
             ]},
            {"name": "partition", "until_layer": 13,
             "faults": [
                 {"kind": "partition", "islands": [[0, 1], [2], [3]]},
                 {"kind": "adversary", "what": "malformed_atx",
                  "count": 6, "via": 1},
             ],
             "traffic": {"storm": {"publishers": 6, "messages": 8,
                                   "interval": 0.4}}},
            {"name": "heal",
             "faults": [{"kind": "heal"}],
             "converge": {"frontier": 12, "deadline": 240.0},
             "asserts": [
                 {"kind": "converged", "frontier": 12},
                 {"kind": "progress", "min_layer": 12},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "sli_present", "name": "gossip_handler_p99"},
                 {"kind": "slo_green"},
                 {"kind": "span", "name": "mesh.process_layer",
                  "min": 8},
                 {"kind": "span", "name": "gossip.deliver", "min": 16},
             ]},
        ],
    }


def storm_256(seed: int = 11, light: int = 252) -> dict:
    """The acceptance scenario: 256 nodes, gossip storm, 3-way
    partition with link degradation and churn, adversarial payloads,
    heal, Tortoise re-convergence, zero consensus divergence."""
    churned = list(range(8, 32))
    return {
        "name": "storm-256", "seed": seed,
        "nodes": {"full": 4, "light": light,
                  "identities": [3, 1, 1, 1]},
        "layer_sec": 2.0, "lpe": 8, "until_layer": 20,
        "digest_frontier": 12,
        "topology": {"degree": 6, "gossip_degree": 4},
        "phases": [
            {"name": "storm", "until_layer": 10,
             "traffic": {"storm": {"publishers": 12, "messages": 30,
                                   "interval": 0.15},
                         "tx_spawn": {}},
             "asserts": [
                 {"kind": "storm_coverage", "min_fraction": 0.9},
             ]},
            {"name": "partition", "until_layer": 13,
             "faults": [
                 {"kind": "partition", "islands": [[0, 1], [2], [3]]},
                 {"kind": "link_policy", "loss": 0.05, "delay": 0.02,
                  "jitter": 0.05, "dup": 0.02, "reorder": 0.02},
                 {"kind": "churn", "light": churned},
                 {"kind": "adversary", "what": "malformed_atx",
                  "count": 6, "via": 40},
                 {"kind": "adversary", "what": "torsion_sig",
                  "count": 4, "via": 41},
                 {"kind": "adversary", "what": "dup_flood",
                  "count": 12, "via": 42, "interval": 0.1},
             ],
             "traffic": {"storm": {"publishers": 8, "messages": 10,
                                   "interval": 0.3}}},
            {"name": "heal",
             "faults": [
                 {"kind": "link_policy"},   # back to clean links
                 {"kind": "heal"},
                 {"kind": "resume", "light": churned},
             ],
             "converge": {"frontier": 12, "deadline": 240.0},
             "asserts": [
                 {"kind": "converged", "frontier": 12},
                 {"kind": "progress", "min_layer": 12},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "sli_present", "name": "gossip_handler_p99"},
                 {"kind": "slo_green"},
                 {"kind": "span", "name": "mesh.process_layer",
                  "min": 8},
                 {"kind": "span", "name": "gossip.deliver", "min": 32},
             ]},
        ],
    }


def storm_1024(seed: int = 17, light: int = 1020) -> dict:
    """The thousand-node acceptance scenario, only reachable on the
    event fabric: 1024 nodes (mostly light relays running NO gossipsub
    control plane), gossip storm, 3-way partition with link degradation
    and heavy light churn, the full adversarial payload set, heal,
    Tortoise re-convergence, zero consensus divergence. Same geometry
    as storm-256 so a fabric regression shows up as wall time, not as
    a different consensus question."""
    churned = list(range(16, 64))
    return {
        "name": "storm-1024", "seed": seed,
        "nodes": {"full": 4, "light": light,
                  "identities": [3, 1, 1, 1]},
        "layer_sec": 2.0, "lpe": 8, "until_layer": 20,
        "digest_frontier": 12,
        # shard the light-relay wheel over host cores (sim/shard.py);
        # resolves to W=1 in-process on small hosts, and every W replays
        # the identical per-W digest
        "shards": "auto",
        # 4x the node count floods ~10x the gossip spans of storm-256;
        # the default 64Ki ring would evict every mesh.process_layer
        # span before the heal-phase span asserts read them
        "trace_capacity": 1 << 19,
        # when sharded, the merged capture must resolve at least one
        # fabric.publish -> shard.publish cross-process parent edge
        # (scenario.py appends merged_procs / cross_proc_links asserts)
        "require_cross_proc_links": 1,
        "topology": {"degree": 6, "gossip_degree": 4},
        "phases": [
            {"name": "storm", "until_layer": 10,
             "traffic": {"storm": {"publishers": 24, "messages": 40,
                                   "interval": 0.12},
                         "tx_spawn": {}},
             "asserts": [
                 {"kind": "storm_coverage", "min_fraction": 0.9},
             ]},
            {"name": "partition", "until_layer": 13,
             "faults": [
                 {"kind": "partition", "islands": [[0, 1], [2], [3]]},
                 {"kind": "link_policy", "loss": 0.05, "delay": 0.02,
                  "jitter": 0.05, "dup": 0.02, "reorder": 0.02},
                 {"kind": "churn", "light": churned},
                 {"kind": "adversary", "what": "malformed_atx",
                  "count": 6, "via": 80},
                 {"kind": "adversary", "what": "torsion_sig",
                  "count": 4, "via": 81},
                 {"kind": "adversary", "what": "dup_flood",
                  "count": 12, "via": 82, "interval": 0.1},
             ],
             "traffic": {"storm": {"publishers": 12, "messages": 10,
                                   "interval": 0.3}}},
            {"name": "heal",
             "faults": [
                 {"kind": "link_policy"},   # back to clean links
                 {"kind": "heal"},
                 {"kind": "resume", "light": churned},
             ],
             "converge": {"frontier": 12, "deadline": 240.0},
             "asserts": [
                 {"kind": "converged", "frontier": 12},
                 {"kind": "progress", "min_layer": 12},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "sli_present", "name": "gossip_handler_p99"},
                 {"kind": "slo_green"},
                 {"kind": "span", "name": "mesh.process_layer",
                  "min": 8},
                 {"kind": "span", "name": "gossip.deliver", "min": 32},
             ]},
        ],
    }


def storm_512_bench(seed: int = 23, light: int = 510) -> dict:
    """The bench workload behind ``sim_fabric_events_per_sec``: a clean
    512-node gossip storm (no faults, no link policies — the data-plane
    RNG is never drawn, so BOTH fabrics replay the identical world and
    must land the identical digest; bench.py asserts that before
    reporting any rate). The scenario isolates the FABRIC: smeshing and
    tracing are off (no PoST init, no ATX/proposal crypto competing for
    the wall clock), and the storm burst is followed by a long quiet
    tail — the regime where per-node consumer tasks and an always-on
    control plane keep burning beats while the event wheel and the
    dirty-set heartbeat cost nothing."""
    return {
        "name": "storm-512-bench", "seed": seed,
        "nodes": {"full": 2, "light": light, "smeshing": False},
        "trace": False,
        # layer_sec 2.0 compresses time ~150x vs mainnet, so the default
        # 1.0-virtual-s beat is 150x SPARSER than gossipsub's real 1 s
        # heartbeat; 0.1 is still 15x sparser, and per-beat cost is the
        # O(nodes)-vs-O(dirty) axis the fabric rewrite targets
        "heartbeat": 0.1,
        "layer_sec": 2.0, "lpe": 8, "until_layer": 40,
        "digest_frontier": 6,
        "topology": {"degree": 6, "gossip_degree": 4},
        "phases": [
            {"name": "storm", "until_layer": 6,
             "traffic": {"storm": {"publishers": 24, "messages": 60,
                                   "interval": 0.1}}},
            {"name": "quiet-tail", "until_layer": 38},
            {"name": "end",
             "converge": {"frontier": 6, "deadline": 180.0},
             "asserts": [
                 {"kind": "converged", "frontier": 6},
                 {"kind": "storm_coverage", "min_fraction": 0.9},
             ]},
        ],
    }


def storm_4096(seed: int = 29, light: int = 4092) -> dict:
    """The four-thousand-node tier-2 drill: storm-1024's geometry at 4x
    the relay population, only reachable with the sharded fabric
    (sim/shard.py) — ``"shards": "auto"`` spreads the light wheels over
    the host cores with conservative virtual-time windows. Same
    consensus question as storm-256/1024, so a fabric scaling
    regression shows up as wall time."""
    churned = list(range(64, 192))
    return {
        "name": "storm-4096", "seed": seed,
        "nodes": {"full": 4, "light": light,
                  "identities": [3, 1, 1, 1]},
        "layer_sec": 2.0, "lpe": 8, "until_layer": 20,
        "digest_frontier": 12,
        "trace_capacity": 1 << 21,
        "shards": "auto",
        "topology": {"degree": 6, "gossip_degree": 4},
        "phases": [
            {"name": "storm", "until_layer": 10,
             "traffic": {"storm": {"publishers": 32, "messages": 48,
                                   "interval": 0.1},
                         "tx_spawn": {}},
             "asserts": [
                 {"kind": "storm_coverage", "min_fraction": 0.9},
             ]},
            {"name": "partition", "until_layer": 13,
             "faults": [
                 {"kind": "partition", "islands": [[0, 1], [2], [3]]},
                 {"kind": "link_policy", "loss": 0.05, "delay": 0.02,
                  "jitter": 0.05, "dup": 0.02, "reorder": 0.02},
                 {"kind": "churn", "light": churned},
                 {"kind": "adversary", "what": "malformed_atx",
                  "count": 6, "via": 300},
                 {"kind": "adversary", "what": "torsion_sig",
                  "count": 4, "via": 301},
                 {"kind": "adversary", "what": "dup_flood",
                  "count": 12, "via": 302, "interval": 0.1},
             ],
             "traffic": {"storm": {"publishers": 16, "messages": 10,
                                   "interval": 0.3}}},
            {"name": "heal",
             "faults": [
                 {"kind": "link_policy"},   # back to clean links
                 {"kind": "heal"},
                 {"kind": "resume", "light": churned},
             ],
             "converge": {"frontier": 12, "deadline": 360.0},
             "asserts": [
                 {"kind": "converged", "frontier": 12},
                 {"kind": "progress", "min_layer": 12},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "slo_green"},
                 {"kind": "span", "name": "mesh.process_layer",
                  "min": 8},
                 {"kind": "span", "name": "gossip.deliver", "min": 32},
             ]},
        ],
    }


def eclipse_campaign(seed: int = 31, light: int = 48) -> dict:
    """Eclipse attack across an epoch boundary: minority full 3 may
    only talk to a clique of attacker lights, which feed it (and the
    honest side) malformed ATXs while the epoch turns. The honest
    majority keeps deciding; every hostile payload dies as a TYPED
    rejection (hub ``rejected``, never a crash); after the eclipse
    clears the victim re-syncs into byte-identical consensus — zero
    divergence. The in-proc analogue of an eclipse campaign against a
    bootstrapping node."""
    attackers = [("light", i) for i in (40, 41, 42, 43)]
    return {
        "name": "eclipse-campaign", "seed": seed,
        "nodes": {"full": 4, "light": light,
                  "identities": [3, 1, 1, 1]},
        "layer_sec": 2.0, "lpe": 8, "until_layer": 20,
        "digest_frontier": 12,
        "shards": "auto",
        "phases": [
            {"name": "warmup", "until_layer": 6,
             "traffic": {"storm": {"publishers": 4, "messages": 12,
                                   "interval": 0.25},
                         "tx_spawn": {}},
             "asserts": [
                 {"kind": "storm_coverage", "min_fraction": 0.9},
             ]},
            # the eclipse holds from layer 6 through 11 — across the
            # epoch boundary at layer 8, the window where an isolated
            # node's ATX/beacon view is most poisonable
            {"name": "eclipse", "until_layer": 11,
             "faults": [
                 {"kind": "eclipse", "victim": ["full", 3],
                  "attackers": attackers},
                 {"kind": "adversary", "what": "malformed_atx",
                  "count": 8, "via": 40},
                 {"kind": "adversary", "what": "torsion_sig",
                  "count": 4, "via": 41},
             ],
             "traffic": {"storm": {"publishers": 4, "messages": 8,
                                   "interval": 0.4}}},
            {"name": "heal",
             "faults": [
                 {"kind": "clear_eclipse", "victim": ["full", 3]},
                 {"kind": "heal"},
             ],
             "converge": {"frontier": 12, "deadline": 240.0},
             "asserts": [
                 {"kind": "converged", "frontier": 12},
                 {"kind": "progress", "min_layer": 12},
                 {"kind": "hub_stat", "name": "rejected", "min": 1},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "slo_green"},
             ]},
        ],
    }


def soak_epochs(seed: int = 37, light: int = 252) -> dict:
    """The multi-epoch soak (tier-2): three and a half epochs of
    continuous storm + VM transaction traffic on the sharded fabric,
    with STATE-ROOT EQUALITY asserted at every epoch boundary — the
    drift detector for slow divergence that single-epoch drills can't
    see — plus green windowed SLOs over the whole run."""
    return {
        "name": "soak-epochs", "seed": seed,
        "nodes": {"full": 4, "light": light,
                  "identities": [3, 1, 1, 1]},
        "layer_sec": 2.0, "lpe": 8, "until_layer": 30,
        "digest_frontier": 26,
        "shards": "auto",
        "topology": {"degree": 6, "gossip_degree": 4},
        "phases": [
            {"name": "soak", "until_layer": 28,
             "traffic": {"storm": {"publishers": 8, "messages": 64,
                                   "interval": 0.5},
                         "tx_spawn": {}}},
            {"name": "end",
             "converge": {"frontier": 26, "deadline": 360.0},
             "asserts": [
                 {"kind": "converged", "frontier": 26},
                 {"kind": "progress", "min_layer": 26},
                 {"kind": "epoch_roots", "upto_layer": 26},
                 {"kind": "storm_coverage", "min_fraction": 0.9},
                 {"kind": "sli_present", "name": "layer_apply_p99"},
                 {"kind": "slo_green"},
             ]},
        ],
    }


def crash_store(seed: int = 13, light: int = 24) -> dict:
    """Composed crash-store-mid-partition drill: full node 2 is cut off
    in its own island and then SIGKILLed (storage left on disk), the
    majority island keeps certifying; after heal the node RESTARTS over
    its surviving stores (the PR-13 recovery path through App.prepare)
    and must re-sync into byte-identical consensus with the majority —
    the fault every production operator actually fears, crash + netsplit
    at once."""
    return {
        "name": "crash-store", "seed": seed,
        "nodes": {"full": 3, "light": light, "identities": [2, 1, 1]},
        "layer_sec": 2.0, "lpe": 3, "until_layer": 16,
        "digest_frontier": 11,
        "phases": [
            {"name": "warmup", "until_layer": 6,
             "traffic": {"storm": {"publishers": 4, "messages": 10,
                                   "interval": 0.25}}},
            {"name": "partition-crash", "until_layer": 9,
             "faults": [
                 {"kind": "partition", "islands": [[0, 1], [2]]},
                 {"kind": "kill", "full": 2},
             ]},
            {"name": "heal-restart", "until_layer": 12,
             "faults": [
                 {"kind": "heal"},
                 {"kind": "restart", "full": 2},
             ]},
            {"name": "end",
             "converge": {"frontier": 11, "deadline": 240.0},
             "asserts": [
                 {"kind": "converged", "frontier": 11},
                 {"kind": "progress", "min_layer": 11},
                 {"kind": "slo_green"},
             ]},
        ],
    }


def byzantine_verifyd(seed: int = 9) -> dict:
    """One fleet replica turns byzantine mid-load: transport healthy,
    admission healthy, every verdict flipped. The FleetVerifier's
    verdict audit (``audit.items`` spot-checks per successful remote
    batch against the bit-identical local farm) must detect the
    divergence, trip THAT replica's breaker, and keep serving correct
    verdicts from the survivors; after the replica is restored the
    probe path re-closes the breaker. Zero wrong verdicts may reach a
    caller at any point."""
    return {
        "name": "byzantine-verifyd", "engine": "fleet", "seed": seed,
        "waves": 14, "wave_interval_s": 0.5,
        "replicas": [
            {"name": "r0", "router_max_clients": 64,
             "service": {"max_clients": 512, "max_pending_items": 4096,
                         "workers": 2}},
            {"name": "r1", "router_max_clients": 64,
             "service": {"max_clients": 512, "max_pending_items": 4096,
                         "workers": 2}},
            {"name": "r2", "router_max_clients": 64,
             "service": {"max_clients": 512, "max_pending_items": 4096,
                         "workers": 2}},
        ],
        "clients": {"active_per_wave": 10, "overflow": 0,
                    "pinned_hot": 0, "items": [2, 4],
                    "mix": {"sig": 6, "vrf": 1, "membership": 1,
                            "pow": 2}},
        "breaker": {"failure_budget": 2, "window_s": 60.0,
                    "cooldown_s": 1.0, "cooldown_cap_s": 2.0},
        "audit": {"items": 2},
        "faults": {"byzantine": {"replica": "r1", "wave": 3,
                                 "restore_wave": 9}},
        "workload": {"sigs": 48, "vrfs": 6, "posts": 2,
                     "memberships": 8, "pows": 10},
        "asserts": [
            {"kind": "no_wrong_verdicts"},
            {"kind": "typed_sheds_only", "reasons": []},
            {"kind": "byzantine_detected", "replica": "r1", "min": 1},
            {"kind": "breaker_sequence", "replica": "r1"},
            {"kind": "path_served", "path": "remote", "min": 60},
            {"kind": "failback"},
            {"kind": "sli_present", "name": "fleet_block_p99"},
            {"kind": "slo_green", "name": "fleet_block_p99",
             "target": 0.25},
        ],
    }


def timeskew_kill(seed: int = 5, light: int = 16) -> dict:
    """tests/test_cluster_chaos.py's assertions on the deterministic
    fabric: skew one node's clock layers ahead mid-run, reset it, then
    SIGKILL another node — the survivors (including the formerly
    skewed one) must keep applying layers and agree on state."""
    return {
        "name": "timeskew-kill", "seed": seed,
        "nodes": {"full": 3, "light": light, "identities": [2, 1, 1]},
        "layer_sec": 2.0, "lpe": 3, "until_layer": 14,
        "digest_frontier": 9,
        "phases": [
            {"name": "warmup", "until_layer": 4},
            {"name": "skew", "until_layer": 6,
             "faults": [{"kind": "timeskew", "full": 2, "offset": 4.0}]},
            {"name": "reset", "until_layer": 8,
             "faults": [{"kind": "timeskew", "full": 2, "offset": 0.0}]},
            {"name": "kill", "until_layer": 11,
             "faults": [{"kind": "kill", "full": 1}]},
            {"name": "end",
             "converge": {"frontier": 9, "deadline": 240.0},
             "asserts": [
                 {"kind": "converged", "frontier": 9},
                 {"kind": "progress", "min_layer": 9},
                 {"kind": "slo_green"},
             ]},
        ],
    }


def verifyd_load(seed: int = 7, light: int = 3) -> dict:
    """Open-loop mixed load from ``light`` in-budget clients plus one
    heavy client whose offered rate is far over its token budget: the
    heavy client sheds (typed ``rate``), the light clients never do,
    and every admitted verdict matches inline verification."""
    mix = {"sig": 6, "vrf": 1, "membership": 1, "pow": 2, "post": 1}
    clients = [
        {"id": f"light-{i}", "rate": 8000.0, "burst": 4000.0,
         "requests_per_wave": 2, "items": [3, 6], "mix": mix,
         "lane": "gossip"}
        for i in range(max(int(light), 1))]
    clients.append(
        {"id": "heavy", "rate": 40.0, "burst": 60.0,
         "requests_per_wave": 4, "items": [6, 10], "mix": mix,
         "lane": "sync"})
    return {
        "name": "verifyd-load", "engine": "verifyd", "seed": seed,
        "waves": 10, "wave_interval_s": 0.05,
        "service": {"max_clients": 8, "max_pending_items": 4096,
                    "workers": 3},
        "workload": {"sigs": 48, "vrfs": 6, "posts": 4,
                     "memberships": 8, "pows": 10},
        "clients": clients,
        "asserts": [
            {"kind": "no_wrong_verdicts"},
            {"kind": "shed", "client": "heavy", "reason": "rate",
             "min": 3},
            {"kind": "no_shed", "client": "light-0"},
            {"kind": "ok_requests", "client": "light-0", "min": 15},
            {"kind": "bounded_pending", "max": 4096},
            {"kind": "sli_present", "name": "verifyd_request_p99"},
        ],
    }


def crash_recovery(seed: int = 7) -> dict:
    """Crash-injection sweep over a tiny init's write-path op sites
    (every 3rd site, seed-offset; power-cut and torn-write variants
    alternating), each restart recovered and asserted bit-identical to
    the uninjected reference, then an ENOSPC hold window that must
    flip the ``post.store`` probe degraded and converge after the plan
    releases space. All fault points are exact op counts — no sleeps,
    byte-identical digest across ``--repeat`` runs."""
    return {
        "name": "crash-recovery", "engine": "crashrec", "seed": seed,
        "labels": 512, "batch": 128, "scrypt_n": 2,
        "max_file_size": 4096, "interval_labels": 128,
        "crash_every": 3, "variants": ["powercut", "torn"],
        "enospc": {"op": 2, "hold": 6},
        "asserts": [
            {"kind": "bit_identical"},
            {"kind": "recovered", "min": 3},
            {"kind": "enospc_degraded"},
            {"kind": "fault_metrics", "min": 3},
        ],
    }


def verifyd_outage(seed: int = 7) -> dict:
    """Kill verifyd mid-load; the node must serve every request from
    the local farm (bit-identical verdicts), keep the BLOCK-lane p99
    green, bound its attempts against the dead service to the breaker
    budget + probes, and fail back to remote after recovery."""
    return {
        "name": "verifyd-outage", "engine": "failover",
        "mode": "verifyd-outage", "seed": seed,
        "waves": 20, "wave_interval_s": 0.5, "requests_per_wave": 2,
        "items": [3, 6],
        "mix": {"sig": 6, "vrf": 1, "membership": 1, "pow": 2},
        "outage": {"kill_wave": 5, "restore_wave": 11},
        "breaker": {"failure_budget": 2, "window_s": 60.0,
                    "cooldown_s": 1.0, "cooldown_cap_s": 2.0},
        "service": {"max_clients": 4, "max_pending_items": 4096,
                    "workers": 2},
        "workload": {"sigs": 48, "vrfs": 6, "posts": 2,
                     "memberships": 8, "pows": 10},
        "asserts": [
            {"kind": "no_wrong_verdicts"},
            {"kind": "outage_local"},
            {"kind": "path_served", "path": "remote", "min": 10},
            {"kind": "path_served", "path": "local", "min": 8},
            {"kind": "remote_attempts_bounded", "max": 6},
            {"kind": "failback"},
            {"kind": "breaker_sequence"},
            {"kind": "sli_present", "name": "failover_block_p99"},
            {"kind": "slo_green", "name": "failover_block_p99",
             "target": 0.25},
        ],
    }


def fleet(seed: int = 7) -> dict:
    """Three sharded verifyd replicas behind one FleetVerifier: 2,400
    placed client identities fill the fleet-wide admission bound (the
    overflow client hears a typed ``registry_full``), a hot replica's
    registry pressure drives re-routes and work steals, a replica kill
    mid-load is absorbed by the survivors with zero verdict divergence,
    a full blackout lands every request on the local farm, and the
    fleet probes its way back to remote serving — BLOCK-lane p99 green
    throughout, byte-identical digest across ``--repeat`` runs."""
    return {
        "name": "fleet", "engine": "fleet", "seed": seed,
        "waves": 18, "wave_interval_s": 0.5,
        "replicas": [
            # r0's own registry is tiny: registry_full sheds re-route
            # its placed clients and heat it up into a steal source
            {"name": "r0", "router_max_clients": 800,
             "service": {"max_clients": 6, "max_pending_items": 4096,
                         "workers": 2}},
            {"name": "r1", "router_max_clients": 800,
             "service": {"max_clients": 512, "max_pending_items": 4096,
                         "workers": 2}},
            {"name": "r2", "router_max_clients": 800,
             "service": {"max_clients": 512, "max_pending_items": 4096,
                         "workers": 2}},
        ],
        "clients": {"active_per_wave": 14, "pinned_hot": 3,
                    "overflow": 2, "items": [2, 4], "hot_replica": "r0",
                    "mix": {"sig": 6, "vrf": 1, "membership": 1,
                            "pow": 2}},
        "breaker": {"failure_budget": 2, "window_s": 60.0,
                    "cooldown_s": 1.0, "cooldown_cap_s": 2.0},
        "faults": {"kill": {"replica": "r1", "wave": 3,
                            "restore_wave": 7},
                   "blackout": {"wave": 11, "restore_wave": 13}},
        "workload": {"sigs": 48, "vrfs": 6, "posts": 2,
                     "memberships": 8, "pows": 10},
        "asserts": [
            {"kind": "no_wrong_verdicts"},
            {"kind": "typed_sheds_only", "reasons": ["registry_full"]},
            {"kind": "fleet_bound", "clients": 2400},
            {"kind": "shed", "client": "over-", "reason":
             "registry_full", "min": 18},
            {"kind": "reroutes", "min": 3},
            {"kind": "steals", "min": 3},
            {"kind": "path_served", "path": "remote", "min": 100},
            {"kind": "path_served", "path": "local", "min": 10},
            {"kind": "path_served", "replica": "r2", "min": 20},
            {"kind": "blackout_local"},
            {"kind": "dead_replica_attempts_bounded", "replica": "r1",
             "max": 8},
            {"kind": "breaker_sequence", "replica": "r1"},
            {"kind": "failback"},
            {"kind": "autoscale", "min_desired": 3},
            {"kind": "sli_present", "name": "fleet_block_p99"},
            {"kind": "sli_present",
             "name": "fleet_replica_r0_shed_per_sec"},
            {"kind": "slo_green", "name": "fleet_block_p99",
             "target": 0.25},
            {"kind": "merged_capture", "min_spans": 1},
        ],
    }


def runtime_degrade(seed: int = 3) -> dict:
    """Seeded device-dispatch fault plan through the runtime engine's
    breaker: open after the failure budget, host fallback carries the
    fault span bit-identically, device recovery re-closes."""
    return {
        "name": "runtime-degrade", "engine": "failover",
        "mode": "runtime-degrade", "seed": seed,
        "batches": 80, "inflight": 3, "step_s": 0.5,
        "fault": {"start": 10, "end": 30},
        "breaker": {"failure_budget": 3, "window_s": 120.0,
                    "cooldown_s": 2.0, "cooldown_cap_s": 6.0,
                    "recover_slack": 14},
        "asserts": [
            {"kind": "bit_identical"},
            {"kind": "device_attempts_bounded", "max": 10},
            {"kind": "fallbacks", "min": 15},
            {"kind": "breaker_sequence"},
            {"kind": "breaker_recloses"},
        ],
    }


_BUILTINS = {
    "smoke": smoke,
    "verifyd-load": verifyd_load,
    "crash-recovery": crash_recovery,
    "partition-heal": partition_heal,
    "storm-256": storm_256,
    "storm-1024": storm_1024,
    "storm-4096": storm_4096,
    "storm-512-bench": storm_512_bench,
    "eclipse-campaign": eclipse_campaign,
    "soak-epochs": soak_epochs,
    "crash-store": crash_store,
    "byzantine-verifyd": byzantine_verifyd,
    "timeskew-kill": timeskew_kill,
    "verifyd-outage": verifyd_outage,
    "runtime-degrade": runtime_degrade,
    "fleet": fleet,
}


def builtin_names() -> list[str]:
    return sorted(_BUILTINS)


def builtin(name: str, **kwargs) -> dict:
    try:
        builder = _BUILTINS[name]
    except KeyError:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"have {builtin_names()}") from None
    return builder(**kwargs)
