"""CLI: run scripted scenarios, compare replay digests.

    python -m spacemesh_tpu.sim --scenario partition-heal --seed 7
    python -m spacemesh_tpu.sim --scenario partition-heal --light 60 \
        --repeat 2            # replay determinism: digests must match
    python -m spacemesh_tpu.sim --script scenario.json --json out.json

``--repeat N`` runs the SAME script N times (fresh loop + fresh data
dirs each run) and exits non-zero unless every run's event digest is
byte-identical and every assertion held — the CI scenario-smoke
contract. A YAML script file works too when PyYAML is importable;
JSON always works.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .scenario import run_scenario
from .scenarios import builtin, builtin_names


def _load_script(path: str) -> dict:
    text = Path(path).read_text()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml  # type: ignore
        except ImportError as exc:
            raise SystemExit(
                f"{path} is not JSON and PyYAML is unavailable: {exc}")
        return yaml.safe_load(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spacemesh_tpu.sim",
        description="deterministic scenario engine (docs/SCENARIOS.md)")
    ap.add_argument("--scenario", choices=builtin_names(),
                    help="built-in scenario name")
    ap.add_argument("--script", help="path to a JSON/YAML script")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--light", type=int, default=None,
                    help="light-node count override")
    ap.add_argument("--shards", default=None,
                    help="worker-process count for the sharded fabric: "
                         "an integer, or 'auto' for min(cores, light//64) "
                         "(SPACEMESH_SIM_SHARDS overrides)")
    ap.add_argument("--repeat", type=int, default=1,
                    help="run N times; digests must be byte-identical")
    ap.add_argument("--json", dest="json_out",
                    help="write the (last) full result JSON here")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if bool(args.scenario) == bool(args.script):
        ap.error("exactly one of --scenario / --script is required")
    if args.scenario:
        kwargs = {}
        if args.seed is not None:
            kwargs["seed"] = args.seed
        if args.light is not None:
            kwargs["light"] = args.light
        script = builtin(args.scenario, **kwargs)
    else:
        script = _load_script(args.script)
        if args.seed is not None:
            script["seed"] = args.seed
    if args.shards is not None:
        script["shards"] = args.shards

    # script "engine" selects the runner: the network scenario engine
    # (default), the verifyd service-load engine (sim/verifyd_load.py),
    # the POST crash-recovery engine (sim/crash_recovery.py), the
    # self-healing failover engine (sim/failover.py), or the verifyd
    # fleet engine (sim/fleet.py)
    if script.get("engine") == "verifyd":
        from .verifyd_load import run_scenario as run_fn
    elif script.get("engine") == "crashrec":
        from .crash_recovery import run_scenario as run_fn
    elif script.get("engine") == "failover":
        from .failover import run_scenario as run_fn
    elif script.get("engine") == "fleet":
        from .fleet import run_scenario as run_fn
    else:
        run_fn = run_scenario

    digests, ok = [], True
    result = None
    for i in range(max(args.repeat, 1)):
        result = run_fn(script)
        digests.append(result.digest)
        ok = ok and result.ok
        failed = [a for a in result.asserts if not a["ok"]]
        print(f"run {i + 1}/{args.repeat}: digest={result.digest} "
              f"ok={result.ok}"
              + (f" failed={failed}" if failed else ""))
        if not args.quiet:
            for k, v in sorted(result.slis.items()):
                print(f"  sli {k}={v:.6f}")
            for k, v in sorted(result.stats.get("hub", {}).items()):
                print(f"  hub {k}={v}")
    if args.json_out and result is not None:
        Path(args.json_out).write_text(result.to_json())
    if len(set(digests)) != 1:
        print(f"DIGEST MISMATCH across {args.repeat} runs: {digests}",
              file=sys.stderr)
        return 2
    if not ok:
        print("scenario assertions failed", file=sys.stderr)
        return 1
    print(f"OK: {len(digests)} run(s), digest {digests[0]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
