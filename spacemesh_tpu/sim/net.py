"""Simulated network: topology, fault state, gossip hub, req/resp.

One :class:`SimNetwork` owns the ground truth the fault-injection layer
mutates — the scenario engine's scripted faults are all method calls
here, the in-proc analogue of the reference's systest chaos tooling
(iptables partitions, systest/chaos/partition.go) and of the transport's
own ``Host.chaos_block`` hooks:

* **topology**: a seeded ring+chords graph of degree ~k — gossip frames
  only travel along edges, so a partition really separates islands;
* **partition groups / eclipse / blocked links / downed nodes** decide
  :meth:`SimNetwork.reachable`;
* **link policies** (loss, delay, jitter, duplication, reorder) apply
  per send with the network's seeded RNG — deterministic on the virtual
  clock, every delayed delivery lands at an exact virtual instant.

:class:`MeshHub` is the pubsub hub surface (``PubSub._hub``) running the
REAL gossipsub-lite control plane (p2p/gossipmesh.py): per-node
degree-bounded topic meshes, GRAFT/PRUNE, lazy IHAVE/IWANT repair —
exactly what ``p2p/transport.py`` runs over sockets, minus the sockets.
:class:`SimNet` is the req/resp surface (``Server._net``); requests may
reach any live peer in the same partition group (the real transport
dials any learned address, so adjacency does not constrain req/resp).
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Iterable, Optional

from ..p2p.gossipmesh import (
    IHAVE,
    SEEN_CAP,
    GossipMesh,
    encode_ctrl,
    mark_seen,
)
from ..p2p.server import RequestError, Server


@dataclasses.dataclass
class LinkPolicy:
    """Per-link degradation; probabilities in [0,1], delays in virtual
    seconds. ``reorder`` is the probability a frame takes an extra
    ``reorder_delay`` detour — later frames overtake it."""

    loss: float = 0.0
    delay: float = 0.0
    jitter: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    reorder_delay: float = 0.5


class SimNetwork:
    """Topology + fault ground truth shared by MeshHub and SimNet."""

    def __init__(self, seed: int, *, degree: int = 6):
        self.seed = int(seed)
        self.degree = int(degree)
        self.rng = random.Random(("simnet", self.seed).__repr__())
        self.names: list[bytes] = []        # join order (deterministic)
        self.adj: dict[bytes, set[bytes]] = {}
        self.group: dict[bytes, int] = {}
        self.eclipsed: dict[bytes, frozenset] = {}
        self.blocked: set[frozenset] = set()
        self.down: set[bytes] = set()
        self.default_policy = LinkPolicy()
        self.link_policy: dict[frozenset, LinkPolicy] = {}
        self.stats = {"loss": 0, "dup": 0, "reorder": 0, "blocked": 0}

    # --- membership / topology ---------------------------------------

    def add_node(self, name: bytes) -> None:
        if name in self.adj:
            return
        self.names.append(name)
        self.adj[name] = set()
        self.group.setdefault(name, 0)

    def build_topology(self, degree: int | None = None) -> None:
        """Ring (connectivity guarantee) + seeded random chords up to
        ~``degree`` per node. Deterministic for a given (seed, join
        order)."""
        k = degree if degree is not None else self.degree
        n = len(self.names)
        for s in self.adj.values():
            s.clear()
        if n <= 1:
            return
        for i, a in enumerate(self.names):
            b = self.names[(i + 1) % n]
            self._connect(a, b)
        rng = random.Random(("topology", self.seed).__repr__())
        for a in self.names:
            tries = 0
            while len(self.adj[a]) < k and tries < 8 * k:
                tries += 1
                b = self.names[rng.randrange(n)]
                if b == a or b in self.adj[a] or len(self.adj[b]) >= k + 2:
                    continue
                self._connect(a, b)

    def _connect(self, a: bytes, b: bytes) -> None:
        self.adj[a].add(b)
        self.adj[b].add(a)

    # --- reachability -------------------------------------------------

    def alive(self, name: bytes) -> bool:
        return name in self.adj and name not in self.down

    def reachable(self, a: bytes, b: bytes) -> bool:
        """May a and b exchange ANY traffic right now (req/resp or a
        gossip edge, if one exists)?"""
        if a == b:
            return False
        if not self.alive(a) or not self.alive(b):
            return False
        if frozenset((a, b)) in self.blocked:
            return False
        if self.group.get(a, 0) != self.group.get(b, 0):
            return False
        ea, eb = self.eclipsed.get(a), self.eclipsed.get(b)
        if ea is not None and b not in ea:
            return False
        if eb is not None and a not in eb:
            return False
        return True

    def neighbors(self, name: bytes) -> set[bytes]:
        """Gossip-edge peers usable right now."""
        if not self.alive(name):
            return set()
        return {p for p in self.adj.get(name, ())
                if self.reachable(name, p)}

    def policy(self, a: bytes, b: bytes) -> LinkPolicy:
        return self.link_policy.get(frozenset((a, b)), self.default_policy)

    # --- the fault vocabulary ----------------------------------------

    def partition(self, groups: Iterable[Iterable[bytes]]) -> None:
        """Split the net: listed groups get ids 1..n, everyone else
        stays in group 0 (so an unlisted bulk forms its own island
        exactly when some nodes ARE listed)."""
        for name in self.group:
            self.group[name] = 0
        for gid, members in enumerate(groups, start=1):
            for name in members:
                self.group[name] = gid

    def heal(self) -> None:
        """Clear partitions, eclipses, and blocked links (downed nodes
        stay down — churn is a separate fault)."""
        for name in self.group:
            self.group[name] = 0
        self.eclipsed.clear()
        self.blocked.clear()

    def eclipse(self, victim: bytes, allowed: Iterable[bytes]) -> None:
        """The victim may only talk to ``allowed`` (its attackers)."""
        self.eclipsed[victim] = frozenset(allowed)

    def clear_eclipse(self, victim: bytes) -> None:
        self.eclipsed.pop(victim, None)

    def block_link(self, a: bytes, b: bytes) -> None:
        self.blocked.add(frozenset((a, b)))

    def unblock_link(self, a: bytes, b: bytes) -> None:
        self.blocked.discard(frozenset((a, b)))

    def set_down(self, name: bytes, is_down: bool = True) -> None:
        if is_down:
            self.down.add(name)
        else:
            self.down.discard(name)

    def set_link_policy(self, policy: LinkPolicy,
                        a: bytes | None = None,
                        b: bytes | None = None) -> None:
        """Set one link's policy, or the network default (a=b=None)."""
        if a is None and b is None:
            self.default_policy = policy
        else:
            self.link_policy[frozenset((a, b))] = policy


class MeshHub:
    """Gossip over SimNetwork edges with the gossipsub-lite control
    plane: per-node topic meshes, eager push along the mesh, lazy
    IHAVE/IWANT repair on :meth:`heartbeat`. The ``PubSub._hub``
    surface, like LoopbackHub — but topology-aware and fault-injected.
    """

    def __init__(self, network: SimNetwork, *, gossip_degree: int = 4):
        self.network = network
        self.gossip_degree = gossip_degree
        self._nodes: dict[bytes, object] = {}      # name -> PubSub
        self._gossip: dict[bytes, GossipMesh] = {}
        self._seen: dict[bytes, dict[bytes, None]] = {}
        self._inboxes: dict[bytes, asyncio.Queue] = {}
        self._consumers: dict[bytes, asyncio.Task] = {}
        self.stats = {"published": 0, "delivered": 0, "dup": 0,
                      "rejected": 0, "relayed": 0, "ihave": 0,
                      "iwant_served": 0, "dropped": 0}

    # --- membership ----------------------------------------------------

    def join(self, ps) -> None:
        name = ps.name
        ps._hub = self
        self.network.add_node(name)
        self._nodes[name] = ps
        d = self.gossip_degree
        self._gossip[name] = GossipMesh(
            degree=d, d_lo=max(2, d - 1), d_hi=d + 2,
            rng=random.Random(("gossip", self.network.seed, name)
                              .__repr__()))
        self._seen[name] = {}
        self._ensure_consumer(name)

    def leave(self, ps) -> None:
        self.suspend(ps.name)
        self._nodes.pop(ps.name, None)

    def suspend(self, name: bytes) -> None:
        """Churn: the node's consumer dies and queued frames are lost
        (its identity and stores survive for a later :meth:`resume`)."""
        task = self._consumers.pop(name, None)
        if task is not None:
            task.cancel()
        self._inboxes.pop(name, None)
        self.network.set_down(name, True)

    def resume(self, name: bytes) -> None:
        self.network.set_down(name, False)
        if name in self._nodes:
            self._ensure_consumer(name)

    def _ensure_consumer(self, name: bytes) -> None:
        if name in self._consumers and not self._consumers[name].done():
            return
        q = self._inboxes.get(name)
        if q is None:
            q = self._inboxes[name] = asyncio.Queue()
        self._consumers[name] = asyncio.ensure_future(
            self._consume(name, q))

    # --- data plane ----------------------------------------------------

    async def broadcast(self, sender, topic: str, data: bytes) -> None:
        """PubSub._hub surface: the publisher floods its topic mesh."""
        from ..core.hashing import sum256

        name = sender.name
        if not self.network.alive(name):
            return
        msg_id = sum256(topic.encode(), data)
        self._mark_seen(name, msg_id)
        mesh = self._gossip.get(name)
        if mesh is None:
            return
        mesh.on_message(msg_id, topic, (topic, msg_id, data))
        self.stats["published"] += 1
        targets = mesh.eager_targets(topic, self.network.neighbors(name))
        for dst in targets:
            self._send(name, dst, ("msg", name, (topic, msg_id, data)))

    def _mark_seen(self, name: bytes, msg_id: bytes) -> bool:
        # the transport's exact dedup policy (shared helper), per node
        return mark_seen(self._seen[name], msg_id, SEEN_CAP)

    def _send(self, src: bytes, dst: bytes, item: tuple) -> None:
        """One frame over one link, with the link's fault policy."""
        net = self.network
        if not net.reachable(src, dst):
            self.stats["dropped"] += 1
            net.stats["blocked"] += 1
            return
        q = self._inboxes.get(dst)
        if q is None:
            self.stats["dropped"] += 1
            return
        pol = net.policy(src, dst)
        rng = net.rng
        copies = 1
        if pol.loss and rng.random() < pol.loss:
            net.stats["loss"] += 1
            return
        if pol.dup and rng.random() < pol.dup:
            net.stats["dup"] += 1
            copies = 2
        for _ in range(copies):
            delay = pol.delay
            if pol.jitter:
                delay += rng.random() * pol.jitter
            if pol.reorder and rng.random() < pol.reorder:
                net.stats["reorder"] += 1
                delay += pol.reorder_delay
            if delay > 0:
                asyncio.get_running_loop().call_later(
                    delay, self._deliver_later, dst, q, item)
            else:
                q.put_nowait(item)

    def _deliver_later(self, dst: bytes, q: asyncio.Queue,
                       item: tuple) -> None:
        # the node may have churned (and its queue been replaced) while
        # the frame was in flight — deliver only to the live queue
        if self._inboxes.get(dst) is q:
            q.put_nowait(item)

    async def _consume(self, name: bytes, q: asyncio.Queue) -> None:
        while True:
            kind, src, payload = await q.get()
            try:
                if kind == "msg":
                    await self._on_msg(name, src, payload)
                else:
                    self._on_ctrl(name, src, payload)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — bad frame must not kill the node
                pass
            finally:
                q.task_done()

    async def _on_msg(self, name: bytes, src: bytes, frame: tuple) -> None:
        topic, msg_id, data = frame
        if not self._mark_seen(name, msg_id):
            self.stats["dup"] += 1
            return
        mesh = self._gossip[name]
        mesh.on_message(msg_id, topic, frame)
        ps = self._nodes.get(name)
        if ps is None:
            return
        ok = await ps.deliver(topic, src, data)
        self.stats["delivered"] += 1
        if ok is True:
            targets = mesh.eager_targets(
                topic, self.network.neighbors(name), exclude=src)
            for dst in targets:
                self.stats["relayed"] += 1
                self._send(name, dst, ("msg", name, frame))
        elif ok is False:
            self.stats["rejected"] += 1

    # --- control plane -------------------------------------------------

    def _on_ctrl(self, name: bytes, src: bytes, payload: bytes) -> None:
        mesh = self._gossip[name]
        seen = self._seen[name]
        replies = mesh.on_control(src, payload,
                                  seen=lambda mid: mid in seen)
        for subtype, topic, ids in replies:
            if subtype == -1:  # answer IWANT with the full frames
                for mid in ids:
                    frame = mesh.cache.get(mid)
                    if frame is not None:
                        self.stats["iwant_served"] += 1
                        self._send(name, src, ("msg", name, frame))
            else:
                self._send(name, src,
                           ("ctrl", name, encode_ctrl(subtype, topic, ids)))

    def heartbeat(self) -> None:
        """One gossip heartbeat for every live node: mesh maintenance
        (GRAFT/PRUNE) + lazy IHAVE. The scenario engine calls this on a
        virtual-time cadence."""
        for name in list(self._nodes):
            if not self.network.alive(name):
                continue
            mesh = self._gossip[name]
            sends = mesh.heartbeat(self.network.neighbors(name))
            for peer, subtype, topic, ids in sends:
                if subtype == IHAVE:
                    self.stats["ihave"] += 1
                self._send(name, peer,
                           ("ctrl", name, encode_ctrl(subtype, topic, ids)))

    async def drain(self) -> None:
        """Wait until every queued frame is fully processed."""
        await asyncio.gather(*(q.join() for q in self._inboxes.values()))


class _NetView:
    """One server's view of the SimNet: ``nodes`` lists only peers it
    can currently reach (partition/eclipse/down honored), so
    ``Server.peers()`` and everything built on it (fetch peer
    selection, peersync quorums) see the faulted world."""

    def __init__(self, simnet: "SimNet", me: bytes):
        self._simnet = simnet
        self._me = me

    @property
    def nodes(self) -> dict[bytes, Server]:
        net = self._simnet.network
        return {n: s for n, s in self._simnet.servers.items()
                if n == self._me or net.reachable(self._me, n)}

    async def route(self, src: bytes, dst: bytes, protocol: str,
                    data: bytes) -> bytes:
        return await self._simnet.route(src, dst, protocol, data)


class SimNet:
    """Req/resp transport over the SimNetwork (``Server._net``)."""

    def __init__(self, network: SimNetwork):
        self.network = network
        self.servers: dict[bytes, Server] = {}

    def join(self, server: Server) -> None:
        self.network.add_node(server.node_id)
        self.servers[server.node_id] = server
        server._net = _NetView(self, server.node_id)

    def leave(self, server: Server) -> None:
        self.servers.pop(server.node_id, None)
        server._net = None

    async def route(self, src: bytes, dst: bytes, protocol: str,
                    data: bytes) -> bytes:
        net = self.network
        target = self.servers.get(dst)
        if target is None or not net.reachable(src, dst):
            raise RequestError(f"peer {dst.hex()[:8]} not reachable")
        pol = net.policy(src, dst)
        if pol.loss and net.rng.random() < pol.loss:
            net.stats["loss"] += 1
            raise RequestError(f"request to {dst.hex()[:8]} lost (chaos)")
        delay = pol.delay + (net.rng.random() * pol.jitter
                             if pol.jitter else 0.0)
        if delay > 0:
            await asyncio.sleep(delay)  # virtual under VirtualClockLoop
        return await target.handle(protocol, src, data)
