"""Simulated network: topology, fault state, gossip hub, req/resp.

One :class:`SimNetwork` owns the ground truth the fault-injection layer
mutates — the scenario engine's scripted faults are all method calls
here, the in-proc analogue of the reference's systest chaos tooling
(iptables partitions, systest/chaos/partition.go) and of the transport's
own ``Host.chaos_block`` hooks:

* **topology**: a seeded ring+chords graph of degree ~k — gossip frames
  only travel along edges, so a partition really separates islands;
* **partition groups / eclipse / blocked links / downed nodes** decide
  :meth:`SimNetwork.reachable`;
* **link policies** (loss, delay, jitter, duplication, reorder) apply
  per send with the network's seeded RNG — deterministic on the virtual
  clock, every delayed delivery lands at an exact virtual instant.

Reachability, neighbor sets, and link policies are memoized behind a
**fault epoch**: every fault mutator bumps :attr:`SimNetwork.epoch` and
clears the caches, so the per-frame path between faults is dict lookups
(storm-256 resolved ``reachable`` 2.3M times; almost all of them hit).

Two hub fabrics implement the pubsub surface (``PubSub._hub``), both
running the gossipsub-lite control plane of p2p/gossipmesh.py for mesh
nodes:

* :class:`EventMeshHub` (default) — a single virtual-time **event
  wheel** (calendar queue keyed on delivery instants, ties broken by
  (instant, seq)) plus per-node inbox deques drained by on-demand
  tasks: a node with an empty inbox costs zero. Light relays skip the
  control plane entirely — they forward along deterministic sparse
  per-topic relay sets — and ``heartbeat()`` only visits the dirty set
  of mesh nodes with pending GRAFT/PRUNE/IHAVE work. Cost scales with
  edges that matter, not population.
* :class:`LegacyMeshHub` — the original one-consumer-task-per-node hub,
  kept behind ``SPACEMESH_SIM_FABRIC=legacy`` as the bench baseline for
  the ``sim_fabric_events_per_sec`` vs_legacy ratio.

:class:`SimNet` is the req/resp surface (``Server._net``); requests may
reach any live peer in the same partition group (the real transport
dials any learned address, so adjacency does not constrain req/resp).
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import heapq
import itertools
import os
import random
from typing import Iterable, Optional

from ..p2p.gossipmesh import (
    IHAVE,
    SEEN_CAP,
    GossipMesh,
    encode_ctrl,
    mark_seen,
    relay_sample,
)
from ..p2p.server import RequestError, Server
from ..utils import metrics


@dataclasses.dataclass
class LinkPolicy:
    """Per-link degradation; probabilities in [0,1], delays in virtual
    seconds. ``reorder`` is the probability a frame takes an extra
    ``reorder_delay`` detour — later frames overtake it."""

    loss: float = 0.0
    delay: float = 0.0
    jitter: float = 0.0
    dup: float = 0.0
    reorder: float = 0.0
    reorder_delay: float = 0.5


class SimNetwork:
    """Topology + fault ground truth shared by MeshHub and SimNet.

    All read paths (:meth:`reachable`, :meth:`neighbors`,
    :meth:`policy`) memoize per fault epoch: any mutator bumps
    :attr:`epoch` and clears the memos, so between faults every lookup
    is O(1) no matter how hostile the world is."""

    def __init__(self, seed: int, *, degree: int = 6):
        self.seed = int(seed)
        self.degree = int(degree)
        self.rng = random.Random(("simnet", self.seed).__repr__())
        self.names: list[bytes] = []        # join order (deterministic)
        self.adj: dict[bytes, set[bytes]] = {}
        self.group: dict[bytes, int] = {}
        self.eclipsed: dict[bytes, frozenset] = {}
        self.blocked: set[frozenset] = set()
        self.down: set[bytes] = set()
        self.default_policy = LinkPolicy()
        self.link_policy: dict[frozenset, LinkPolicy] = {}
        self.stats = {"loss": 0, "dup": 0, "reorder": 0, "blocked": 0}
        self.epoch = 0
        self.cache_stats = {"hit": 0, "miss": 0}
        self._reach_cache: dict[tuple[bytes, bytes], bool] = {}
        self._nbr_cache: dict[bytes, frozenset] = {}
        self._pol_cache: dict[tuple[bytes, bytes], LinkPolicy] = {}
        # Optional fault-mutation listener (sim/shard.py): called as
        # listener(method_name, args_tuple) AFTER each mutator applies,
        # so shard workers can replay the mutation on their replica
        # SimNetwork at the same virtual instant.
        self.listener = None

    def _bump_epoch(self) -> None:
        self.epoch += 1
        self._reach_cache.clear()
        self._nbr_cache.clear()
        self._pol_cache.clear()

    def _notify(self, method: str, args: tuple) -> None:
        if self.listener is not None:
            self.listener(method, args)

    def min_delay_floor(self) -> float:
        """Conservative cross-shard lookahead: the smallest delay any
        link policy currently in force could apply to a frame. Jitter
        and reorder only ADD delay, so ``delay`` itself is the floor."""
        floor = self.default_policy.delay
        for pol in self.link_policy.values():
            floor = min(floor, pol.delay)
        return max(0.0, floor)

    # --- membership / topology ---------------------------------------

    def add_node(self, name: bytes) -> None:
        if name in self.adj:
            return
        self.names.append(name)
        self.adj[name] = set()
        self.group.setdefault(name, 0)
        self._bump_epoch()

    def build_topology(self, degree: int | None = None) -> None:
        """Ring (connectivity guarantee) + seeded random chords up to
        ~``degree`` per node. Deterministic for a given (seed, join
        order)."""
        k = degree if degree is not None else self.degree
        n = len(self.names)
        for s in self.adj.values():
            s.clear()
        self._bump_epoch()
        if n <= 1:
            return
        for i, a in enumerate(self.names):
            b = self.names[(i + 1) % n]
            self._connect(a, b)
        rng = random.Random(("topology", self.seed).__repr__())
        for a in self.names:
            tries = 0
            while len(self.adj[a]) < k and tries < 8 * k:
                tries += 1
                b = self.names[rng.randrange(n)]
                if b == a or b in self.adj[a] or len(self.adj[b]) >= k + 2:
                    continue
                self._connect(a, b)

    def _connect(self, a: bytes, b: bytes) -> None:
        self.adj[a].add(b)
        self.adj[b].add(a)

    # --- reachability -------------------------------------------------

    def alive(self, name: bytes) -> bool:
        return name in self.adj and name not in self.down

    def reachable(self, a: bytes, b: bytes) -> bool:
        """May a and b exchange ANY traffic right now (req/resp or a
        gossip edge, if one exists)? Memoized per fault epoch —
        reachability is symmetric, so one resolve fills both
        directions."""
        r = self._reach_cache.get((a, b))
        if r is not None:
            self.cache_stats["hit"] += 1
            return r
        self.cache_stats["miss"] += 1
        r = self._reachable(a, b)
        self._reach_cache[(a, b)] = r
        self._reach_cache[(b, a)] = r
        return r

    def _reachable(self, a: bytes, b: bytes) -> bool:
        if a == b:
            return False
        if not self.alive(a) or not self.alive(b):
            return False
        if frozenset((a, b)) in self.blocked:
            return False
        if self.group.get(a, 0) != self.group.get(b, 0):
            return False
        ea, eb = self.eclipsed.get(a), self.eclipsed.get(b)
        if ea is not None and b not in ea:
            return False
        if eb is not None and a not in eb:
            return False
        return True

    def neighbors(self, name: bytes) -> frozenset:
        """Gossip-edge peers usable right now (memoized per epoch)."""
        nbrs = self._nbr_cache.get(name)
        if nbrs is not None:
            self.cache_stats["hit"] += 1
            return nbrs
        self.cache_stats["miss"] += 1
        if not self.alive(name):
            nbrs = frozenset()
        else:
            nbrs = frozenset(p for p in self.adj.get(name, ())
                             if self.reachable(name, p))
        self._nbr_cache[name] = nbrs
        return nbrs

    def policy(self, a: bytes, b: bytes) -> LinkPolicy:
        pol = self._pol_cache.get((a, b))
        if pol is None:
            pol = self.link_policy.get(frozenset((a, b)),
                                       self.default_policy)
            self._pol_cache[(a, b)] = pol
            self._pol_cache[(b, a)] = pol
        return pol

    # --- the fault vocabulary ----------------------------------------

    def partition(self, groups: Iterable[Iterable[bytes]]) -> None:
        """Split the net: listed groups get ids 1..n, everyone else
        stays in group 0 (so an unlisted bulk forms its own island
        exactly when some nodes ARE listed)."""
        groups = [list(members) for members in groups]
        for name in self.group:
            self.group[name] = 0
        for gid, members in enumerate(groups, start=1):
            for name in members:
                self.group[name] = gid
        self._bump_epoch()
        self._notify("partition", (groups,))

    def heal(self) -> None:
        """Clear partitions, eclipses, and blocked links (downed nodes
        stay down — churn is a separate fault)."""
        for name in self.group:
            self.group[name] = 0
        self.eclipsed.clear()
        self.blocked.clear()
        self._bump_epoch()
        self._notify("heal", ())

    def eclipse(self, victim: bytes, allowed: Iterable[bytes]) -> None:
        """The victim may only talk to ``allowed`` (its attackers)."""
        self.eclipsed[victim] = frozenset(allowed)
        self._bump_epoch()
        self._notify("eclipse", (victim, sorted(self.eclipsed[victim])))

    def clear_eclipse(self, victim: bytes) -> None:
        self.eclipsed.pop(victim, None)
        self._bump_epoch()
        self._notify("clear_eclipse", (victim,))

    def block_link(self, a: bytes, b: bytes) -> None:
        self.blocked.add(frozenset((a, b)))
        self._bump_epoch()
        self._notify("block_link", (a, b))

    def unblock_link(self, a: bytes, b: bytes) -> None:
        self.blocked.discard(frozenset((a, b)))
        self._bump_epoch()
        self._notify("unblock_link", (a, b))

    def set_down(self, name: bytes, is_down: bool = True) -> None:
        if is_down:
            self.down.add(name)
        else:
            self.down.discard(name)
        self._bump_epoch()
        self._notify("set_down", (name, is_down))

    def set_link_policy(self, policy: LinkPolicy,
                        a: bytes | None = None,
                        b: bytes | None = None) -> None:
        """Set one link's policy, or the network default (a=b=None)."""
        if a is None and b is None:
            self.default_policy = policy
        else:
            self.link_policy[frozenset((a, b))] = policy
        self._bump_epoch()
        self._notify("set_link_policy", (dataclasses.asdict(policy), a, b))


class EventMeshHub:
    """Event-driven gossip fabric: one virtual-time wheel, zero cost
    for idle nodes.

    * **Delivery** goes straight onto the destination's inbox deque
      (delay 0) or into the calendar queue ``_wheel`` keyed on
      ``(delivery instant, seq)`` — the seq tie-break makes pop order
      deterministic. A per-node drainer task exists only while that
      node's inbox is non-empty.
    * **Churn** bumps the node's incarnation counter; wheel frames
      scheduled for an earlier incarnation are dropped on pop, so a
      resumed node never sees pre-crash traffic (same semantics as the
      legacy hub replacing the inbox queue).
    * **Light relays** (``join(..., light=True)``) run no gossipsub
      control plane at all: they dedup, deliver, and forward along a
      deterministic sparse relay set (p2p/gossipmesh.relay_sample) of
      their current neighbors, recomputed only when the fault epoch
      moves.
    * **heartbeat()** visits only the dirty set: mesh nodes with
      pending control-plane work (new traffic, received control
      frames, or a fault-epoch change). A quiet node costs nothing.
    """

    light_control_plane = False

    def __init__(self, network: SimNetwork, *, gossip_degree: int = 4):
        self.network = network
        self.gossip_degree = gossip_degree
        self._nodes: dict[bytes, object] = {}      # name -> PubSub
        self._gossip: dict[bytes, GossipMesh] = {}  # mesh (non-light) only
        self._light: set[bytes] = set()
        self._seen: dict[bytes, dict[bytes, None]] = {}
        self._inbox: dict[bytes, collections.deque] = {}
        self._gen: dict[bytes, int] = {}           # incarnation per name
        self._drainers: dict[bytes, asyncio.Task] = {}
        self._wheel: list[tuple] = []              # (instant, seq, dst, gen, item)
        self._seq = itertools.count()
        self._timer: asyncio.TimerHandle | None = None
        self._timer_due = float("inf")
        self._light_ready: collections.deque = collections.deque()
        self._light_task: asyncio.Task | None = None
        self._dirty: set[bytes] = set()
        self._hb_epoch = -1
        self._relay_cache: dict[tuple[bytes, str], tuple[int, tuple]] = {}
        self.stats = {"published": 0, "delivered": 0, "dup": 0,
                      "rejected": 0, "relayed": 0, "ihave": 0,
                      "iwant_served": 0, "dropped": 0,
                      "events_scheduled": 0, "events_fired": 0,
                      "hb_visits": 0}
        self._flushed: dict[str, int] = {}

    # --- membership ----------------------------------------------------

    def join(self, ps, *, light: bool = False) -> None:
        name = ps.name
        ps._hub = self
        self.network.add_node(name)
        self._nodes[name] = ps
        self._seen[name] = {}
        self._inbox[name] = collections.deque()
        self._gen[name] = self._gen.get(name, 0) + 1
        if light:
            self._light.add(name)
            self._gossip.pop(name, None)
            return
        self._light.discard(name)
        d = self.gossip_degree
        self._gossip[name] = GossipMesh(
            degree=d, d_lo=max(2, d - 1), d_hi=d + 2,
            rng=random.Random(("gossip", self.network.seed, name)
                              .__repr__()))
        self._dirty.add(name)

    def leave(self, ps) -> None:
        self.suspend(ps.name)
        self._nodes.pop(ps.name, None)
        self._gossip.pop(ps.name, None)
        self._light.discard(ps.name)
        self._seen.pop(ps.name, None)
        self._inbox.pop(ps.name, None)

    def suspend(self, name: bytes) -> None:
        """Churn: queued and in-flight frames are lost (identity and
        stores survive for a later :meth:`resume`)."""
        task = self._drainers.pop(name, None)
        if task is not None:
            task.cancel()
        inbox = self._inbox.get(name)
        if inbox:
            self.stats["dropped"] += len(inbox)
            inbox.clear()
        self._gen[name] = self._gen.get(name, 0) + 1
        self._dirty.discard(name)
        self.network.set_down(name, True)

    def resume(self, name: bytes) -> None:
        self.network.set_down(name, False)
        if name in self._gossip:
            self._dirty.add(name)

    # --- data plane ----------------------------------------------------

    async def broadcast(self, sender, topic: str, data: bytes) -> None:
        """PubSub._hub surface: the publisher floods its topic mesh (or,
        for a light relay, its sparse relay set)."""
        from ..core.hashing import sum256

        name = sender.name
        if name not in self._nodes or not self.network.alive(name):
            return
        msg_id = sum256(topic.encode(), data)
        self._mark_seen(name, msg_id)
        self.stats["published"] += 1
        frame = (topic, msg_id, data)
        if name in self._light:
            targets = self._relay_targets(name, topic)
        else:
            mesh = self._gossip[name]
            mesh.on_message(msg_id, topic, frame)
            self._dirty.add(name)
            targets = mesh.eager_targets(topic,
                                         self.network.neighbors(name))
        for dst in targets:
            self._send(name, dst, ("msg", name, frame))

    def _mark_seen(self, name: bytes, msg_id: bytes) -> bool:
        # the transport's exact dedup policy (shared helper), per node
        return mark_seen(self._seen[name], msg_id, SEEN_CAP)

    def _relay_targets(self, name: bytes, topic: str,
                       exclude: bytes | None = None):
        """Light relay's per-topic forward set — deterministic
        (sha256-ranked, cross-process stable) and cached until the
        fault epoch moves."""
        key = (name, topic)
        ent = self._relay_cache.get(key)
        if ent is None or ent[0] != self.network.epoch:
            ent = (self.network.epoch,
                   relay_sample(topic, name, self.network.neighbors(name),
                                self.gossip_degree))
            self._relay_cache[key] = ent
        if exclude is None:
            return ent[1]
        return [p for p in ent[1] if p != exclude]

    def _send(self, src: bytes, dst: bytes, item: tuple) -> None:
        """One frame over one link, with the link's fault policy. The
        RNG draw order matches LegacyMeshHub exactly so both fabrics
        replay the same world from the same seed."""
        net = self.network
        if not net.reachable(src, dst):
            self.stats["dropped"] += 1
            net.stats["blocked"] += 1
            return
        inbox = self._inbox.get(dst)
        if inbox is None:
            self.stats["dropped"] += 1
            return
        pol = net.policy(src, dst)
        rng = net.rng
        copies = 1
        if pol.loss and rng.random() < pol.loss:
            net.stats["loss"] += 1
            return
        if pol.dup and rng.random() < pol.dup:
            net.stats["dup"] += 1
            copies = 2
        for _ in range(copies):
            delay = pol.delay
            if pol.jitter:
                delay += rng.random() * pol.jitter
            if pol.reorder and rng.random() < pol.reorder:
                net.stats["reorder"] += 1
                delay += pol.reorder_delay
            if delay > 0:
                self._schedule(delay, dst, item)
            else:
                self._deliver_now(dst, item)

    def _deliver_now(self, dst: bytes, item: tuple) -> None:
        """Hand a frame to its consumer. Light relays — the node-count
        majority — share ONE long-lived drainer fed by a global FIFO
        (their handlers never truly suspend, so head-of-line cost is
        nil); that kills the task-per-burst churn a per-node drainer
        pays. Mesh nodes keep per-node drainers so one node's slow
        validator never delays another's."""
        if dst in self._light:
            self._light_ready.append((dst, self._gen.get(dst, 0), item))
            t = self._light_task
            if t is None or t.done():
                self._light_task = asyncio.ensure_future(
                    self._drain_lights())
        else:
            self._inbox[dst].append(item)
            self._ensure_drainer(dst)

    # --- the event wheel ------------------------------------------------

    def _schedule(self, delay: float, dst: bytes, item: tuple) -> None:
        loop = asyncio.get_running_loop()
        # spacecheck: ok=SC001 wheel instants must share call_at's timebase; under the sim that loop IS the engine's VirtualClockLoop
        due = loop.time() + delay
        heapq.heappush(self._wheel, (due, next(self._seq), dst,
                                     self._gen.get(dst, 0), item))
        self.stats["events_scheduled"] += 1
        # ONE loop timer serves the whole wheel, re-armed only when a new
        # head undercuts it (delays are near-constant per policy, so this
        # is rare). A consumer-task design wakes and re-arms a wait_for
        # on EVERY schedule — measured 4.5 loop iterations per frame at
        # 1024 nodes, dwarfing the actual delivery work.
        if self._timer is None or due < self._timer_due:
            self._arm(loop, due)

    def _schedule_at(self, instant: float, dst: bytes, item: tuple) -> None:
        """Wheel insert at an ABSOLUTE virtual instant (cross-shard
        frames arrive tagged with their delivery instant; re-deriving a
        relative delay would lose determinism to float round-trips)."""
        loop = asyncio.get_running_loop()
        heapq.heappush(self._wheel, (instant, next(self._seq), dst,
                                     self._gen.get(dst, 0), item))
        self.stats["events_scheduled"] += 1
        if self._timer is None or instant < self._timer_due:
            self._arm(loop, instant)

    def _arm(self, loop, due: float) -> None:
        if self._timer is not None:
            self._timer.cancel()
        self._timer_due = due
        self._timer = loop.call_at(due, self._fire)

    def _fire(self) -> None:
        """Wheel timer callback: move every due frame onto its
        destination inbox in (instant, seq) order, then re-arm for the
        next delivery instant (a virtual-clock jump, zero wall cost)."""
        loop = asyncio.get_running_loop()
        self._timer = None
        now = loop.time()  # spacecheck: ok=SC001 same wheel timebase as _schedule
        wheel = self._wheel
        while wheel and wheel[0][0] <= now:
            _, _, dst, gen, item = heapq.heappop(wheel)
            self.stats["events_fired"] += 1
            if self._gen.get(dst) != gen:
                self.stats["dropped"] += 1  # churned while in flight
                continue
            if dst not in self._nodes:
                self.stats["dropped"] += 1
                continue
            self._deliver_now(dst, item)
        if wheel:
            self._arm(loop, wheel[0][0])
        else:
            self._timer_due = float("inf")

    def _ensure_drainer(self, name: bytes) -> None:
        if name in self._drainers:
            return
        self._drainers[name] = asyncio.ensure_future(
            self._drain_node(name))

    async def _drain_lights(self) -> None:
        """The shared light-relay consumer: global FIFO, frames from a
        since-churned incarnation dropped by generation check."""
        q = self._light_ready
        try:
            while q:
                name, gen, (kind, src, payload) = q.popleft()
                if self._gen.get(name) != gen:
                    self.stats["dropped"] += 1  # churned while queued
                    continue
                try:
                    if kind == "msg":
                        await self._on_msg(name, src, payload)
                    else:
                        self._on_ctrl(name, src, payload)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — bad frame must not kill the fabric
                    pass
        finally:
            if self._light_task is asyncio.current_task():
                self._light_task = None

    async def _drain_node(self, name: bytes) -> None:
        inbox = self._inbox.get(name)
        try:
            while inbox:
                kind, src, payload = inbox.popleft()
                try:
                    if kind == "msg":
                        await self._on_msg(name, src, payload)
                    else:
                        self._on_ctrl(name, src, payload)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — bad frame must not kill the node
                    pass
        finally:
            # no await between the emptiness check and this unlink, so a
            # frame can't slip in unobserved; a replacement drainer
            # (post-churn) must not be unlinked by the cancelled one
            if self._drainers.get(name) is asyncio.current_task():
                del self._drainers[name]

    async def _on_msg(self, name: bytes, src: bytes, frame: tuple) -> None:
        topic, msg_id, data = frame
        if not self._mark_seen(name, msg_id):
            self.stats["dup"] += 1
            return
        light = name in self._light
        if not light:
            mesh = self._gossip[name]
            mesh.on_message(msg_id, topic, frame)
            self._dirty.add(name)
        ps = self._nodes.get(name)
        if ps is None:
            return
        ok = await ps.deliver(topic, src, data)
        self.stats["delivered"] += 1
        if ok is True:
            if light:
                targets = self._relay_targets(name, topic, exclude=src)
            else:
                targets = mesh.eager_targets(
                    topic, self.network.neighbors(name), exclude=src)
            for dst in targets:
                self.stats["relayed"] += 1
                self._send(name, dst, ("msg", name, frame))
        elif ok is False:
            self.stats["rejected"] += 1

    # --- control plane -------------------------------------------------

    def _on_ctrl(self, name: bytes, src: bytes, payload: bytes) -> None:
        if name in self._light:
            return  # light relays run no control plane
        mesh = self._gossip[name]
        self._dirty.add(name)
        seen = self._seen[name]
        replies = mesh.on_control(src, payload,
                                  seen=lambda mid: mid in seen)
        for subtype, topic, ids in replies:
            if subtype == -1:  # answer IWANT with the full frames
                for mid in ids:
                    frame = mesh.cache.get(mid)
                    if frame is not None:
                        self.stats["iwant_served"] += 1
                        self._send(name, src, ("msg", name, frame))
            else:
                self._send(name, src,
                           ("ctrl", name, encode_ctrl(subtype, topic, ids)))

    def heartbeat(self) -> None:
        """One gossip heartbeat over the DIRTY mesh nodes only. A fault
        epoch change re-dirties every live mesh node (neighbor sets
        moved); a node leaves the set when a beat produced no control
        sends and its message cache has fully aged out."""
        net = self.network
        if self._hb_epoch != net.epoch:
            self._hb_epoch = net.epoch
            self._dirty.update(n for n in self._gossip if net.alive(n))
        if not self._dirty:
            self._flush_metrics()
            return
        dirty = self._dirty
        for name in [n for n in self._gossip if n in dirty]:
            if not net.alive(name):
                dirty.discard(name)
                continue
            mesh = self._gossip[name]
            self.stats["hb_visits"] += 1
            sends = mesh.heartbeat(net.neighbors(name))
            for peer, subtype, topic, ids in sends:
                if subtype == IHAVE:
                    self.stats["ihave"] += 1
                self._send(name, peer,
                           ("ctrl", name, encode_ctrl(subtype, topic, ids)))
            if not sends and mesh.cache.empty():
                dirty.discard(name)
        metrics.sim_fabric_dirty.set(len(dirty))
        self._flush_metrics()

    def _flush_metrics(self) -> None:
        """Publish fabric counter deltas to the shared registry (hot
        paths bump plain ints; the registry sees them once per beat)."""
        for kind, key in (("scheduled", "events_scheduled"),
                          ("fired", "events_fired")):
            delta = self.stats[key] - self._flushed.get(key, 0)
            if delta:
                metrics.sim_fabric_events.inc(delta, kind=kind)
                self._flushed[key] = self.stats[key]
        cs = self.network.cache_stats
        for result in ("hit", "miss"):
            delta = cs[result] - self._flushed.get(result, 0)
            if delta:
                metrics.sim_fabric_cache.inc(delta, result=result)
                self._flushed[result] = cs[result]

    async def drain(self) -> None:
        """Wait until every queued frame is fully processed (in-wheel
        frames wait for their delivery instant, exactly like the legacy
        hub's call_later frames)."""
        while self._drainers or self._light_task is not None:
            tasks = list(self._drainers.values())
            if self._light_task is not None:
                tasks.append(self._light_task)
            await asyncio.gather(*tasks, return_exceptions=True)


class LegacyMeshHub:
    """The original fabric: gossip over SimNetwork edges with one
    always-on consumer task and one queue per node. O(nodes) per beat
    and per hop — kept as the ``SPACEMESH_SIM_FABRIC=legacy`` baseline
    the event fabric's speedup is measured against.
    """

    light_control_plane = True

    def __init__(self, network: SimNetwork, *, gossip_degree: int = 4):
        self.network = network
        self.gossip_degree = gossip_degree
        self._nodes: dict[bytes, object] = {}      # name -> PubSub
        self._gossip: dict[bytes, GossipMesh] = {}
        self._seen: dict[bytes, dict[bytes, None]] = {}
        self._inboxes: dict[bytes, asyncio.Queue] = {}
        self._consumers: dict[bytes, asyncio.Task] = {}
        self.stats = {"published": 0, "delivered": 0, "dup": 0,
                      "rejected": 0, "relayed": 0, "ihave": 0,
                      "iwant_served": 0, "dropped": 0}

    # --- membership ----------------------------------------------------

    def join(self, ps, *, light: bool = False) -> None:
        # ``light`` is accepted for surface parity and ignored: the
        # legacy fabric runs the full control plane on every node
        name = ps.name
        ps._hub = self
        self.network.add_node(name)
        self._nodes[name] = ps
        d = self.gossip_degree
        self._gossip[name] = GossipMesh(
            degree=d, d_lo=max(2, d - 1), d_hi=d + 2,
            rng=random.Random(("gossip", self.network.seed, name)
                              .__repr__()))
        self._seen[name] = {}
        self._ensure_consumer(name)

    def leave(self, ps) -> None:
        self.suspend(ps.name)
        self._nodes.pop(ps.name, None)

    def suspend(self, name: bytes) -> None:
        """Churn: the node's consumer dies and queued frames are lost
        (its identity and stores survive for a later :meth:`resume`)."""
        task = self._consumers.pop(name, None)
        if task is not None:
            task.cancel()
        self._inboxes.pop(name, None)
        self.network.set_down(name, True)

    def resume(self, name: bytes) -> None:
        self.network.set_down(name, False)
        if name in self._nodes:
            self._ensure_consumer(name)

    def _ensure_consumer(self, name: bytes) -> None:
        if name in self._consumers and not self._consumers[name].done():
            return
        q = self._inboxes.get(name)
        if q is None:
            q = self._inboxes[name] = asyncio.Queue()
        self._consumers[name] = asyncio.ensure_future(
            self._consume(name, q))

    # --- data plane ----------------------------------------------------

    async def broadcast(self, sender, topic: str, data: bytes) -> None:
        """PubSub._hub surface: the publisher floods its topic mesh."""
        from ..core.hashing import sum256

        name = sender.name
        if not self.network.alive(name):
            return
        msg_id = sum256(topic.encode(), data)
        self._mark_seen(name, msg_id)
        mesh = self._gossip.get(name)
        if mesh is None:
            return
        mesh.on_message(msg_id, topic, (topic, msg_id, data))
        self.stats["published"] += 1
        targets = mesh.eager_targets(topic, self.network.neighbors(name))
        for dst in targets:
            self._send(name, dst, ("msg", name, (topic, msg_id, data)))

    def _mark_seen(self, name: bytes, msg_id: bytes) -> bool:
        # the transport's exact dedup policy (shared helper), per node
        return mark_seen(self._seen[name], msg_id, SEEN_CAP)

    def _send(self, src: bytes, dst: bytes, item: tuple) -> None:
        """One frame over one link, with the link's fault policy."""
        net = self.network
        if not net.reachable(src, dst):
            self.stats["dropped"] += 1
            net.stats["blocked"] += 1
            return
        q = self._inboxes.get(dst)
        if q is None:
            self.stats["dropped"] += 1
            return
        pol = net.policy(src, dst)
        rng = net.rng
        copies = 1
        if pol.loss and rng.random() < pol.loss:
            net.stats["loss"] += 1
            return
        if pol.dup and rng.random() < pol.dup:
            net.stats["dup"] += 1
            copies = 2
        for _ in range(copies):
            delay = pol.delay
            if pol.jitter:
                delay += rng.random() * pol.jitter
            if pol.reorder and rng.random() < pol.reorder:
                net.stats["reorder"] += 1
                delay += pol.reorder_delay
            if delay > 0:
                asyncio.get_running_loop().call_later(
                    delay, self._deliver_later, dst, q, item)
            else:
                q.put_nowait(item)

    def _deliver_later(self, dst: bytes, q: asyncio.Queue,
                       item: tuple) -> None:
        # the node may have churned (and its queue been replaced) while
        # the frame was in flight — deliver only to the live queue
        if self._inboxes.get(dst) is q:
            q.put_nowait(item)

    async def _consume(self, name: bytes, q: asyncio.Queue) -> None:
        while True:
            kind, src, payload = await q.get()
            try:
                if kind == "msg":
                    await self._on_msg(name, src, payload)
                else:
                    self._on_ctrl(name, src, payload)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — bad frame must not kill the node
                pass
            finally:
                q.task_done()

    async def _on_msg(self, name: bytes, src: bytes, frame: tuple) -> None:
        topic, msg_id, data = frame
        if not self._mark_seen(name, msg_id):
            self.stats["dup"] += 1
            return
        mesh = self._gossip[name]
        mesh.on_message(msg_id, topic, frame)
        ps = self._nodes.get(name)
        if ps is None:
            return
        ok = await ps.deliver(topic, src, data)
        self.stats["delivered"] += 1
        if ok is True:
            targets = mesh.eager_targets(
                topic, self.network.neighbors(name), exclude=src)
            for dst in targets:
                self.stats["relayed"] += 1
                self._send(name, dst, ("msg", name, frame))
        elif ok is False:
            self.stats["rejected"] += 1

    # --- control plane -------------------------------------------------

    def _on_ctrl(self, name: bytes, src: bytes, payload: bytes) -> None:
        mesh = self._gossip[name]
        seen = self._seen[name]
        replies = mesh.on_control(src, payload,
                                  seen=lambda mid: mid in seen)
        for subtype, topic, ids in replies:
            if subtype == -1:  # answer IWANT with the full frames
                for mid in ids:
                    frame = mesh.cache.get(mid)
                    if frame is not None:
                        self.stats["iwant_served"] += 1
                        self._send(name, src, ("msg", name, frame))
            else:
                self._send(name, src,
                           ("ctrl", name, encode_ctrl(subtype, topic, ids)))

    def heartbeat(self) -> None:
        """One gossip heartbeat for every live node: mesh maintenance
        (GRAFT/PRUNE) + lazy IHAVE. The scenario engine calls this on a
        virtual-time cadence."""
        for name in list(self._nodes):
            if not self.network.alive(name):
                continue
            mesh = self._gossip[name]
            sends = mesh.heartbeat(self.network.neighbors(name))
            for peer, subtype, topic, ids in sends:
                if subtype == IHAVE:
                    self.stats["ihave"] += 1
                self._send(name, peer,
                           ("ctrl", name, encode_ctrl(subtype, topic, ids)))

    async def drain(self) -> None:
        """Wait until every queued frame is fully processed."""
        await asyncio.gather(*(q.join() for q in self._inboxes.values()))


def MeshHub(network: SimNetwork, *, gossip_degree: int = 4,
            shards: int = 1):
    """Fabric selector: the event wheel by default, the legacy
    task-per-node hub under ``SPACEMESH_SIM_FABRIC=legacy`` (the bench
    baseline), or the multi-process sharded wheel when ``shards > 1``
    (sim/shard.py; forced back to 1 under the legacy fabric)."""
    fabric = os.environ.get("SPACEMESH_SIM_FABRIC", "").strip().lower()
    if fabric == "legacy":
        return LegacyMeshHub(network, gossip_degree=gossip_degree)
    if shards and int(shards) > 1:
        from .shard import ShardedMeshHub

        return ShardedMeshHub(network, gossip_degree=gossip_degree,
                              shards=int(shards))
    return EventMeshHub(network, gossip_degree=gossip_degree)


class _NetView:
    """One server's view of the SimNet: ``nodes`` lists only peers it
    can currently reach (partition/eclipse/down honored), so
    ``Server.peers()`` and everything built on it (fetch peer
    selection, peersync quorums) see the faulted world."""

    def __init__(self, simnet: "SimNet", me: bytes):
        self._simnet = simnet
        self._me = me

    @property
    def nodes(self) -> dict[bytes, Server]:
        net = self._simnet.network
        return {n: s for n, s in self._simnet.servers.items()
                if n == self._me or net.reachable(self._me, n)}

    async def route(self, src: bytes, dst: bytes, protocol: str,
                    data: bytes) -> bytes:
        return await self._simnet.route(src, dst, protocol, data)


class SimNet:
    """Req/resp transport over the SimNetwork (``Server._net``)."""

    def __init__(self, network: SimNetwork):
        self.network = network
        self.servers: dict[bytes, Server] = {}

    def join(self, server: Server) -> None:
        self.network.add_node(server.node_id)
        self.servers[server.node_id] = server
        server._net = _NetView(self, server.node_id)

    def leave(self, server: Server) -> None:
        self.servers.pop(server.node_id, None)
        server._net = None

    async def route(self, src: bytes, dst: bytes, protocol: str,
                    data: bytes) -> bytes:
        net = self.network
        target = self.servers.get(dst)
        if target is None or not net.reachable(src, dst):
            raise RequestError(f"peer {dst.hex()[:8]} not reachable")
        pol = net.policy(src, dst)
        if pol.loss and net.rng.random() < pol.loss:
            net.stats["loss"] += 1
            raise RequestError(f"request to {dst.hex()[:8]} lost (chaos)")
        delay = pol.delay + (net.rng.random() * pol.jitter
                             if pol.jitter else 0.0)
        if delay > 0:
            await asyncio.sleep(delay)  # virtual under VirtualClockLoop
        return await target.handle(protocol, src, data)
