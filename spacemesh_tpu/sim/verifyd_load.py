"""Deterministic open-loop multi-client verifyd load scenario.

The scenario engine's network scripts (sim/scenario.py) exercise whole
nodes; this module exercises the verification SERVICE the same way the
thousand-node engine exercises gossip: scripted, seeded, replayable —
same seed, byte-identical digest across processes (the CLI's
``--repeat`` contract, sim/__main__.py dispatches here when a script
carries ``"engine": "verifyd"``).

Determinism contract: the service runs on a VIRTUAL clock advanced only
between waves, so every admission decision (token buckets, deadline
estimates) is a pure function of the script.  Each wave issues every
client's requests open-loop (tasks created without awaiting — the farm
coalesces across clients), then the wave gathers before the clock
advances, so queue state at each admission instant is reproducible.
Verdicts are deterministic (fixed workload seeds + pinned K3 post
seed), so the event digest — per request: client, wave, kinds, typed
outcome, verdicts — replays byte-identically.

Script schema (all numbers deterministic functions of the seed)::

    {"name": ..., "engine": "verifyd", "seed": 7,
     "waves": 12, "wave_interval_s": 0.05,
     "service": {"max_clients": 8, "max_pending_items": 4096, ...},
     "workload": {"sigs": 64, "vrfs": 8, "posts": 4,
                  "memberships": 8, "pows": 12},
     "clients": [
        {"id": "light-0", "rate": 4000, "burst": 2000,
         "requests_per_wave": 2, "items": [4, 8],
         "mix": {"sig": 6, "vrf": 1, "membership": 1, "pow": 2},
         "lane": "gossip"},
        {"id": "heavy", "rate": 60, "burst": 80, ...}],
     "asserts": [
        {"kind": "no_wrong_verdicts"},
        {"kind": "shed", "client": "heavy", "reason": "rate", "min": 1},
        {"kind": "ok_requests", "client": "light-0", "min": 10},
        {"kind": "bounded_pending", "max": 4096},
        {"kind": "sli_present", "name": "verifyd_request_p99"}]}
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import random

from ..obs import sli as sli_mod
from ..utils import metrics
from ..verifyd.service import Shed, VerifydService


class _VClock:
    """The scenario's virtual time source (advanced between waves)."""

    def __init__(self, start: float = 1000.0):
        self._t = float(start)

    def now(self) -> float:
        return self._t

    def advance(self, dt: float) -> None:
        self._t += float(dt)


@dataclasses.dataclass
class VerifydLoadResult:
    """CLI-compatible result (sim/__main__.py prints digest/ok/slis/
    stats["hub"] for every engine)."""

    name: str
    seed: int
    digest: str
    ok: bool
    asserts: list
    slis: dict
    stats: dict
    events: list

    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "seed": self.seed, "digest": self.digest,
            "ok": self.ok, "asserts": self.asserts, "slis": self.slis,
            "stats": self.stats, "events": self.events,
        }, indent=1, sort_keys=True)


def _build_pools(script: dict, post_dir: str) -> dict:
    """Per-kind pools of (request, expected verdict) from the shared
    deterministic workload builder."""
    from ..verify import workload

    wl_cfg = dict(script.get("workload") or {})
    w = workload.build(post_dir,
                       sigs=int(wl_cfg.get("sigs", 64)),
                       vrfs=int(wl_cfg.get("vrfs", 8)),
                       posts=int(wl_cfg.get("posts", 4)),
                       memberships=int(wl_cfg.get("memberships", 8)),
                       pows=int(wl_cfg.get("pows", 12)),
                       post_challenges=int(wl_cfg.get("post_challenges",
                                                      2)),
                       rng_seed=int(script.get("seed", 7)))
    expected = w.inline_all()
    pools: dict[str, list] = {}
    for req, verdict in zip(w.requests, expected):
        pools.setdefault(req.kind, []).append((req, verdict))
    return {"pools": pools, "workload": w}


def _pick_items(rng: random.Random, pools: dict, mix: dict,
                count: int) -> list:
    kinds = sorted(k for k in mix if pools.get(k))
    if not kinds:
        raise ValueError(f"client mix {mix} matches no workload pool")
    weights = [float(mix[k]) for k in kinds]
    out = []
    for _ in range(count):
        kind = rng.choices(kinds, weights=weights)[0]
        pool = pools[kind]
        out.append(pool[rng.randrange(len(pool))])
    return out


async def _run(script: dict, pools: dict, clock: _VClock,
               events: list, service_stats: dict,
               slis_out: dict) -> None:
    from ..verifyd import protocol

    svc_cfg = dict(script.get("service") or {})
    svc_cfg.setdefault("workers", 3)
    service = VerifydService(time_source=clock.now, **svc_cfg)
    w = pools["workload"]
    service.farm.ed_verifier = w.ed
    service.farm.vrf_verifier = w.vrf
    service.farm.post_params = w.post_params
    service.farm.post_seed = w.post_seed
    sampler = sli_mod.SliSampler(metrics.REGISTRY, window_s=3600.0)
    rng = random.Random(int(script.get("seed", 7)))
    waves = int(script.get("waves", 8))
    interval = float(script.get("wave_interval_s", 0.05))
    try:
        await service.start()
        for c in script.get("clients") or ():
            service.register_client(
                str(c["id"]), weight=float(c.get("weight", 1.0)),
                rate=c.get("rate"), burst=c.get("burst"),
                max_queued=c.get("max_queued"))
        sampler.sample(clock.now())

        async def one_request(cid: str, picked: list, lane, deadline):
            reqs = [r for r, _v in picked]
            exp = [bool(v) for _r, v in picked]
            try:
                got = await service.verify(cid, reqs, lane=lane,
                                           deadline_s=deadline)
                return ("ok", [bool(v) for v in got], exp)
            except Shed as e:
                return (f"shed:{e.reason}", None, exp)

        for wave in range(waves):
            tasks = []
            for c in script.get("clients") or ():
                cid = str(c["id"])
                lane = protocol.parse_lane(c.get("lane"))
                lo, hi = (c.get("items") or [4, 8])[:2]
                for r in range(int(c.get("requests_per_wave", 1))):
                    picked = _pick_items(rng, pools["pools"],
                                         c.get("mix") or {"sig": 1},
                                         rng.randint(int(lo), int(hi)))
                    tasks.append((cid, wave, r,
                                  [p[0].kind for p in picked],
                                  asyncio.ensure_future(one_request(
                                      cid, picked, lane,
                                      c.get("deadline_s")))))
            for cid, wv, r, kinds, task in tasks:
                outcome, got, exp = await task
                events.append({"client": cid, "wave": wv, "req": r,
                               "kinds": kinds, "outcome": outcome,
                               "verdicts": got, "expected": exp})
            clock.advance(interval)
            sampler.sample(clock.now())
        service_stats.update(service.stats_doc())
        for spec in sli_mod.verifyd_slis():
            v = sampler.compute(spec)
            if v is not None:
                slis_out[spec.name] = v
        for spec in sli_mod.verifyd_client_slis(
                [str(c["id"]) for c in script.get("clients") or ()]):
            v = sampler.compute(spec)
            if v is not None:
                slis_out[spec.name] = v
    finally:
        # explicit client lifecycle: every registered id unregisters
        # (per-client series leave the registry) before the drain
        for c in script.get("clients") or ():
            service.unregister_client(str(c["id"]))
        await service.aclose()


def _evaluate(script: dict, events: list, service_stats: dict,
              slis: dict) -> list:
    asserts = []
    wrong = [e for e in events
             if e["outcome"] == "ok" and e["verdicts"] != e["expected"]]
    for spec in script.get("asserts") or (
            [{"kind": "no_wrong_verdicts"}]):
        kind = spec.get("kind")
        ent = dict(spec)
        if kind == "no_wrong_verdicts":
            ent["ok"] = not wrong
            ent["detail"] = f"{len(wrong)} diverging requests"
        elif kind == "shed":
            reason = spec.get("reason")
            n = sum(1 for e in events
                    if (spec.get("client") is None
                        or e["client"] == spec["client"])
                    and e["outcome"].startswith("shed:")
                    and (reason is None
                         or e["outcome"] == f"shed:{reason}"))
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} sheds"
        elif kind == "ok_requests":
            n = sum(1 for e in events
                    if (spec.get("client") is None
                        or e["client"] == spec["client"])
                    and e["outcome"] == "ok")
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} admitted requests"
        elif kind == "no_shed":
            n = sum(1 for e in events
                    if (spec.get("client") is None
                        or e["client"] == spec["client"])
                    and e["outcome"].startswith("shed:"))
            ent["ok"] = n == 0
            ent["detail"] = f"{n} sheds"
        elif kind == "bounded_pending":
            peak = service_stats.get("pending_peak", 0)
            ent["ok"] = peak <= int(spec["max"])
            ent["detail"] = f"pending peak {peak}"
        elif kind == "sli_present":
            ent["ok"] = spec.get("name") in slis
            ent["detail"] = f"slis: {sorted(slis)}"
        else:
            ent["ok"] = False
            ent["detail"] = f"unknown assert kind {kind!r}"
        asserts.append(ent)
    return asserts


def run_scenario(script: dict) -> VerifydLoadResult:
    """Run one verifyd load script (fresh service, fresh loop); returns
    the CLI-compatible result with the replay-stable event digest."""
    import tempfile

    events: list = []
    service_stats: dict = {}
    slis: dict = {}
    clock = _VClock()
    with tempfile.TemporaryDirectory() as d:
        pools = _build_pools(script, d)
        asyncio.run(_run(script, pools, clock, events, service_stats,
                         slis))
    asserts = _evaluate(script, events, service_stats, slis)
    # digest covers ONLY replay-stable facts: the script identity and
    # the per-request outcome log (wall-derived values — rates, SLI
    # magnitudes — stay out, exactly like scenario.py's digest)
    digest_doc = {
        "name": script.get("name"), "seed": script.get("seed"),
        "engine": "verifyd", "waves": script.get("waves"),
        "events": events,
        "asserts": [{k: v for k, v in a.items() if k != "detail"}
                    for a in asserts],
    }
    digest = hashlib.sha256(
        json.dumps(digest_doc, sort_keys=True).encode()).hexdigest()[:16]
    hub = {
        "requests": len(events),
        "admitted": sum(1 for e in events if e["outcome"] == "ok"),
        "shed": sum(1 for e in events
                    if e["outcome"].startswith("shed:")),
        "clients": len(script.get("clients") or ()),
    }
    return VerifydLoadResult(
        name=str(script.get("name", "verifyd-load")),
        seed=int(script.get("seed", 7)), digest=digest,
        ok=all(a["ok"] for a in asserts), asserts=asserts, slis=slis,
        stats={"hub": hub, "service": service_stats}, events=events)
