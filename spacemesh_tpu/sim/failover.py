"""Deterministic self-healing scenarios: verifyd outage, device decay.

Two chaos drills for the remediation layer (obs/remediate.py,
verifyd/failover.py), run the way every sim engine runs: seeded,
scripted, on a virtual clock advanced only between steps, with a
replay-stable event digest (``--repeat N`` must produce byte-identical
digests).  ``sim/__main__.py`` dispatches here when a script carries
``"engine": "failover"``; ``mode`` selects the drill.

**verifyd-outage** — a node's :class:`~..verifyd.failover.
FailoverVerifier` drives mixed verification waves against an in-process
:class:`~..verifyd.service.VerifydService` through a killable
transport.  Mid-load the transport dies (every call raises —
the socket's-eye view of a killed verifyd).  The node must: keep
answering every request with verdicts bit-identical to inline
verification (the local farm carries the load), trip the breaker after
its failure budget so the dead service stops being re-paid per
request, keep the BLOCK-lane latency SLO green straight through the
outage (asserted from windowed SLIs on the virtual clock — zero
sleeps), and, once the transport returns, half-open-probe its way back
to remote serving (failback).

**runtime-degrade** — the runtime engine's device-dispatch path
(runtime/engine.py ``Pipeline(breaker=...)``) under a seeded
device-fault plan: dispatch fails for a scripted span of batches.  The
breaker must open after exactly the configured failure budget (the
counter assert the PR-11 fallback hook never had: N device attempts
for an M≫N-batch outage, not M), the host fallback must carry every
batch bit-identically, and device recovery must re-close the breaker
through a half-open probe.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import random

from ..obs import remediate as remediate_mod
from ..obs import sli as sli_mod
from ..utils import metrics
from ..verify.farm import Lane
from .verifyd_load import _VClock, _build_pools, _pick_items


@dataclasses.dataclass
class FailoverResult:
    """CLI-compatible result (sim/__main__.py prints digest/ok/slis/
    stats["hub"] for every engine)."""

    name: str
    seed: int
    digest: str
    ok: bool
    asserts: list
    slis: dict
    stats: dict
    events: list

    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "seed": self.seed, "digest": self.digest,
            "ok": self.ok, "asserts": self.asserts, "slis": self.slis,
            "stats": self.stats, "events": self.events,
        }, indent=1, sort_keys=True, default=str)


def _digest_of(script: dict, events: list, asserts: list) -> str:
    doc = {
        "name": script.get("name"), "seed": script.get("seed"),
        "engine": "failover", "mode": script.get("mode"),
        "events": events,
        "asserts": [{k: v for k, v in a.items() if k != "detail"}
                    for a in asserts],
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


# --- verifyd-outage -----------------------------------------------------


class _KillableTransport:
    """The failover verifier's remote endpoint: an in-process verifyd
    service behind a kill switch.  ``down=True`` is the wire's view of
    a killed verifyd — every call raises ConnectionError."""

    def __init__(self, service, client_id: str):
        self.service = service
        self.client_id = client_id
        self.down = False
        self.calls = 0

    async def verify(self, reqs: list, *, lane: str = "gossip",
                     deadline_s: float | None = None) -> list[bool]:
        self.calls += 1
        if self.down:
            raise ConnectionError("verifyd is down")
        from ..verifyd import protocol

        return await self.service.verify(self.client_id, reqs,
                                         lane=protocol.parse_lane(lane),
                                         deadline_s=deadline_s)


async def _run_outage(script: dict, pools: dict, clock: _VClock,
                      events: list, stats_out: dict,
                      slis_out: dict) -> None:
    from ..verify.farm import VerificationFarm
    from ..verifyd.failover import FailoverVerifier
    from ..verifyd.service import VerifydService

    w = pools["workload"]
    svc_cfg = dict(script.get("service") or {})
    svc_cfg.setdefault("workers", 2)
    service = VerifydService(time_source=clock.now, **svc_cfg)
    service.farm.ed_verifier = w.ed
    service.farm.vrf_verifier = w.vrf
    service.farm.post_params = w.post_params
    service.farm.post_seed = w.post_seed
    local_farm = VerificationFarm(ed_verifier=w.ed, vrf_verifier=w.vrf,
                                  post_params=w.post_params,
                                  post_seed=w.post_seed)
    sampler = sli_mod.SliSampler(metrics.REGISTRY, window_s=3600.0)
    rng = random.Random(int(script.get("seed", 7)))
    waves = int(script.get("waves", 16))
    interval = float(script.get("wave_interval_s", 0.5))
    outage = dict(script.get("outage") or {})
    kill_wave = int(outage.get("kill_wave", waves // 3))
    restore_wave = int(outage.get("restore_wave", (2 * waves) // 3))
    br_cfg = dict(script.get("breaker") or {})
    transport = _KillableTransport(service, "node")

    def on_transition(frm: str, to: str) -> None:
        events.append({"breaker": to, "from": frm,
                       "t": round(clock.now(), 6)})

    breaker = remediate_mod.CircuitBreaker(
        "verifyd.remote",
        failure_budget=int(br_cfg.get("failure_budget", 2)),
        window_s=float(br_cfg.get("window_s", 60.0)),
        cooldown_s=float(br_cfg.get("cooldown_s", 2.0)),
        cooldown_cap_s=float(br_cfg.get("cooldown_cap_s", 8.0)),
        seed=int(script.get("seed", 7)),
        time_source=clock.now, on_transition=on_transition)
    fv = FailoverVerifier(remote=transport, farm=local_farm,
                          breaker=breaker, time_source=clock.now)
    try:
        await service.start()
        fv.start()
        service.register_client("node", rate=1e9, burst=1e9,
                                max_queued=4096)
        sampler.sample(clock.now())
        per_wave = int(script.get("requests_per_wave", 2))
        lo, hi = (script.get("items") or [3, 6])[:2]
        mix = script.get("mix") or {"sig": 6, "vrf": 1, "pow": 2}
        for wave in range(waves):
            if wave == kill_wave:
                transport.down = True
                events.append({"fault": "kill_verifyd", "wave": wave})
            if wave == restore_wave:
                transport.down = False
                events.append({"fault": "restore_verifyd", "wave": wave})
            for r in range(per_wave):
                picked = _pick_items(rng, pools["pools"], mix,
                                     rng.randint(int(lo), int(hi)))
                reqs = [p[0] for p in picked]
                exp = [bool(p[1]) for p in picked]
                lane = Lane.BLOCK if r % 2 == 0 else Lane.GOSSIP
                before = dict(fv.stats)
                verdicts = await fv.verify_batch(reqs, lane)
                after = fv.stats
                if after["remote_ok"] > before["remote_ok"]:
                    path = "remote"
                elif after["local"] > before["local"]:
                    path = "local"
                else:
                    path = "local_fastfail"
                events.append({
                    "wave": wave, "req": r,
                    "lane": lane.name.lower(),
                    "kinds": [q.kind for q in reqs],
                    "path": path,
                    "verdicts": list(verdicts), "expected": exp,
                })
            clock.advance(interval)
            sampler.sample(clock.now())
        stats_out.update({"failover": dict(fv.stats),
                          "transport_calls": transport.calls,
                          "breaker": breaker.state_doc()})
        for spec in sli_mod.failover_slis():
            v = sampler.compute(spec)
            if v is not None:
                slis_out[spec.name] = v
    finally:
        service.unregister_client("node")
        await fv.aclose()
        await service.aclose()
        await local_farm.aclose()


def _eval_outage(script: dict, events: list, stats: dict,
                 slis: dict) -> list:
    served = [e for e in events if "path" in e]
    wrong = [e for e in served if e["verdicts"] != e["expected"]]
    transitions = [e["breaker"] for e in events if "breaker" in e]
    outage = dict(script.get("outage") or {})
    kill_wave = int(outage.get("kill_wave", 0))
    restore_wave = int(outage.get("restore_wave", 1 << 30))
    in_outage = [e for e in served
                 if kill_wave <= e["wave"] < restore_wave]
    asserts = []
    for spec in script.get("asserts") or [{"kind": "no_wrong_verdicts"}]:
        kind = spec.get("kind")
        ent = dict(spec)
        if kind == "no_wrong_verdicts":
            ent["ok"] = not wrong
            ent["detail"] = f"{len(wrong)} diverging of {len(served)}"
        elif kind == "path_served":
            n = sum(1 for e in served if e["path"] == spec["path"]
                    or (spec["path"] == "local"
                        and e["path"] == "local_fastfail"))
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} requests via {spec['path']}"
        elif kind == "outage_local":
            # every request issued while verifyd was dead still got its
            # verdicts — from the farm
            bad = [e for e in in_outage if e["path"] == "remote"]
            ent["ok"] = bool(in_outage) and not bad
            ent["detail"] = (f"{len(in_outage)} outage requests, "
                             f"{len(bad)} claimed remote")
        elif kind == "remote_attempts_bounded":
            # the breaker's whole point: the dead service is paid for
            # at most budget + half-open-probe attempts, NOT once per
            # request
            n = stats["failover"]["remote_failed"]
            ent["ok"] = n <= int(spec["max"])
            ent["detail"] = (f"{n} failed remote attempts over "
                             f"{len(in_outage)} outage requests")
        elif kind == "failback":
            last_wave = max((e["wave"] for e in served), default=-1)
            tail = [e for e in served if e["wave"] == last_wave]
            ent["ok"] = bool(tail) and all(e["path"] == "remote"
                                           for e in tail)
            ent["detail"] = (f"wave {last_wave}: "
                             f"{[e['path'] for e in tail]}")
        elif kind == "breaker_sequence":
            want = ["open", "half_open", "closed"]
            it = iter(transitions)
            ent["ok"] = all(any(t == step for t in it) for step in want)
            ent["detail"] = f"transitions: {transitions}"
        elif kind == "slo_green":
            name = spec.get("name", "failover_block_p99")
            value = slis.get(name)
            target = float(spec.get("target", 0.25))
            ent["ok"] = value is not None and value <= target
            ent["detail"] = f"{name}={value} target<={target}"
        elif kind == "sli_present":
            ent["ok"] = spec.get("name") in slis
            ent["detail"] = f"slis: {sorted(slis)}"
        else:
            ent["ok"] = False
            ent["detail"] = f"unknown assert kind {kind!r}"
        asserts.append(ent)
    return asserts


def _run_verifyd_outage(script: dict) -> FailoverResult:
    import tempfile

    events: list = []
    stats: dict = {}
    slis: dict = {}
    clock = _VClock()
    with tempfile.TemporaryDirectory() as d:
        pools = _build_pools(script, d)
        asyncio.run(_run_outage(script, pools, clock, events, stats,
                                slis))
    asserts = _eval_outage(script, events, stats, slis)
    served = [e for e in events if "path" in e]
    hub = {
        "requests": len(served),
        "remote": sum(1 for e in served if e["path"] == "remote"),
        "local": sum(1 for e in served
                     if e["path"].startswith("local")),
        "remote_failures": stats["failover"]["remote_failed"],
        "failbacks": stats["failover"]["failbacks"],
    }
    return FailoverResult(
        name=str(script.get("name", "verifyd-outage")),
        seed=int(script.get("seed", 7)),
        digest=_digest_of(script, events, asserts),
        ok=all(a["ok"] for a in asserts), asserts=asserts, slis=slis,
        stats={"hub": hub, "failover": stats}, events=events)


# --- runtime-degrade ----------------------------------------------------


def _label(seed: int, i: int) -> str:
    """The batch's 'result': one deterministic digest the device and
    host paths both compute — bit-identity is equality."""
    return hashlib.sha256(b"rt-degrade:%d:%d" % (seed, i)).hexdigest()[:16]


def _run_runtime_degrade(script: dict) -> FailoverResult:
    from ..runtime import engine

    seed = int(script.get("seed", 3))
    batches = int(script.get("batches", 60))
    step = float(script.get("step_s", 0.5))
    fault = dict(script.get("fault") or {})
    f_start = int(fault.get("start", batches // 4))
    f_end = int(fault.get("end", (3 * batches) // 4))
    br_cfg = dict(script.get("breaker") or {})
    clock = _VClock()
    events: list = []
    attempts = {"device": 0, "device_in_fault": 0}

    def on_transition(frm: str, to: str) -> None:
        events.append({"breaker": to, "from": frm,
                       "t": round(clock.now(), 6)})

    breaker = remediate_mod.CircuitBreaker(
        "runtime.device",
        failure_budget=int(br_cfg.get("failure_budget", 3)),
        window_s=float(br_cfg.get("window_s", 120.0)),
        cooldown_s=float(br_cfg.get("cooldown_s", 5.0)),
        cooldown_cap_s=float(br_cfg.get("cooldown_cap_s", 20.0)),
        seed=seed, time_source=clock.now, on_transition=on_transition)
    remediate_mod.BREAKERS.register(breaker)
    try:
        def items():
            for i in range(batches):
                yield i
                clock.advance(step)

        def dispatch(i: int):
            attempts["device"] += 1
            if f_start <= i < f_end:
                attempts["device_in_fault"] += 1
                raise RuntimeError("injected device fault")
            return ("device", i, _label(seed, i))

        def fallback(i: int, exc: Exception):
            return ("host", i, _label(seed, i))

        results: list = []

        def retire(ticket):
            path, i, digest = ticket
            results.append(ticket)
            events.append({"batch": i, "path": path, "digest": digest,
                           "t": round(clock.now(), 6)})
            return None

        pipe = engine.Pipeline(kind="simdev",
                               inflight=int(script.get("inflight", 3)),
                               fallback=fallback, breaker=breaker)
        pipe.run(items(), dispatch, retire)
        final_state = breaker.state
        stats = {
            "device_attempts": attempts["device"],
            "device_attempts_in_fault": attempts["device_in_fault"],
            "fallbacks": pipe.stats.fallbacks,
            "batches": pipe.stats.batches,
            "breaker": breaker.state_doc(),
        }
    finally:
        remediate_mod.BREAKERS.unregister(breaker)

    reference = {i: _label(seed, i) for i in range(batches)}
    wrong = [e for e in events if "batch" in e
             and e["digest"] != reference[e["batch"]]]
    tail = [e for e in events if "batch" in e
            and e["batch"] >= f_end + max(
                int(br_cfg.get("recover_slack", 12)), 1)]
    asserts = []
    for spec in script.get("asserts") or [{"kind": "bit_identical"}]:
        kind = spec.get("kind")
        ent = dict(spec)
        if kind == "bit_identical":
            n = sum(1 for e in events if "batch" in e)
            ent["ok"] = n == batches and not wrong
            ent["detail"] = f"{n}/{batches} batches, {len(wrong)} wrong"
        elif kind == "device_attempts_bounded":
            # the regression the breaker fixes: a dead device is paid
            # budget + probe attempts across the WHOLE fault span, not
            # once per batch
            n = stats["device_attempts_in_fault"]
            ent["ok"] = n <= int(spec["max"])
            ent["detail"] = (f"{n} device attempts across a "
                             f"{f_end - f_start}-batch fault span")
        elif kind == "fallbacks":
            ent["ok"] = stats["fallbacks"] >= int(spec.get("min", 1))
            ent["detail"] = f"{stats['fallbacks']} fallbacks"
        elif kind == "breaker_recloses":
            ent["ok"] = (final_state == remediate_mod.CLOSED
                         and bool(tail)
                         and all(e["path"] == "device" for e in tail))
            ent["detail"] = (f"final={final_state}, "
                             f"{len(tail)} post-recovery device batches")
        elif kind == "breaker_sequence":
            transitions = [e["breaker"] for e in events if "breaker" in e]
            want = ["open", "half_open", "closed"]
            it = iter(transitions)
            ent["ok"] = all(any(t == step for t in it) for step in want)
            ent["detail"] = f"transitions: {transitions}"
        else:
            ent["ok"] = False
            ent["detail"] = f"unknown assert kind {kind!r}"
        asserts.append(ent)
    hub = {
        "batches": batches,
        "device": sum(1 for e in events
                      if e.get("path") == "device"),
        "host": sum(1 for e in events if e.get("path") == "host"),
        "device_attempts": stats["device_attempts"],
    }
    return FailoverResult(
        name=str(script.get("name", "runtime-degrade")), seed=seed,
        digest=_digest_of(script, events, asserts),
        ok=all(a["ok"] for a in asserts), asserts=asserts, slis={},
        stats={"hub": hub, "runtime": stats}, events=events)


def run_scenario(script: dict) -> FailoverResult:
    """Run one failover script (mode selects the drill)."""
    mode = script.get("mode", "verifyd-outage")
    if mode == "verifyd-outage":
        return _run_verifyd_outage(script)
    if mode == "runtime-degrade":
        return _run_runtime_degrade(script)
    raise ValueError(f"unknown failover mode {mode!r}")
