"""The declarative scenario engine.

A scenario is a plain dict (or YAML loaded into one — sim/__main__.py):

    {
      "name": "partition-heal",
      "seed": 7,
      "nodes": {"full": 4, "light": 60, "identities": [2, 1, 1, 1]},
      "layer_sec": 2.0, "lpe": 8, "until_layer": 14,
      "topology": {"degree": 6, "gossip_degree": 4},
      "phases": [
        {"name": "warmup", "until_layer": 10},
        {"name": "partition", "until_layer": 13,
         "faults": [{"kind": "partition", "islands": [[0, 1], [2], [3]]}],
         "traffic": {"storm": {"publishers": 6, "messages": 24,
                               "interval": 0.25}}},
        {"name": "heal",
         "faults": [{"kind": "heal"}],
         "converge": {"frontier": 12, "deadline": 240.0},
         "asserts": [{"kind": "converged", "frontier": 12},
                     {"kind": "slo_green"},
                     {"kind": "span", "name": "mesh.process_layer",
                      "min": 1}]},
      ],
    }

Everything runs on ONE VirtualClockLoop: phase boundaries are layer
starts on a virtual LayerClock, faults land at exact virtual instants,
and assertions read windowed SLIs (obs/sli.py) + span traces
(utils/tracing.py) + consensus state — never a wall-clock sleep.

**Event digest.** The digest covers replay-stable content only: the
scenario header, every booted identity, the fault script as applied,
and the CONSENSUS RECORD — each full node's applied block per layer up
to the scripted ``digest_frontier`` plus its state root, and the
outcomes of the consensus assertions. Wall-time-derived values (SLI
quantiles measure real compute seconds; hub counters shift with
scheduler micro-ordering) stay in the report but OUT of the digest, so
``same seed => byte-identical digest`` holds on a loaded CI box while
any consensus/replay divergence still changes it.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import tempfile
from pathlib import Path
from typing import Optional

from ..node import clock as clock_mod
from ..obs import sli as sli_mod
from ..obs.health import Slo
from ..utils import metrics, tracing
from ..utils.vclock import ChaosClockLoop, VirtualClockLoop, cancel_all_tasks
from . import faults as faults_mod
from .net import MeshHub, SimNet, SimNetwork
from .node import STORM_TOPIC, FullNode, LightNode, storm_payload
from .shard import ShardWorkerCrash, resolve_shards

# generous-by-design CI targets: the quantiles measure REAL compute
# seconds while hundreds of coroutines share one GIL, so these catch
# pathologies (a wedged pipeline, a minutes-long stall), not latency
# regressions — the production targets live in obs/health.default_slos
def scenario_slos() -> list[Slo]:
    return [
        Slo(name="layer_apply_latency", sli="layer_apply_p99", target=15.0),
        Slo(name="gossip_handler_latency", sli="gossip_handler_p99",
            target=15.0),
        Slo(name="farm_queue_wait", sli="farm_queue_wait_p99", target=10.0),
    ]


@dataclasses.dataclass
class ScenarioResult:
    name: str
    seed: int
    digest: str
    ok: bool
    asserts: list
    events: list
    slis: dict
    stats: dict

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, default=str)


class ScenarioEngine:
    def __init__(self, script: dict, *, tmp: Path | None = None,
                 vtimeout: float = 30_000.0):
        self.script = dict(script)
        self.seed = int(script.get("seed", 0))
        self.name = script.get("name", "scenario")
        self.vtimeout = vtimeout
        self._own_tmp: Optional[tempfile.TemporaryDirectory] = None
        if tmp is None:
            self._own_tmp = tempfile.TemporaryDirectory(prefix="simrun-")
            tmp = Path(self._own_tmp.name)
        self.tmp = Path(tmp)
        self.events: list = []          # (vtime, line) — human report
        self._digest_lines: list = []   # replay-stable content only
        self.asserts: list = []
        self.fulls: list[FullNode] = []
        self.lights: list[LightNode] = []
        self._aux_tasks: list = []
        self._run_tasks: list = []

    # --- recording ------------------------------------------------------

    def _now(self) -> float:
        # the engine OWNS this loop and it is always a VirtualClockLoop:
        # its clock IS the scenario's virtual time source
        return self.loop.time()  # spacecheck: ok=SC001 engine-owned VirtualClockLoop

    def record(self, line: str, digest: bool = True) -> None:
        self.events.append((round(self._now(), 6), line))
        if digest:
            self._digest_lines.append(line)

    # --- lifecycle ------------------------------------------------------

    def run(self) -> ScenarioResult:
        chaos = self.script.get("chaos_schedule")
        self.loop = (ChaosClockLoop(int(chaos)) if chaos is not None
                     else VirtualClockLoop())
        try:
            self.loop.run_until_complete(
                asyncio.wait_for(self._go(), self.vtimeout))
        except ShardWorkerCrash as e:
            # typed scenario failure, never a hang: detach the governor
            # so teardown below runs clean, record, and judge failed
            self.loop.time_governor = None
            self.record("fault shard-worker-crash shard=%d" % e.shard,
                        digest=False)
            self.asserts.append({"phase": "fabric", "kind": "shard_worker",
                                 "ok": False, "detail": str(e),
                                 "last_metrics": e.last_metrics is not None,
                                 "last_spans": e.last_spans is not None})
            self._crash_result()
        finally:
            self.loop.time_governor = None
            try:
                self.loop.run_until_complete(cancel_all_tasks())
            finally:
                hub = getattr(self, "hub", None)
                if hub is not None and hasattr(hub, "close"):
                    hub.close()
                for fn in self.fulls:
                    fn.close()
                if tracing.is_enabled():
                    tracing.stop()
                try:
                    self.loop.run_until_complete(
                        self.loop.shutdown_asyncgens())
                    self.loop.run_until_complete(
                        self.loop.shutdown_default_executor())
                finally:
                    asyncio.set_event_loop(None)
                    self.loop.close()
                if self._own_tmp is not None:
                    self._own_tmp.cleanup()
        return self.result

    def _crash_result(self) -> None:
        digest = hashlib.sha256(
            "\n".join(self._digest_lines).encode()).hexdigest()
        self.result = ScenarioResult(
            name=self.name, seed=self.seed, digest=digest, ok=False,
            asserts=self.asserts,
            events=[f"{t:.3f} {line}" for t, line in self.events],
            slis={}, stats={})

    async def _go(self) -> None:
        s = self.script
        nodes = s.get("nodes", {})
        n_full = int(nodes.get("full", 2))
        n_light = int(nodes.get("light", 16))
        identities = nodes.get("identities") or [1] * n_full
        topo = s.get("topology", {})
        self.layer_sec = float(s.get("layer_sec", 2.0))
        self.lpe = int(s.get("lpe", 8))
        self.until_layer = int(s.get("until_layer", 14))

        if s.get("trace", True):
            # the parent of the (possibly sharded) fabric: worker
            # captures federate into this process under shard-<k> roles
            tracing.set_process_identity("parent")
            tracing.start(capacity=int(s.get("trace_capacity", 65536)))
        self.network = SimNetwork(self.seed,
                                  degree=int(topo.get("degree", 6)))
        shards = resolve_shards(s.get("shards"), n_light)
        self.hub = MeshHub(self.network,
                           gossip_degree=int(topo.get("gossip_degree", 4)),
                           shards=shards)
        self.shard_count = getattr(self.hub, "shards", 1)
        if self.shard_count > 1:
            # conservative-window barriers ride the clock's idle jumps;
            # the shard count must NOT enter the digest (assertions are
            # W-invariant, the byte-identical contract is per (seed, W))
            self.loop.time_governor = self.hub.governor
        self.record("fabric shards=%d" % self.shard_count, digest=False)
        self.simnet = SimNet(self.network)
        self.sampler = sli_mod.SliSampler(
            metrics.REGISTRY, window_s=float(s.get("sli_window", 300.0)))
        self._sli_specs = {spec.name: spec
                           for spec in sli_mod.default_slis()}

        self.record("scenario name=%s seed=%d full=%d light=%d until=%d"
                    % (self.name, self.seed, n_full, n_light,
                       self.until_layer))
        # full nodes first so their topology slots are stable, then the
        # light fabric; topology is a pure function of (seed, order)
        for i in range(n_full):
            self.fulls.append(FullNode(
                self.seed, i, tmp=self.tmp, hub=self.hub,
                simnet=self.simnet, loop_time=self.loop.time,
                layer_sec=self.layer_sec, lpe=self.lpe,
                num_identities=int(identities[i]),
                smeshing=bool(nodes.get("smeshing", True))))
        for i in range(n_light):
            self.lights.append(LightNode(self.seed, i, self.hub))
        self.network.build_topology()
        for i, fn in enumerate(self.fulls):
            self.record("boot full=%d id=%s ids=%d"
                        % (i, fn.name.hex()[:16], identities[i]))
        light_digest = hashlib.sha256(
            b"".join(ln.name for ln in self.lights)).hexdigest()[:16]
        self.record("boot light n=%d digest=%s" % (n_light, light_digest))

        # POST init sequentially: concurrent worker threads are the one
        # wall-clock-ordered thing in the process, and boot order must
        # not depend on them
        for fn in self.fulls:
            await fn.prepare()

        # spacecheck: ok=SC001 genesis anchors to the engine's own virtual clock
        genesis = self.loop.time() + 1.0
        self.clock = clock_mod.LayerClock(genesis, self.layer_sec,
                                          time_source=self.loop.time)
        for fn in self.fulls:
            fn.rebase_clock(genesis)
        self._run_tasks = [fn.start(self.until_layer) for fn in self.fulls]
        self._aux_tasks.append(asyncio.ensure_future(self._heartbeats(
            float(s.get("heartbeat", 1.0)))))
        self._aux_tasks.append(asyncio.ensure_future(self._sampling(
            float(s.get("sample_interval", 2.0)))))

        phases = s.get("phases", [])
        for pi, phase in enumerate(phases):
            await self._run_phase(pi, phase, last=(pi == len(phases) - 1))

        await self._finish()

    async def _run_phase(self, pi: int, phase: dict, *, last: bool) -> None:
        pname = phase.get("name", f"phase{pi}")
        self.record("phase name=%s" % pname)
        for fault in phase.get("faults", ()):
            if fault.get("kind") == "adversary":
                line = self._start_adversary(fault)
            elif fault.get("kind") == "restart":
                line = await self._restart_full(fault)
            else:
                line = faults_mod.apply_fault(self, fault)
            self.record("fault phase=%s %s" % (pname, line))
        traffic_tasks = self._start_traffic(phase.get("traffic", {}))
        if "until_layer" in phase:
            await self.clock.await_layer(int(phase["until_layer"]))
        elif "duration" in phase:
            await asyncio.sleep(float(phase["duration"]))
        if last:
            # the apps' run loops end at the scripted until_layer; wait
            # for their final hare drains before judging convergence
            await asyncio.gather(*self._run_tasks, return_exceptions=True)
        for t in traffic_tasks:
            if not t.done():
                t.cancel()
        if "converge" in phase:
            await self._wait_converged(**phase["converge"])
        self.sampler.sample(self._now())
        for spec in phase.get("asserts", ()):
            self._evaluate(pname, dict(spec))

    async def _finish(self) -> None:
        for t in self._aux_tasks:
            t.cancel()
        for fn in self.fulls:
            fn.app.syncer.stop()
        frontier = int(self.script.get(
            "digest_frontier", self.until_layer - 2))
        for fn in self.fulls:
            if not fn.alive:
                self.record("record full=%d killed" % fn.index)
                continue
            rec = fn.applied_record(self.lpe, frontier)
            root = fn.state_root(frontier)
            self.record("record full=%d applied=%s root=%s" % (
                fn.index,
                ";".join("%d:%s" % (lyr, b.hex()[:16]) for lyr, b in rec),
                (root or b"").hex()[:16]))
        # merged light event record: per-shard delivery counts merged in
        # deterministic (name-sorted) order — shard-structure invariant,
        # so W=1 and W=k agree on loss-free links.  A sharded fabric must
        # quiesce first: the tail of a flood can still be bouncing
        # light -> full -> light between the parent wheel and the worker
        # wheels, and those hops only progress while the loop runs.
        # Events-only (digest=False): the digest must stay FABRIC
        # invariant — event and legacy fabrics relay along different
        # edges, so raw delivery counts differ even though consensus
        # (the digested content) is identical, and the bench's
        # event-vs-legacy digest gate depends on that equality.
        # Cross-W delivery equivalence is still enforced through the
        # storm_coverage assertion, which reads these merged counts.
        if self.shard_count > 1 and hasattr(self.hub, "drain"):
            await self.hub.drain()
        if hasattr(self.hub, "finalize"):
            self.hub.finalize()
        merged = sorted((ln.name.hex()[:16], c)
                        for ln, c in self._light_storm_counts())
        self.record("record lights storm=%s n=%d" % (
            hashlib.sha256(repr(merged).encode()).hexdigest()[:16],
            len(merged)), digest=False)
        doc = None
        if tracing.is_enabled():
            doc = tracing.export()
            tracing.stop()
            self._judge_merged_trace(doc)
        slis = {k: self.sampler.compute(spec)
                for k, spec in self._sli_specs.items()}
        stats = {"hub": dict(self.hub.stats),
                 "net": dict(self.network.stats)}
        if getattr(self, "_merged_trace", None) is not None:
            stats["merged_trace"] = self._merged_trace
        ok = all(a["ok"] for a in self.asserts)
        digest = hashlib.sha256(
            "\n".join(self._digest_lines).encode()).hexdigest()
        self.result = ScenarioResult(
            name=self.name, seed=self.seed, digest=digest, ok=ok,
            asserts=self.asserts,
            events=[f"{t:.3f} {line}" for t, line in self.events],
            slis={k: v for k, v in slis.items() if v is not None},
            stats=stats)

    def _judge_merged_trace(self, doc: dict) -> None:
        """Merge the parent capture with every federated shard-worker
        capture into ONE timeline and judge the fleet-observability
        contract. Every assert kind below is emitted for every W —
        W=1 degenerates to the parent's own capture and passes
        trivially — so assertion OUTCOMES stay W-invariant."""
        caps = dict(getattr(self.hub, "worker_captures", {}))
        merged = tracing.merge_captures(
            [doc] + [caps[k] for k in sorted(caps)])
        try:
            warnings = tracing.validate(merged)
            trace_ok = True
        except Exception:  # noqa: BLE001 — recorded, judged below
            warnings, trace_ok = [], False
        self.asserts.append({"phase": "final", "kind": "trace_valid",
                             "ok": trace_ok,
                             "value": doc["otherData"].get(
                                 "captured_spans")})
        od = merged["otherData"]
        procs = od.get("procs", [])
        contributed = sum(1 for p in procs
                          if p.get("captured_spans", 0) > 0)
        self.asserts.append({"phase": "final", "kind": "merged_procs",
                             "ok": contributed == self.shard_count,
                             "value": contributed})
        links = dict(od.get("links") or {})
        # scripts may demand resolved cross-process parent edges, but
        # only when the fabric actually sharded (shards "auto" resolves
        # to W=1 on small hosts, where no process boundary exists)
        need_links = (int(self.script.get("require_cross_proc_links", 0))
                      if self.shard_count > 1 else 0)
        self.asserts.append({
            "phase": "final", "kind": "cross_proc_links",
            "ok": (links.get("unresolved", 0) == 0
                   and links.get("resolved", 0) >= need_links),
            "value": links.get("resolved", 0)})
        # federation cardinality: every live worker's proc= series are
        # present NOW (they are dropped at hub.close — the leak test's
        # other half). Range is empty for W=1: trivially ok.
        from ..obs.federate import FEDERATION
        live = FEDERATION.procs()
        missing = [f"shard-{s}" for s in range(1, self.shard_count)
                   if not live.get(f"shard-{s}", {}).get("series")]
        self.asserts.append({"phase": "final", "kind": "proc_series_live",
                             "ok": not missing,
                             "value": self.shard_count - 1 - len(missing)})
        self._merged_trace = {
            "digest": tracing.span_multiset_digest(merged),
            "procs": len(procs),
            "links": links,
            "captured_spans": od.get("captured_spans"),
            "dropped_spans": od.get("dropped_spans"),
            "warnings": list(warnings),
        }
        self.record(
            "trace merged procs=%d resolved=%d unresolved=%d digest=%s"
            % (len(procs), links.get("resolved", 0),
               links.get("unresolved", 0),
               self._merged_trace["digest"][:16]), digest=False)
        # the merged timeline itself lands next to the run's artifacts
        # so `profiler --timeline <tmp>/merged_trace.json` (and the CI
        # obs-fleet-smoke job) can digest exactly what was judged
        try:
            (self.tmp / "merged_trace.json").write_text(
                json.dumps(merged))
        except OSError:
            pass  # diagnostics only; the digest above is the contract

    def _light_storm_counts(self) -> list:
        """(light, distinct storm messages seen) — from the node object
        in-process, from the owning shard's merged counts otherwise."""
        counts = (self.hub.light_counts(STORM_TOPIC)
                  if hasattr(self.hub, "light_counts") else {})
        return [(ln, counts.get(ln.name, ln.storm_seen))
                for ln in self.lights]

    # --- background cadences -------------------------------------------

    async def _heartbeats(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            self.hub.heartbeat()

    async def _sampling(self, interval: float) -> None:
        while True:
            await asyncio.sleep(interval)
            self.sampler.sample(self._now())

    # --- traffic --------------------------------------------------------

    def _start_traffic(self, traffic: dict) -> list:
        tasks = []
        if "storm" in traffic:
            tasks.append(asyncio.ensure_future(
                self._storm(**traffic["storm"])))
        if "tx_spawn" in traffic:
            tasks.append(asyncio.ensure_future(self._tx_spawn()))
        self._aux_tasks.extend(tasks)
        return tasks

    async def _storm(self, publishers: int = 4, messages: int = 16,
                     interval: float = 0.25, size: int = 200) -> None:
        """Gossip storm from rotating light publishers."""
        if not self.lights:
            return
        for m in range(int(messages)):
            ln = self.lights[(m * 7) % min(publishers, len(self.lights))]
            if self.network.alive(ln.name):
                await ln.pubsub.publish(
                    STORM_TOPIC, storm_payload(self.seed, m, size))
            await asyncio.sleep(interval)

    async def _tx_spawn(self) -> None:
        """Each full node publishes its signer's wallet-spawn tx (valid
        once layer rewards funded the coinbase; duplicates dedup)."""
        from ..p2p.pubsub import TOPIC_TX
        from ..vm import sdk

        for fn in self.fulls:
            if not fn.alive:
                continue
            tx = sdk.spawn_wallet(fn.signer)
            await fn.pubsub.publish(TOPIC_TX, tx.raw)
            # spacecheck: ok=SC001 virtual pacing: 0.1 VIRTUAL seconds between publishes, zero wall cost
            await asyncio.sleep(0.1)

    async def _restart_full(self, spec: dict) -> str:
        """Crash recovery fault: bring a killed full node back over its
        surviving on-disk stores (needs an await for prepare(), so it
        lives here rather than in the sync fault vocabulary)."""
        fn = self.fulls[int(spec["full"])]
        if fn.alive:
            raise faults_mod.FaultError(
                f"restart full={fn.index}: node is alive (kill it first)")
        await fn.restart(self.until_layer)
        # the final phase gathers _run_tasks before judging convergence;
        # the reborn node's run loop must be part of that barrier
        self._run_tasks.append(fn.run_task)
        return "restart full=%d id=%s" % (fn.index, fn.name.hex()[:16])

    def _start_adversary(self, spec: dict) -> str:
        what = spec["what"]
        count = int(spec.get("count", 8))
        via = int(spec.get("via", 0))
        interval = float(spec.get("interval", 0.2))

        async def attack() -> None:
            from ..p2p.pubsub import TOPIC_ATX, TOPIC_HARE

            ln = self.lights[via]
            if what == "malformed_atx":
                for blob in faults_mod.malformed_atx_blobs(self.seed,
                                                           count):
                    await ln.pubsub.publish(TOPIC_ATX, blob)
                    await asyncio.sleep(interval)
            elif what == "torsion_sig":
                for i in range(count):
                    layer = int(self.clock.current_layer())
                    await ln.pubsub.publish(
                        TOPIC_HARE, faults_mod.torsion_hare_message(
                            layer, self.seed + i))
                    await asyncio.sleep(interval)
            elif what == "dup_flood":
                payload = storm_payload(self.seed, 0xD0D0)
                for _ in range(count):
                    await ln.pubsub.publish(STORM_TOPIC, payload)
                    await asyncio.sleep(interval)
            else:
                raise faults_mod.FaultError(
                    f"unknown adversary {what!r}")

        self._aux_tasks.append(asyncio.ensure_future(attack()))
        return "adversary what=%s count=%d via=%d" % (what, count, via)

    # --- condition waits (no sleep-and-hope) ----------------------------

    def _live_fulls(self) -> list[FullNode]:
        return [fn for fn in self.fulls if fn.alive]

    def _convergence(self, frontier: int, from_layer: int | None = None):
        """(ok, detail): every live full node applied the SAME block per
        layer and the SAME state root at the frontier."""
        lo = self.lpe if from_layer is None else from_layer
        live = self._live_fulls()
        if not live:
            return False, "no live full nodes"
        for fn in live:
            if fn.last_applied() < frontier:
                return False, ("full=%d applied=%d < frontier %d"
                               % (fn.index, fn.last_applied(), frontier))
        records = {fn.index: tuple(fn.applied_record(lo, frontier))
                   for fn in live}
        if len(set(records.values())) != 1:
            return False, "applied blocks diverge: %s" % {
                i: [f"{lyr}:{b.hex()[:8]}" for lyr, b in rec]
                for i, rec in records.items()}
        roots = {fn.state_root(frontier) for fn in live}
        if len(roots) != 1 or None in roots:
            return False, "state roots diverge at %d" % frontier
        return True, "converged at %d across %d nodes" % (frontier,
                                                          len(live))

    async def _wait_converged(self, frontier: int,
                              deadline: float = 240.0,
                              from_layer: int | None = None) -> None:
        """Drive until convergence or the VIRTUAL deadline. Syncers are
        driven DIRECTLY (back-to-back passes at a near-frozen virtual
        instant) rather than waiting on their background cadence: every
        idle wait advances the virtual clock, so the tip would otherwise
        outrun a healing node pass for pass. This is a condition wait —
        it returns the moment the predicate holds."""
        t0 = self._now()
        while self._now() - t0 < deadline:
            ok, _ = self._convergence(frontier, from_layer)
            if ok:
                return
            for fn in self._live_fulls():
                try:
                    await fn.app.syncer.synchronize()
                except Exception:  # noqa: BLE001 — next pass retries
                    pass
            # spacecheck: ok=SC001 condition-wait poll cadence in VIRTUAL seconds (the predicate, not the sleep, terminates the wait)
            await asyncio.sleep(0.5)

    # --- assertions -----------------------------------------------------

    def _evaluate(self, pname: str, spec: dict) -> None:
        kind = spec.pop("kind")
        entry = {"phase": pname, "kind": kind, **spec}
        digestable = False
        if kind == "converged":
            ok, detail = self._convergence(
                int(spec["frontier"]), spec.get("from_layer"))
            entry.update(ok=ok, detail=detail)
            digestable = True
        elif kind == "progress":
            live = self._live_fulls()
            applied = {fn.index: fn.last_applied() for fn in live}
            ok = bool(live) and min(applied.values()) >= int(
                spec["min_layer"])
            entry.update(ok=ok, value=applied)
            digestable = True
        elif kind == "sli":
            sspec = self._sli_specs.get(spec["name"])
            value = self.sampler.compute(sspec) if sspec else None
            if value is None:
                ok = not spec.get("required", True)
            else:
                op, target = spec.get("op", "<="), float(spec["target"])
                ok = value <= target if op == "<=" else value >= target
            entry.update(ok=ok, value=value)
        elif kind == "sli_present":
            sspec = self._sli_specs.get(spec["name"])
            value = self.sampler.compute(sspec) if sspec else None
            entry.update(ok=value is not None, value=value)
        elif kind == "slo_green":
            slos = scenario_slos()
            violated = {}
            for slo in slos:
                value = self.sampler.compute(self._sli_specs[slo.sli])
                if value is not None and slo.violated(value):
                    violated[slo.name] = value
            entry.update(ok=not violated, violated=violated)
        elif kind == "span":
            doc = tracing.export() if tracing.is_enabled() else {
                "traceEvents": []}
            n = sum(1 for e in doc["traceEvents"]
                    if e.get("name") == spec["name"]
                    and e.get("ph") in ("X", "B", "i"))
            entry.update(ok=n >= int(spec.get("min", 1)), value=n)
        elif kind == "storm_coverage":
            seen = {ln.name: c for ln, c in self._light_storm_counts()}
            live = [ln for ln in self.lights
                    if self.network.alive(ln.name)]
            got = sum(1 for ln in live if seen.get(ln.name, 0) > 0)
            frac = got / len(live) if live else 0.0
            entry.update(ok=frac >= float(spec.get("min_fraction", 0.9)),
                         value=round(frac, 4))
        elif kind == "hub_stat":
            value = self.hub.stats.get(spec["name"], 0)
            ok = value >= int(spec.get("min", 1))
            if "max" in spec:
                ok = ok and value <= int(spec["max"])
            entry.update(ok=ok, value=value)
        elif kind == "epoch_roots":
            # state-root equality across live fulls at EVERY epoch
            # boundary up to the frontier (the multi-epoch soak gate)
            upto = int(spec.get("upto_layer", self.until_layer - 2))
            live = self._live_fulls()
            boundaries, diverged = [], []
            for lyr in range(self.lpe, upto + 1, self.lpe):
                roots = {fn.state_root(lyr) for fn in live}
                boundaries.append(lyr)
                if len(roots) != 1 or None in roots:
                    diverged.append(lyr)
            ok = bool(live) and bool(boundaries) and not diverged
            entry.update(ok=ok, value={"epoch_layers": boundaries,
                                       "diverged": diverged})
            digestable = True
        else:
            entry.update(ok=False, detail=f"unknown assert kind {kind!r}")
        self.asserts.append(entry)
        if digestable:
            self.record("assert phase=%s kind=%s name=%s ok=%s"
                        % (pname, kind, spec.get("name", ""), entry["ok"]))
        else:
            self.record("assert phase=%s kind=%s ok=%s value=%s"
                        % (pname, kind, entry["ok"],
                           entry.get("value")), digest=False)


def run_scenario(script: dict, *, tmp: Path | None = None,
                 vtimeout: float = 30_000.0) -> ScenarioResult:
    """Build + run one scenario on a fresh VirtualClockLoop."""
    return ScenarioEngine(script, tmp=tmp, vtimeout=vtimeout).run()
