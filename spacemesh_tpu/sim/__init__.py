"""Deterministic thousand-node scenario engine (ROADMAP item 4).

Runs hundreds-to-thousands of lightweight in-proc nodes on ONE
VirtualClockLoop through scripted scenarios: partitions and healing,
eclipse, churn, link degradation (delay/loss/duplication/reorder) and
adversarial payloads — asserting health from the PR-7 SLO engine
(obs/sli.py windowed SLIs) and PR-5 span traces instead of wall-clock
sleeps. Same seed => same event digest, so any failure replays exactly.

Layout:
  net.py        SimNetwork (topology + fault state) + MeshHub (gossip
                over p2p/gossipmesh.py meshes) + SimNet (req/resp)
  node.py       LightNode / FullNode factories (shared event loop)
  shard.py      multi-process fabric: light nodes partitioned over W
                worker processes with conservative virtual-time windows
  scenario.py   the declarative engine: phases, traffic, faults,
                SLI/trace assertions, event digest
  scenarios.py  built-in scripts (partition-heal, storm-256,
                timeskew-kill, ...)
  __main__.py   CLI: python -m spacemesh_tpu.sim --scenario ... --seed N

Exports resolve lazily (PEP 562): shard WORKER processes import
`spacemesh_tpu.sim.shard` only, and must not pay for (or depend on)
the jax-heavy scenario/node stack that `scenario.py` pulls in.

See docs/SCENARIOS.md for the script format and the replay workflow.
"""

_EXPORTS = {
    "LinkPolicy": "net",
    "MeshHub": "net",
    "SimNet": "net",
    "SimNetwork": "net",
    "ScenarioResult": "scenario",
    "run_scenario": "scenario",
    "builtin": "scenarios",
    "builtin_names": "scenarios",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f".{mod}", __name__), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
