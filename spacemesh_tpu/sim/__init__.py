"""Deterministic thousand-node scenario engine (ROADMAP item 4).

Runs hundreds-to-thousands of lightweight in-proc nodes on ONE
VirtualClockLoop through scripted scenarios: partitions and healing,
eclipse, churn, link degradation (delay/loss/duplication/reorder) and
adversarial payloads — asserting health from the PR-7 SLO engine
(obs/sli.py windowed SLIs) and PR-5 span traces instead of wall-clock
sleeps. Same seed => same event digest, so any failure replays exactly.

Layout:
  net.py        SimNetwork (topology + fault state) + MeshHub (gossip
                over p2p/gossipmesh.py meshes) + SimNet (req/resp)
  node.py       LightNode / FullNode factories (shared event loop)
  scenario.py   the declarative engine: phases, traffic, faults,
                SLI/trace assertions, event digest
  scenarios.py  built-in scripts (partition-heal, storm-256,
                timeskew-kill, ...)
  __main__.py   CLI: python -m spacemesh_tpu.sim --scenario ... --seed N

See docs/SCENARIOS.md for the script format and the replay workflow.
"""

from .net import LinkPolicy, MeshHub, SimNet, SimNetwork  # noqa: F401
from .scenario import ScenarioResult, run_scenario  # noqa: F401
from .scenarios import builtin, builtin_names  # noqa: F401
