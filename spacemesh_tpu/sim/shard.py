"""Multi-process scenario fabric: the event wheel sharded over host
cores with conservative virtual-time windows.

PR-18 made the fabric event-driven, but one asyncio loop still
serializes every light-relay hop. This module partitions the LIGHT
relays over W-1 worker subprocesses (shard 0 — the parent — keeps the
full nodes, the SimNet, and the engine); each worker runs a synchronous
PR-18-style event wheel over its subset, and shards advance together
under the classic conservative PDES contract (Chandy–Misra/Bryant,
barrier-synchronized YAWNS windows): no speculation, no rollback.

**Safe horizon.** Let N be the earliest pending event instant across
all shards and L the per-link delay floor (`SimNetwork.min_delay_floor`
— jitter and reorder only ever ADD delay). Any frame generated at an
instant >= N arrives at >= N + L, so every shard may process the window
[N, N+L) without hearing from anyone. When L == 0 the window degenerates
to the single instant N and same-instant exchange rounds run until the
flood quiesces — correct (zero-lookahead) but chattier, which is why
hostile worlds with a delay floor parallelize best.

**Determinism.** Cross-shard frames carry (instant, seq) tags: each
side assigns sequence numbers from its own deterministic counter, the
parent sorts every incoming batch by (instant, src shard, src seq)
before insertion, and each worker draws link-policy randomness from its
own `random.Random(("simshard", seed, W, shard))` stream in execution
order. Replay with the same (seed, W) is therefore byte-identical;
scenario ASSERTIONS are identical across any W (on loss-free links even
the merged per-light delivery record is W-invariant, because flood
coverage under relay-set forwarding does not depend on arrival order).
W=1 never constructs this class at all — `MeshHub` returns the plain
in-process `EventMeshHub`, byte-identical to PR 18.

**Transport.** Length-prefixed pickle over the worker's stdin/stdout
pipes. The parent's side runs synchronously inside the
`VirtualClockLoop.time_governor` hook (utils/vclock.py), i.e. while the
loop is idle at a window edge — barrier waits are exactly the wall time
workers spend computing. A worker that dies mid-window surfaces as
:class:`ShardWorkerCrash`, which the scenario engine converts into a
typed failed assertion (never a hang).
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import heapq
import itertools
import os
import pickle
import random
import struct
import subprocess
import sys
from pathlib import Path

from ..core.hashing import sum256
from ..p2p.gossipmesh import SEEN_CAP, mark_seen, relay_sample
from ..utils import metrics, tracing
from .net import EventMeshHub, LinkPolicy, SimNetwork

# obs.federate is imported lazily inside PARENT-side methods only: the
# worker subprocess must stay importable without jax (the obs package
# drags in the health/SLI stack), and workers never touch FEDERATION.

_LEN = struct.Struct("<I")
_INF = float("inf")
_EPS = 1e-9          # instant-comparison tolerance (grid spacing is 1e-6)
_MAX_ROUNDS = 100_000  # runaway-exchange backstop, not a tuning knob


class ShardWorkerCrash(RuntimeError):
    """A shard worker process died mid-run (typed scenario failure).

    Carries the dead worker's last federated snapshot — the metrics
    sample and trace capture it shipped most recently — so the typed
    failure itself holds the forensics (docs/OBSERVABILITY.md § Fleet
    observability). ``None`` when the worker died before its first
    snapshot."""

    def __init__(self, shard: int, detail: str = "",
                 last_metrics=None, last_spans=None):
        self.shard = shard
        self.last_metrics = last_metrics
        self.last_spans = last_spans
        msg = f"sim shard worker {shard} crashed"
        super().__init__(msg + (f": {detail}" if detail else ""))


def resolve_shards(spec, n_light: int) -> int:
    """Resolve a scenario's ``shards`` spec to a worker-process count W.

    ``SPACEMESH_SIM_SHARDS`` overrides the script. ``"auto"`` picks
    ``min(host cores, n_light // 64)``; an explicit integer is honored
    (tests force W=4 on small hosts). W is clamped so every worker owns
    at least one light — with too few lights W collapses to 1 (the
    plain in-process fabric)."""
    env = os.environ.get("SPACEMESH_SIM_SHARDS", "").strip()
    if env:
        spec = env
    if spec in (None, "", 0, "0", 1, "1"):
        return 1
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover — non-linux
        cores = os.cpu_count() or 1
    if isinstance(spec, str) and spec.strip().lower() == "auto":
        w = min(cores, n_light // 64)
    else:
        w = int(spec)
    if w > 1:
        w = min(w, n_light + 1)   # >= 1 light per worker shard
    return max(1, w)


# --- pipe framing ------------------------------------------------------


def _write_msg(fp, obj) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    fp.write(_LEN.pack(len(blob)))
    fp.write(blob)
    fp.flush()


def _read_msg(fp):
    hdr = fp.read(4)
    if len(hdr) < 4:
        raise EOFError("shard pipe closed")
    n = _LEN.unpack(hdr)[0]
    blob = fp.read(n)
    if len(blob) < n:
        raise EOFError("shard pipe truncated")
    return pickle.loads(blob)


# --- the worker (subprocess side) --------------------------------------


_STATS_KEYS = ("published", "delivered", "dup", "rejected", "relayed",
               "dropped", "events_scheduled", "events_fired")


class ShardWorker:
    """Synchronous event-wheel processor over one shard's light relays.

    Owns a deterministic replica of the parent's SimNetwork (topology
    snapshot at spawn + replayed fault ops), per-node seen caches, and
    its own link-policy RNG stream. Only ever advances when granted a
    horizon by the parent, so it can never observe the future."""

    def __init__(self, snap: dict):
        self.shard = int(snap["shard"])
        self.shards = int(snap["shards"])
        self.gossip_degree = int(snap["gossip_degree"])
        net = SimNetwork(snap["seed"], degree=snap["degree"])
        for name in snap["names"]:
            net.add_node(name)
        for name, peers in snap["adj"].items():
            net.adj[name] = set(peers)
        net.group.update(snap["group"])
        net.down = set(snap["down"])
        net.eclipsed = {k: frozenset(v)
                        for k, v in snap["eclipsed"].items()}
        net.blocked = {frozenset(pair) for pair in snap["blocked"]}
        net.default_policy = LinkPolicy(**snap["default_policy"])
        net.link_policy = {frozenset(pair): LinkPolicy(**pol)
                           for pair, pol in snap["link_policy"]}
        net._bump_epoch()
        self.net = net
        self.shard_of: dict[bytes, int] = snap["shard_of"]
        self.rng = random.Random(
            ("simshard", snap["seed"], self.shards, self.shard).__repr__())
        self.seen = {name: {} for name in snap["owned"]}
        self.gen = {name: 1 for name in snap["owned"]}
        self.counts: dict[tuple, int] = collections.defaultdict(int)
        self.wheel: list[tuple] = []   # (instant, seq, dst, gen, item)
        self._seq = itertools.count()
        self._out_seq = itertools.count()
        self.out: list[tuple] = []     # (arrival, seq, dst, item)
        self.now = 0.0
        self._relay_cache: dict[tuple, tuple] = {}
        self.stats = dict.fromkeys(_STATS_KEYS, 0)
        self.runs = 0
        self.fed_every = int(snap.get("fed_every", 128))
        if snap.get("trace"):
            # worker-side capture: virtual-time spans in this worker's
            # own ring, shipped to the parent's federation plane.
            # Identity is set ONLY here — an in-process ShardWorker
            # (unit tests) must not rename the host process.
            tracing.set_process_identity(f"shard-{self.shard}",
                                         clock_domain="virtual")
            tracing.TRACER.start(capacity=snap.get("trace_capacity"),
                                 jax_bridge=False)

    # -- fault-op replay (parent order == apply order) --

    def apply_op(self, op: tuple) -> None:
        kind = op[0]
        if kind == "publish":
            instant, name, topic, data = op[1:5]
            token = op[5] if len(op) > 5 else None
            heapq.heappush(self.wheel, (instant, next(self._seq), name,
                                        self.gen.get(name, 0),
                                        ("pub", topic, data, token)))
            self.stats["events_scheduled"] += 1
        elif kind == "churn":
            name = op[1]
            if name in self.gen:
                self.gen[name] += 1
        elif kind == "set_link_policy":
            _, pol, a, b = op
            self.net.set_link_policy(LinkPolicy(**pol), a, b)
        elif kind == "partition":
            self.net.partition(op[1])
        elif kind == "heal":
            self.net.heal()
        elif kind == "eclipse":
            self.net.eclipse(op[1], op[2])
        elif kind == "clear_eclipse":
            self.net.clear_eclipse(op[1])
        elif kind == "block_link":
            self.net.block_link(op[1], op[2])
        elif kind == "unblock_link":
            self.net.unblock_link(op[1], op[2])
        elif kind == "set_down":
            self.net.set_down(op[1], op[2])
        else:
            raise ValueError(f"unknown shard op {kind!r}")

    # -- the granted-horizon run --

    def run(self, upto: float, inclusive: bool, ops: list,
            frames: list) -> tuple:
        for op in ops:
            self.apply_op(op)
        for instant, dst, item in frames:
            heapq.heappush(self.wheel, (instant, next(self._seq), dst,
                                        self.gen.get(dst, 0), item))
            self.stats["events_scheduled"] += 1
        lim = upto + _EPS if inclusive else upto - _EPS
        wheel = self.wheel
        fired0 = self.stats["events_fired"]
        wstart = None
        while wheel and wheel[0][0] <= lim:
            instant, _, dst, gen, item = heapq.heappop(wheel)
            self.stats["events_fired"] += 1
            if wstart is None:
                wstart = instant
            self.now = instant
            if self.gen.get(dst) != gen:
                self.stats["dropped"] += 1   # churned while in flight
                continue
            kind = item[0]
            if kind == "pub":
                self._publish(dst, item[1], item[2],
                              item[3] if len(item) > 3 else None)
            elif kind == "msg":
                self._on_msg(dst, item[1], item[2])
            # "ctrl": light relays run no control plane — dropped, same
            # as EventMeshHub._on_ctrl's light short-circuit
        fired = self.stats["events_fired"] - fired0
        if fired and tracing.TRACER.enabled:
            # one span per non-empty granted window, stamped in VIRTUAL
            # microseconds — all wheels share one virtual clock, so the
            # merged timeline aligns exactly across shards
            ts0 = int(wstart * 1e6)
            tracing.TRACER._record(
                "shard.window", "sim", ts0,
                max(int(self.now * 1e6) - ts0, 0),
                next(tracing.TRACER._ids), None, {"fired": fired}, "X")
        out, self.out = self.out, []
        nxt = wheel[0][0] if wheel else _INF
        return nxt, out

    # -- light-relay semantics (mirror of EventMeshHub's light path) --

    def _publish(self, name: bytes, topic: str, data: bytes,
                 token: str | None = None) -> None:
        msg_id = sum256(topic.encode(), data)
        mark_seen(self.seen[name], msg_id, SEEN_CAP)
        self.stats["published"] += 1
        if tracing.TRACER.enabled:
            attrs: dict = {"topic": topic}
            if token:
                # the parent's fabric.publish link token — resolved to a
                # cross-process parent edge by merge_captures()
                attrs["link"] = token
            tracing.TRACER._record(
                "shard.publish", "sim", int(self.now * 1e6), 0,
                next(tracing.TRACER._ids), None, attrs, "X")
        frame = (topic, msg_id, data)
        for dst in self._relay_targets(name, topic):
            self._send(name, dst, ("msg", name, frame))

    def _on_msg(self, name: bytes, src: bytes, frame: tuple) -> None:
        topic, msg_id, data = frame
        if not mark_seen(self.seen[name], msg_id, SEEN_CAP):
            self.stats["dup"] += 1
            return
        # a light relay's handler set accepts every topic (PubSub
        # returns True with no handlers) — count and relay
        self.counts[(name, topic)] += 1
        self.stats["delivered"] += 1
        for dst in self._relay_targets(name, topic, exclude=src):
            self.stats["relayed"] += 1
            self._send(name, dst, ("msg", name, frame))

    def _relay_targets(self, name: bytes, topic: str,
                       exclude: bytes | None = None):
        key = (name, topic)
        ent = self._relay_cache.get(key)
        if ent is None or ent[0] != self.net.epoch:
            ent = (self.net.epoch,
                   relay_sample(topic, name, self.net.neighbors(name),
                                self.gossip_degree))
            self._relay_cache[key] = ent
        if exclude is None:
            return ent[1]
        return [p for p in ent[1] if p != exclude]

    def _send(self, src: bytes, dst: bytes, item: tuple) -> None:
        net = self.net
        if not net.reachable(src, dst):
            self.stats["dropped"] += 1
            net.stats["blocked"] += 1
            return
        pol = net.policy(src, dst)
        rng = self.rng
        copies = 1
        if pol.loss and rng.random() < pol.loss:
            net.stats["loss"] += 1
            return
        if pol.dup and rng.random() < pol.dup:
            net.stats["dup"] += 1
            copies = 2
        for _ in range(copies):
            delay = pol.delay
            if pol.jitter:
                delay += rng.random() * pol.jitter
            if pol.reorder and rng.random() < pol.reorder:
                net.stats["reorder"] += 1
                delay += pol.reorder_delay
            arrival = self.now + delay
            if self.shard_of.get(dst, 0) == self.shard:
                heapq.heappush(self.wheel,
                               (arrival, next(self._seq), dst,
                                self.gen.get(dst, 0), item))
                self.stats["events_scheduled"] += 1
            else:
                self.out.append((arrival, next(self._out_seq), dst, item))

    # -- federation snapshots (docs/OBSERVABILITY.md § Fleet obs) --

    def fed_snapshot(self) -> dict:
        """This worker's full registry sample + trace capture, shipped
        over the pipe for the parent's ``obs.federate`` plane."""
        for k, v in self.stats.items():
            metrics.sim_shard_worker_stats.set(
                float(v), shard=str(self.shard), stat=k)
        return {
            "metrics": metrics.REGISTRY.sample(),
            "trace": tracing.export() if tracing.TRACER.enabled else None,
        }

    def maybe_fed(self) -> dict | None:
        """Periodic snapshot piggybacked on run replies — the FIRST
        window always ships one, so a worker that crashes early still
        leaves last-known forensics behind, then every ``fed_every``
        windows after that."""
        self.runs += 1
        if self.runs == 1 or self.runs % self.fed_every == 0:
            return self.fed_snapshot()
        return None


def worker_main() -> int:   # pragma: no cover — exercised via subprocess
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    try:
        tag, snap = _read_msg(stdin)
        if tag != "init":
            return 2
        w = ShardWorker(snap)
        _write_msg(stdout, ("ready", w.shard))
        while True:
            msg = _read_msg(stdin)
            kind = msg[0]
            if kind == "run":
                _, upto, inclusive, ops, frames = msg
                nxt, out = w.run(upto, inclusive, ops, frames)
                _write_msg(stdout, ("done", nxt, out, w.maybe_fed()))
            elif kind == "counts":
                topic = msg[1]
                _write_msg(stdout, ("counts", {
                    name: c for (name, t), c in w.counts.items()
                    if t == topic}))
            elif kind == "finalize":
                _write_msg(stdout, ("final", dict(w.stats),
                                    dict(w.counts), dict(w.net.stats),
                                    w.fed_snapshot()))
            elif kind == "exit":
                return 0
            else:
                return 2
    except EOFError:
        return 0


# --- the parent hub ----------------------------------------------------


class _Worker:
    __slots__ = ("shard", "proc", "next", "ops_cursor", "pending",
                 "last_fed")

    def __init__(self, shard: int, proc):
        self.shard = shard
        self.proc = proc
        self.next = _INF          # earliest pending instant, as reported
        self.ops_cursor = 0       # index into the hub's fault-op log
        self.pending: list = []   # frames awaiting flush (arrival, seq, dst, item)
        self.last_fed = None      # last federated snapshot (crash forensics)


class ShardedMeshHub(EventMeshHub):
    """Shard-0 hub: the parent's EventMeshHub over the full nodes, plus
    the conservative-window exchange plane for W-1 light-relay workers.

    The engine attaches :meth:`governor` as the VirtualClockLoop's
    ``time_governor``; every idle clock jump first settles the current
    instant across shards, then advances to the next safe horizon."""

    def __init__(self, network: SimNetwork, *, gossip_degree: int = 4,
                 shards: int = 2):
        super().__init__(network, gossip_degree=gossip_degree)
        self.shards = max(2, int(shards))
        self._shard_of: dict[bytes, int] = {}
        self._owned: dict[int, list[bytes]] = {
            s: [] for s in range(1, self.shards)}
        self._light_join_idx = 0
        self._workers: list[_Worker] = []
        self._ops_log: list[tuple] = []
        self._out_seq = itertools.count()
        self._spawned = False
        self._crashed: ShardWorkerCrash | None = None
        self._counts: dict[tuple, int] = {}
        self._final: list | None = None
        self.barrier_rounds = 0
        self.fed_every = 128      # worker snapshot cadence (windows)
        self.worker_captures: dict[str, dict] = {}
        network.listener = self._on_net_mutation

    # -- membership: lights round-robin onto workers by join index --

    def join(self, ps, *, light: bool = False) -> None:
        if not light:
            return super().join(ps)
        name = ps.name
        shard = 1 + self._light_join_idx % (self.shards - 1)
        self._light_join_idx += 1
        ps._hub = self
        self.network.add_node(name)
        self._shard_of[name] = shard
        self._owned[shard].append(name)

    def suspend(self, name: bytes) -> None:
        shard = self._shard_of.get(name, 0)
        if shard == 0:
            return super().suspend(name)
        self._ops_log.append(("churn", name))
        self.network.set_down(name, True)   # listener logs the set_down

    def resume(self, name: bytes) -> None:
        if self._shard_of.get(name, 0) == 0:
            return super().resume(name)
        self.network.set_down(name, False)

    # -- fault mirror --

    def _on_net_mutation(self, method: str, args: tuple) -> None:
        self._ops_log.append((method, *args))

    # -- data plane: remote publishers and cross-shard sends --

    async def broadcast(self, sender, topic: str, data: bytes) -> None:
        name = sender.name
        if self._shard_of.get(name, 0) == 0:
            return await super().broadcast(sender, topic, data)
        if not self.network.alive(name):
            return
        loop = asyncio.get_running_loop()
        # the publish op carries a link token so the worker's
        # shard.publish span can parent to this fabric.publish span
        # across the process boundary (merge_captures resolves it)
        with tracing.span("fabric.publish", {"topic": topic}, cat="sim"):
            token = tracing.link_token()
        # spacecheck: ok=SC001 virtual publish instant from the engine's VirtualClockLoop
        self._ops_log.append(("publish", loop.time(), name, topic, data,
                              token))

    def _send(self, src: bytes, dst: bytes, item: tuple) -> None:
        shard = self._shard_of.get(dst, 0)
        if shard == 0:
            return super()._send(src, dst, item)
        net = self.network
        if not net.reachable(src, dst):
            self.stats["dropped"] += 1
            net.stats["blocked"] += 1
            return
        # same draw order as the in-process path: the parent draws for
        # frames its OWN nodes originate; workers draw for theirs
        pol = net.policy(src, dst)
        rng = net.rng
        copies = 1
        if pol.loss and rng.random() < pol.loss:
            net.stats["loss"] += 1
            return
        if pol.dup and rng.random() < pol.dup:
            net.stats["dup"] += 1
            copies = 2
        # spacecheck: ok=SC001 frame instants share the engine's virtual-clock timebase
        now = asyncio.get_running_loop().time()
        w = self._workers[shard - 1] if self._spawned else None
        for _ in range(copies):
            delay = pol.delay
            if pol.jitter:
                delay += rng.random() * pol.jitter
            if pol.reorder and rng.random() < pol.reorder:
                net.stats["reorder"] += 1
                delay += pol.reorder_delay
            entry = (now + delay, next(self._out_seq), dst, item)
            if w is not None:
                w.pending.append(entry)
            else:
                self._prespawn_pending(shard, entry)

    def _prespawn_pending(self, shard: int, entry: tuple) -> None:
        # sends before the first window (none in practice — the first
        # publish happens well after boot) are held per shard
        buf = getattr(self, "_prespawn", None)
        if buf is None:
            buf = self._prespawn = {}
        buf.setdefault(shard, []).append(entry)

    # -- worker lifecycle --

    def _spawn(self) -> None:
        self._spawned = True
        net = self.network
        common = dict(
            seed=net.seed, degree=net.degree, shards=self.shards,
            gossip_degree=self.gossip_degree,
            names=list(net.names),
            adj={n: sorted(ps) for n, ps in net.adj.items()},
            group=dict(net.group),
            down=sorted(net.down),
            eclipsed={k: sorted(v) for k, v in net.eclipsed.items()},
            blocked=[sorted(pair) for pair in net.blocked],
            default_policy=dataclasses.asdict(net.default_policy),
            link_policy=[(sorted(pair), dataclasses.asdict(pol))
                         for pair, pol in net.link_policy.items()],
            shard_of=dict(self._shard_of),
            trace=tracing.TRACER.enabled,
            trace_capacity=tracing.TRACER.capacity,
            fed_every=self.fed_every,
        )
        # the snapshot covers every NETWORK mutation so far, so those ops
        # must not be applied twice — but publish ops are data, not
        # topology: a publish logged before the first idle point (and
        # hence before the lazy spawn) still has to reach its worker
        self._ops_log = [op for op in self._ops_log if op[0] == "publish"]
        root = Path(__file__).resolve().parents[2]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [str(root)] + ([env["PYTHONPATH"]]
                           if env.get("PYTHONPATH") else []))
        for s in range(1, self.shards):
            proc = subprocess.Popen(
                [sys.executable, "-m", "spacemesh_tpu.sim.shard"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
            w = _Worker(s, proc)
            self._workers.append(w)
            self._ssend(w, ("init", dict(common, shard=s,
                                         owned=list(self._owned[s]))))
        for w in self._workers:
            tag, shard = self._recv(w)
            if tag != "ready" or shard != w.shard:
                raise ShardWorkerCrash(w.shard, "bad init handshake")
        pre = getattr(self, "_prespawn", None)
        if pre:
            for s, entries in pre.items():
                self._workers[s - 1].pending.extend(entries)
            self._prespawn = {}

    def close(self) -> None:
        """Terminate every worker (engine teardown; idempotent). Clean
        workers' federated ``proc=`` series are dropped here — the
        cardinality-hygiene half of the federation contract — while a
        CRASHED worker's snapshot stays retained and flagged."""
        self.network.listener = None
        workers, self._workers = self._workers, []
        if workers:
            from ..obs.federate import FEDERATION
            crashed = (self._crashed.shard
                       if self._crashed is not None else None)
            for w in workers:
                if w.shard != crashed:
                    FEDERATION.drop(f"shard-{w.shard}")
        for w in workers:
            try:
                _write_msg(w.proc.stdin, ("exit",))
                w.proc.stdin.close()
            except OSError:
                pass
        for w in workers:
            try:
                w.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:   # pragma: no cover
                w.proc.kill()
                w.proc.wait()

    # -- pipe helpers with typed crash translation --

    def _crash(self, w: _Worker, detail: str) -> ShardWorkerCrash:
        """Build the typed crash carrying the dead worker's last
        federated snapshot, and flag (not drop) its federation entry."""
        fed = w.last_fed or {}
        from ..obs.federate import FEDERATION
        FEDERATION.mark_crashed(f"shard-{w.shard}")
        self._crashed = ShardWorkerCrash(
            w.shard, detail,
            last_metrics=fed.get("metrics"),
            last_spans=fed.get("trace"))
        return self._crashed

    def _federate(self, w: _Worker, fed: dict | None) -> None:
        if fed is None:
            return
        w.last_fed = fed
        from ..obs.federate import FEDERATION
        FEDERATION.update_from_samples(
            f"shard-{w.shard}", fed["metrics"], trace=fed.get("trace"))

    def _ssend(self, w: _Worker, msg: tuple) -> None:
        try:
            _write_msg(w.proc.stdin, msg)
        except (OSError, ValueError) as e:
            raise self._crash(w, repr(e)) from None

    def _recv(self, w: _Worker):
        try:
            return _read_msg(w.proc.stdout)
        except (EOFError, OSError) as e:
            raise self._crash(w, repr(e)) from None

    # -- the conservative-window exchange plane --

    def _wnext(self, w: _Worker) -> float:
        nxt = w.next
        if w.pending:
            nxt = min(nxt, min(p[0] for p in w.pending))
        return nxt

    def _flush_and_run(self, need: list, upto: float,
                       inclusive: bool) -> bool:
        """One exchange round: grant ``need`` the horizon, route what
        comes back. Returns True if a frame landed on the PARENT wheel
        at or before ``upto`` (same-instant work to process)."""
        self.barrier_rounds += 1
        metrics.sim_shard_barrier_waits.inc()
        for w in need:
            ops = self._ops_log[w.ops_cursor:]
            w.ops_cursor = len(self._ops_log)
            frames = [(a, dst, item)
                      for a, _, dst, item in sorted(w.pending)]
            w.pending = []
            self._ssend(w, ("run", upto, inclusive, ops, frames))
        local_new = False
        for w in need:
            tag, nxt, out, fed = self._recv(w)
            if tag != "done":
                raise ShardWorkerCrash(w.shard, f"bad reply {tag!r}")
            self._federate(w, fed)
            w.next = nxt
            for arrival, _, dst, item in sorted(out):
                dshard = self._shard_of.get(dst, 0)
                if dshard == 0:
                    self._schedule_at(arrival, dst, item)
                    if arrival <= upto + _EPS and inclusive:
                        local_new = True
                else:
                    self._workers[dshard - 1].pending.append(
                        (arrival, next(self._out_seq), dst, item))
        return local_new

    def _settle(self, now: float) -> bool:
        """Drive every shard through the current instant: flush pending
        ops/frames and run same-instant exchange rounds until no frame
        at <= now remains anywhere. Returns True if the PARENT received
        same-instant work (the caller must let the loop run it before
        advancing time)."""
        local_new = False
        for _ in range(_MAX_ROUNDS):
            need = [w for w in self._workers
                    if w.pending or w.ops_cursor < len(self._ops_log)
                    or w.next <= now + _EPS]
            if not need:
                return local_new
            local_new |= self._flush_and_run(need, now, True)
        raise RuntimeError("sim shard settlement did not quiesce")

    def _run_window(self, horizon: float) -> None:
        """Grant every lagging worker the safe window [*, horizon):
        lookahead guarantees everything generated inside arrives at or
        after the horizon, so one round suffices unless ops trickle."""
        for _ in range(_MAX_ROUNDS):
            need = [w for w in self._workers
                    if w.ops_cursor < len(self._ops_log)
                    or self._wnext(w) < horizon - _EPS]
            if not need:
                return
            self._flush_and_run(need, horizon, False)
        raise RuntimeError("sim shard window did not quiesce")

    def governor(self, now: float, proposed: float | None):
        """VirtualClockLoop.time_governor hook — returns the next
        virtual instant the parent may advance to."""
        if self._crashed is not None:
            raise self._crashed
        if not self._spawned:
            self._spawn()
        if self._settle(now):
            return now    # same-instant frames landed: process first
        cap = _INF if proposed is None else proposed
        nxt = min((self._wnext(w) for w in self._workers), default=_INF)
        if nxt < cap:
            lookahead = self.network.min_delay_floor()
            if lookahead > 0.0:
                self._run_window(nxt + lookahead)
                nxt = min((self._wnext(w) for w in self._workers),
                          default=_INF)
            # lookahead 0: advance to nxt; settle() there runs the
            # zero-delay exchange rounds at that single instant
        target = min(cap, nxt, self._timer_due)
        return None if target == _INF else target

    async def drain(self) -> None:
        """Quiesce the WHOLE fabric at the current instant: parent
        drainers, worker wheels, and the same-instant relay chains that
        bounce between them (light -> full -> light needs the parent
        loop to run between exchange rounds)."""
        loop = asyncio.get_running_loop()
        for _ in range(_MAX_ROUNDS):
            await super().drain()
            # spacecheck: ok=SC001 exchange rounds settle AT the engine's current virtual instant
            if self._spawned and self._settle(loop.time()):
                await asyncio.sleep(0)   # fire the just-landed frames
                continue
            # spacecheck: ok=SC001 due-frame check against the same virtual clock the wheel is keyed on
            if self._wheel and self._wheel[0][0] <= loop.time() + _EPS:
                await asyncio.sleep(0)   # due parent frames not yet fired
                continue
            return
        raise RuntimeError("sim shard drain did not quiesce")

    # -- merge plane: counts, stats, metrics --

    def light_counts(self, topic: str) -> dict:
        """Merged per-light delivery counts for one topic (distinct
        messages seen — arrival-order invariant)."""
        if self._final is not None or not self._spawned:
            return {name: c for (name, t), c in self._counts.items()
                    if t == topic}
        # spacecheck: ok=SC001 engine-owned VirtualClockLoop instant
        self._settle(asyncio.get_running_loop().time())
        out: dict = {}
        for w in self._workers:
            self._ssend(w, ("counts", topic))
        for w in self._workers:
            tag, d = self._recv(w)
            if tag != "counts":
                raise ShardWorkerCrash(w.shard, f"bad reply {tag!r}")
            out.update(d)
        return out

    def finalize(self) -> None:
        """Drain every shard through the current instant, then merge
        worker stats/counts into the parent's (idempotent; the engine
        calls this before recording the merged event record)."""
        if self._final is not None or not self._spawned:
            self._final = self._final or []
            return
        # spacecheck: ok=SC001 engine-owned VirtualClockLoop instant
        self._settle(asyncio.get_running_loop().time())
        self._final = []
        fired = [self.stats["events_fired"]]
        for w in self._workers:
            self._ssend(w, ("finalize",))
        for w in self._workers:
            tag, stats, counts, netstats, fed = self._recv(w)
            if tag != "final":
                raise ShardWorkerCrash(w.shard, f"bad reply {tag!r}")
            self._federate(w, fed)
            if fed and fed.get("trace") is not None:
                self.worker_captures[f"shard-{w.shard}"] = fed["trace"]
            self._final.append((w.shard, stats))
            fired.append(stats["events_fired"])
            for k, v in stats.items():
                self.stats[k] = self.stats.get(k, 0) + v
            for k, v in netstats.items():
                self.network.stats[k] = self.network.stats.get(k, 0) + v
            for key, c in counts.items():
                self._counts[key] = self._counts.get(key, 0) + c
            metrics.sim_shard_events.inc(stats["events_fired"],
                                         shard=str(w.shard), kind="fired")
        metrics.sim_shard_events.inc(fired[0], shard="0", kind="fired")
        top = max(fired)
        metrics.sim_shard_imbalance.set(
            (top - min(fired)) / top if top else 0.0)


if __name__ == "__main__":   # pragma: no cover — the worker entry point
    sys.exit(worker_main())
