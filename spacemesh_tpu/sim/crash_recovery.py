"""Deterministic crash-recovery scenario over the POST storage plane.

The network scenario engine (sim/scenario.py) exercises whole nodes;
this engine exercises the CRASH SAFETY of the POST data plane the same
way: scripted, seeded, replayable — same seed, byte-identical outcome
digest across processes (the CLI's ``--repeat`` contract;
sim/__main__.py dispatches here when a script carries
``"engine": "crashrec"``).

One run:

1. an **uninjected reference init** (tiny geometry from the script)
   through a counting :class:`post.faultfs.FaultFS` — its mutating-op
   total defines the crash sites, its store sha256 the ground truth;
2. a seeded selection of op indices (``crash_every``-th site, offset
   by the seed) each gets a fresh data dir and a scripted fault —
   power-cut and torn-write variants alternate — then crash → reboot
   (un-fsynced bytes vanish) → reopen → recovery → resume, looping
   until the init completes; the finished store must hash identical
   to the reference;
3. an **ENOSPC phase**: the disk "fills" at a scripted op for a
   scripted hold window; the writer pool must park (degraded — the
   ``post.store`` probe flips, sampled from inside the injection
   hook), resume when the plan releases space, and still converge
   bit-identically.

Determinism: faults fire at exact op counts (no wall clock), label
computation is bit-deterministic, the writer pool runs one thread, and
metadata checkpoints are label-interval-driven (the time interval is
pinned far away), so the whole event log replays byte-identically.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
import shutil
import tempfile
from pathlib import Path

from ..obs import health as health_mod
from ..post import faultfs, initializer
from ..post.data import LabelStore, PostMetadata
from ..utils import metrics

NODE_SEED = b"crashrec-node"
COMMIT_SEED = b"crashrec-commit"
MAX_RESUMES = 6


@dataclasses.dataclass
class CrashRecResult:
    """CLI-compatible result (sim/__main__.py prints digest/ok/slis/
    stats["hub"] for every engine)."""

    name: str
    seed: int
    digest: str
    ok: bool
    asserts: list
    slis: dict
    stats: dict
    events: list

    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "seed": self.seed, "digest": self.digest,
            "ok": self.ok, "asserts": self.asserts, "slis": self.slis,
            "stats": self.stats, "events": self.events,
        }, indent=1, sort_keys=True)


def _init_kwargs(script: dict) -> dict:
    labels = int(script.get("labels", 512))
    return dict(
        node_id=hashlib.sha256(NODE_SEED).digest(),
        commitment=hashlib.sha256(COMMIT_SEED).digest(),
        num_units=1, labels_per_unit=labels,
        scrypt_n=int(script.get("scrypt_n", 2)),
        max_file_size=int(script.get("max_file_size", 4096)),
        batch_size=int(script.get("batch", 128)),
        writers=1, mesh=None, save_barrier=True,
        meta_interval_s=1e9,  # label-driven checkpoints only (determinism)
        meta_interval_labels=int(script.get("interval_labels", 128)),
    )


def _store_sha(d) -> tuple[str, int]:
    meta = PostMetadata.load(d)
    store = LabelStore(d, meta)
    try:
        sha = hashlib.sha256(
            store.read_labels(0, meta.total_labels)).hexdigest()
    finally:
        store.close()
    return sha, int(meta.vrf_nonce if meta.vrf_nonce is not None else -1)


def _run_to_completion(d, kw: dict, fs: faultfs.FaultFS,
                       enospc_retry_s: float = 0.01) -> int:
    """Drive one init across crash/reboot cycles; returns reboots."""
    reboots = 0
    while True:
        try:
            initializer.initialize(d, fs=fs,
                                   enospc_retry_s=enospc_retry_s, **kw)
            return reboots
        except BaseException as e:  # noqa: BLE001 — PowerCut rides behind pool errors
            if faultfs.power_cut_behind(e) is None:
                raise
            if reboots >= MAX_RESUMES:
                raise RuntimeError(
                    f"init did not converge within {MAX_RESUMES} "
                    "reboots") from e
            fs.reboot()
            reboots += 1


def run_scenario(script: dict) -> CrashRecResult:
    seed = int(script.get("seed", 7))
    rng = random.Random(seed)
    kw = _init_kwargs(script)
    events: list = []
    faults_before = metrics.post_store_fault_injections.sample()
    recov_before = metrics.post_store_recovery_runs.sample()

    root = Path(tempfile.mkdtemp(prefix="crashrec-"))
    try:
        # 1. uninjected reference
        ref_dir = root / "ref"
        count_fs = faultfs.FaultFS()
        initializer.initialize(ref_dir, fs=count_fs, **kw)
        total_ops = count_fs.write_ops
        ref_sha, ref_nonce = _store_sha(ref_dir)
        events.append({"phase": "reference", "ops": total_ops,
                       "sha": ref_sha[:16], "vrf_nonce": ref_nonce})

        # 2. seeded crash sweep: every crash_every-th op site, phase
        # offset drawn from the seed, variants alternating
        every = max(int(script.get("crash_every", 3)), 1)
        offset = rng.randrange(every)
        variants = list(script.get("variants") or ["powercut", "torn"])
        for i, op in enumerate(range(1 + offset, total_ops + 1, every)):
            kind = variants[i % len(variants)]
            d = root / f"crash-{op}-{kind}"
            plan = faultfs.FaultPlan(
                [faultfs.FaultSpec(op=op, kind=kind)], seed=seed)
            fs = faultfs.FaultFS(plan)
            reboots = _run_to_completion(d, kw, fs)
            sha, nonce = _store_sha(d)
            events.append({
                "phase": "crash", "op": op, "kind": kind,
                "reboots": reboots,
                "fired": [e["kind"] for e in fs.injected],
                "bit_identical": sha == ref_sha and nonce == ref_nonce,
            })

        # 3. ENOSPC: the disk fills mid-init and stays full for a
        # scripted op window; the probe must flip degraded (sampled
        # from inside the injection hook — deterministic, sleep-free)
        en = dict(script.get("enospc") or {"op": 2, "hold": 6})
        degraded_seen = [False]

        def on_inject(spec, n):
            if spec.kind != "enospc":
                return
            report = health_mod.HEALTH.report(0.0)
            ent = report.get("post.store")
            if ent is not None and not ent["healthy"]:
                degraded_seen[0] = True

        d = root / "enospc"
        plan = faultfs.FaultPlan(
            [faultfs.FaultSpec(op=int(en.get("op", 2)), kind="enospc",
                               hold_ops=int(en.get("hold", 6)))],
            seed=seed, on_inject=on_inject)
        fs = faultfs.FaultFS(plan)
        reboots = _run_to_completion(d, kw, fs)
        sha, nonce = _store_sha(d)
        events.append({
            "phase": "enospc", "op": int(en.get("op", 2)),
            "hold": int(en.get("hold", 6)), "reboots": reboots,
            "degraded_seen": degraded_seen[0],
            "waits": len([e for e in fs.injected
                          if e["kind"] == "enospc"]),
            "bit_identical": sha == ref_sha and nonce == ref_nonce,
        })
    finally:
        shutil.rmtree(root, ignore_errors=True)

    faults_after = metrics.post_store_fault_injections.sample()
    recov_after = metrics.post_store_recovery_runs.sample()
    fault_delta = (sum(faults_after.values())
                   - sum(faults_before.values()))
    recov_delta = (sum(recov_after.values())
                   - sum(recov_before.values()))

    crash_events = [e for e in events if e["phase"] == "crash"]
    asserts = []
    for spec in script.get("asserts") or (
            [{"kind": "bit_identical"}, {"kind": "recovered", "min": 1}]):
        kind = spec.get("kind")
        ent = dict(spec)
        if kind == "bit_identical":
            bad = [e for e in events if e.get("bit_identical") is False]
            ent["ok"] = not bad and bool(crash_events)
            ent["detail"] = (f"{len(bad)} diverging stores of "
                            f"{len(crash_events) + 1} injected runs")
        elif kind == "recovered":
            n = sum(e["reboots"] for e in crash_events)
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} crash/reboot/resume cycles"
        elif kind == "enospc_degraded":
            en_ev = [e for e in events if e["phase"] == "enospc"]
            ent["ok"] = bool(en_ev) and en_ev[0]["degraded_seen"] \
                and en_ev[0]["bit_identical"]
            ent["detail"] = f"enospc events: {en_ev}"
        elif kind == "fault_metrics":
            ent["ok"] = fault_delta >= int(spec.get("min", 1)) \
                and recov_delta >= 1
            ent["detail"] = (f"{fault_delta} injections, "
                            f"{recov_delta} recovery runs")
        else:
            ent["ok"] = False
            ent["detail"] = f"unknown assert kind {kind!r}"
        asserts.append(ent)

    # digest covers ONLY replay-stable facts: script identity + the
    # per-run outcome log (metric deltas are cross-run cumulative on a
    # shared registry, so they argue in asserts, not the digest)
    digest_doc = {
        "name": script.get("name"), "seed": seed, "engine": "crashrec",
        "events": events,
        "asserts": [{k: v for k, v in a.items() if k != "detail"}
                    for a in asserts],
    }
    digest = hashlib.sha256(
        json.dumps(digest_doc, sort_keys=True).encode()).hexdigest()[:16]
    hub = {
        "runs": len(crash_events) + 2,
        "crashes": sum(e["reboots"] for e in crash_events),
        "op_sites": len(crash_events),
        "enospc_waits": next((e["waits"] for e in events
                              if e["phase"] == "enospc"), 0),
    }
    return CrashRecResult(
        name=str(script.get("name", "crash-recovery")), seed=seed,
        digest=digest, ok=all(a["ok"] for a in asserts),
        asserts=asserts, slis={}, stats={"hub": hub}, events=events)
