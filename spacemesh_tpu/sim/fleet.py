"""Deterministic verifyd FLEET scenario: the whole control plane under
chaos (verifyd/fleet.py, docs/VERIFYD.md).

One :class:`~..verifyd.fleet.FleetVerifier` drives thousands of placed
client identities across three in-process sharded
:class:`~..verifyd.service.VerifydService` replicas (``shard=`` keeps
their registries, tenant namespaces and metric series disjoint inside
one process) through killable transports, the way every sim engine
runs: seeded, scripted, on a virtual clock advanced only between waves,
with a replay-stable event digest (``--repeat N`` must produce
byte-identical digests).  ``sim/__main__.py`` dispatches here when a
script carries ``"engine": "fleet"``.

What the drill must prove, all from one script:

* **Sharded admission** — client placement fills the FLEET-wide bound
  (the sum of the replicas' router-side ``max_clients``); the client
  past it hears a typed ``registry_full``, never a silent serve.
* **Re-route, don't surface** — a replica whose own registry is full
  sheds typed; the router re-places the client on its next ring choice
  and the caller never sees the shed.
* **Work stealing** — a replica made hot (shed pressure on its kinds)
  is stolen from: chains for its clients try the coolest healthy
  replica first, visibly (``fleet_steals_total``).
* **Replica kill mid-load** — the killed replica's breaker opens after
  its failure budget (attempts against the corpse stay bounded), its
  clients' requests keep being answered by the survivors with verdicts
  bit-identical to inline verification, and the BLOCK-lane p99 SLO
  stays green from windowed SLIs on the virtual clock.
* **Full blackout → local farm** — with every replica dead the local
  farm serves every request (the bit-identical last resort), and after
  restore the fleet half-open-probes its way back to remote serving.
* **Autoscaling signal** — the router folds the windowed per-replica
  SLIs into load scores and the ``fleet_desired_replicas`` gauge; the
  script asserts the signal reacts to the hot span.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import json
import random

from ..obs import remediate as remediate_mod
from ..obs import sli as sli_mod
from ..utils import metrics, tracing
from ..verify.farm import Lane
from ..verifyd import protocol
from ..verifyd.fleet import FleetRouter, FleetVerifier
from ..verifyd.service import Shed, VerifydService
from .verifyd_load import _VClock, _build_pools, _pick_items

_LANES = (Lane.BLOCK, Lane.GOSSIP, Lane.SYNC)


@dataclasses.dataclass
class FleetSimResult:
    """CLI-compatible result (sim/__main__.py prints digest/ok/slis/
    stats["hub"] for every engine)."""

    name: str
    seed: int
    digest: str
    ok: bool
    asserts: list
    slis: dict
    stats: dict
    events: list

    def to_json(self) -> str:
        return json.dumps({
            "name": self.name, "seed": self.seed, "digest": self.digest,
            "ok": self.ok, "asserts": self.asserts, "slis": self.slis,
            "stats": self.stats, "events": self.events,
        }, indent=1, sort_keys=True, default=str)


def _digest_of(script: dict, events: list, asserts: list) -> str:
    doc = {
        "name": script.get("name"), "seed": script.get("seed"),
        "engine": "fleet", "waves": script.get("waves"),
        "events": events,
        "asserts": [{k: v for k, v in a.items() if k != "detail"}
                    for a in asserts],
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


class _ReplicaTransport:
    """One replica's endpoint: an in-process sharded verifyd service
    behind a kill switch.  ``down=True`` is the wire's view of a killed
    replica — every call raises ConnectionError (and is counted, so the
    script can assert the breaker bounded attempts against the corpse).
    """

    def __init__(self, service: VerifydService):
        self.service = service
        self.down = False
        self.byzantine = False   # answer with flipped (wrong) verdicts
        self.calls = 0
        self.calls_down = 0

    def _gate(self) -> None:
        self.calls += 1
        if self.down:
            self.calls_down += 1
            raise ConnectionError(
                f"replica {self.service.shard} is down")

    async def register(self, client: str, **kwargs) -> dict:
        self._gate()
        kwargs.setdefault("rate", 1e9)
        kwargs.setdefault("burst", 1e9)
        kwargs.setdefault("max_queued", 4096)
        self.service.register_client(str(client), **kwargs)
        return {"client": str(client)}

    async def unregister(self, client: str) -> None:
        self._gate()
        self.service.unregister_client(str(client))

    async def verify(self, reqs: list, *, client: str,
                     lane: str = "gossip",
                     deadline_s: float | None = None) -> list[bool]:
        self._gate()
        verdicts = await self.service.verify(
            str(client), reqs, lane=protocol.parse_lane(lane),
            deadline_s=deadline_s)
        if self.byzantine:
            # a stale/hostile replica: transport healthy, admission
            # healthy, every verdict wrong — only a verdict-level audit
            # can catch this failure mode
            return [not v for v in verdicts]
        return verdicts

    async def aclose(self) -> None:
        return None


async def _run(script: dict, pools: dict, clock: _VClock, events: list,
               stats_out: dict, slis_out: dict) -> None:
    from ..verify.farm import VerificationFarm

    w = pools["workload"]
    seed = int(script.get("seed", 7))
    rng = random.Random(seed)
    waves = int(script.get("waves", 18))
    interval = float(script.get("wave_interval_s", 0.5))
    br_cfg = dict(script.get("breaker") or {})
    faults = dict(script.get("faults") or {})
    kill = dict(faults.get("kill") or {})
    blackout = dict(faults.get("blackout") or {})
    byzantine = dict(faults.get("byzantine") or {})
    ccfg = dict(script.get("clients") or {})

    services: dict[str, VerifydService] = {}
    transports: dict[str, _ReplicaTransport] = {}
    router = FleetRouter(seed=seed, time_source=clock.now)
    local_farm = VerificationFarm(ed_verifier=w.ed, vrf_verifier=w.vrf,
                                  post_params=w.post_params,
                                  post_seed=w.post_seed)

    def on_transition(name: str):
        def cb(frm: str, to: str) -> None:
            events.append({"breaker": to, "from": frm, "replica": name,
                           "t": round(clock.now(), 6)})
        return cb

    replica_specs = list(script.get("replicas") or ())
    for spec in replica_specs:
        name = str(spec["name"])
        svc_cfg = dict(spec.get("service") or {})
        svc_cfg.setdefault("workers", 2)
        service = VerifydService(time_source=clock.now, shard=name,
                                 **svc_cfg)
        service.farm.ed_verifier = w.ed
        service.farm.vrf_verifier = w.vrf
        service.farm.post_params = w.post_params
        service.farm.post_seed = w.post_seed
        services[name] = service
        transports[name] = _ReplicaTransport(service)
        breaker = remediate_mod.CircuitBreaker(
            f"verifyd.replica.{name}",
            failure_budget=int(br_cfg.get("failure_budget", 2)),
            window_s=float(br_cfg.get("window_s", 60.0)),
            cooldown_s=float(br_cfg.get("cooldown_s", 1.0)),
            cooldown_cap_s=float(br_cfg.get("cooldown_cap_s", 2.0)),
            seed=seed, time_source=clock.now,
            on_transition=on_transition(name))
        router.register_replica(
            name, transports[name], breaker=breaker,
            max_clients=int(spec.get("router_max_clients", 64)))

    holder: dict = {}

    def observer(kind: str, **kw) -> None:
        if kind == "served":
            holder.update(kw)
        elif kind == "audit_divergence":
            events.append({"audit_divergence": str(kw.get("replica")),
                           "index": int(kw.get("index", 0)),
                           "t": round(clock.now(), 6)})

    audit = dict(script.get("audit") or {})
    fv = FleetVerifier(router=router, farm=local_farm,
                       own_router=True, observer=observer,
                       time_source=clock.now,
                       audit_k=int(audit.get("items", 0)))
    sampler = sli_mod.SliSampler(metrics.REGISTRY, window_s=3600.0)
    replica_names = sorted(services)
    sli_specs = sli_mod.fleet_slis(replica_names)

    try:
        for service in services.values():
            await service.start()
        fv.start()

        # fill placement to the FLEET bound: per-shard registries make
        # admission capacity the SUM of the replicas' bounds
        total = int(ccfg.get("placed") or router.fleet_max_clients())
        placed = [f"c{i:04d}" for i in range(total)]
        for cid in placed:
            router.place_client(cid)
        overflow = [f"over-{i}" for i in
                    range(int(ccfg.get("overflow", 2)))]
        hot_replica = str(ccfg.get("hot_replica", replica_specs[0]["name"]))
        pinned = sorted(
            c for c, r in router.placement.assign.items()
            if r == hot_replica)[:int(ccfg.get("pinned_hot", 3))]
        active_n = int(ccfg.get("active_per_wave", 16))
        lo, hi = (ccfg.get("items") or [2, 4])[:2]
        mix = ccfg.get("mix") or {"sig": 6, "vrf": 1, "pow": 2}

        sampler.sample(clock.now())
        for wave in range(waves):
            if wave == int(kill.get("wave", -1)):
                transports[str(kill["replica"])].down = True
                events.append({"fault": "kill_replica",
                               "replica": str(kill["replica"]),
                               "wave": wave})
            if wave == int(kill.get("restore_wave", -1)):
                transports[str(kill["replica"])].down = False
                events.append({"fault": "restore_replica",
                               "replica": str(kill["replica"]),
                               "wave": wave})
            if wave == int(blackout.get("wave", -1)):
                for name, t in transports.items():
                    t.down = True
                events.append({"fault": "blackout", "wave": wave})
            if wave == int(blackout.get("restore_wave", -1)):
                for name, t in transports.items():
                    t.down = False
                events.append({"fault": "restore_all", "wave": wave})
            if wave == int(byzantine.get("wave", -1)):
                transports[str(byzantine["replica"])].byzantine = True
                events.append({"fault": "byzantine_replica",
                               "replica": str(byzantine["replica"]),
                               "wave": wave})
            if wave == int(byzantine.get("restore_wave", -1)):
                transports[str(byzantine["replica"])].byzantine = False
                events.append({"fault": "restore_byzantine",
                               "replica": str(byzantine["replica"]),
                               "wave": wave})

            active = list(pinned)
            for cid in rng.sample(placed, active_n):
                if cid not in active:
                    active.append(cid)
            for idx, cid in enumerate(active + overflow):
                picked = _pick_items(rng, pools["pools"], mix,
                                     rng.randint(int(lo), int(hi)))
                reqs = [p[0] for p in picked]
                exp = [bool(p[1]) for p in picked]
                lane = _LANES[idx % len(_LANES)]
                ent = {"client": cid, "wave": wave,
                       "lane": lane.name.lower(),
                       "kinds": [q.kind for q in reqs],
                       "expected": exp}
                try:
                    verdicts = await fv.verify_batch(reqs, lane,
                                                     client_id=cid)
                except Shed as e:
                    ent.update({"outcome": f"shed:{e.reason}",
                                "verdicts": None, "served_by": None,
                                "path": None})
                else:
                    ent.update({"outcome": "ok",
                                "verdicts": list(verdicts),
                                "served_by": holder.get("served_by"),
                                "path": holder.get("path")})
                holder.clear()
                events.append(ent)

            clock.advance(interval)
            sampler.sample(clock.now())
            values = {}
            for spec in sli_specs:
                v = sampler.compute(spec)
                if v is not None:
                    values[spec.name] = v
            sig = router.update_signals(values)
            events.append({
                "wave": wave,
                "signals": {k: round(v, 4)
                            for k, v in sorted(sig["scores"].items())},
                "desired": int(sig["desired_replicas"])})

        slis_out.update({k: v for k, v in values.items()})
        stats_out.update({
            "router": router.state_doc(),
            "verifier": dict(fv.stats),
            "transports": {
                name: {"calls": t.calls, "calls_down": t.calls_down}
                for name, t in sorted(transports.items())},
            "services": {name: s.stats_doc()
                         for name, s in sorted(services.items())},
        })
    finally:
        for name in sorted(services):
            router.unregister_replica(name)
        await fv.aclose()
        for service in services.values():
            await service.aclose()
        await local_farm.aclose()


def _evaluate(script: dict, events: list, stats: dict,
              slis: dict, merged: dict | None = None) -> list:
    served = [e for e in events if e.get("outcome") == "ok"]
    shed = [e for e in events
            if str(e.get("outcome", "")).startswith("shed:")]
    wrong = [e for e in served if e["verdicts"] != e["expected"]]
    faults = dict(script.get("faults") or {})
    kill = dict(faults.get("kill") or {})
    blackout = dict(faults.get("blackout") or {})
    transitions = [(e["replica"], e["breaker"]) for e in events
                   if "breaker" in e]
    rstats = (stats.get("router") or {}).get("stats") or {}
    asserts = []
    for spec in script.get("asserts") or [{"kind": "no_wrong_verdicts"}]:
        kind = spec.get("kind")
        ent = dict(spec)
        if kind == "no_wrong_verdicts":
            ent["ok"] = not wrong
            ent["detail"] = f"{len(wrong)} diverging of {len(served)}"
        elif kind == "typed_sheds_only":
            # every non-served outcome is a TYPED shed, and only of the
            # reasons the script declares survivable
            allowed = set(spec.get("reasons") or ())
            reasons = {e["outcome"].split(":", 1)[1] for e in shed}
            answered = all("outcome" in e
                           for e in events if "client" in e)
            ent["ok"] = answered and reasons <= allowed
            ent["detail"] = f"shed reasons seen: {sorted(reasons)}"
        elif kind == "shed":
            reason = spec.get("reason")
            n = sum(1 for e in shed
                    if (spec.get("client") is None
                        or e["client"].startswith(spec["client"]))
                    and (reason is None
                         or e["outcome"] == f"shed:{reason}"))
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} sheds"
        elif kind == "path_served":
            if "replica" in spec:
                n = sum(1 for e in served
                        if e["served_by"] == spec["replica"])
                what = f"replica {spec['replica']}"
            else:
                n = sum(1 for e in served
                        if e["path"] == spec["path"]
                        or (spec["path"] == "local"
                            and e["path"] == "local_fastfail"))
                what = f"path {spec['path']}"
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} requests via {what}"
        elif kind == "blackout_local":
            span = [e for e in served
                    if int(blackout.get("wave", 1 << 30)) <= e["wave"]
                    < int(blackout.get("restore_wave", -1))]
            bad = [e for e in span if not e["path"].startswith("local")]
            ent["ok"] = bool(span) and not bad
            ent["detail"] = (f"{len(span)} blackout requests, "
                             f"{len(bad)} claimed remote")
        elif kind == "dead_replica_attempts_bounded":
            # the per-replica breaker's whole point: the corpse is paid
            # budget + half-open probes, NOT once per request
            name = str(spec.get("replica", kill.get("replica")))
            n = stats["transports"][name]["calls_down"]
            ent["ok"] = n <= int(spec["max"])
            ent["detail"] = f"{n} calls against dead {name}"
        elif kind == "failback":
            last_wave = max((e["wave"] for e in served), default=-1)
            tail = [e for e in served if e["wave"] == last_wave
                    and e["outcome"] == "ok"]
            ent["ok"] = bool(tail) and all(e["path"] == "remote"
                                           for e in tail)
            ent["detail"] = (f"wave {last_wave}: "
                             f"{sorted({e['path'] for e in tail})}")
        elif kind == "byzantine_detected":
            byz = dict(faults.get("byzantine") or {})
            name = str(spec.get("replica", byz.get("replica")))
            n = sum(1 for e in events
                    if e.get("audit_divergence") == name)
            stray = sum(1 for e in events
                        if "audit_divergence" in e
                        and e["audit_divergence"] != name)
            ent["ok"] = n >= int(spec.get("min", 1)) and stray == 0
            ent["detail"] = (f"{n} divergences on {name}, "
                             f"{stray} on honest replicas")
        elif kind == "breaker_sequence":
            name = str(spec.get("replica", kill.get("replica")))
            seq = [t for r, t in transitions if r == name]
            want = ["open", "half_open", "closed"]
            it = iter(seq)
            ent["ok"] = all(any(t == step for t in it) for step in want)
            ent["detail"] = f"{name} transitions: {seq}"
        elif kind == "reroutes":
            n = int(rstats.get("reroutes", 0))
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} reroutes"
        elif kind == "steals":
            n = int(rstats.get("steals", 0))
            ent["ok"] = n >= int(spec.get("min", 1))
            ent["detail"] = f"{n} steals"
        elif kind == "fleet_bound":
            placed = ((stats.get("router") or {}).get("placement")
                      or {}).get("clients", 0)
            bound = (stats.get("router") or {}).get("fleet_max_clients", 0)
            ent["ok"] = placed == bound == int(spec["clients"])
            ent["detail"] = f"{placed} placed of bound {bound}"
        elif kind == "autoscale":
            peaks = [e["desired"] for e in events if "desired" in e]
            peak = max(peaks, default=0)
            ent["ok"] = peak >= int(spec.get("min_desired", 1))
            ent["detail"] = f"desired_replicas peak {peak}"
        elif kind == "slo_green":
            name = spec.get("name", "fleet_block_p99")
            value = slis.get(name)
            target = float(spec.get("target", 0.25))
            ent["ok"] = value is not None and value <= target
            ent["detail"] = f"{name}={value} target<={target}"
        elif kind == "sli_present":
            ent["ok"] = spec.get("name") in slis
            ent["detail"] = f"slis: {sorted(slis)}"
        elif kind == "merged_capture":
            # digest-stable merged-timeline facts (ISSUE 20): the run's
            # capture validates clean, carries spans, and resolves every
            # cross-process link token it saw. "detail" (excluded from
            # the digest) carries the raw numbers.
            od = (merged or {}).get("otherData") or {}
            links = dict(od.get("links") or {})
            clean = merged is not None
            if clean:
                try:
                    tracing.validate(merged)
                except Exception:  # noqa: BLE001 — judged, not raised
                    clean = False
            spans = int(od.get("captured_spans") or 0)
            ent["ok"] = (clean and spans >= int(spec.get("min_spans", 1))
                         and int(links.get("unresolved", 0)) == 0)
            ent["detail"] = (f"{spans} spans over "
                             f"{len(od.get('procs') or [])} procs, "
                             f"unresolved={links.get('unresolved', 0)}")
        else:
            ent["ok"] = False
            ent["detail"] = f"unknown assert kind {kind!r}"
        asserts.append(ent)
    return asserts


def run_scenario(script: dict) -> FleetSimResult:
    """Run one fleet script (fresh services, fresh loop); returns the
    CLI-compatible result with the replay-stable event digest."""
    import tempfile

    events: list = []
    stats: dict = {}
    slis: dict = {}
    clock = _VClock()
    # capture the whole drill so merged_capture asserts can judge the
    # timeline; an already-running outer capture is used as-is
    own_trace = not tracing.is_enabled()
    if own_trace:
        tracing.set_process_identity("fleet-sim")
        tracing.start(capacity=1 << 16)
    with tempfile.TemporaryDirectory() as d:
        pools = _build_pools(script, d)
        asyncio.run(_run(script, pools, clock, events, stats, slis))
    merged = tracing.merge_captures([tracing.export()])
    if own_trace:
        tracing.stop()
    asserts = _evaluate(script, events, stats, slis, merged=merged)
    served = [e for e in events if e.get("outcome") == "ok"]
    hub = {
        "requests": sum(1 for e in events if "client" in e),
        "served": len(served),
        "remote": sum(1 for e in served if e["path"] == "remote"),
        "local": sum(1 for e in served
                     if str(e["path"]).startswith("local")),
        "shed": sum(1 for e in events
                    if str(e.get("outcome", "")).startswith("shed:")),
        "placed_clients": ((stats.get("router") or {}).get("placement")
                           or {}).get("clients", 0),
        "steals": ((stats.get("router") or {}).get("stats")
                   or {}).get("steals", 0),
        "reroutes": ((stats.get("router") or {}).get("stats")
                     or {}).get("reroutes", 0),
    }
    return FleetSimResult(
        name=str(script.get("name", "fleet")),
        seed=int(script.get("seed", 7)),
        digest=_digest_of(script, events, asserts),
        ok=all(a["ok"] for a in asserts), asserts=asserts, slis=slis,
        stats={"hub": hub, **stats}, events=events)
