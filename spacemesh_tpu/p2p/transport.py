"""TCP transport: real sockets beneath the PubSub / Server seams.

Fills the round-1 gap ("no sockets anywhere"): one `Host` per node owns a
TCP listener plus outbound dials and slots in as BOTH the pubsub hub
(`PubSub._hub`) and the request/response net (`Server._net`), so every
existing protocol component runs unchanged over a real network.

Reference parity (behavior, not mechanism — the reference rides libp2p):
- noise security: every connection runs an X25519+ChaCha20-Poly1305
  channel (p2p/noise.py) and the peer's node id is its ed25519 key,
  PROVEN by a channel-binding signature in the encrypted HELLO — ids
  are unforgeable (reference p2p/host.go:27-28, 306-309: libp2p noise +
  key-derived peer ids).
- network-cookie handshake: the 20-byte genesis id salts the channel
  keys AND rides in the HELLO; mismatch fails decryption / closes the
  connection (reference p2p/handshake/handshake.go — splits testnets
  from mainnet).
- gossip: flood-publish with content-id dedup and relay-on-accept; a
  validation reject penalizes the sending peer and repeated rejects drop
  it (reference pubsub.go:168 DropPeerOnValidationReject, gossipsub
  scoring).
- req/resp: varint-style framed request/response streams with per-request
  correlation ids (reference p2p/server/server.go).
- peer exchange + redial: HELLO carries the listen port; peers gossip
  known addresses and a maintainer task keeps dialing until min_peers
  (reference p2p discovery/bootstrap, p2p/dhtdiscovery).

Framing: u32 LE length, then u8 frame type, then the payload. One
connection per peer pair (simultaneous-dial ties broken by node id:
the dial initiated by the LOWER id survives).
"""

from __future__ import annotations

import asyncio
import random
import struct
import time
from typing import Optional

from ..core.hashing import sum256
from .noise import ChannelError, NoiseChannel

MSG_HELLO = 0
MSG_GOSSIP = 1
MSG_REQ = 2
MSG_RESP = 3
MSG_PEERS = 4
MSG_GOSSIP_CTRL = 5   # gossipsub-lite GRAFT/PRUNE/IHAVE/IWANT
MSG_FIND = 6          # iterative discovery: find peers near a target id
MSG_FOUND = 7         # reply: (id, addr) entries sorted by XOR distance

MAX_FRAME = 64 << 20
SEEN_CAP = 1 << 14


class HandshakeError(Exception):
    pass


def _xor_dist(a: bytes, b: bytes) -> int:
    """Kademlia XOR metric over 32-byte ids."""
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


SEND_QUEUE_CAP = 4096


class _Conn:
    """One live peer connection (post-handshake).

    Outbound frames go through a bounded per-connection queue drained by a
    writer task: a stalled peer (full socket buffer, SIGSTOP'd process)
    must never block the sender's consensus rounds — the queue overflows
    and the connection drops instead."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, node_id: bytes,
                 listen_addr: Optional[tuple[str, int]], outbound: bool,
                 channel: NoiseChannel | None = None):
        self.reader = reader
        self.writer = writer
        self.channel = channel
        self.node_id = node_id
        self.listen_addr = listen_addr
        self.outbound = outbound
        self.score = 0
        self.send_queue: asyncio.Queue = asyncio.Queue()
        # ordered gossip delivery per peer (frames arrive in order; handler
        # execution must preserve it, like LoopbackHub's per-receiver inbox)
        self.gossip_queue: asyncio.Queue = asyncio.Queue()
        self.closed = asyncio.Event()
        self.tasks: list[asyncio.Task] = []

    async def send(self, frame_type: int, payload: bytes) -> None:
        if self.closed.is_set():
            raise ConnectionError("connection closed")
        if self.send_queue.qsize() >= SEND_QUEUE_CAP:
            self.close()  # peer is not draining; don't buffer unboundedly
            raise ConnectionError("send queue overflow")
        # encrypt at enqueue: the queue is FIFO and the writer drains it
        # in order, so nonce order matches wire order
        self.send_queue.put_nowait(
            self.channel.encrypt_frame(frame_type, payload))

    async def write_loop(self) -> None:
        try:
            while not self.closed.is_set():
                frame = await self.send_queue.get()
                if frame is None:
                    return
                self.writer.write(frame)
                await self.writer.drain()
        except (OSError, ConnectionError):
            self.close()

    def close(self) -> None:
        self.closed.set()
        # wake blocked queue consumers so their tasks can exit
        self.send_queue.put_nowait(None)
        self.gossip_queue.put_nowait(None)
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001 — best-effort teardown of an
            # already-dying socket; the connection is closed either way
            # and the caller's drop path owns the accounting
            pass


class Host:
    """One node's transport endpoint: listener + dials + gossip + req/resp.

    Usage:
        host = Host(signer=EdSigner(...), genesis_id=...,
                    listen="127.0.0.1:0", bootstrap=["127.0.0.1:7513"])
        await host.start()
        host.join_pubsub(pubsub)   # pubsub hub seam
        host.join(server)          # req/resp net seam (Server._net)

    The node id IS the signer's ed25519 public key: the handshake proves
    possession of the key, so ids can't be spoofed.
    """

    def __init__(self, *, signer, genesis_id: bytes,
                 listen: str = "127.0.0.1:0", bootstrap: list[str] = (),
                 min_peers: int = 3, max_peers: int = 32,
                 reject_limit: int = 16, ban_seconds: float = 60.0,
                 request_timeout: float = 10.0,
                 gossip_degree: int = 6, gossip_heartbeat: float = 1.0,
                 time_source=None):
        from ..core.signing import EdVerifier
        from .gossipmesh import GossipMesh

        # injected by App so ban windows / dial pacing / heartbeats run
        # on the node's clock (virtual under the sim engine, skewable by
        # chaos timeskew); only deltas are taken, so wall vs monotonic
        # vs virtual origins all work (SC001 clock discipline)
        self._now = time_source or time.monotonic
        self.signer = signer
        self.node_id = signer.node_id
        self.verifier = EdVerifier(prefix=signer.prefix)
        self.genesis_id = genesis_id
        self.listen = listen
        self.bootstrap = list(bootstrap)
        self.min_peers = min_peers
        self.max_peers = max_peers
        self.reject_limit = reject_limit
        self.ban_seconds = ban_seconds
        self.request_timeout = request_timeout

        self.address: tuple[str, int] | None = None  # bound listen addr
        self._conns: dict[bytes, _Conn] = {}
        self._known: dict[tuple[str, int], float] = {}  # addr -> last dial
        self._banned: dict[bytes, float] = {}           # node_id -> until
        self._seen: dict[bytes, None] = {}              # gossip msg-id LRU
        self._req_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._disc_pending: dict[int, asyncio.Future] = {}
        # chaos fault injection (systest partition tooling; reference
        # systest/chaos/partition.go does this with iptables — here the
        # transport refuses the blocked peers itself). chaos_link adds
        # seeded loss/delay/duplication on gossip relays.
        self._blocked_addrs: set[tuple] = set()
        self._blocked_ids: set[bytes] = set()
        self._chaos_link: dict | None = None
        self._tasks: list[asyncio.Task] = []
        self._listener: asyncio.AbstractServer | None = None
        self._pubsub = None
        self._server = None
        self._stopping = False
        # gossipsub-lite mesh (p2p/gossipmesh.py); degree bounds scale
        # from the configured degree like the reference's D/D_lo/D_hi
        self.gossip = GossipMesh(
            degree=gossip_degree,
            d_lo=max(2, gossip_degree - 2), d_hi=gossip_degree + 2,
            rng=random.Random(int.from_bytes(self.node_id[:4], "little")))
        self.gossip_heartbeat = gossip_heartbeat
        self.stats = {"gossip_tx": 0, "gossip_rx": 0, "gossip_dup": 0,
                      "ihave_tx": 0, "iwant_served": 0}

    # ------------------------------------------------------------------
    # seam plumbing

    def join_pubsub(self, pubsub) -> None:
        pubsub._hub = self
        self._pubsub = pubsub

    def join(self, server) -> None:  # Server._net surface (LoopbackNet.join)
        server._net = self
        self._server = server

    def leave(self, server) -> None:
        server._net = None
        self._server = None

    @property
    def nodes(self) -> dict[bytes, _Conn]:
        """Connected peer ids (Server.peers() surface)."""
        return self._conns

    # ------------------------------------------------------------------
    # lifecycle

    async def start(self) -> tuple[str, int]:
        host, _, port = self.listen.rpartition(":")
        self.address = await self._listen(host or "127.0.0.1", int(port or 0))
        for spec in self.bootstrap:
            h, _, p = spec.rpartition(":")
            self._known[(h, int(p))] = 0.0
        self._tasks.append(asyncio.ensure_future(self._maintain()))
        return self.address

    # -- transport plumbing (overridden by QuicHost, p2p/quic.py) --

    async def _listen(self, host: str, port: int) -> tuple[str, int]:
        self._listener = await asyncio.start_server(self._accept, host, port)
        return self._listener.sockets[0].getsockname()[:2]

    async def _open_connection(self, addr: tuple[str, int]):
        return await asyncio.open_connection(addr[0], addr[1])

    async def _close_listener(self) -> None:
        if self._listener is not None:
            self._listener.close()
            await self._listener.wait_closed()

    async def stop(self) -> None:
        self._stopping = True
        for task in self._tasks:
            task.cancel()
        for conn in list(self._conns.values()):
            self._drop(conn)
        self._conns.clear()
        await self._close_listener()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("host stopped"))
        self._pending.clear()

    async def _maintain(self, interval: float = 1.0) -> None:
        """Keep dialing known addresses until min_peers is met."""
        last_heartbeat = 0.0
        while not self._stopping:
            try:
                if len(self._conns) < self.min_peers:
                    now = self._now()
                    for addr, last in list(self._known.items()):
                        if addr == self.address:
                            continue
                        if now - last < 2.0:
                            continue
                        if any(c.listen_addr == addr
                               for c in self._conns.values()):
                            continue
                        self._known[addr] = now
                        asyncio.ensure_future(self._dial(addr))
                now = self._now()
                if now - last_heartbeat >= self.gossip_heartbeat:
                    last_heartbeat = now
                    await self._gossip_heartbeat()
            except Exception:  # noqa: BLE001 — keep the maintainer alive
                pass
            await asyncio.sleep(min(interval, self.gossip_heartbeat))

    async def _gossip_heartbeat(self) -> None:
        """Mesh maintenance + lazy IHAVE (gossipsub heartbeat)."""
        from .gossipmesh import IHAVE, encode_ctrl

        sends = self.gossip.heartbeat(set(self._conns))
        for peer, subtype, topic, ids in sends:
            conn = self._conns.get(peer)
            if conn is None:
                continue
            if subtype == IHAVE:
                self.stats["ihave_tx"] += 1
            try:
                await conn.send(MSG_GOSSIP_CTRL,
                                encode_ctrl(subtype, topic, ids))
            except (OSError, ConnectionError):
                self._drop(conn)

    # ------------------------------------------------------------------
    # connections

    def _hello_payload(self, channel: NoiseChannel) -> bytes:
        port = self.address[1] if self.address else 0
        sig = channel.sign_binding(self.signer, channel.initiator)
        return (struct.pack("<B", len(self.genesis_id)) + self.genesis_id
                + self.node_id + struct.pack("<H", port) + sig)

    @staticmethod
    def _parse_hello(payload: bytes) -> tuple[bytes, bytes, int, bytes]:
        # length-check before slicing: a truncated HELLO from an
        # untrusted peer must surface as HandshakeError, not IndexError
        # (ADVICE r2: unhandled parse errors leaked the socket)
        if len(payload) < 1 or len(payload) < 1 + payload[0] + 34 + 64:
            raise HandshakeError("malformed HELLO")
        glen = payload[0]
        genesis = payload[1:1 + glen]
        node_id = payload[1 + glen:1 + glen + 32]
        (port,) = struct.unpack_from("<H", payload, 1 + glen + 32)
        sig = payload[1 + glen + 34:1 + glen + 34 + 64]
        return genesis, node_id, port, sig

    # -- chaos fault injection (systest partition scenarios) --

    def chaos_block(self, addrs: list = (), node_ids: list = ()) -> None:
        """Sever + refuse the given peers (listen addrs and/or ids) until
        chaos_clear(). The transport-level stand-in for the reference's
        iptables partition (systest/chaos/partition.go:14)."""
        self._blocked_addrs.update(tuple(a) for a in addrs)
        self._blocked_ids.update(node_ids)
        for pid, conn in list(self._conns.items()):
            if pid in self._blocked_ids or (
                    conn.listen_addr
                    and tuple(conn.listen_addr) in self._blocked_addrs):
                self._drop(conn)

    def chaos_link(self, *, loss: float = 0.0, delay: float = 0.0,
                   jitter: float = 0.0, dup: float = 0.0,
                   seed: int = 0) -> None:
        """Degrade every outbound gossip relay until chaos_clear():
        ``loss`` drops frames, ``delay``+``jitter`` defers them,
        ``dup`` sends twice. The link-quality sibling of chaos_block
        for scripted scenarios (sim/faults.py vocabulary; the netem/tc
        analogue of the reference's iptables chaos) — seeded, so a
        scenario's drop pattern replays exactly."""
        if loss or delay or jitter or dup:
            self._chaos_link = {
                "loss": float(loss), "delay": float(delay),
                "jitter": float(jitter), "dup": float(dup),
                "rng": random.Random(("chaos-link", seed).__repr__())}
        else:
            self._chaos_link = None

    def chaos_clear(self) -> None:
        self._blocked_addrs.clear()
        self._blocked_ids.clear()
        self._chaos_link = None

    async def _dial(self, addr: tuple[str, int]) -> None:
        if tuple(addr) in self._blocked_addrs:
            return
        try:
            reader, writer = await asyncio.wait_for(
                self._open_connection(addr), 5.0)
        except (OSError, asyncio.TimeoutError):
            return
        try:
            await self._handshake(reader, writer, outbound=True,
                                  dialed_addr=addr)
        except Exception:  # noqa: BLE001 — any peer garbage: close the fd
            writer.close()

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            await self._handshake(reader, writer, outbound=False)
        except Exception:  # noqa: BLE001 — any peer garbage: close the fd
            writer.close()

    async def _handshake(self, reader, writer, *, outbound: bool,
                         dialed_addr: tuple[str, int] | None = None) -> None:
        await asyncio.wait_for(self._do_handshake(
            reader, writer, outbound=outbound, dialed_addr=dialed_addr), 10.0)

    async def _do_handshake(self, reader, writer, *, outbound: bool,
                            dialed_addr=None) -> None:
        # 1) ephemeral key exchange -> encrypted channel (wrong-genesis
        # peers derive different keys and fail at the first frame)
        channel = await NoiseChannel.establish(
            reader, writer, genesis_id=self.genesis_id, initiator=outbound)
        # 2) encrypted HELLO: identity + listen port + channel-binding
        # signature proving possession of the ed25519 key
        await channel.send(MSG_HELLO, self._hello_payload(channel))
        ftype, payload = await channel.recv()
        if ftype != MSG_HELLO:
            raise HandshakeError("expected HELLO")
        genesis, peer_id, peer_port, sig = self._parse_hello(payload)
        if genesis != self.genesis_id:
            raise HandshakeError("genesis mismatch")  # network cookie
        if not channel.verify_binding(self.verifier, peer_id, sig,
                                      role_initiator=not outbound):
            raise HandshakeError("identity signature invalid")
        if peer_id == self.node_id:
            raise HandshakeError("self-dial")
        if self._banned.get(peer_id, 0) > self._now():
            raise HandshakeError("peer banned")
        if peer_id in self._blocked_ids:
            raise HandshakeError("peer blocked (chaos)")
        if (len(self._conns) >= self.max_peers
                and peer_id not in self._conns):
            raise HandshakeError("max peers reached")
        peer_host = writer.get_extra_info("peername")[0]
        listen_addr = dialed_addr or ((peer_host, peer_port)
                                      if peer_port else None)
        if listen_addr and tuple(listen_addr) in self._blocked_addrs:
            raise HandshakeError("address blocked (chaos)")
        conn = _Conn(reader, writer, peer_id, listen_addr, outbound,
                     channel=channel)

        # one connection per peer pair: on simultaneous dial, the dial
        # initiated by the LOWER node id survives
        existing = self._conns.get(peer_id)
        if existing is not None and not existing.closed.is_set():
            initiator = self.node_id if outbound else peer_id
            if initiator == min(self.node_id, peer_id):
                existing.close()
            else:
                raise HandshakeError("duplicate connection")
        self._conns[peer_id] = conn
        if listen_addr:
            self._known.setdefault(listen_addr, 0.0)
        conn.tasks = [asyncio.ensure_future(self._read_loop(conn)),
                      asyncio.ensure_future(self._gossip_loop(conn)),
                      asyncio.ensure_future(conn.write_loop())]
        # peer exchange: tell the new peer every listen addr we know
        await self._send_peers(conn)

    async def _send_peers(self, conn: _Conn) -> None:
        addrs = [a for a in self._known if a != conn.listen_addr][:64]
        payload = struct.pack("<H", len(addrs))
        for host_s, port in addrs:
            hb = host_s.encode()
            payload += struct.pack("<BH", len(hb), port) + hb
        try:
            await conn.send(MSG_PEERS, payload)
        except (OSError, ConnectionError):
            pass

    def _drop(self, conn: _Conn, ban: bool = False) -> None:
        conn.close()
        if self._conns.get(conn.node_id) is conn:
            del self._conns[conn.node_id]
            self.gossip.drop_peer(conn.node_id)
        if ban:
            self._banned[conn.node_id] = self._now() + self.ban_seconds
        # let the conn's own loops finish, then reap them (peer churn must
        # not accumulate tasks/queues forever)
        for task in conn.tasks:
            if task is not asyncio.current_task():
                task.cancel()
        conn.tasks = []

    # ------------------------------------------------------------------
    # frame processing

    async def _read_loop(self, conn: _Conn) -> None:
        try:
            while not conn.closed.is_set():
                ftype, payload = await conn.channel.recv()
                if ftype == MSG_GOSSIP:
                    # bounded like the send side: a gossip flood faster
                    # than local validation drains must not grow memory —
                    # drop the frame (gossip is redundant across peers)
                    # and penalize the flooder
                    if conn.gossip_queue.qsize() >= SEND_QUEUE_CAP:
                        self._penalize(conn)
                    else:
                        conn.gossip_queue.put_nowait(payload)
                elif ftype == MSG_REQ:
                    asyncio.ensure_future(self._handle_req(conn, payload))
                elif ftype == MSG_RESP:
                    self._handle_resp(conn, payload)
                elif ftype == MSG_PEERS:
                    self._handle_peers(payload)
                elif ftype == MSG_GOSSIP_CTRL:
                    await self._handle_gossip_ctrl(conn, payload)
                elif ftype == MSG_FIND:
                    await self._handle_find(conn, payload)
                elif ftype == MSG_FOUND:
                    self._handle_found(conn, payload)
        except (OSError, ConnectionError, asyncio.IncompleteReadError,
                HandshakeError, ChannelError, struct.error, ValueError,
                IndexError, UnicodeDecodeError):
            # the last four: truncated MSG_RESP/MSG_PEERS payloads from a
            # hostile peer — drop the connection, never kill the task
            # with an unhandled error (ADVICE r2)
            pass
        finally:
            self._drop(conn)

    async def _gossip_loop(self, conn: _Conn) -> None:
        while not conn.closed.is_set():
            payload = await conn.gossip_queue.get()
            if payload is None:  # close sentinel
                return
            try:
                await self._handle_gossip(conn, payload)
            except Exception:  # noqa: BLE001 — bad msg must not kill the loop
                pass

    @staticmethod
    def _gossip_frame(topic: str, data: bytes) -> tuple[bytes, bytes]:
        tb = topic.encode()
        msg_id = sum256(tb, data)
        return msg_id, struct.pack("<B", len(tb)) + tb + msg_id + data

    def _mark_seen(self, msg_id: bytes) -> bool:
        """True if newly seen (shared insert/evict policy —
        gossipmesh.mark_seen — so the sim hub's dedup window can never
        silently diverge from the transport it models)."""
        from .gossipmesh import mark_seen

        return mark_seen(self._seen, msg_id, SEEN_CAP)

    async def _handle_gossip(self, conn: _Conn, payload: bytes) -> None:
        tlen = payload[0]
        topic = payload[1:1 + tlen].decode()
        msg_id = payload[1 + tlen:1 + tlen + 32]
        data = payload[1 + tlen + 32:]
        if sum256(topic.encode(), data) != msg_id:
            self._penalize(conn)
            return
        self.stats["gossip_rx"] += 1
        if not self._mark_seen(msg_id):
            self.stats["gossip_dup"] += 1
            return
        ok = True
        if self._pubsub is not None:
            ok = await self._pubsub.deliver(topic, conn.node_id, data)
        if ok:
            # eager-push along the topic mesh only (gossipsub forwarding);
            # lazy IHAVE repairs non-mesh peers at the next heartbeat
            self.gossip.on_message(msg_id, topic, payload)
            targets = self.gossip.eager_targets(topic, set(self._conns),
                                                exclude=conn.node_id)
            await self._relay(payload, targets)
        elif ok is False:
            self._penalize(conn)
        # ok is None: accepted but relay-suppressed (graded-gossip dup) —
        # an honest relayer must not be penalized for delivering it

    async def _handle_gossip_ctrl(self, conn: _Conn, payload: bytes) -> None:
        """GRAFT/PRUNE/IHAVE/IWANT (gossipsub control plane)."""
        from .gossipmesh import encode_ctrl

        replies = self.gossip.on_control(conn.node_id, payload,
                                         seen=lambda mid: mid in self._seen)
        for subtype, topic, ids in replies:
            try:
                if subtype == -1:  # answer IWANT with the full frames
                    for mid in ids:
                        frame = self.gossip.cache.get(mid)
                        if frame is not None:
                            self.stats["iwant_served"] += 1
                            self.stats["gossip_tx"] += 1
                            await conn.send(MSG_GOSSIP, frame)
                else:
                    await conn.send(MSG_GOSSIP_CTRL,
                                    encode_ctrl(subtype, topic, ids))
            except (OSError, ConnectionError):
                self._drop(conn)
                return

    def _penalize(self, conn: _Conn) -> None:
        conn.score += 1
        if conn.score >= self.reject_limit:
            self._drop(conn, ban=True)

    async def _relay(self, frame_payload: bytes,
                     targets: set[bytes]) -> None:
        pol = self._chaos_link
        for peer_id in targets:
            conn = self._conns.get(peer_id)
            if conn is None:
                continue
            copies = 1
            if pol is not None:
                rng = pol["rng"]
                if pol["loss"] and rng.random() < pol["loss"]:
                    continue
                if pol["dup"] and rng.random() < pol["dup"]:
                    copies = 2
                wait = pol["delay"] + (rng.random() * pol["jitter"]
                                       if pol["jitter"] else 0.0)
                if wait > 0:
                    asyncio.get_running_loop().call_later(
                        wait, self._relay_later, conn, frame_payload,
                        copies)
                    continue
            for _ in range(copies):
                self.stats["gossip_tx"] += 1
                try:
                    await conn.send(MSG_GOSSIP, frame_payload)
                except (OSError, ConnectionError):
                    self._drop(conn)
                    break

    def _relay_later(self, conn: _Conn, frame_payload: bytes,
                     copies: int) -> None:
        """Deferred chaos_link delivery; the peer may be gone by now.
        Encrypt-at-enqueue is preserved (nonce order == queue order ==
        wire order), as is the send-queue overflow contract."""
        if conn.closed.is_set():
            return
        for _ in range(copies):
            if conn.send_queue.qsize() >= SEND_QUEUE_CAP:
                conn.close()
                return
            self.stats["gossip_tx"] += 1
            try:
                conn.send_queue.put_nowait(
                    conn.channel.encrypt_frame(MSG_GOSSIP, frame_payload))
            except Exception:  # noqa: BLE001 — chaos must not kill the caller
                return

    async def _handle_req(self, conn: _Conn, payload: bytes) -> None:
        try:
            (req_id,) = struct.unpack_from("<Q", payload)
            plen = payload[8]
            proto = payload[9:9 + plen].decode()
            data = payload[9 + plen:]
        except (struct.error, IndexError, UnicodeDecodeError):
            # runs as its own task: a truncated request must not become
            # an unhandled task exception (ADVICE r2)
            self._penalize(conn)
            return
        status, resp = 0, b""
        try:
            if self._server is None:
                raise ConnectionError("no server attached")
            resp = await self._server.handle(proto, conn.node_id, data)
        except Exception as e:  # noqa: BLE001 — error travels to the caller
            status, resp = 1, str(e).encode()[:512]
        try:
            await conn.send(MSG_RESP,
                            struct.pack("<QB", req_id, status) + resp)
        except (OSError, ConnectionError):
            self._drop(conn)

    def _handle_resp(self, conn: _Conn, payload: bytes) -> None:
        (req_id,) = struct.unpack_from("<Q", payload)
        status = payload[8]
        data = payload[9:]
        # keyed by (peer, req_id): a response only resolves a request that
        # was sent to THAT peer — req_ids are sequential and guessable, so
        # a malicious peer must not be able to answer someone else's
        # request with forged data
        fut = self._pending.pop((conn.node_id, req_id), None)
        if fut is None or fut.done():
            return
        if status == 0:
            fut.set_result(data)
        else:
            from .server import RequestError

            fut.set_exception(RequestError(data.decode(errors="replace")))

    # ------------------------------------------------------------------
    # iterative discovery (Kad-lite; reference p2p/dhtdiscovery/)

    DISC_K = 8       # entries per FIND answer
    DISC_ALPHA = 3   # parallel queries per lookup round

    async def _handle_find(self, conn: _Conn, payload: bytes) -> None:
        """FIND(nonce, target): answer the K connected peers closest to
        target by XOR distance, with their listen addresses (the
        FIND_NODE of Kademlia, scoped to live connections)."""
        (nonce,) = struct.unpack_from("<Q", payload)
        target = payload[8:40]
        if len(target) != 32:
            self._penalize(conn)
            return
        entries = []
        for pid, c in self._conns.items():
            if c.listen_addr is None or pid == conn.node_id:
                continue
            entries.append((_xor_dist(pid, target), pid, c.listen_addr))
        entries.sort(key=lambda e: e[0])
        blob = struct.pack("<QH", nonce, min(len(entries), self.DISC_K))
        for _, pid, (ip, port) in entries[:self.DISC_K]:
            ib = ip.encode()
            blob += pid + struct.pack("<BH", len(ib), port) + ib
        try:
            await conn.send(MSG_FOUND, blob)
        except (OSError, ConnectionError):
            self._drop(conn)

    def _handle_found(self, conn: _Conn, payload: bytes) -> None:
        nonce, count = struct.unpack_from("<QH", payload)
        off = 10
        entries = []
        for _ in range(min(count, self.DISC_K)):
            pid = payload[off:off + 32]
            iplen, port = struct.unpack_from("<BH", payload, off + 32)
            ip = payload[off + 35:off + 35 + iplen].decode()
            off += 35 + iplen
            entries.append((pid, (ip, port)))
        # keyed by (peer, nonce) like _handle_resp: sequential nonces are
        # guessable, a peer must not be able to answer another peer's
        # lookup (discovery poisoning)
        fut = self._disc_pending.pop((conn.node_id, nonce), None)
        if fut is not None and not fut.done():
            fut.set_result(entries)

    async def _find(self, peer_id: bytes, target: bytes,
                    timeout: float = 3.0,
                    addr: tuple | None = None) -> list[tuple[bytes, tuple]]:
        conn = self._conns.get(peer_id)
        if conn is None and addr is not None:
            # Kademlia iterates by CONTACTING closer nodes: dial first
            await self._dial(tuple(addr))
            conn = self._conns.get(peer_id)
        if conn is None:
            return []
        self._req_id += 1
        nonce = self._req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._disc_pending[(peer_id, nonce)] = fut
        try:
            await conn.send(MSG_FIND,
                            struct.pack("<Q", nonce) + target)
            return await asyncio.wait_for(fut, timeout)
        except (OSError, ConnectionError, asyncio.TimeoutError):
            return []
        finally:
            self._disc_pending.pop((peer_id, nonce), None)

    async def discover(self, target: bytes,
                       max_rounds: int = 5) -> list[tuple[bytes, tuple]]:
        """Iterative lookup: repeatedly ask the closest known peers for
        peers closer to ``target`` until no progress (Kademlia's
        FIND_NODE loop over live connections).  Every address learned is
        fed to the dial maintainer, so lookups double as discovery
        beyond the bootstrap list."""
        shortlist: dict[bytes, tuple] = {
            pid: c.listen_addr for pid, c in self._conns.items()
            if c.listen_addr is not None}
        queried: set[bytes] = set()
        for _ in range(max_rounds):
            frontier = sorted(
                (pid for pid in shortlist if pid not in queried),
                key=lambda p: _xor_dist(p, target))[:self.DISC_ALPHA]
            if not frontier:
                break
            queried.update(frontier)
            results = await asyncio.gather(
                *(self._find(pid, target, addr=shortlist[pid])
                  for pid in frontier))
            for entries in results:
                for pid, addr in entries:
                    if pid == self.node_id or pid in shortlist:
                        continue
                    shortlist[pid] = addr
                    if len(self._known) < 1024:
                        self._known.setdefault(tuple(addr), 0.0)
            # termination: every unqueried candidate exhausted (the walk
            # must tolerate "farther" hops — a chain topology routes
            # through nodes whose ids are XOR-farther than the start)
        return sorted(shortlist.items(),
                      key=lambda e: _xor_dist(e[0], target))

    def _handle_peers(self, payload: bytes) -> None:
        (count,) = struct.unpack_from("<H", payload)
        off = 2
        for _ in range(min(count, 64)):
            hlen, port = struct.unpack_from("<BH", payload, off)
            off += 3
            host_s = payload[off:off + hlen].decode()
            off += hlen
            addr = (host_s, port)
            if addr != self.address and len(self._known) < 1024:
                self._known.setdefault(addr, 0.0)

    # ------------------------------------------------------------------
    # pubsub hub surface (PubSub._hub)

    async def broadcast(self, sender, topic: str, data: bytes) -> None:
        msg_id, frame = self._gossip_frame(topic, data)
        self._mark_seen(msg_id)  # don't re-deliver our own message
        self.gossip.on_message(msg_id, topic, frame)
        await self._relay(frame,
                          self.gossip.eager_targets(topic, set(self._conns)))

    # ------------------------------------------------------------------
    # req/resp net surface (Server._net)

    async def route(self, src: bytes, dst: bytes, protocol: str,
                    data: bytes) -> bytes:
        from .server import RequestError

        conn = self._conns.get(dst)
        if conn is None or conn.closed.is_set():
            raise RequestError(f"peer {dst.hex()[:8]} not reachable")
        self._req_id += 1
        req_id = self._req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[(dst, req_id)] = fut
        pb = protocol.encode()
        try:
            await conn.send(MSG_REQ, struct.pack("<QB", req_id, len(pb))
                            + pb + data)
            # bounded even when called without Server.request's wait_for:
            # a peer that accepts the request but never answers must not
            # hang the caller
            return await asyncio.wait_for(fut, self.request_timeout)
        except asyncio.TimeoutError:
            raise RequestError(f"request to {dst.hex()[:8]} timed out")
        finally:
            self._pending.pop((dst, req_id), None)
