"""Fetch: hash -> data resolution across peers, with batching.

Mirrors the reference fetch layer (reference fetch/fetch.go: requests are
coalesced per peer into hash batches, responses stream back blobs which are
dispatched to per-kind validator callbacks wired at node startup
node/node.go:1166-1211; server-side handlers expose the local database by
hint; epoch/layer index endpoints live beside them, fetch/mesh_data.go).

Hints name the store a hash lives in (reference datastore.BlobStore):
  atx ballot block tx poet active_set malfeasance
Protocols:
  hs/1  hashes -> blobs        (reference fetch.go hashProtocol)
  ep/1  epoch  -> atx id list  (reference "ax/1"-family epoch info)
  ld/1  layer  -> ballot/block/cert ids (reference layer data)
"""

from __future__ import annotations

import asyncio
import struct
from typing import Awaitable, Callable

from ..core import codec
from ..core.codec import fixed, u8, u32, var_bytes, vec
from .server import RequestError, Server

P_HASH = "hs/1"
P_EPOCH = "ep/1"
P_LAYER = "ld/1"

HINT_ATX = 0
HINT_BALLOT = 1
HINT_BLOCK = 2
HINT_TX = 3
HINT_POET = 4
HINT_ACTIVESET = 5
HINT_MALFEASANCE = 6


@codec.register
class HashRequest:
    hint: int
    hashes: list[bytes]
    FIELDS = [("hint", u8), ("hashes", vec(fixed(32), 1 << 12))]


@codec.register
class HashResponse:
    blobs: list[bytes]           # parallel to request; empty = missing
    FIELDS = [("blobs", vec(var_bytes, 1 << 12))]


@codec.register
class LayerData:
    ballots: list[bytes]
    blocks: list[bytes]
    certified: bytes             # EMPTY32 if none
    FIELDS = [("ballots", vec(fixed(32))), ("blocks", vec(fixed(32))),
              ("certified", fixed(32))]


# blob readers: hint -> (db, id) -> bytes|None; writers: validator callbacks
Reader = Callable[[bytes], bytes | None]
Validator = Callable[[bytes, bytes], Awaitable[bool]]  # (id, blob) -> ok


class Fetch:
    def __init__(self, server: Server, batch_size: int = 128):
        self.server = server
        self.batch = batch_size
        self._readers: dict[int, Reader] = {}
        self._validators: dict[int, Validator] = {}
        server.register(P_HASH, self._serve_hashes)

    # --- wiring -----------------------------------------------------

    def set_reader(self, hint: int, reader: Reader) -> None:
        self._readers[hint] = reader

    def set_validator(self, hint: int, validator: Validator) -> None:
        """Per-kind ingestion callback (reference fetch.SetValidators)."""
        self._validators[hint] = validator

    # --- server side ------------------------------------------------

    async def _serve_hashes(self, peer: bytes, data: bytes) -> bytes:
        req = HashRequest.from_bytes(data)
        reader = self._readers.get(req.hint)
        blobs = []
        for h in req.hashes:
            blob = reader(h) if reader else None
            blobs.append(blob if blob is not None else b"")
        return HashResponse(blobs=blobs).to_bytes()

    # --- client side ------------------------------------------------

    async def get_hashes(self, hint: int, ids: list[bytes]) -> dict[bytes, bool]:
        """Resolve ids across peers in batches; each retrieved blob goes
        through the hint's validator. Ids already present locally (the
        hint's reader answers) are skipped. Returns id -> success."""
        result = {i: False for i in ids}
        reader = self._readers.get(hint)
        missing = []
        for i in dict.fromkeys(ids):
            if reader is not None and reader(i) is not None:
                result[i] = True  # already stored locally
            else:
                missing.append(i)
        peers = self.server.peers()
        if not peers:
            return result
        validator = self._validators.get(hint)
        for pi, peer in enumerate(peers):
            if not missing:
                break
            still = []
            for k in range(0, len(missing), self.batch):
                chunk = missing[k:k + self.batch]
                try:
                    resp = HashResponse.from_bytes(await self.server.request(
                        peer, P_HASH,
                        HashRequest(hint=hint, hashes=chunk).to_bytes()))
                except (RequestError, asyncio.TimeoutError, codec.DecodeError):
                    still.extend(chunk)
                    continue
                if len(resp.blobs) != len(chunk):
                    # short answer: nothing in it is trustworthy-complete;
                    # retry the whole chunk elsewhere
                    still.extend(chunk)
                    continue
                for h, blob in zip(chunk, resp.blobs):
                    if not blob:
                        still.append(h)
                        continue
                    ok = await validator(h, blob) if validator else True
                    result[h] = bool(ok)
                    if not ok:
                        still.append(h)
            missing = still
        return result

    async def get_epoch_atxs(self, epoch: int) -> list[bytes]:
        """Union of peers' ATX id lists for the epoch, fetched + validated."""
        ids: list[bytes] = []
        seen: set[bytes] = set()
        for peer in self.server.peers():
            try:
                resp = await self.server.request(
                    peer, P_EPOCH, struct.pack("<I", epoch))
            except (RequestError, asyncio.TimeoutError):
                continue
            for k in range(0, len(resp), 32):
                i = resp[k:k + 32]
                if i not in seen:
                    seen.add(i)
                    ids.append(i)
        await self.get_hashes(HINT_ATX, ids)
        return ids

    async def get_layer_data(self, layer: int) -> LayerData | None:
        for peer in self.server.peers():
            try:
                resp = await self.server.request(
                    peer, P_LAYER, struct.pack("<I", layer))
                return LayerData.from_bytes(resp)
            except (RequestError, asyncio.TimeoutError, codec.DecodeError):
                continue
        return None
