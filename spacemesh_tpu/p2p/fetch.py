"""Fetch: hash -> data resolution across peers, with batching.

Mirrors the reference fetch layer (reference fetch/fetch.go: requests are
coalesced per peer into hash batches, responses stream back blobs which are
dispatched to per-kind validator callbacks wired at node startup
node/node.go:1166-1211; server-side handlers expose the local database by
hint; epoch/layer index endpoints live beside them, fetch/mesh_data.go).

Hints name the store a hash lives in (reference datastore.BlobStore):
  atx ballot block tx poet active_set malfeasance
Protocols:
  hs/1  hashes -> blobs        (reference fetch.go hashProtocol)
  ep/1  epoch  -> atx id list  (reference "ax/1"-family epoch info)
  ld/1  layer  -> ballot/block/cert ids (reference layer data)
"""

from __future__ import annotations

import asyncio
import random
import struct
import time
from typing import Awaitable, Callable

from ..core import codec
from ..core.codec import fixed, u8, u32, var_bytes, vec
from .server import RequestError, Server

P_HASH = "hs/1"
P_EPOCH = "ep/1"
P_LAYER = "ld/1"

HINT_ATX = 0
HINT_BALLOT = 1
HINT_BLOCK = 2
HINT_TX = 3
HINT_POET = 4
HINT_ACTIVESET = 5
HINT_MALFEASANCE = 6


@codec.register
class HashRequest:
    hint: int
    hashes: list[bytes]
    FIELDS = [("hint", u8), ("hashes", vec(fixed(32), 1 << 12))]


@codec.register
class HashResponse:
    blobs: list[bytes]           # parallel to request; empty = missing
    FIELDS = [("blobs", vec(var_bytes, 1 << 12))]


@codec.register
class LayerData:
    ballots: list[bytes]
    blocks: list[bytes]
    certified: bytes             # EMPTY32 if none
    FIELDS = [("ballots", vec(fixed(32))), ("blocks", vec(fixed(32))),
              ("certified", fixed(32))]


# blob readers: hint -> (db, id) -> bytes|None; writers: validator callbacks
Reader = Callable[[bytes], bytes | None]
Validator = Callable[[bytes, bytes], Awaitable[bool]]  # (id, blob) -> ok


class Fetch:
    def __init__(self, server: Server, batch_size: int = 128,
                 bad_peer_threshold: int = 10, *,
                 retry_rounds: int = 3, backoff_base: float = 0.05,
                 backoff_cap: float = 2.0, penalty_base: float = 0.5,
                 penalty_cap: float = 30.0,
                 rng: random.Random | None = None):
        self.server = server
        self.batch = batch_size
        self.bad_peer_threshold = bad_peer_threshold
        # failed-chunk retry policy: bounded rounds with capped
        # exponential backoff + jitter between them, and a per-peer
        # penalty WINDOW after a transport-level chunk failure — the
        # old behavior (retry the whole chunk elsewhere immediately,
        # then hammer the same flapping peer set on the next call)
        # turned one flaky peer into synchronized retry storms
        self.retry_rounds = max(int(retry_rounds), 1)
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.penalty_base = penalty_base
        self.penalty_cap = penalty_cap
        self._rng = rng or random.Random(0x5EED5)
        self._readers: dict[int, Reader] = {}
        self._validators: dict[int, Validator] = {}
        # peer scoring (reference fetch/peers/peers.go): failures — bad
        # blobs, short answers, timeouts — push a peer down the selection
        # order and eventually out of it; successes slowly rehabilitate
        self._peer_score: dict[bytes, int] = {}
        self._penalty_until: dict[bytes, float] = {}
        self._consec_fail: dict[bytes, int] = {}
        server.register(P_HASH, self._serve_hashes)

    # --- peer selection ---------------------------------------------

    @staticmethod
    def _now() -> float:
        """Loop clock when one is running (virtual-clock-aware: penalty
        windows expire in SIM time under a VirtualClockLoop), wall
        monotonic otherwise."""
        try:
            return asyncio.get_running_loop().time()
        except RuntimeError:
            # spacecheck: ok=SC001 loop-less fallback of this module's declared time source (_now)
            return time.monotonic()

    def report_failure(self, peer: bytes, weight: int = 1) -> None:
        self._peer_score[peer] = self._peer_score.get(peer, 0) + weight

    def report_success(self, peer: bytes) -> None:
        s = self._peer_score.get(peer, 0)
        if s > 0:
            self._peer_score[peer] = s - 1
        self._consec_fail.pop(peer, None)
        self._penalty_until.pop(peer, None)

    def _chunk_failure(self, peer: bytes) -> None:
        """Transport-level chunk failure (timeout / error / short
        answer): score it AND open an escalating penalty window during
        which the peer is skipped by selection."""
        self.report_failure(peer)
        n = self._consec_fail.get(peer, 0) + 1
        self._consec_fail[peer] = n
        window = min(self.penalty_cap,
                     self.penalty_base * (2 ** (n - 1)))
        self._penalty_until[peer] = self._now() + window

    def failure_score(self, peer: bytes) -> int:
        """Accumulated failure score — HIGHER is WORSE; peers at or above
        bad_peer_threshold are dropped from selection."""
        return self._peer_score.get(peer, 0)

    def penalized(self, peer: bytes) -> bool:
        return self._penalty_until.get(peer, 0.0) > self._now()

    def peers(self) -> list[bytes]:
        """Connected peers, best score first: chronically bad ones are
        dropped from selection entirely and peers inside a penalty
        window are skipped while anyone else is available."""
        ranked = sorted(self.server.peers(),
                        key=lambda p: self._peer_score.get(p, 0))
        good = [p for p in ranked
                if self._peer_score.get(p, 0) < self.bad_peer_threshold]
        usable = [p for p in good if not self.penalized(p)]
        if usable:
            return usable
        # if everyone looks bad/penalized, fall back to the least-bad
        # peers rather than stalling sync forever
        return good or ranked[:2]

    async def _backoff(self, round_no: int) -> None:
        """Jittered capped exponential delay between retry rounds (the
        jitter de-synchronizes many nodes retrying the same flap)."""
        delay = min(self.backoff_cap,
                    self.backoff_base * (2 ** round_no))
        await asyncio.sleep(delay * (0.5 + self._rng.random() * 0.5))

    # --- wiring -----------------------------------------------------

    def set_reader(self, hint: int, reader: Reader) -> None:
        self._readers[hint] = reader

    def set_validator(self, hint: int, validator: Validator) -> None:
        """Per-kind ingestion callback (reference fetch.SetValidators)."""
        self._validators[hint] = validator

    # --- server side ------------------------------------------------

    async def _serve_hashes(self, peer: bytes, data: bytes) -> bytes:
        req = HashRequest.from_bytes(data)
        reader = self._readers.get(req.hint)
        blobs = []
        for h in req.hashes:
            blob = reader(h) if reader else None
            blobs.append(blob if blob is not None else b"")
        return HashResponse(blobs=blobs).to_bytes()

    # --- client side ------------------------------------------------

    async def get_hashes(self, hint: int, ids: list[bytes]) -> dict[bytes, bool]:
        """Resolve ids across peers in batches; each retrieved blob goes
        through the hint's validator. Ids already present locally (the
        hint's reader answers) are skipped. Returns id -> success.

        Retry shape: one pass over the (penalty-filtered) peer set per
        round; a round is re-run — after a capped, jittered exponential
        backoff — only while ids remain AND some chunk failed at the
        TRANSPORT level (timeout/error/short answer). Peers that simply
        don't hold an id answer definitively (empty blob) and never
        trigger a retry round."""
        result = {i: False for i in ids}
        reader = self._readers.get(hint)
        missing = []
        for i in dict.fromkeys(ids):
            if reader is not None and reader(i) is not None:
                result[i] = True  # already stored locally
            else:
                missing.append(i)
        validator = self._validators.get(hint)
        for round_no in range(self.retry_rounds):
            if not missing:
                break
            if round_no:
                await self._backoff(round_no - 1)
            peers = self.peers()
            if not peers:
                break
            transient = False
            for peer in peers:
                if not missing:
                    break
                still = []
                for k in range(0, len(missing), self.batch):
                    chunk = missing[k:k + self.batch]
                    try:
                        resp = HashResponse.from_bytes(
                            await self.server.request(
                                peer, P_HASH,
                                HashRequest(hint=hint,
                                            hashes=chunk).to_bytes()))
                    except (RequestError, asyncio.TimeoutError,
                            codec.DecodeError):
                        self._chunk_failure(peer)
                        transient = True
                        still.extend(chunk)
                        continue
                    if len(resp.blobs) != len(chunk):
                        # short answer: nothing in it is trustworthy-
                        # complete; retry the whole chunk elsewhere
                        self._chunk_failure(peer)
                        transient = True
                        still.extend(chunk)
                        continue
                    for h, blob in zip(chunk, resp.blobs):
                        if not blob:
                            still.append(h)
                            continue
                        ok = await validator(h, blob) if validator else True
                        result[h] = bool(ok)
                        if ok:
                            self.report_success(peer)
                        else:
                            # an invalid blob for a requested id is strong
                            # evidence of a bad peer (content-addressed)
                            self.report_failure(peer, weight=3)
                            still.append(h)
                missing = still
            if not transient:
                break
        return result

    async def get_epoch_atxs(self, epoch: int) -> list[bytes]:
        """Union of peers' ATX id lists for the epoch, fetched + validated."""
        ids: list[bytes] = []
        seen: set[bytes] = set()
        for peer in self.peers():
            try:
                resp = await self.server.request(
                    peer, P_EPOCH, struct.pack("<I", epoch))
            except (RequestError, asyncio.TimeoutError):
                self.report_failure(peer)
                continue
            for k in range(0, len(resp), 32):
                i = resp[k:k + 32]
                if i not in seen:
                    seen.add(i)
                    ids.append(i)
        await self.get_hashes(HINT_ATX, ids)
        return ids

    async def get_layer_data(self, layer: int,
                             max_peers: int = 5) -> LayerData | None:
        """Cross-peer layer opinion (reference syncer/data_fetch.go polls
        several peers): UNION of ballot/block ids — one lying peer cannot
        hide data the rest of the network has (fabricated ids fail the
        content-hash validators and cost the liar its score) — and the
        MAJORITY certified block id (a single peer cannot steer a late
        joiner onto a fake hare output)."""
        ballots: list[bytes] = []
        blocks: list[bytes] = []
        cert_votes: dict[bytes, int] = {}
        answered = 0
        for peer in self.peers()[:max_peers]:
            try:
                resp = await self.server.request(
                    peer, P_LAYER, struct.pack("<I", layer))
                data = LayerData.from_bytes(resp)
            except (RequestError, asyncio.TimeoutError, codec.DecodeError):
                self.report_failure(peer)
                continue
            answered += 1
            for b in data.ballots:
                if b not in ballots:
                    ballots.append(b)
            for b in data.blocks:
                if b not in blocks:
                    blocks.append(b)
            if data.certified != bytes(32):
                cert_votes[data.certified] = \
                    cert_votes.get(data.certified, 0) + 1
        if answered == 0:
            return None
        # majority certified id if one exists; ALL reported candidates ride
        # along (vote-ordered) so the caller can let certificate
        # VALIDATION arbitrate ties — with one honest and one lying peer
        # the vote is 1-1, but only the honest certificate verifies
        candidates = [c for c, _ in sorted(cert_votes.items(),
                                           key=lambda kv: -kv[1])]
        certified = bytes(32)
        if candidates and (cert_votes[candidates[0]] * 2 > answered
                           or answered == 1):
            certified = candidates[0]
        data = LayerData(ballots=ballots, blocks=blocks, certified=certified)
        data.cert_candidates = candidates  # non-wire, local-only attribute
        return data
