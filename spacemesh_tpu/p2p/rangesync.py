"""Range-based set reconciliation (the reference's sync2/rangesync).

Two peers holding large, mostly-equal sets of 32-byte ids (ATXs of an
epoch, malfeasance proofs, ...) converge by comparing XOR FINGERPRINTS
of key ranges and recursively bisecting the ranges that differ
(reference sync2/rangesync/rangesync.go; fingerprint.go uses a 12-byte
XOR fingerprint — same associative/self-inverse trick, 32 bytes here).
Transfer cost is O(diff * log n) instead of O(n).

Redesign notes (not a translation):
* the ordered set is a sorted key list + an XOR FENWICK TREE, so any
  range fingerprint is O(log n) — the reference walks an FPTree;
* the wire protocol is CLIENT-DRIVEN bisection over the existing
  req/resp server (protocol "rs/1"): the initiator asks for
  (fingerprint, count) of a range, recurses on mismatch, and asks for
  items when a differing range is small (DefaultMaxSendRange=16, like
  the reference).  Client-driven framing keeps the responder stateless.

Wire format (request, one frame):
  op u8: 0 = FINGERPRINT, 1 = ITEMS
  x, y: 32-byte range bounds [x, y)   (x == y means the full circle;
        here ranges are plain half-open intervals — wraparound is not
        needed for our callers, who reconcile whole id spaces)
Response:
  FINGERPRINT -> fp(32) || count u64
  ITEMS       -> concatenated 32-byte keys (bounded by max_items)
"""

from __future__ import annotations

import struct
from bisect import bisect_left, insort
from typing import Iterable

KEY = 32
ZERO = bytes(KEY)
TOP = b"\xff" * KEY + b"\x01"  # sorts after every 32-byte key
P_RANGESYNC = "rs/1"
MAX_SEND_RANGE = 16     # reference DefaultMaxSendRange
MAX_ITEMS = 4096        # per ITEMS answer


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


class XorFenwick:
    """Fenwick tree under XOR (associative + self-inverse, so both
    point-update and prefix queries are the classic loops)."""

    def __init__(self, n: int):
        self._t = [ZERO] * (n + 1)
        self.n = n

    def update(self, i: int, key: bytes) -> None:
        i += 1
        while i <= self.n:
            self._t[i] = _xor(self._t[i], key)
            i += i & (-i)

    def prefix(self, i: int) -> bytes:
        out = ZERO
        while i > 0:
            out = _xor(out, self._t[i])
            i -= i & (-i)
        return out


class OrderedSet:
    """Sorted 32-byte keys with O(log n) range fingerprints.

    Inserts rebuild the Fenwick lazily in batches: consensus ingests in
    bursts and reconciliation reads in bursts, so amortizing the rebuild
    beats per-insert tree shifting (a Fenwick can't insert mid-array)."""

    def __init__(self, keys: Iterable[bytes] = ()):
        self._keys: list[bytes] = sorted(set(keys))
        self._fen: XorFenwick | None = None
        self._pending: list[bytes] = []

    def add(self, key: bytes) -> None:
        if len(key) != KEY:
            raise ValueError("keys are 32 bytes")
        self._pending.append(key)

    def __len__(self) -> int:
        self._settle()
        return len(self._keys)

    def __contains__(self, key: bytes) -> bool:
        self._settle()
        i = bisect_left(self._keys, key)
        return i < len(self._keys) and self._keys[i] == key

    def keys(self) -> list[bytes]:
        self._settle()
        return list(self._keys)

    def _settle(self) -> None:
        if self._pending:
            pending, self._pending = set(self._pending), []
            for k in pending:
                i = bisect_left(self._keys, k)
                if i >= len(self._keys) or self._keys[i] != k:
                    insort(self._keys, k)
            self._fen = None
        if self._fen is None:
            self._fen = XorFenwick(len(self._keys))
            for i, k in enumerate(self._keys):
                self._fen.update(i, k)

    def _bounds(self, x: bytes, y: bytes) -> tuple[int, int]:
        return bisect_left(self._keys, x), bisect_left(self._keys, y)

    def fingerprint(self, x: bytes = ZERO, y: bytes = TOP) -> tuple[bytes, int]:
        """XOR of keys in [x, y) and their count."""
        self._settle()
        lo, hi = self._bounds(x, y)
        return _xor(self._fen.prefix(hi), self._fen.prefix(lo)), hi - lo

    def items(self, x: bytes, y: bytes, limit: int = MAX_ITEMS) -> list[bytes]:
        self._settle()
        lo, hi = self._bounds(x, y)
        return self._keys[lo:min(hi, lo + limit)]


def _midpoint(x: bytes, y: bytes) -> bytes:
    """Numeric midpoint of [x, y) over 32-byte keys."""
    xi = int.from_bytes(x.ljust(KEY, b"\0")[:KEY], "big")
    yi = int.from_bytes(y.ljust(KEY, b"\0")[:KEY], "big") \
        if len(y) == KEY else (1 << (8 * KEY))
    return ((xi + yi) // 2).to_bytes(KEY, "big")


# --- server side (stateless; rides p2p/server.py) -------------------------


class RangeSyncResponder:
    def __init__(self, set_for: "callable"):
        """``set_for(name: str) -> OrderedSet | None`` resolves which set
        a request targets (e.g. 'atx/5' = epoch-5 ATX ids)."""
        self.set_for = set_for

    async def handle(self, peer: bytes, data: bytes) -> bytes:
        if len(data) < 1 + 1:
            return b""
        op = data[0]
        nlen = data[1]
        name = data[2:2 + nlen].decode()
        rest = data[2 + nlen:]
        oset = self.set_for(name)
        if oset is None or len(rest) < 2 * KEY:
            return b""
        x, y = rest[:KEY], rest[KEY:2 * KEY]
        # ff*32 (the client's truncated TOP) and (0,0) mean "to the end"
        if y == b"\xff" * KEY or (x == ZERO and y == ZERO):
            y = TOP
        if op == 0:
            fp, count = oset.fingerprint(x, y)
            return fp + struct.pack("<Q", count)
        if op == 1:
            return b"".join(oset.items(x, y))
        return b""


# --- client side ----------------------------------------------------------


class RangeSyncClient:
    """Client-driven recursive reconciliation against one peer."""

    def __init__(self, server, peer: bytes, name: str,
                 timeout: float = 10.0):
        self.server = server
        self.peer = peer
        self.name = name
        self.timeout = timeout
        self.roundtrips = 0

    async def _ask(self, op: int, x: bytes, y: bytes) -> bytes:
        nb = self.name.encode()
        self.roundtrips += 1
        return await self.server.request(
            self.peer, P_RANGESYNC,
            bytes([op, len(nb)]) + nb + x + y[:KEY], timeout=self.timeout)

    async def _fingerprint(self, x: bytes, y: bytes) -> tuple[bytes, int]:
        resp = await self._ask(0, x, y)
        if len(resp) != KEY + 8:
            raise ValueError("malformed fingerprint response")
        return resp[:KEY], struct.unpack("<Q", resp[KEY:])[0]

    async def _items(self, x: bytes, y: bytes) -> list[bytes]:
        resp = await self._ask(1, x, y)
        if len(resp) % KEY:
            raise ValueError("malformed items response")
        return [resp[i:i + KEY] for i in range(0, len(resp), KEY)]

    async def reconcile(self, local: OrderedSet,
                        max_send_range: int = MAX_SEND_RANGE) -> list[bytes]:
        """Return the peer's keys MISSING locally (reference semantics:
        reconciliation surfaces what to fetch; the peer learns nothing —
        run the roles both ways for a symmetric sync)."""
        missing: list[bytes] = []

        async def recurse(x: bytes, y: bytes) -> None:
            theirs_fp, theirs_n = await self._fingerprint(x, y)
            ours_fp, ours_n = local.fingerprint(x, y)
            if theirs_fp == ours_fp and theirs_n == ours_n:
                return
            if theirs_n == 0:
                return  # they have nothing here; nothing to fetch
            if theirs_n <= max_send_range:
                for key in await self._items(x, y):
                    if key not in local:
                        missing.append(key)
                return
            mid = _midpoint(x, y)
            if mid <= x or mid >= y[:KEY].ljust(KEY, b"\xff"):
                # range no longer splittable: take the items
                for key in await self._items(x, y):
                    if key not in local:
                        missing.append(key)
                return
            await recurse(x, mid)
            await recurse(mid, y)

        await recurse(ZERO, TOP)
        return missing
