"""Gossipsub-lite: degree-bounded per-topic meshes + lazy IHAVE/IWANT.

Replaces flood relay (O(edges) duplication) with the gossipsub structure
the reference rides (reference p2p/pubsub/pubsub.go:211-311 mesh
parameters; libp2p gossipsub v1.0 semantics):

* per-topic MESH of degree ~D: full messages are eager-pushed only to
  mesh peers;
* lazy gossip to a few non-mesh peers each heartbeat: IHAVE(recent ids);
  a peer missing one answers IWANT and gets the full frame — the repair
  path that keeps sparse meshes connected;
* GRAFT/PRUNE keep each topic mesh within [d_lo, d_hi], symmetric via
  the GRAFT handshake (over-subscribed peers answer PRUNE).

Every node here subscribes to every topic (the node runs all protocol
handlers), so subscription bookkeeping is implicit.  Control frames ride
the transport as MSG_GOSSIP_CTRL; full messages stay MSG_GOSSIP so the
wire format of data frames is unchanged.
"""

from __future__ import annotations

import hashlib
import random
import struct

GRAFT, PRUNE, IHAVE, IWANT = range(4)

_ID = 32  # gossip message ids are sum256 digests

SEEN_CAP = 1 << 14


def mark_seen(seen: dict, msg_id: bytes, cap: int = SEEN_CAP) -> bool:
    """Insert into an insertion-ordered seen-cache; True if newly seen.
    Evicts the oldest quarter when full. ONE implementation shared by
    the socket transport and the sim hub so their dedup windows can
    never silently diverge."""
    if msg_id in seen:
        return False
    seen[msg_id] = None
    if len(seen) > cap:
        for key in list(seen)[:cap // 4]:
            del seen[key]
    return True


def relay_sample(topic: str, name: bytes, peers, k: int) -> tuple:
    """Deterministic sparse relay set for a light relay: the first ``k``
    of ``peers`` ranked by sha256(topic || name || peer). Every (topic,
    node) pair gets a different but cross-process-stable subset, so the
    union of relay edges forms a connected expander over the topology
    without any node running the gossipsub control plane."""
    tb = topic.encode()
    ranked = sorted(peers,
                    key=lambda p: hashlib.sha256(tb + name + p).digest())
    return tuple(ranked[:k])


def encode_ctrl(subtype: int, topic: str, ids: list[bytes] = ()) -> bytes:
    tb = topic.encode()
    return struct.pack("<BB", subtype, len(tb)) + tb + b"".join(ids)


def decode_ctrl(payload: bytes) -> tuple[int, str, list[bytes]]:
    subtype, tlen = struct.unpack_from("<BB", payload)
    topic = payload[2:2 + tlen].decode()
    blob = payload[2 + tlen:]
    if len(blob) % _ID:
        raise ValueError("ragged id list")
    ids = [blob[i:i + _ID] for i in range(0, len(blob), _ID)]
    return subtype, topic, ids


class MessageCache:
    """Recent full frames by id, with a sliding IHAVE window (gossipsub
    mcache: `history` heartbeats of ids, payloads kept for IWANT)."""

    def __init__(self, history: int = 5, max_msgs: int = 1 << 10):
        self.history = history
        self.max_msgs = max_msgs
        self._frames: dict[bytes, tuple[str, bytes]] = {}  # id -> (topic, frame)
        self._window: list[list[tuple[bytes, str]]] = [[]]  # per-heartbeat ids

    def put(self, msg_id: bytes, topic: str, frame: bytes) -> None:
        if msg_id in self._frames:
            return
        self._frames[msg_id] = (topic, frame)
        self._window[0].append((msg_id, topic))
        # age out whole rounds first...
        while len(self._window) > 1 and len(self._frames) > self.max_msgs:
            for mid, _ in self._window.pop():
                self._frames.pop(mid, None)
        # ...then hard-trim the current round: a burst bigger than the
        # cache within ONE heartbeat must not balloon memory (frames can
        # be large; ids stay droppable — IWANT for them just misses)
        while len(self._frames) > self.max_msgs and self._window[0]:
            mid, _ = self._window[0].pop(0)
            self._frames.pop(mid, None)

    def get(self, msg_id: bytes) -> bytes | None:
        entry = self._frames.get(msg_id)
        return entry[1] if entry else None

    def empty(self) -> bool:
        """True once every frame AND every window round has aged out —
        the hub's dirty-set heartbeat uses this to retire quiet nodes
        (an empty cache has no IHAVE left to advertise)."""
        return not self._frames and not any(self._window)

    def shift(self) -> None:
        """One heartbeat passed: rotate the IHAVE window."""
        self._window.insert(0, [])
        while len(self._window) > self.history:
            for mid, _ in self._window.pop():
                self._frames.pop(mid, None)

    def recent_ids(self, topic: str) -> list[bytes]:
        return [mid for round_ in self._window
                for mid, t in round_ if t == topic]


class GossipMesh:
    """Mesh membership + control-plane logic; the Host owns the sockets
    and calls in with peer ids, getting (peer, frame-payload) sends out."""

    MAX_TOPICS = 64  # control-frame topic-spam guard (see on_control)

    def __init__(self, *, degree: int = 6, d_lo: int = 4, d_hi: int = 8,
                 lazy: int = 3, history: int = 20,
                 rng: random.Random | None = None):
        # history (IHAVE window in heartbeats) is deliberately deeper than
        # gossipsub's default 5: repair must survive a loaded event loop
        # where several heartbeats' worth of work lands late; ids are 32
        # bytes and frames are already capped by max_msgs, so depth is
        # nearly free
        self.degree = degree
        self.d_lo = d_lo
        self.d_hi = d_hi
        self.lazy = lazy            # IHAVE fanout per heartbeat per topic
        self.mesh: dict[str, set[bytes]] = {}
        self.cache = MessageCache(history=history)
        self.rng = rng or random.Random(0xC0FFEE)
        # ids a peer asked for repeatedly (IWANT abuse guard)
        self._served: dict[tuple[bytes, bytes], int] = {}

    def topics(self) -> list[str]:
        return list(self.mesh)

    def _mesh(self, topic: str) -> set[bytes]:
        return self.mesh.setdefault(topic, set())

    # -- data plane --------------------------------------------------

    def eager_targets(self, topic: str, connected: set[bytes],
                      exclude: bytes | None = None) -> set[bytes]:
        """Peers that get the full frame NOW.  Until the mesh for a topic
        has formed (bootstrap), fall back to flood so nothing stalls.
        Read-only on the topic table: relaying must not grow it (the
        spam cap in on_message owns admission)."""
        mesh = self.mesh.get(topic, set()) & connected
        targets = mesh if mesh else set(connected)
        if exclude is not None:
            targets = targets - {exclude}
        return targets

    def on_message(self, msg_id: bytes, topic: str, frame: bytes) -> None:
        # learn the topic — but attacker-chosen topic strings on DATA
        # frames must not grow the per-topic tables (and with them the
        # heartbeat's GRAFT/IHAVE work) without bound, same cap as the
        # control plane; the frame still lands in the (size-bounded)
        # cache so IWANT can serve it
        if topic in self.mesh or len(self.mesh) < self.MAX_TOPICS:
            self._mesh(topic)
        self.cache.put(msg_id, topic, frame)

    # -- control plane -----------------------------------------------

    def on_control(self, peer: bytes, payload: bytes,
                   seen) -> list[tuple[int, str, list[bytes]]]:
        """Handle one control frame; returns replies [(subtype, topic,
        ids)] to send back to ``peer``.  ``seen(msg_id)`` tells whether
        we already hold a message."""
        subtype, topic, ids = decode_ctrl(payload)
        if topic not in self.mesh and len(self.mesh) >= self.MAX_TOPICS:
            # topic-spam guard: a hostile peer must not grow the
            # per-topic tables without bound — unknown topics past the
            # cap answer GRAFT with PRUNE and drop the rest (data
            # frames hit the same cap in on_message; the node's own
            # topics were learned long before any attacker fills it)
            return [(PRUNE, topic, [])] if subtype == GRAFT else []
        mesh = self._mesh(topic)
        if subtype == GRAFT:
            if len(mesh) >= self.d_hi:
                return [(PRUNE, topic, [])]
            mesh.add(peer)
            return []
        if subtype == PRUNE:
            mesh.discard(peer)
            return []
        if subtype == IHAVE:
            want = [i for i in ids if not seen(i)]
            return [(IWANT, topic, want[:64])] if want else []
        if subtype == IWANT:
            out = []
            for mid in ids[:64]:
                key = (peer, mid)
                self._served[key] = self._served.get(key, 0) + 1
                if self._served[key] > 3:
                    continue  # IWANT spam guard (gossipsub GossipRetransmission)
                if len(self._served) > (1 << 12):
                    self._served.clear()
                if self.cache.get(mid) is not None:
                    out.append(mid)
            return [(-1, topic, out)] if out else []  # -1: send full frames
        raise ValueError(f"unknown control subtype {subtype}")

    def drop_peer(self, peer: bytes) -> None:
        for mesh in self.mesh.values():
            mesh.discard(peer)

    # -- heartbeat ---------------------------------------------------

    def heartbeat(self, connected: set[bytes]) -> list[tuple[bytes, int, str,
                                                             list[bytes]]]:
        """Mesh maintenance + lazy gossip; returns control sends
        [(peer, subtype, topic, ids)]."""
        out: list[tuple[bytes, int, str, list[bytes]]] = []
        for topic in list(self.mesh):
            mesh = self._mesh(topic)
            mesh &= connected  # forget gone peers
            if len(mesh) < self.d_lo:
                candidates = sorted(connected - mesh)
                self.rng.shuffle(candidates)
                for peer in candidates[:self.degree - len(mesh)]:
                    mesh.add(peer)
                    out.append((peer, GRAFT, topic, []))
            elif len(mesh) > self.d_hi:
                excess = sorted(mesh)
                self.rng.shuffle(excess)
                for peer in excess[:len(mesh) - self.degree]:
                    mesh.discard(peer)
                    out.append((peer, PRUNE, topic, []))
            # lazy gossip: advertise the recent window to non-mesh peers
            ids = self.cache.recent_ids(topic)
            if ids:
                lazy_pool = sorted(connected - mesh)
                self.rng.shuffle(lazy_pool)
                for peer in lazy_pool[:self.lazy]:
                    out.append((peer, IHAVE, topic, ids[-64:]))
        self.cache.shift()
        return out
