"""Publish/subscribe seam + in-proc loopback hub.

Mirrors the reference's gossip topic registration (reference
p2p/pubsub/pubsub.go: topics `ax1 pp1 tx1 b1 bo1 mp1 bc1 ...` with
validator handlers; handlers return accept/reject and rejection can drop
the peer). Topic names are kept. The LoopbackHub wires N in-proc nodes
fully connected — the TestNetwork equivalent (reference
node/test_network.go) — delivering to every OTHER node's handlers and,
like gossipsub, not echoing to the publisher (publishers handle their own
messages locally, as the reference does via pubsub self-delivery... which
IS echoed there; here `deliver_self` controls it, default True to match).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable

from ..utils import metrics, tracing

_log = logging.getLogger("pubsub")

# reference topic names (p2p/pubsub/pubsub.go:54-81)
TOPIC_ATX = "ax1"
TOPIC_PROPOSAL = "pp1"
TOPIC_TX = "tx1"
TOPIC_BEACON_PROPOSAL = "bp1"
TOPIC_BEACON_FIRST = "bf1"
TOPIC_BEACON_FOLLOW = "bo1"
TOPIC_BEACON_WEAK_COIN = "bw1"
TOPIC_HARE = "b1"
TOPIC_MALFEASANCE = "mp1"
TOPIC_CERTIFY = "bc1"
TOPIC_POET = "pt1"

# (peer, data) -> True: accept + relay; None: accept but do NOT relay
# (graded-gossip duplicate/suppressed); False: reject (penalize sender).
Handler = Callable[[bytes, bytes], Awaitable[bool]]


class PubSub:
    """One node's view: register validators, publish bytes."""

    def __init__(self, node_name: bytes = b"local",
                 deliver_self: bool = True):
        self.name = node_name
        self.deliver_self = deliver_self
        self._handlers: dict[str, list[Handler]] = {}
        self._hub: "LoopbackHub | None" = None

    def register(self, topic: str, handler: Handler) -> None:
        self._handlers.setdefault(topic, []).append(handler)

    async def publish(self, topic: str, data: bytes) -> None:
        if self.deliver_self:
            await self.deliver(topic, self.name, data)
        if self._hub is not None:
            await self._hub.broadcast(self, topic, data)

    async def deliver(self, topic: str, peer: bytes, data: bytes):
        """Tri-state aggregate over the topic's handlers: False if any
        rejected, else None if any suppressed relay, else True.

        One raising handler must not abort delivery to the REMAINING
        subscribers (nor kill the bus): the exception is counted as a
        reject, logged, and surfaced in pubsub_handler_drops_total so a
        silently-crashing validator is visible to operators.

        Under a span-trace capture (utils/tracing.py) each delivery is
        the ROOT of a causal timeline: the per-handler validator spans —
        and everything they await, verify-farm submits included —
        parent into it, so one gossip message's whole processing path
        reads as a single tree in the Perfetto export."""
        ok = True
        dsp = tracing.span("gossip.deliver",
                           {"topic": topic, "peer": peer.hex()[:16],
                            "bytes": len(data)}
                           if tracing.is_enabled() else None)
        async with dsp:
            for h in self._handlers.get(topic, ()):
                t0 = time.perf_counter()
                try:
                    async with tracing.span(
                            "gossip.handler",
                            {"topic": topic,
                             "handler": getattr(h, "__qualname__", str(h))}
                            if tracing.is_enabled() else None):
                        r = await h(peer, data)
                except asyncio.CancelledError:
                    raise  # shutdown must still propagate
                except Exception as exc:  # noqa: BLE001 — bad message ≠ dead bus
                    metrics.pubsub_handler_drops.inc(topic=topic)
                    _log.warning("handler %r dropped message on topic %s: %r",
                                 getattr(h, "__qualname__", h), topic, exc)
                    r = False
                finally:
                    # handler wall time INCLUDING farm queue wait — the
                    # gossip-latency SLI an admission decision keys off
                    metrics.gossip_handler_seconds.observe(
                        time.perf_counter() - t0, topic=topic)
                if r is False:
                    ok = False
                elif r is None and ok is True:
                    ok = None
            if dsp is not tracing._NOP:
                dsp.set(result={True: "accept", False: "reject",
                                None: "no-relay"}[ok])
        return ok


class LoopbackHub:
    """Fully-connected in-proc network of PubSub endpoints.

    Delivery is fire-and-forget with a per-receiver ordered inbox, like
    real gossipsub: a publisher never waits on other nodes' validators
    (a slow or stuck receiver must not be able to stall the sender's
    consensus rounds), while each receiver still processes messages in
    arrival order.
    """

    def __init__(self) -> None:
        self._nodes: list[PubSub] = []
        self._inboxes: dict[int, asyncio.Queue] = {}
        self._consumers: dict[int, asyncio.Task] = {}

    def join(self, ps: PubSub) -> None:
        ps._hub = self
        self._nodes.append(ps)

    def leave(self, ps: PubSub) -> None:
        ps._hub = None
        self._nodes.remove(ps)
        task = self._consumers.pop(id(ps), None)
        if task is not None:
            task.cancel()
        self._inboxes.pop(id(ps), None)

    def _inbox(self, ps: PubSub) -> asyncio.Queue:
        key = id(ps)
        if key not in self._inboxes:
            self._inboxes[key] = asyncio.Queue()

            async def consume(node=ps, q=self._inboxes[key]):
                while True:
                    topic, peer, data = await q.get()
                    try:
                        await node.deliver(topic, peer, data)
                    except Exception:  # noqa: BLE001 — deliver() already
                        # counts + logs per-handler failures
                        # (pubsub_handler_drops_total); this guard only
                        # keeps the hub consumer task alive
                        pass
                    finally:
                        q.task_done()

            self._consumers[key] = asyncio.ensure_future(consume())
        return self._inboxes[key]

    async def broadcast(self, sender: PubSub, topic: str, data: bytes) -> None:
        for n in self._nodes:
            if n is not sender:
                self._inbox(n).put_nowait((topic, sender.name, data))

    async def drain(self) -> None:
        """Wait until every queued message is fully DELIVERED (join(), not
        emptiness: the last message may still be mid-handler)."""
        await asyncio.gather(*(q.join() for q in self._inboxes.values()))
