"""QUIC-lite: a UDP transport with connection ids + ARQ reliability.

Second transport under the same ``Host`` seam (reference
p2p/host.go:28-29,166 EnableQUICTransport — libp2p quic + quicreuse;
aioquic is not in this image, so this is an own implementation of the
properties the stack needs rather than RFC 9000):

* one UDP socket per endpoint, many connections (QUIC's socket sharing —
  quicreuse);
* 8-byte DESTINATION connection ids on every packet, chosen by the
  receiver at handshake — delivery is keyed by conn id, not source
  address, so a peer surviving a NAT rebind keeps its connection
  (QUIC connection migration, RFC 9000 §5.1 in spirit);
* per-connection ordered reliable byte stream: DATA packets carry u32
  sequence numbers; the receiver buffers out-of-order packets and
  cumulatively ACKs; the sender keeps an in-flight window with RTO
  retransmission (doubling backoff) and 3-dup-ACK fast retransmit;
* keepalive PING / idle teardown, FIN close.

The stream is exposed as an ``asyncio.StreamReader`` + a writer facade
with the ``write/drain/close/get_extra_info`` surface the TCP path uses,
so the noise channel (p2p/noise.py — X25519 + ChaCha20-Poly1305 with
channel-binding ids) and the whole Host frame protocol run UNCHANGED
over either transport. Security lives in noise, exactly like the TCP
path; this layer only provides ordered reliable delivery.

Chaos/test hooks: ``QuicEndpoint.loss_rate`` drops that fraction of
outgoing DATA packets (deterministic rng) to exercise retransmission.
"""

from __future__ import annotations

import asyncio
import os
import random
import struct
import time

MAGIC = 0x51  # 'Q'
SYN, SYNACK, DATA, ACK, FIN, PING = 1, 2, 3, 4, 5, 6

HEADER = struct.Struct("<BB8sII")  # magic, type, dest conn id, seq, ack
MAX_PAYLOAD = 1200
WINDOW = 128              # max in-flight DATA packets
RECV_BUF_CAP = 4 << 20    # stop advancing recv_next past this much
                          # undrained reader data (flow control)
RTO_MIN, RTO_MAX = 0.2, 2.0
IDLE_TIMEOUT = 30.0
KEEPALIVE = 5.0
SYN_RETRIES = 5
MAX_HALF_OPEN = 64        # server conns accepted but with no DATA yet —
                          # a spoofed SYN flood stops allocating state here
                          # (the TCP path gets this from the kernel accept
                          # queue; ADVICE r4)
MAX_CONNS = 1024          # hard cap on live connections per endpoint


class CountingReader(asyncio.StreamReader):
    """StreamReader that tracks buffered bytes (fed minus consumed) so
    receive flow control does not rely on asyncio's private ``_buffer``
    attribute (ADVICE r4: if that internal were renamed, backpressure
    would silently never engage)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._fed = 0
        self._consumed = 0

    @property
    def buffered(self) -> int:
        return self._fed - self._consumed

    def feed_data(self, data) -> None:
        self._fed += len(data)
        super().feed_data(data)

    # Consumption is counted ONLY at the primitive consume points —
    # read(n>=0), readexactly, readuntil. read(-1) loops over
    # self.read(limit) and readline delegates to self.readuntil, so
    # counting in those wrappers too would double-count every byte and
    # drive `buffered` negative (code-review r5).

    async def read(self, n=-1):
        if n < 0:
            return await super().read(n)  # delegates to counted read(n)
        data = await super().read(n)
        self._consumed += len(data)
        return data

    async def readexactly(self, n):
        try:
            data = await super().readexactly(n)
        except asyncio.IncompleteReadError as e:
            self._consumed += len(e.partial)  # partial IS consumed
            raise
        self._consumed += len(data)
        return data

    async def readuntil(self, separator=b"\n"):
        try:
            data = await super().readuntil(separator)
        except asyncio.IncompleteReadError as e:
            self._consumed += len(e.partial)  # EOF drains the buffer
            raise
        self._consumed += len(data)
        return data

    async def readline(self):
        # StreamReader.readline swallows LimitOverrunError by truncating
        # the private ``_buffer`` directly — bytes this counter never sees
        # as consumed, permanently inflating ``buffered`` and wedging
        # receive flow control. No caller needs line framing (noise.py is
        # readexactly-only), so fail loudly instead of corrupting the
        # accounting (ADVICE r5).
        raise NotImplementedError(
            "CountingReader does not support readline(): its "
            "LimitOverrunError recovery bypasses flow-control accounting; "
            "use readexactly/readuntil")


class QuicWriter:
    """asyncio.StreamWriter-shaped facade over a QuicConnection."""

    def __init__(self, conn: "QuicConnection"):
        self._conn = conn

    def write(self, data: bytes) -> None:
        self._conn.feed_send(data)

    async def drain(self) -> None:
        await self._conn.drained()

    def close(self) -> None:
        self._conn.close()

    def get_extra_info(self, name: str, default=None):
        if name == "peername":
            return self._conn.remote_addr
        if name == "sockname":
            return self._conn.endpoint.address
        return default


class QuicConnection:
    def __init__(self, endpoint: "QuicEndpoint", remote_addr, local_id: bytes):
        self.endpoint = endpoint
        self.remote_addr = remote_addr
        self.local_id = local_id          # what the PEER puts in dest id
        self.remote_id: bytes | None = None
        self.reader = CountingReader()
        self.writer = QuicWriter(self)
        self.established = asyncio.Event()
        self.closed = False
        self.half_open = False            # server-accepted, no DATA yet
        self._peer_key = None             # (client_id, addr) accept index
        # send side
        self._send_buf = bytearray()
        self._next_seq = 0                # next seq to assign
        self._inflight: dict[int, tuple[bytes, float]] = {}  # seq -> (pkt, t)
        self._base = 0                    # lowest unacked seq
        self._rto = RTO_MIN
        self._dup_acks = 0
        self._drain_ev = asyncio.Event()
        self._drain_ev.set()
        # recv side
        self._recv_next = 0
        self._ooo: dict[int, bytes] = {}
        self.last_heard = self.endpoint._now()
        self._tasks: list[asyncio.Task] = []

    # --- lifecycle ---

    def start_io(self) -> None:
        self._tasks.append(asyncio.ensure_future(self._retransmit_loop()))

    def close(self, *, _send_fin: bool = True) -> None:
        if self.closed:
            return
        self.closed = True
        if self.half_open:
            self.half_open = False
            self.endpoint.half_open_count -= 1
        if _send_fin and self.remote_id is not None:
            self.endpoint._send_raw(FIN, self.remote_id, 0, 0, b"",
                                    self.remote_addr)
        self.reader.feed_eof()
        self.established.set()
        self._drain_ev.set()
        for t in self._tasks:
            t.cancel()
        self.endpoint._forget(self)

    # --- send path ---

    def feed_send(self, data: bytes) -> None:
        if self.closed:
            raise ConnectionError("quic connection closed")
        self._send_buf += data
        self._pump()

    async def drained(self) -> None:
        await self._drain_ev.wait()
        if self.closed:
            raise ConnectionError("quic connection closed")

    def _pump(self) -> None:
        """Move bytes from the send buffer into the in-flight window."""
        while self._send_buf and len(self._inflight) < WINDOW:
            chunk = bytes(self._send_buf[:MAX_PAYLOAD])
            del self._send_buf[:len(chunk)]
            seq = self._next_seq
            self._next_seq += 1
            pkt = HEADER.pack(MAGIC, DATA, self.remote_id, seq,
                              self._recv_next) + chunk
            self._inflight[seq] = (pkt, self.endpoint._now())
            self.endpoint._send_pkt(pkt, self.remote_addr, data=True)
        if self._send_buf or len(self._inflight) >= WINDOW:
            self._drain_ev.clear()
        else:
            self._drain_ev.set()

    def _on_ack(self, ack: int) -> None:
        if ack > self._base:
            for seq in range(self._base, ack):
                self._inflight.pop(seq, None)
            self._base = ack
            self._rto = RTO_MIN
            self._dup_acks = 0
            self._pump()
        elif ack == self._base and self._base < self._next_seq:
            self._dup_acks += 1
            if self._dup_acks >= 3:  # fast retransmit of the base packet
                self._dup_acks = 0
                ent = self._inflight.get(self._base)
                if ent is not None:
                    self.endpoint.stats["retx"] += 1
                    self.endpoint._send_pkt(ent[0], self.remote_addr,
                                            data=True)

    async def _retransmit_loop(self) -> None:
        while not self.closed:
            await asyncio.sleep(self._rto / 2)
            now = self.endpoint._now()
            if self.last_heard + IDLE_TIMEOUT < now:
                self.close()
                return
            ent = self._inflight.get(self._base)
            if ent is not None and now - ent[1] > self._rto:
                pkt, _ = ent
                self._inflight[self._base] = (pkt, now)
                self.endpoint.stats["retx"] += 1
                self.endpoint._send_pkt(pkt, self.remote_addr, data=True)
                self._rto = min(self._rto * 2, RTO_MAX)
            elif not self._inflight and self.remote_id is not None \
                    and self.last_heard + KEEPALIVE < now:
                self.endpoint._send_raw(PING, self.remote_id, 0,
                                        self._recv_next, b"",
                                        self.remote_addr)

    # --- receive path ---

    def on_packet(self, ptype: int, seq: int, ack: int, payload: bytes,
                  addr) -> None:
        self.last_heard = self.endpoint._now()
        # connection-id routing: the peer may have migrated address
        if addr != self.remote_addr:
            self.remote_addr = addr
        if ptype == DATA:
            self._on_ack(ack)
            # flow control: TCP gets backpressure from the kernel recv
            # window; here the stand-in is refusing to advance recv_next
            # while the application hasn't drained the reader — the
            # sender's window fills and its RTO paces retransmission
            # until we catch up (no unbounded reader growth)
            if self.half_open:
                self.half_open = False
                self.endpoint.half_open_count -= 1
            if seq == self._recv_next and self.reader.buffered < RECV_BUF_CAP:
                self.reader.feed_data(payload)
                self._recv_next += 1
                while self._recv_next in self._ooo:
                    self.reader.feed_data(self._ooo.pop(self._recv_next))
                    self._recv_next += 1
            elif seq > self._recv_next:
                if len(self._ooo) < 4 * WINDOW:   # bound rogue buffering
                    self._ooo[seq] = payload
            self.endpoint._send_raw(ACK, self.remote_id, 0,
                                    self._recv_next, b"", self.remote_addr)
        elif ptype == ACK:
            self._on_ack(ack)
        elif ptype == PING:
            self.endpoint._send_raw(ACK, self.remote_id, 0,
                                    self._recv_next, b"", self.remote_addr)
        elif ptype == FIN:
            # full teardown via close() so the half-open accounting runs
            # (code-review r5: a SYN->FIN pair that skipped the decrement
            # leaked admission slots until the endpoint refused everyone);
            # no FIN echo — the peer initiated the close
            self.close(_send_fin=False)


class QuicEndpoint(asyncio.DatagramProtocol):
    """One UDP socket serving many QUIC-lite connections."""

    def __init__(self, on_accept=None, loss_rate: float = 0.0,
                 rng: random.Random | None = None, time_source=None):
        # injected (QuicHost forwards the node clock) so RTO aging,
        # idle timeouts and keepalives follow virtual/skewed time in
        # sim and chaos scenarios; deltas only (SC001 clock discipline)
        self._now = time_source or time.monotonic
        self.on_accept = on_accept        # async callback(reader, writer)
        self.transport: asyncio.DatagramTransport | None = None
        self.address: tuple[str, int] | None = None
        self._by_id: dict[bytes, QuicConnection] = {}
        self._accepted: dict[tuple, QuicConnection] = {}
        # ^ (client_id, addr) -> conn, so retransmitted-SYN dedupe is
        #   O(1) — the SYN path must do constant work per packet or the
        #   flood it refuses still starves the event loop
        self.half_open_count = 0          # O(1) admission check under flood
        self._syn_waiters: dict[bytes, asyncio.Future] = {}
        self.loss_rate = loss_rate
        self._rng = rng or random.Random(0xC0FFEE)
        self.stats = {"tx": 0, "rx": 0, "dropped": 0, "retx": 0}

    # --- lifecycle ---

    async def listen(self, host: str, port: int) -> tuple[str, int]:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(host, port))
        self.address = self.transport.get_extra_info("sockname")[:2]
        return self.address

    def close(self) -> None:
        for conn in list(self._by_id.values()):
            conn.close()
        if self.transport is not None:
            self.transport.close()

    # --- outbound ---

    async def connect(self, addr: tuple[str, int], timeout: float = 5.0):
        """Dial: returns (reader, writer) once the SYN/SYNACK completes."""
        local_id = os.urandom(8)
        conn = QuicConnection(self, tuple(addr), local_id)
        self._by_id[local_id] = conn
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._syn_waiters[local_id] = fut
        try:
            per_try = timeout / SYN_RETRIES
            for _ in range(SYN_RETRIES):
                self._send_raw(SYN, bytes(8), 0, 0, local_id, tuple(addr))
                try:
                    await asyncio.wait_for(asyncio.shield(fut), per_try)
                    break
                except asyncio.TimeoutError:
                    continue
            if not fut.done():
                raise asyncio.TimeoutError("quic connect timeout")
            conn.remote_id = fut.result()  # spacecheck: ok=SC002 fut.done() is guaranteed just above — a done future's result() cannot block
        except BaseException:
            # failed/cancelled dial: the conn was registered in _by_id at
            # construction — without this, every redial to an unreachable
            # bootnode leaks a connection forever
            conn.close()
            raise
        finally:
            self._syn_waiters.pop(local_id, None)
        conn.established.set()
        conn.start_io()
        return conn.reader, conn.writer

    # --- packet IO ---

    def _send_pkt(self, pkt: bytes, addr, data: bool = False) -> None:
        if self.transport is None or self.transport.is_closing():
            return
        self.stats["tx"] += 1
        if data and self.loss_rate and self._rng.random() < self.loss_rate:
            self.stats["dropped"] += 1
            return
        self.transport.sendto(pkt, addr)

    def _send_raw(self, ptype: int, dest_id: bytes | None, seq: int,
                  ack: int, payload: bytes, addr) -> None:
        if dest_id is None:
            return
        self._send_pkt(HEADER.pack(MAGIC, ptype, dest_id, seq, ack)
                       + payload, addr)

    def _forget(self, conn: QuicConnection) -> None:
        if self._by_id.get(conn.local_id) is conn:
            del self._by_id[conn.local_id]
        if conn._peer_key is not None \
                and self._accepted.get(conn._peer_key) is conn:
            del self._accepted[conn._peer_key]

    def datagram_received(self, data: bytes, addr) -> None:
        if len(data) < HEADER.size:
            return
        magic, ptype, dest_id, seq, ack = HEADER.unpack_from(data)
        if magic != MAGIC:
            return
        payload = data[HEADER.size:]
        self.stats["rx"] += 1
        if ptype == SYN:
            # payload = client's chosen id; allocate ours, reply SYNACK.
            # Retransmitted SYNs for a known client id reuse the
            # existing connection (no duplicate accept).
            client_id = payload[:8]
            if len(client_id) != 8:
                return
            known = self._accepted.get((client_id, addr))
            if known is not None:
                self._send_raw(SYNACK, client_id, 0, 0, known.local_id,
                               addr)
                return
            # admission control: a spoofed SYN flood must not grow
            # _by_id and its tasks unboundedly — refuse new state once
            # too many accepted connections have never sent DATA, or
            # the endpoint is at its hard connection cap (ADVICE r4).
            # The counter keeps this O(1) on the flooded path.
            if self.half_open_count >= MAX_HALF_OPEN \
                    or len(self._by_id) >= MAX_CONNS:
                self.stats["syn_refused"] = \
                    self.stats.get("syn_refused", 0) + 1
                return
            local_id = os.urandom(8)
            conn = QuicConnection(self, addr, local_id)
            conn.remote_id = client_id
            conn.half_open = True
            conn._peer_key = (client_id, addr)
            self.half_open_count += 1
            self._by_id[local_id] = conn
            self._accepted[conn._peer_key] = conn
            conn.established.set()
            conn.start_io()
            self._send_raw(SYNACK, client_id, 0, 0, local_id, addr)
            if self.on_accept is not None:
                asyncio.ensure_future(
                    self.on_accept(conn.reader, conn.writer))
            return
        if ptype == SYNACK:
            fut = self._syn_waiters.get(dest_id)
            if fut is not None and not fut.done() and len(payload) >= 8:
                fut.set_result(payload[:8])
            return
        conn = self._by_id.get(dest_id)
        if conn is not None:
            conn.on_packet(ptype, seq, ack, payload, addr)

    def error_received(self, exc) -> None:  # pragma: no cover - os-specific
        pass


from .transport import Host as _HostBase  # noqa: E402 (no import cycle:
# transport does not import quic; the app picks the Host class by config)


class QuicHost(_HostBase):
    """The same Host protocol stack (noise handshake, HELLO identity
    proof, gossipsub-lite, req/resp, peer exchange, chaos hooks) over
    QUIC-lite instead of TCP — config-selectable (reference
    p2p/host.go:166,321 EnableQUICTransport + libp2p transport options).

    ``quic_loss_rate`` injects deterministic outbound DATA loss for
    retransmission tests/chaos."""

    def __init__(self, *args, quic_loss_rate: float = 0.0, **kw):
        super().__init__(*args, **kw)
        self._endpoint = QuicEndpoint(
            on_accept=self._accept, loss_rate=quic_loss_rate,
            rng=random.Random(int.from_bytes(self.node_id[:4], "big")),
            time_source=self._now)

    async def _listen(self, host: str, port: int) -> tuple[str, int]:
        return await self._endpoint.listen(host, port)

    async def _open_connection(self, addr: tuple[str, int]):
        return await self._endpoint.connect(tuple(addr))

    async def _close_listener(self) -> None:
        self._endpoint.close()
