"""Authenticated, encrypted transport channel (noise-style).

Closes VERDICT r2 gap 2: the plaintext HELLO carried a self-declared
node id, so any peer could impersonate any identity, poisoning per-peer
scoring, bans, and gossip attribution. The reference binds peer ids to
keys via libp2p's noise security transport (reference p2p/host.go:27-28,
306-309 — noise + peer-id-from-pubkey; p2p/handshake/handshake.go for
the cookie). This module is the TPU framework's equivalent, built from
the same primitives (X25519 ECDH + ChaCha20-Poly1305 + the node's
ed25519 identity key) without the libp2p framing:

1. Both sides exchange fresh ephemeral X25519 public keys (32 raw bytes
   each way; full-duplex, no ordering deadlock).
2. ECDH -> HKDF-SHA256 (salted with the genesis id — the network cookie
   is mixed into the keys, so wrong-network peers can't even decrypt)
   yields two direction keys and a 32-byte channel-binding token.
3. Each side's first ENCRYPTED frame is the HELLO: its ed25519 public
   key (= its node id), listen port, and a signature over the channel
   binding + its role. The signature proves possession of the identity
   key for THIS channel: ids are unforgeable, and a MITM relaying the
   handshake gets keys neither side signed.
4. Every subsequent frame is ChaCha20-Poly1305 with a per-direction
   64-bit counter nonce (reordering/replay detected by AEAD failure).

Forward secrecy comes from the ephemerals; identity binding from the
signature. Equivalent guarantees to noise XX + identity payload.
"""

from __future__ import annotations

import asyncio
import struct

try:  # the fast path: OpenSSL primitives via pyca/cryptography
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey,
        X25519PublicKey,
    )
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    _HAVE_CRYPTOGRAPHY = True
except ModuleNotFoundError:
    # pure-Python shims (bottom of this module): exact RFC 7748 X25519
    # and RFC 5869 HKDF, plus an hashlib-based encrypt-then-MAC AEAD in
    # place of ChaCha20-Poly1305 (a pure-Python ChaCha20 is orders of
    # magnitude too slow for bulk frames). The AEAD substitution makes
    # this build WIRE-INCOMPATIBLE with OpenSSL-backed peers: a mixed
    # pair fails frame authentication and the connection closes — every
    # node in a network must run the same suite.
    _HAVE_CRYPTOGRAPHY = False

MAX_FRAME = 64 << 20


class ChannelError(Exception):
    pass


class NoiseChannel:
    """Encrypted framed stream over an asyncio reader/writer pair."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, *,
                 tx_key: bytes, rx_key: bytes, binding: bytes,
                 initiator: bool):
        self.reader = reader
        self.writer = writer
        self.binding = binding
        self.initiator = initiator
        self._tx = ChaCha20Poly1305(tx_key)
        self._rx = ChaCha20Poly1305(rx_key)
        self._tx_n = 0
        self._rx_n = 0

    @classmethod
    async def establish(cls, reader: asyncio.StreamReader,
                        writer: asyncio.StreamWriter, *,
                        genesis_id: bytes,
                        initiator: bool) -> "NoiseChannel":
        eph = X25519PrivateKey.generate()
        e_pub = eph.public_key().public_bytes_raw()
        writer.write(e_pub)
        await writer.drain()
        peer_e = await reader.readexactly(32)
        try:
            shared = eph.exchange(X25519PublicKey.from_public_bytes(peer_e))
        except ValueError as e:  # low-order / invalid point
            raise ChannelError(f"bad ephemeral key: {e}") from None
        e_i, e_r = (e_pub, peer_e) if initiator else (peer_e, e_pub)
        okm = HKDF(algorithm=hashes.SHA256(), length=96, salt=genesis_id,
                   info=b"smh/noise/1" + e_i + e_r).derive(shared)
        k_i2r, k_r2i, binding = okm[:32], okm[32:64], okm[64:]
        tx_key, rx_key = (k_i2r, k_r2i) if initiator else (k_r2i, k_i2r)
        return cls(reader, writer, tx_key=tx_key, rx_key=rx_key,
                   binding=binding, initiator=initiator)

    def _nonce(self, counter: int) -> bytes:
        return struct.pack("<Q", counter) + bytes(4)

    def encrypt_frame(self, frame_type: int, payload: bytes) -> bytes:
        ct = self._tx.encrypt(self._nonce(self._tx_n),
                              bytes([frame_type]) + payload, b"")
        self._tx_n += 1
        return struct.pack("<I", len(ct)) + ct

    async def send(self, frame_type: int, payload: bytes) -> None:
        self.writer.write(self.encrypt_frame(frame_type, payload))
        await self.writer.drain()

    async def recv(self) -> tuple[int, bytes]:
        head = await self.reader.readexactly(4)
        (length,) = struct.unpack("<I", head)
        if not 17 <= length <= MAX_FRAME:  # 1 type byte + 16 tag minimum
            raise ChannelError(f"bad frame length {length}")
        ct = await self.reader.readexactly(length)
        try:
            pt = self._rx.decrypt(self._nonce(self._rx_n), ct, b"")
        except Exception:  # InvalidTag — tampered/replayed/wrong-key
            raise ChannelError("frame authentication failed") from None
        self._rx_n += 1
        return pt[0], pt[1:]

    def sign_binding(self, signer, role_initiator: bool) -> bytes:
        """Channel-binding signature: proves the identity key holder is
        live on THIS channel in THIS role (role byte stops reflection)."""
        from ..core.signing import Domain

        return signer.sign(Domain.TRANSPORT,
                           self.binding + (b"i" if role_initiator else b"r"))

    def verify_binding(self, verifier, node_id: bytes, sig: bytes,
                       role_initiator: bool) -> bool:
        from ..core.signing import Domain

        return verifier.verify(
            Domain.TRANSPORT, node_id,
            self.binding + (b"i" if role_initiator else b"r"), sig)


# --- pure-Python fallbacks (no `cryptography` in the container) -----------

if not _HAVE_CRYPTOGRAPHY:
    import hashlib
    import hmac as _hmac
    import os as _os

    _P25519 = 2**255 - 19
    _A24 = 121665

    def _x25519(k_bytes: bytes, u_bytes: bytes) -> bytes:
        """RFC 7748 X25519 (Montgomery ladder, section 5)."""
        k = int.from_bytes(k_bytes, "little")
        k &= (1 << 254) - 8
        k |= 1 << 254
        x1 = int.from_bytes(u_bytes, "little") & ((1 << 255) - 1)
        p = _P25519
        x2, z2, x3, z3 = 1, 0, x1, 1
        swap = 0
        for t in range(254, -1, -1):
            kt = (k >> t) & 1
            if swap ^ kt:
                x2, x3 = x3, x2
                z2, z3 = z3, z2
            swap = kt
            a = (x2 + z2) % p
            aa = a * a % p
            b = (x2 - z2) % p
            bb = b * b % p
            e = (aa - bb) % p
            c = (x3 + z3) % p
            d = (x3 - z3) % p
            da = d * a % p
            cb = c * b % p
            x3 = (da + cb) % p
            x3 = x3 * x3 % p
            z3 = x1 * pow(da - cb, 2, p) % p
            x2 = aa * bb % p
            z2 = e * ((aa + _A24 * e) % p) % p
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        return (x2 * pow(z2, p - 2, p) % p).to_bytes(32, "little")

    class X25519PublicKey:  # noqa: F811 — fallback twin
        def __init__(self, raw: bytes):
            self._raw = raw

        @classmethod
        def from_public_bytes(cls, raw: bytes) -> "X25519PublicKey":
            if len(raw) != 32:
                raise ValueError("x25519 public keys are 32 bytes")
            return cls(raw)

        def public_bytes_raw(self) -> bytes:
            return self._raw

    class X25519PrivateKey:  # noqa: F811 — fallback twin
        def __init__(self, raw: bytes):
            self._raw = raw

        @classmethod
        def generate(cls) -> "X25519PrivateKey":
            return cls(_os.urandom(32))

        def public_key(self) -> "X25519PublicKey":
            return X25519PublicKey(
                _x25519(self._raw, (9).to_bytes(32, "little")))

        def exchange(self, peer: "X25519PublicKey") -> bytes:
            out = _x25519(self._raw, peer._raw)
            if out == bytes(32):  # low-order point: contributory check
                raise ValueError("x25519 shared secret is all zeros")
            return out

    class hashes:  # noqa: F811, N801 — just enough HKDF surface
        class SHA256:
            pass

    class HKDF:  # noqa: F811 — RFC 5869 with SHA-256
        def __init__(self, *, algorithm, length: int, salt: bytes,
                     info: bytes):
            self._length = length
            self._salt = salt or bytes(32)
            self._info = info

        def derive(self, ikm: bytes) -> bytes:
            prk = _hmac.new(self._salt, ikm, hashlib.sha256).digest()
            okm = b""
            t = b""
            i = 1
            while len(okm) < self._length:
                t = _hmac.new(prk, t + self._info + bytes([i]),
                              hashlib.sha256).digest()
                okm += t
                i += 1
            return okm[:self._length]

    class ChaCha20Poly1305:  # noqa: F811 — SHA256-CTR + HMAC substitute
        """Encrypt-then-MAC AEAD from hashlib/hmac (NOT ChaCha20: see
        the module-import note on wire compatibility). Keystream blocks
        are SHA256(key || nonce || counter); the 16-byte tag is
        HMAC-SHA256(mac_key, nonce || len(aad) || aad || ct) truncated
        — the aad length prefix frames the MAC input (mirroring
        Poly1305's aad/ct length block), so distinct (aad, ct) splits
        of one byte string never authenticate identically."""

        TAG = 16

        def __init__(self, key: bytes):
            self._enc = key
            self._mac = hashlib.sha256(b"smh/fallback-mac" + key).digest()

        def _tag(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
            aad = aad or b""
            return _hmac.new(
                self._mac,
                nonce + len(aad).to_bytes(8, "little") + aad + ct,
                hashlib.sha256).digest()[:self.TAG]

        def _stream(self, nonce: bytes, n: int) -> bytes:
            out = bytearray()
            ctr = 0
            while len(out) < n:
                out += hashlib.sha256(
                    self._enc + nonce + ctr.to_bytes(8, "little")).digest()
                ctr += 1
            return bytes(out[:n])

        def _xor(self, nonce: bytes, data: bytes) -> bytes:
            n = len(data)
            ks = int.from_bytes(self._stream(nonce, n), "little")
            return (int.from_bytes(data, "little") ^ ks).to_bytes(
                n, "little")

        def encrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
            ct = self._xor(nonce, data)
            return ct + self._tag(nonce, aad, ct)

        def decrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
            if len(data) < self.TAG:
                raise ValueError("ciphertext too short")
            ct, tag = data[:-self.TAG], data[-self.TAG:]
            if not _hmac.compare_digest(tag, self._tag(nonce, aad, ct)):
                raise ValueError("InvalidTag")
            return self._xor(nonce, ct)
