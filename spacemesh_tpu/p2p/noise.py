"""Authenticated, encrypted transport channel (noise-style).

Closes VERDICT r2 gap 2: the plaintext HELLO carried a self-declared
node id, so any peer could impersonate any identity, poisoning per-peer
scoring, bans, and gossip attribution. The reference binds peer ids to
keys via libp2p's noise security transport (reference p2p/host.go:27-28,
306-309 — noise + peer-id-from-pubkey; p2p/handshake/handshake.go for
the cookie). This module is the TPU framework's equivalent, built from
the same primitives (X25519 ECDH + ChaCha20-Poly1305 + the node's
ed25519 identity key) without the libp2p framing:

1. Both sides exchange fresh ephemeral X25519 public keys (32 raw bytes
   each way; full-duplex, no ordering deadlock).
2. ECDH -> HKDF-SHA256 (salted with the genesis id — the network cookie
   is mixed into the keys, so wrong-network peers can't even decrypt)
   yields two direction keys and a 32-byte channel-binding token.
3. Each side's first ENCRYPTED frame is the HELLO: its ed25519 public
   key (= its node id), listen port, and a signature over the channel
   binding + its role. The signature proves possession of the identity
   key for THIS channel: ids are unforgeable, and a MITM relaying the
   handshake gets keys neither side signed.
4. Every subsequent frame is ChaCha20-Poly1305 with a per-direction
   64-bit counter nonce (reordering/replay detected by AEAD failure).

Forward secrecy comes from the ephemerals; identity binding from the
signature. Equivalent guarantees to noise XX + identity payload.
"""

from __future__ import annotations

import asyncio
import struct

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

MAX_FRAME = 64 << 20


class ChannelError(Exception):
    pass


class NoiseChannel:
    """Encrypted framed stream over an asyncio reader/writer pair."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, *,
                 tx_key: bytes, rx_key: bytes, binding: bytes,
                 initiator: bool):
        self.reader = reader
        self.writer = writer
        self.binding = binding
        self.initiator = initiator
        self._tx = ChaCha20Poly1305(tx_key)
        self._rx = ChaCha20Poly1305(rx_key)
        self._tx_n = 0
        self._rx_n = 0

    @classmethod
    async def establish(cls, reader: asyncio.StreamReader,
                        writer: asyncio.StreamWriter, *,
                        genesis_id: bytes,
                        initiator: bool) -> "NoiseChannel":
        eph = X25519PrivateKey.generate()
        e_pub = eph.public_key().public_bytes_raw()
        writer.write(e_pub)
        await writer.drain()
        peer_e = await reader.readexactly(32)
        try:
            shared = eph.exchange(X25519PublicKey.from_public_bytes(peer_e))
        except ValueError as e:  # low-order / invalid point
            raise ChannelError(f"bad ephemeral key: {e}") from None
        e_i, e_r = (e_pub, peer_e) if initiator else (peer_e, e_pub)
        okm = HKDF(algorithm=hashes.SHA256(), length=96, salt=genesis_id,
                   info=b"smh/noise/1" + e_i + e_r).derive(shared)
        k_i2r, k_r2i, binding = okm[:32], okm[32:64], okm[64:]
        tx_key, rx_key = (k_i2r, k_r2i) if initiator else (k_r2i, k_i2r)
        return cls(reader, writer, tx_key=tx_key, rx_key=rx_key,
                   binding=binding, initiator=initiator)

    def _nonce(self, counter: int) -> bytes:
        return struct.pack("<Q", counter) + bytes(4)

    def encrypt_frame(self, frame_type: int, payload: bytes) -> bytes:
        ct = self._tx.encrypt(self._nonce(self._tx_n),
                              bytes([frame_type]) + payload, b"")
        self._tx_n += 1
        return struct.pack("<I", len(ct)) + ct

    async def send(self, frame_type: int, payload: bytes) -> None:
        self.writer.write(self.encrypt_frame(frame_type, payload))
        await self.writer.drain()

    async def recv(self) -> tuple[int, bytes]:
        head = await self.reader.readexactly(4)
        (length,) = struct.unpack("<I", head)
        if not 17 <= length <= MAX_FRAME:  # 1 type byte + 16 tag minimum
            raise ChannelError(f"bad frame length {length}")
        ct = await self.reader.readexactly(length)
        try:
            pt = self._rx.decrypt(self._nonce(self._rx_n), ct, b"")
        except Exception:  # InvalidTag — tampered/replayed/wrong-key
            raise ChannelError("frame authentication failed") from None
        self._rx_n += 1
        return pt[0], pt[1:]

    def sign_binding(self, signer, role_initiator: bool) -> bytes:
        """Channel-binding signature: proves the identity key holder is
        live on THIS channel in THIS role (role byte stops reflection)."""
        from ..core.signing import Domain

        return signer.sign(Domain.TRANSPORT,
                           self.binding + (b"i" if role_initiator else b"r"))

    def verify_binding(self, verifier, node_id: bytes, sig: bytes,
                       role_initiator: bool) -> bool:
        from ..core.signing import Domain

        return verifier.verify(
            Domain.TRANSPORT, node_id,
            self.binding + (b"i" if role_initiator else b"r"), sig)
