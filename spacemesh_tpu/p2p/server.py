"""Request/response protocols between peers.

Mirrors the reference's p2p/server (libp2p streams with varint-framed
SCALE messages, per-protocol handlers, rate limits; used by fetch, hare4
compaction, peersync). The transport here is pluggable: the in-proc
`LoopbackNet` connects Server endpoints directly (the mocknet equivalent,
reference p2p/pubsub tests + node/test_network.go), and the QUIC transport
can slot in underneath with the same Server surface.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

Handler = Callable[[bytes, bytes], Awaitable[bytes]]  # (peer, req) -> resp


class RequestError(Exception):
    pass


class Server:
    """One node's protocol endpoint."""

    def __init__(self, node_id: bytes):
        self.node_id = node_id
        self._protocols: dict[str, Handler] = {}
        self._net: "LoopbackNet | None" = None

    def register(self, protocol: str, handler: Handler) -> None:
        self._protocols[protocol] = handler

    async def handle(self, protocol: str, peer: bytes, data: bytes) -> bytes:
        h = self._protocols.get(protocol)
        if h is None:
            raise RequestError(f"unknown protocol {protocol}")
        return await h(peer, data)

    async def request(self, peer: bytes, protocol: str, data: bytes,
                      timeout: float = 10.0) -> bytes:
        if self._net is None:
            raise RequestError("not connected")
        return await asyncio.wait_for(
            self._net.route(self.node_id, peer, protocol, data), timeout)

    def peers(self) -> list[bytes]:
        if self._net is None:
            return []
        return [n for n in self._net.nodes if n != self.node_id]


class LoopbackNet:
    """Fully-connected in-proc transport for Servers."""

    def __init__(self) -> None:
        self.nodes: dict[bytes, Server] = {}

    def join(self, server: Server) -> None:
        server._net = self
        self.nodes[server.node_id] = server

    def leave(self, server: Server) -> None:
        server._net = None
        self.nodes.pop(server.node_id, None)

    async def route(self, src: bytes, dst: bytes, protocol: str,
                    data: bytes) -> bytes:
        target = self.nodes.get(dst)
        if target is None:
            raise RequestError(f"peer {dst.hex()[:8]} not reachable")
        return await target.handle(protocol, src, data)
