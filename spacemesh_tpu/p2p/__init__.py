"""Networking: pubsub abstraction, loopback hub, QUIC-style host (M3),
fetch, and sync. The consensus layers speak only the PublishSubscriber
interface (reference p2p/pubsub/pubsub.go:137), so in-proc loopback,
multi-node test hubs, and the real network are interchangeable."""

from .pubsub import LoopbackHub, PubSub  # noqa: F401
