"""Syncer: the catch-up state machine.

Mirrors the reference syncer (reference syncer/syncer.go:60-80 states
notSynced -> gossipSync -> synced; :474 per-epoch ATX sync via
atxsync.Download; :372 per-layer data sync; state_syncer.go:34
processLayers applies certificates/tortoise opinions). A late-joining node
pulls: poet proofs + epoch ATXs for every epoch up to now, then per-layer
ballots/blocks/certificates, feeding everything through the SAME gossip
validators the live path uses — sync and gossip share ingestion, as in the
reference.
"""

from __future__ import annotations

import asyncio
import enum
import logging
from typing import Awaitable, Callable

_log = logging.getLogger("sync")

from .fetch import (
    Fetch,
    HINT_ATX,
    HINT_BALLOT,
    HINT_BLOCK,
    HINT_POET,
    HINT_TX,
    LayerData,
    P_LAYER,
)


class SyncState(enum.Enum):
    NOT_SYNCED = "notSynced"
    GOSSIP = "gossipSync"
    SYNCED = "synced"


class Syncer:
    def __init__(self, *, fetch: Fetch, current_layer: Callable[[], int],
                 processed_layer: Callable[[], int],
                 process_layer: Callable[[int, "LayerData | None"],
                                         Awaitable[None]],
                 layers_per_epoch: int,
                 store_beacon: Callable[[int, bytes], None] | None = None,
                 layer_hash: Callable[[int], bytes | None] | None = None,
                 on_fork: Callable[[int], None] | None = None,
                 derive_beacon=None, rangesync_sets=None):
        self.store_beacon = store_beacon
        # derive_beacon(epoch, ballot_ids): adopt the epoch beacon from
        # synced ballots' signed EpochData (weight-majority) when peer
        # answers alone can't settle it
        self.derive_beacon = derive_beacon
        # rangesync_sets(name) -> rangesync.OrderedSet | None resolves
        # the LOCAL set for fingerprint reconciliation ("atx/<epoch>",
        # "malfeasance"); None disables the rangesync backfill pass
        self.rangesync_sets = rangesync_sets
        self.fetch = fetch
        self.current_layer = current_layer
        self.processed_layer = processed_layer
        self.process_layer = process_layer
        self.layers_per_epoch = layers_per_epoch
        self.layer_hash = layer_hash      # local aggregated mesh hash
        self.on_fork = on_fork
        self.state = SyncState.NOT_SYNCED
        self._stop = False
        # one pass at a time: the background run() loop and an external
        # driver (the sim scenario engine's convergence wait, a test's
        # heal loop) may both call synchronize(); interleaved passes
        # would double-process layers mid-flight
        self._busy = asyncio.Lock()

    def is_synced(self) -> bool:
        return self.state == SyncState.SYNCED

    async def synchronize(self) -> bool:
        """One sync pass; returns True when caught up to the tip."""
        async with self._busy:
            return await self._synchronize()

    async def _synchronize(self) -> bool:
        tip = self.current_layer()
        cur_epoch = tip // self.layers_per_epoch
        # 1) per epoch: beacon, poet proofs, then ATXs (validation order)
        for epoch in range(0, cur_epoch + 2):
            await self._sync_beacon(epoch)
            refs = await self._peer_poet_refs(epoch)
            if refs:
                await self.fetch.get_hashes(HINT_POET, refs)
            await self.fetch.get_epoch_atxs(epoch)
            # fingerprint reconciliation mops up whatever the bulk pull
            # missed (a peer's epoch index answered before a late ATX
            # landed): one rs/1 roundtrip per peer when the sets already
            # match, O(diff * log n) otherwise. Fetched blobs ingest
            # through the same validators, i.e. the verification farm's
            # SYNC lane.
            await self._rangesync_backfill(f"atx/{epoch}", HINT_ATX)
        # 1b) malfeasance proofs (reference syncer/malsync): a node must
        # learn who is malicious before counting their weight
        await self._sync_malfeasance()
        # 2) per-layer data up to the tip
        start = self.processed_layer() + 1
        deferred = False
        for layer in range(start, tip + 1):
            if self._stop:
                return False
            data = await self.fetch.get_layer_data(layer)
            # recent layers may still be under hare on the peers: without a
            # certificate, defer them to the next pass instead of wrongly
            # settling on "empty" (the reference's layerpatrol keeps
            # hare-owned layers away from the syncer, layerpatrol/patrol.go)
            recent = layer > tip - 2
            has_cert = data is not None and (
                data.certified != bytes(32)
                or getattr(data, "cert_candidates", []))
            if recent and not has_cert:
                deferred = True
                break
            if data is not None:
                # beacon first: ballot eligibility and certificate shares
                # verify against the epoch beacon — when peer answers
                # couldn't settle it (tie from a lying peer), derive it
                # from the ballots' own signed, ATX-weighted EpochData
                if self.derive_beacon is not None and data.ballots:
                    await self.derive_beacon(
                        layer // self.layers_per_epoch, data.ballots)
                # blocks BEFORE ballots: tortoise.on_ballot must be able to
                # resolve every support vote against a known block, else the
                # votes count as AGAINST and a fresh node invalidates layers
                # the network holds valid
                await self.fetch.get_hashes(HINT_BLOCK, data.blocks)
                await self.fetch.get_hashes(HINT_BALLOT, data.ballots)
            await self.process_layer(layer, data)
        behind = self.current_layer() - self.processed_layer()
        # a recent-layer deferral means we are as caught up as the
        # network allows (peers have no certificate yet either): still
        # SYNCED, or in a quiescent net the node would sit at behind==2
        # forever in gossipSync and the fork check below would never run
        if behind <= 1 or (deferred and behind <= 3):
            self.state = SyncState.SYNCED
        elif behind <= 2:
            self.state = SyncState.GOSSIP
        else:
            self.state = SyncState.NOT_SYNCED
        # 3) fork detection once caught up: our aggregated mesh hash at
        # the frontier must match the network's
        if self.state == SyncState.SYNCED and await self._check_fork():
            self.state = SyncState.NOT_SYNCED
            return False
        return self.state == SyncState.SYNCED

    async def _rangesync_backfill(self, name: str, hint: str,
                                  peers: int = 2) -> None:
        """Reconcile one named id set (p2p/rangesync.py) against a few
        peers and fetch what they have that we lack. Failures are
        tolerated — the bulk pull remains the primary mechanism and the
        next pass retries."""
        if self.rangesync_sets is None:
            return
        try:
            local = self.rangesync_sets(name)
        except Exception:  # noqa: BLE001 — a bad epoch name must not kill sync
            return
        if local is None:
            return
        from .rangesync import RangeSyncClient

        missing: set[bytes] = set()
        for peer in self.fetch.peers()[:peers]:
            try:
                client = RangeSyncClient(self.fetch.server, peer, name)
                missing.update(await client.reconcile(local))
            except Exception:  # noqa: BLE001 — peer gone / no rs/1 support
                continue
        if missing:
            await self.fetch.get_hashes(hint, sorted(missing))

    async def _sync_malfeasance(self) -> None:
        from .fetch import HINT_MALFEASANCE
        from .server import RequestError

        ids: set[bytes] = set()
        for peer in self.fetch.peers()[:3]:
            try:
                resp = await self.fetch.server.request(peer, "ml/1", b"")
            except (RequestError, asyncio.TimeoutError):
                continue
            for k in range(0, len(resp), 32):
                nid = resp[k:k + 32]
                if len(nid) == 32:  # ragged tail from a bad peer
                    ids.add(nid)
        if ids:
            await self.fetch.get_hashes(HINT_MALFEASANCE, sorted(ids))

    async def _check_fork(self) -> bool:
        """Compare aggregated layer hashes with peers at the frontier;
        on mismatch bisect to the FIRST divergent layer, FETCH the
        dissenting chain's blocks/ballots, and hand the layer to
        on_fork for arbitration (reference syncer/find_fork.go). Fork
        CHOICE is not made here: the tortoise's vote weight decides —
        which also kills the rollback-DoS vector (ADVICE r2): a lying
        peer can waste fetch bandwidth but cannot move applied state
        without real ballot weight behind its chain."""
        import struct

        from .server import RequestError

        if self.layer_hash is None or self.on_fork is None:
            return False
        frontier = self.processed_layer() - 1
        if frontier < 1:
            return False
        local = self.layer_hash(frontier)
        if local is None:
            return False

        async def peer_hash(peer, layer) -> bytes | None:
            try:
                resp = await self.fetch.server.request(
                    peer, "lh/1", struct.pack("<I", layer))
            except (RequestError, asyncio.TimeoutError):
                return None
            return resp if len(resp) == 32 else None

        async def peer_tip(peer) -> int | None:
            try:
                resp = await self.fetch.server.request(
                    peer, "lh/1", struct.pack("<I", 0xFFFFFFFF))
            except (RequestError, asyncio.TimeoutError):
                return None
            if len(resp) != 36:
                return None
            return struct.unpack_from("<I", resp)[0]

        # anchor at the COMMON frontier: our tip may be ahead of a peer's
        # (e.g. we applied empty layers while it idled) — comparing where
        # the peer has no hash would blind the fork finder entirely
        peers = self.fetch.peers()[:3]
        tips = [t for t in [await peer_tip(p) for p in peers]
                if t is not None]
        if tips:
            frontier = min(frontier, max(tips))
        if frontier < 1:
            return False
        local = self.layer_hash(frontier)
        if local is None:
            return False

        frontier_hashes = [(p, await peer_hash(p, frontier)) for p in peers]
        answered = [(p, h) for p, h in frontier_hashes if h is not None]
        if not answered:
            return False
        disagree = [(p, h) for p, h in answered if h != local]
        acted = False
        for peer, h in disagree:
            # stability re-confirm: a transient lie or a peer racing its
            # own apply must not trigger the (bounded) refetch work
            if await peer_hash(peer, frontier) != h:
                continue
            # bisect [1, frontier] for the first layer where we diverge;
            # a peer that stops answering mid-bisect yields NO divergence
            # point — never act on a guess
            lo, hi = 1, frontier
            aborted = False
            while lo < hi:
                mid = (lo + hi) // 2
                rm = await peer_hash(peer, mid)
                lm = self.layer_hash(mid)
                if rm is None or lm is None:
                    aborted = True
                    break
                if rm == lm:
                    lo = mid + 1
                else:
                    hi = mid
            if aborted:
                continue
            # ingest the dissenting chain's data over the divergent span
            # (bounded per pass) so the tortoise can weigh it: the
            # dissenter's own layer opinion first, then the union view
            _log.info("fork: divergence at layer %d (frontier %d), "
                      "ingesting dissenting span", lo, frontier)
            await self._ingest_span(peer, lo, frontier)
            self.on_fork(lo)
            acted = True
        return acted

    async def _ingest_span(self, peer, lo: int, hi: int,
                           span_cap: int = 32) -> None:
        """Fetch blocks + ballots for layers [lo, hi] — the dissenting
        peer's view plus the usual cross-peer union — through the same
        validated ingestion path sync uses. Failures are tolerated: the
        next pass retries."""
        import struct

        from .server import RequestError

        for layer in range(lo, min(hi, lo + span_cap) + 1):
            datas = []
            try:
                resp = await self.fetch.server.request(
                    peer, P_LAYER, struct.pack("<I", layer))
                datas.append(LayerData.from_bytes(resp))
            except Exception:  # noqa: BLE001 — dissenter may be gone
                pass
            union = await self.fetch.get_layer_data(layer)
            if union is not None:
                datas.append(union)
            blocks: list[bytes] = []
            ballots: list[bytes] = []
            for d in datas:
                blocks += [b for b in d.blocks if b not in blocks]
                ballots += [b for b in d.ballots if b not in ballots]
            if blocks:
                await self.fetch.get_hashes(HINT_BLOCK, blocks)
            if ballots:
                await self.fetch.get_hashes(HINT_BALLOT, ballots)
            # the divergent span's CERTIFICATES too: a layer this node
            # applied differently (e.g. empty, on a skewed clock) heals
            # through validated cert adoption, not just ballot weight —
            # the same processor the normal sync path uses
            for d in datas:
                if d.certified != bytes(32) \
                        or getattr(d, "cert_candidates", []):
                    try:
                        await self.process_layer(layer, d)
                        break  # adopted from this view
                    except Exception:  # noqa: BLE001 — try the next view
                        continue

    async def _sync_beacon(self, epoch: int) -> None:
        """Adopt peers' beacon for the epoch (late joiners never ran the
        beacon protocol; gossip validation needs the value)."""
        import struct

        from .server import RequestError

        if self.store_beacon is None:
            return
        # quorum: adopt only a value reported by a strict majority of the
        # peers that answered — one lying peer must not poison the beacon
        # (ADVICE r1; reference accepts fallback beacons only from a
        # verified bootstrap source)
        async def ask(peer):
            try:
                return await self.fetch.server.request(
                    peer, "bk/1", struct.pack("<I", epoch))
            except (RequestError, asyncio.TimeoutError):
                return None

        responses = await asyncio.gather(
            *(ask(p) for p in self.fetch.peers()))
        votes: dict[bytes, int] = {}
        answered = 0
        for resp in responses:
            if resp is not None and len(resp) == 4:
                answered += 1
                votes[resp] = votes.get(resp, 0) + 1
        if not votes:
            return
        best, count = max(votes.items(), key=lambda kv: kv[1])
        if count * 2 > answered:
            self.store_beacon(epoch, best)

    async def _peer_poet_refs(self, epoch: int) -> list[bytes]:
        """Poet proof refs peers hold for the epoch's round."""
        import struct

        from .server import RequestError

        refs: list[bytes] = []
        for peer in self.fetch.peers():
            try:
                resp = await self.fetch.server.request(
                    peer, "pt/1", struct.pack("<I", epoch))
            except (RequestError, asyncio.TimeoutError):
                continue
            for k in range(0, len(resp), 32):
                r = resp[k:k + 32]
                if r not in refs:
                    refs.append(r)
        return refs

    async def run(self, interval: float = 1.0) -> None:
        """Background loop (reference syncer.Start)."""
        while not self._stop:
            try:
                await self.synchronize()
            except Exception:  # noqa: BLE001 — sync must survive bad peers
                self.state = SyncState.NOT_SYNCED
            await asyncio.sleep(interval)

    def stop(self) -> None:
        self._stop = True
