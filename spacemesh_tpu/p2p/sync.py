"""Syncer: the catch-up state machine.

Mirrors the reference syncer (reference syncer/syncer.go:60-80 states
notSynced -> gossipSync -> synced; :474 per-epoch ATX sync via
atxsync.Download; :372 per-layer data sync; state_syncer.go:34
processLayers applies certificates/tortoise opinions). A late-joining node
pulls: poet proofs + epoch ATXs for every epoch up to now, then per-layer
ballots/blocks/certificates, feeding everything through the SAME gossip
validators the live path uses — sync and gossip share ingestion, as in the
reference.
"""

from __future__ import annotations

import asyncio
import enum
from typing import Awaitable, Callable

from .fetch import (
    Fetch,
    HINT_ATX,
    HINT_BALLOT,
    HINT_BLOCK,
    HINT_POET,
    HINT_TX,
    LayerData,
)


class SyncState(enum.Enum):
    NOT_SYNCED = "notSynced"
    GOSSIP = "gossipSync"
    SYNCED = "synced"


class Syncer:
    def __init__(self, *, fetch: Fetch, current_layer: Callable[[], int],
                 processed_layer: Callable[[], int],
                 process_layer: Callable[[int, "LayerData | None"],
                                         Awaitable[None]],
                 layers_per_epoch: int,
                 store_beacon: Callable[[int, bytes], None] | None = None):
        self.store_beacon = store_beacon
        self.fetch = fetch
        self.current_layer = current_layer
        self.processed_layer = processed_layer
        self.process_layer = process_layer
        self.layers_per_epoch = layers_per_epoch
        self.state = SyncState.NOT_SYNCED
        self._stop = False

    def is_synced(self) -> bool:
        return self.state == SyncState.SYNCED

    async def synchronize(self) -> bool:
        """One sync pass; returns True when caught up to the tip."""
        tip = self.current_layer()
        cur_epoch = tip // self.layers_per_epoch
        # 1) per epoch: beacon, poet proofs, then ATXs (validation order)
        for epoch in range(0, cur_epoch + 2):
            await self._sync_beacon(epoch)
            refs = await self._peer_poet_refs(epoch)
            if refs:
                await self.fetch.get_hashes(HINT_POET, refs)
            await self.fetch.get_epoch_atxs(epoch)
        # 2) per-layer data up to the tip
        start = self.processed_layer() + 1
        for layer in range(start, tip + 1):
            if self._stop:
                return False
            data = await self.fetch.get_layer_data(layer)
            # recent layers may still be under hare on the peers: without a
            # certificate, defer them to the next pass instead of wrongly
            # settling on "empty" (the reference's layerpatrol keeps
            # hare-owned layers away from the syncer, layerpatrol/patrol.go)
            recent = layer > tip - 2
            if recent and (data is None or data.certified == bytes(32)):
                break
            if data is not None:
                # blocks BEFORE ballots: tortoise.on_ballot must be able to
                # resolve every support vote against a known block, else the
                # votes count as AGAINST and a fresh node invalidates layers
                # the network holds valid
                await self.fetch.get_hashes(HINT_BLOCK, data.blocks)
                await self.fetch.get_hashes(HINT_BALLOT, data.ballots)
            await self.process_layer(layer, data)
        behind = self.current_layer() - self.processed_layer()
        if behind <= 1:
            self.state = SyncState.SYNCED
        elif behind <= 2:
            self.state = SyncState.GOSSIP
        else:
            self.state = SyncState.NOT_SYNCED
        return self.state == SyncState.SYNCED

    async def _sync_beacon(self, epoch: int) -> None:
        """Adopt peers' beacon for the epoch (late joiners never ran the
        beacon protocol; gossip validation needs the value)."""
        import struct

        from .server import RequestError

        if self.store_beacon is None:
            return
        # quorum: adopt only a value reported by a strict majority of the
        # peers that answered — one lying peer must not poison the beacon
        # (ADVICE r1; reference accepts fallback beacons only from a
        # verified bootstrap source)
        async def ask(peer):
            try:
                return await self.fetch.server.request(
                    peer, "bk/1", struct.pack("<I", epoch))
            except (RequestError, asyncio.TimeoutError):
                return None

        responses = await asyncio.gather(
            *(ask(p) for p in self.fetch.server.peers()))
        votes: dict[bytes, int] = {}
        answered = 0
        for resp in responses:
            if resp is not None and len(resp) == 4:
                answered += 1
                votes[resp] = votes.get(resp, 0) + 1
        if not votes:
            return
        best, count = max(votes.items(), key=lambda kv: kv[1])
        if count * 2 > answered:
            self.store_beacon(epoch, best)

    async def _peer_poet_refs(self, epoch: int) -> list[bytes]:
        """Poet proof refs peers hold for the epoch's round."""
        import struct

        from .server import RequestError

        refs: list[bytes] = []
        for peer in self.fetch.server.peers():
            try:
                resp = await self.fetch.server.request(
                    peer, "pt/1", struct.pack("<I", epoch))
            except (RequestError, asyncio.TimeoutError):
                continue
            for k in range(0, len(resp), 32):
                r = resp[k:k + 32]
                if r not in refs:
                    refs.append(r)
        return refs

    async def run(self, interval: float = 1.0) -> None:
        """Background loop (reference syncer.Start)."""
        while not self._stop:
            try:
                await self.synchronize()
            except Exception:  # noqa: BLE001 — sync must survive bad peers
                self.state = SyncState.NOT_SYNCED
            await asyncio.sleep(interval)

    def stop(self) -> None:
        self._stop = True
