"""Native runtime components (C++), loaded via ctypes.

The compute path is JAX/XLA/Pallas; the node RUNTIME's hot host-side
ops live here (the reference's equivalents are Rust/C crates).  Builds
are on-demand and cached next to the source; every native component has
a pure-Python twin as fallback and test oracle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

_DIR = Path(__file__).resolve().parent
_LOCK = threading.Lock()
_LIBS: dict[str, ctypes.CDLL | None] = {}


def _build(name: str) -> Path | None:
    src = _DIR / f"{name}.cpp"
    lib = _DIR / f"libsmtpu_{name}.so"
    if lib.exists() and lib.stat().st_mtime >= src.stat().st_mtime:
        return lib
    tmp = lib.with_suffix(".so.tmp%d" % os.getpid())
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", str(tmp), str(src)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        # durable publish (utils/fsio): fsync + atomic rename + dir
        # fsync — a half-flushed .so dlopens as garbage after a crash
        from ..utils import fsio

        fsio.persist(tmp, lib)
        return lib
    except (subprocess.SubprocessError, OSError):
        tmp.unlink(missing_ok=True)
        return None


def load(name: str) -> ctypes.CDLL | None:
    """Compile (if stale) + dlopen libsmtpu_<name>.so; None on any
    failure — callers fall back to their Python twin."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        lib_path = _build(name)
        lib = None
        if lib_path is not None:
            try:
                lib = ctypes.CDLL(str(lib_path))
            except OSError:
                lib = None
        _LIBS[name] = lib
        return lib
