// ECVRF-EDWARDS25519-SHA512-TAI (RFC 9381, suite 0x03) — native twin of
// core/signing.py's pure-Python implementation (reference signing/vrf.go
// wraps curve25519-voi; this is the runtime-hot host op: every ballot
// eligibility, hare message, and beacon proposal validation runs one or
// more VRF verifies).  The Python twin is the TEST ORACLE: identical
// byte-level behavior is asserted by randomized differential tests
// (tests/test_native_ecvrf.py) and by the RFC 9381 vectors the Python
// implementation already passes.
//
// Self-contained: SHA-512 from spec (constant tables generated
// arithmetically from prime cube/square roots and pinned against
// hashlib), 5x51-limb field arithmetic over 2^255-19, extended-
// coordinate point ops mirroring the twin's formulas, and shift-
// subtract scalar reduction mod the group order (division-free,
// obviously-correct; scalar work is negligible next to scalar mults).
//
// Build: g++ -O3 -shared -fPIC -o libsmtpu_ecvrf.so ecvrf.cpp
// NOTE: scalar multiplication is VARIABLE-TIME.  Verification inputs
// are public, so that is fine; proving uses the long-term VRF secret —
// acceptable for this framework's threat model (the reference's CPU
// path is the same machine the miner fully controls), documented here
// so nobody mistakes it for a hardened signer.

#include <cstdint>
#include <cstring>
#include <cstddef>

// --------------------------------------------------------------------
// SHA-512 (tables generated + verified against hashlib; see repo notes)
// --------------------------------------------------------------------

static const uint64_t SHA512_K[80] = {
  0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
  0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
  0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
  0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
  0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
  0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
  0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
  0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
  0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
  0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
  0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
  0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
  0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
  0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
  0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
  0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
  0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
  0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
  0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
  0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};
static const uint64_t SHA512_H0[8] = {
  0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
  0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL, 0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static inline uint64_t ror64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

struct Sha512 {
    uint64_t h[8];
    uint8_t buf[128];
    uint64_t total;
    size_t fill;

    Sha512() { reset(); }
    void reset() {
        memcpy(h, SHA512_H0, sizeof h);
        total = 0;
        fill = 0;
    }
    void block(const uint8_t* p) {
        uint64_t w[80];
        for (int i = 0; i < 16; i++) {
            w[i] = ((uint64_t)p[i * 8] << 56) | ((uint64_t)p[i * 8 + 1] << 48)
                 | ((uint64_t)p[i * 8 + 2] << 40) | ((uint64_t)p[i * 8 + 3] << 32)
                 | ((uint64_t)p[i * 8 + 4] << 24) | ((uint64_t)p[i * 8 + 5] << 16)
                 | ((uint64_t)p[i * 8 + 6] << 8) | (uint64_t)p[i * 8 + 7];
        }
        for (int i = 16; i < 80; i++) {
            uint64_t s0 = ror64(w[i - 15], 1) ^ ror64(w[i - 15], 8) ^ (w[i - 15] >> 7);
            uint64_t s1 = ror64(w[i - 2], 19) ^ ror64(w[i - 2], 61) ^ (w[i - 2] >> 6);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint64_t a = h[0], b = h[1], c = h[2], d = h[3];
        uint64_t e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 80; i++) {
            uint64_t S1 = ror64(e, 14) ^ ror64(e, 18) ^ ror64(e, 41);
            uint64_t ch = (e & f) ^ (~e & g);
            uint64_t t1 = hh + S1 + ch + SHA512_K[i] + w[i];
            uint64_t S0 = ror64(a, 28) ^ ror64(a, 34) ^ ror64(a, 39);
            uint64_t mj = (a & b) ^ (a & c) ^ (b & c);
            uint64_t t2 = S0 + mj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }
    void update(const uint8_t* p, size_t n) {
        total += n;
        while (n) {
            size_t take = 128 - fill;
            if (take > n) take = n;
            memcpy(buf + fill, p, take);
            fill += take; p += take; n -= take;
            if (fill == 128) { block(buf); fill = 0; }
        }
    }
    void final(uint8_t out[64]) {
        uint64_t bits = total * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (fill != 112) update(&z, 1);
        uint8_t len[16] = {0};
        for (int i = 0; i < 8; i++) len[15 - i] = (uint8_t)(bits >> (8 * i));
        update(len, 16);
        for (int i = 0; i < 8; i++)
            for (int j = 0; j < 8; j++)
                out[i * 8 + j] = (uint8_t)(h[i] >> (56 - 8 * j));
    }
};

// --------------------------------------------------------------------
// fe25519: GF(2^255-19), five 51-bit limbs
// --------------------------------------------------------------------

typedef struct { uint64_t v[5]; } fe;

static const uint64_t MASK51 = (1ULL << 51) - 1;

static void fe_frombytes(fe* r, const uint8_t s[32]) {
    uint64_t w[4];
    for (int i = 0; i < 4; i++) {
        w[i] = 0;
        for (int j = 0; j < 8; j++)
            w[i] |= (uint64_t)s[i * 8 + j] << (8 * j);
    }
    r->v[0] = w[0] & MASK51;
    r->v[1] = ((w[0] >> 51) | (w[1] << 13)) & MASK51;
    r->v[2] = ((w[1] >> 38) | (w[2] << 26)) & MASK51;
    r->v[3] = ((w[2] >> 25) | (w[3] << 39)) & MASK51;
    r->v[4] = (w[3] >> 12) & MASK51;  // drops bit 255 (the sign bit)
}

static void fe_carry(fe* r) {
    for (int pass = 0; pass < 2; pass++) {
        uint64_t c;
        for (int i = 0; i < 4; i++) {
            c = r->v[i] >> 51; r->v[i] &= MASK51; r->v[i + 1] += c;
        }
        c = r->v[4] >> 51; r->v[4] &= MASK51; r->v[0] += 19 * c;
    }
}

static void fe_tobytes(uint8_t s[32], const fe* a) {
    fe t = *a;
    fe_carry(&t);
    // full canonical reduction: add 19, see if it wraps past 2^255
    uint64_t q = (t.v[0] + 19) >> 51;
    q = (t.v[1] + q) >> 51;
    q = (t.v[2] + q) >> 51;
    q = (t.v[3] + q) >> 51;
    q = (t.v[4] + q) >> 51;
    t.v[0] += 19 * q;
    uint64_t c;
    for (int i = 0; i < 4; i++) {
        c = t.v[0 + i] >> 51; t.v[i] &= MASK51; t.v[i + 1] += c;
    }
    t.v[4] &= MASK51;
    uint64_t w0 = t.v[0] | (t.v[1] << 51);
    uint64_t w1 = (t.v[1] >> 13) | (t.v[2] << 38);
    uint64_t w2 = (t.v[2] >> 26) | (t.v[3] << 25);
    uint64_t w3 = (t.v[3] >> 39) | (t.v[4] << 12);
    uint64_t w[4] = {w0, w1, w2, w3};
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            s[i * 8 + j] = (uint8_t)(w[i] >> (8 * j));
}

static void fe_0(fe* r) { memset(r, 0, sizeof *r); }
static void fe_1(fe* r) { fe_0(r); r->v[0] = 1; }

static void fe_add(fe* r, const fe* a, const fe* b) {
    for (int i = 0; i < 5; i++) r->v[i] = a->v[i] + b->v[i];
    fe_carry(r);
}

static void fe_sub(fe* r, const fe* a, const fe* b) {
    // a + 2p - b keeps limbs positive
    static const uint64_t TWOP[5] = {
        2 * ((1ULL << 51) - 19), 2 * MASK51, 2 * MASK51, 2 * MASK51,
        2 * MASK51};
    for (int i = 0; i < 5; i++) r->v[i] = a->v[i] + TWOP[i] - b->v[i];
    fe_carry(r);
}

static void fe_mul(fe* r, const fe* a, const fe* b) {
    typedef unsigned __int128 u128;
    const uint64_t a0 = a->v[0], a1 = a->v[1], a2 = a->v[2],
                   a3 = a->v[3], a4 = a->v[4];
    const uint64_t b0 = b->v[0], b1 = b->v[1], b2 = b->v[2],
                   b3 = b->v[3], b4 = b->v[4];
    const uint64_t b1_19 = 19 * b1, b2_19 = 19 * b2, b3_19 = 19 * b3,
                   b4_19 = 19 * b4;
    u128 t0 = (u128)a0 * b0 + (u128)a1 * b4_19 + (u128)a2 * b3_19
            + (u128)a3 * b2_19 + (u128)a4 * b1_19;
    u128 t1 = (u128)a0 * b1 + (u128)a1 * b0 + (u128)a2 * b4_19
            + (u128)a3 * b3_19 + (u128)a4 * b2_19;
    u128 t2 = (u128)a0 * b2 + (u128)a1 * b1 + (u128)a2 * b0
            + (u128)a3 * b4_19 + (u128)a4 * b3_19;
    u128 t3 = (u128)a0 * b3 + (u128)a1 * b2 + (u128)a2 * b1
            + (u128)a3 * b0 + (u128)a4 * b4_19;
    u128 t4 = (u128)a0 * b4 + (u128)a1 * b3 + (u128)a2 * b2
            + (u128)a3 * b1 + (u128)a4 * b0;
    uint64_t r0, r1, r2, r3, r4, c;
    r0 = (uint64_t)t0 & MASK51; t1 += (uint64_t)(t0 >> 51);
    r1 = (uint64_t)t1 & MASK51; t2 += (uint64_t)(t1 >> 51);
    r2 = (uint64_t)t2 & MASK51; t3 += (uint64_t)(t2 >> 51);
    r3 = (uint64_t)t3 & MASK51; t4 += (uint64_t)(t3 >> 51);
    r4 = (uint64_t)t4 & MASK51; c = (uint64_t)(t4 >> 51);
    r0 += 19 * c;
    c = r0 >> 51; r0 &= MASK51; r1 += c;
    c = r1 >> 51; r1 &= MASK51; r2 += c;
    r->v[0] = r0; r->v[1] = r1; r->v[2] = r2; r->v[3] = r3; r->v[4] = r4;
}

static void fe_sq(fe* r, const fe* a) { fe_mul(r, a, a); }

// MSB-first square-and-multiply; exponent little-endian 32 bytes
static void fe_pow(fe* r, const fe* base, const uint8_t exp_le[32]) {
    fe acc;
    fe_1(&acc);
    for (int byte = 31; byte >= 0; byte--) {
        for (int bit = 7; bit >= 0; bit--) {
            fe_sq(&acc, &acc);
            if ((exp_le[byte] >> bit) & 1) fe_mul(&acc, &acc, base);
        }
    }
    *r = acc;
}

static const uint8_t P_MINUS_2[32] = {235,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,127};
static const uint8_t P58[32] = {253,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,15};

static void fe_invert(fe* r, const fe* a) { fe_pow(r, a, P_MINUS_2); }
static void fe_pow58(fe* r, const fe* a) { fe_pow(r, a, P58); }

static int fe_eq(const fe* a, const fe* b) {
    uint8_t sa[32], sb[32];
    fe_tobytes(sa, a);
    fe_tobytes(sb, b);
    return memcmp(sa, sb, 32) == 0;
}

static int fe_iszero(const fe* a) {
    static const uint8_t Z[32] = {0};
    uint8_t s[32];
    fe_tobytes(s, a);
    return memcmp(s, Z, 32) == 0;
}

static void fe_neg(fe* r, const fe* a) {
    fe z;
    fe_0(&z);
    fe_sub(r, &z, a);
}

// --------------------------------------------------------------------
// curve constants
// --------------------------------------------------------------------

static const uint8_t D_BYTES[32] = {163,120,89,19,202,77,235,117,171,216,65,65,77,10,112,0,152,232,121,119,121,64,199,140,115,254,111,43,238,108,3,82};
static const uint8_t SQRTM1_BYTES[32] = {176,160,14,74,39,27,238,196,120,228,47,173,6,24,67,47,167,215,251,61,153,0,77,43,11,223,193,79,128,36,131,43};
static const uint8_t B_BYTES[32] = {88,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102,102};

// --------------------------------------------------------------------
// points: extended projective (X, Y, Z, T), XY = ZT — SAME formulas as
// the Python twin (core/signing.py _pt_add / _pt_mul / _pt_decode)
// --------------------------------------------------------------------

typedef struct { fe X, Y, Z, T; } ge;

static void ge_identity(ge* r) {
    fe_0(&r->X); fe_1(&r->Y); fe_1(&r->Z); fe_0(&r->T);
}

static void ge_add(ge* r, const ge* p, const ge* q) {
    fe d_const, a, b, c, dd, e, f, g, h, t;
    fe_frombytes(&d_const, D_BYTES);
    // a = (y1-x1)(y2-x2)
    fe t1, t2;
    fe_sub(&t1, &p->Y, &p->X);
    fe_sub(&t2, &q->Y, &q->X);
    fe_mul(&a, &t1, &t2);
    // b = (y1+x1)(y2+x2)
    fe_add(&t1, &p->Y, &p->X);
    fe_add(&t2, &q->Y, &q->X);
    fe_mul(&b, &t1, &t2);
    // c = 2*d*t1*t2
    fe_mul(&t, &p->T, &q->T);
    fe_mul(&c, &t, &d_const);
    fe_add(&c, &c, &c);
    // dd = 2*z1*z2
    fe_mul(&dd, &p->Z, &q->Z);
    fe_add(&dd, &dd, &dd);
    fe_sub(&e, &b, &a);
    fe_sub(&f, &dd, &c);
    fe_add(&g, &dd, &c);
    fe_add(&h, &b, &a);
    fe_mul(&r->X, &e, &f);
    fe_mul(&r->Y, &g, &h);
    fe_mul(&r->Z, &f, &g);
    fe_mul(&r->T, &e, &h);
}

// dedicated doubling (EFD dbl-2008-hwcd for a=-1): 4 squarings + 4 muls
// vs the unified add's 9 muls
static void ge_dbl(ge* r, const ge* p) {
    fe A, B, C, D, E, F, G, H, t;
    fe_sq(&A, &p->X);
    fe_sq(&B, &p->Y);
    fe_sq(&C, &p->Z);
    fe_add(&C, &C, &C);        // C = 2 Z^2
    fe_neg(&D, &A);            // D = a*A, a = -1
    fe_add(&t, &p->X, &p->Y);
    fe_sq(&E, &t);
    fe_sub(&E, &E, &A);
    fe_sub(&E, &E, &B);        // E = (X+Y)^2 - A - B
    fe_add(&G, &D, &B);        // G = D + B
    fe_sub(&F, &G, &C);        // F = G - C
    fe_sub(&H, &D, &B);        // H = D - B
    fe_mul(&r->X, &E, &F);
    fe_mul(&r->Y, &G, &H);
    fe_mul(&r->Z, &F, &G);
    fe_mul(&r->T, &E, &H);
}

// scalar as little-endian bytes; LSB-first double-and-add, mirroring
// the twin's _pt_mul (variable-time — see file header)
static void ge_scalarmult(ge* r, const uint8_t* scalar_le, size_t len,
                          const ge* p) {
    ge acc, base = *p;
    ge_identity(&acc);
    for (size_t i = 0; i < len; i++) {
        uint8_t byte = scalar_le[i];
        for (int bit = 0; bit < 8; bit++) {
            if ((byte >> bit) & 1) ge_add(&acc, &acc, &base);
            ge_dbl(&base, &base);
        }
    }
    *r = acc;
}

// Shamir's trick: r = a*P + b*Q in one MSB-first pass — one shared
// doubling chain instead of two (verify's U and V are this shape)
static void ge_double_scalarmult(ge* r, const uint8_t* a_le, size_t alen,
                                 const ge* p, const uint8_t* b_le,
                                 size_t blen, const ge* q) {
    ge pq, acc;
    ge_add(&pq, p, q);
    ge_identity(&acc);
    size_t bits = (alen > blen ? alen : blen) * 8;
    for (size_t i = bits; i-- > 0;) {
        ge_dbl(&acc, &acc);
        int abit = i < alen * 8 && (a_le[i / 8] >> (i % 8)) & 1;
        int bbit = i < blen * 8 && (b_le[i / 8] >> (i % 8)) & 1;
        if (abit && bbit) ge_add(&acc, &acc, &pq);
        else if (abit) ge_add(&acc, &acc, p);
        else if (bbit) ge_add(&acc, &acc, q);
    }
    *r = acc;
}

// shared wire encoding: y bytes with x-parity in bit 255 — challenge
// hashing and proof/pk encoding MUST stay byte-identical
static void ge_encode_affine(uint8_t s[32], const ge* p, const fe* zi) {
    fe x, y;
    fe_mul(&x, &p->X, zi);
    fe_mul(&y, &p->Y, zi);
    fe_tobytes(s, &y);
    uint8_t xb[32];
    fe_tobytes(xb, &x);
    s[31] |= (xb[0] & 1) << 7;
}

static void ge_tobytes(uint8_t s[32], const ge* p) {
    fe zi;
    fe_invert(&zi, &p->Z);
    ge_encode_affine(s, p, &zi);
}

// returns 0 on failure (not on curve / non-canonical), 1 on success
static int ge_frombytes(ge* r, const uint8_t s[32]) {
    // reject y >= p (canonical check, like the twin's `y >= _P`)
    static const uint8_t P_LE[32] = {237,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,255,127};
    uint8_t ycheck[32];
    memcpy(ycheck, s, 32);
    ycheck[31] &= 0x7f;
    for (int i = 31; i >= 0; i--) {
        if (ycheck[i] < P_LE[i]) break;
        if (ycheck[i] > P_LE[i]) return 0;
        if (i == 0) return 0;  // equal to p
    }
    int sign = s[31] >> 7;
    fe y;
    fe_frombytes(&y, s);
    // x^2 = (y^2-1)/(d y^2+1); candidate x = u*v^3 * (u*v^7)^((p-5)/8)
    fe u, v, d_const, one, t, v3, v7, x;
    fe_frombytes(&d_const, D_BYTES);
    fe_1(&one);
    fe_sq(&t, &y);
    fe_sub(&u, &t, &one);          // u = y^2 - 1
    fe_mul(&v, &t, &d_const);
    fe_add(&v, &v, &one);          // v = d y^2 + 1
    fe_sq(&v3, &v);
    fe_mul(&v3, &v3, &v);          // v^3
    fe_sq(&v7, &v3);
    fe_mul(&v7, &v7, &v);          // v^7
    fe_mul(&t, &u, &v7);
    fe_pow58(&t, &t);              // (u v^7)^((p-5)/8)
    fe_mul(&x, &u, &v3);
    fe_mul(&x, &x, &t);
    fe vx2, negu;
    fe_sq(&t, &x);
    fe_mul(&vx2, &v, &t);          // v x^2
    fe_neg(&negu, &u);
    if (fe_eq(&vx2, &u)) {
        // x ok
    } else if (fe_eq(&vx2, &negu)) {
        fe sqrtm1;
        fe_frombytes(&sqrtm1, SQRTM1_BYTES);
        fe_mul(&x, &x, &sqrtm1);
    } else {
        return 0;
    }
    if (fe_iszero(&x) && sign) return 0;
    uint8_t xb[32];
    fe_tobytes(xb, &x);
    if ((xb[0] & 1) != sign) fe_neg(&x, &x);
    r->X = x;
    r->Y = y;
    fe_1(&r->Z);
    fe_mul(&r->T, &x, &y);
    return 1;
}

// --------------------------------------------------------------------
// scalars mod q = 2^252 + 27742...: 32-bit limb bignum, shift-subtract
// reduction (division-free; runs once per prove — not a hot path)
// --------------------------------------------------------------------

static const uint8_t Q_LE[32] = {237,211,245,92,26,99,18,88,214,156,247,162,222,249,222,20,0,0,0,0,0,0,0,0,0,0,0,0,0,0,0,16};

typedef struct { uint32_t w[24]; } bn;  // 768 bits headroom

static void bn_zero(bn* r) { memset(r, 0, sizeof *r); }

static void bn_from_le(bn* r, const uint8_t* s, size_t len) {
    bn_zero(r);
    for (size_t i = 0; i < len && i < 96; i++)
        r->w[i / 4] |= (uint32_t)s[i] << (8 * (i % 4));
}

static void bn_to_le32(uint8_t out[32], const bn* a) {
    for (int i = 0; i < 32; i++)
        out[i] = (uint8_t)(a->w[i / 4] >> (8 * (i % 4)));
}

static int bn_cmp(const bn* a, const bn* b) {
    for (int i = 23; i >= 0; i--) {
        if (a->w[i] < b->w[i]) return -1;
        if (a->w[i] > b->w[i]) return 1;
    }
    return 0;
}

static void bn_sub(bn* r, const bn* a, const bn* b) {
    uint64_t borrow = 0;
    for (int i = 0; i < 24; i++) {
        uint64_t t = (uint64_t)a->w[i] - b->w[i] - borrow;
        r->w[i] = (uint32_t)t;
        borrow = (t >> 32) & 1;
    }
}

static void bn_shl1(bn* r) {
    uint32_t carry = 0;
    for (int i = 0; i < 24; i++) {
        uint32_t nc = r->w[i] >> 31;
        r->w[i] = (r->w[i] << 1) | carry;
        carry = nc;
    }
}

static int bn_bit(const bn* a, int i) {
    return (a->w[i / 32] >> (i % 32)) & 1;
}

static void bn_mod_q(bn* r, const bn* a) {
    bn q;
    bn_from_le(&q, Q_LE, 32);
    bn acc;
    bn_zero(&acc);
    for (int i = 767; i >= 0; i--) {
        bn_shl1(&acc);
        if (bn_bit(a, i)) acc.w[0] |= 1;
        if (bn_cmp(&acc, &q) >= 0) {
            bn tmp;
            bn_sub(&tmp, &acc, &q);
            acc = tmp;
        }
    }
    *r = acc;
}

static void bn_mul(bn* r, const bn* a, const bn* b) {
    // schoolbook over the low 8x8 limbs (inputs < 2^256 each)
    uint64_t acc[24] = {0};
    for (int i = 0; i < 8; i++) {
        uint64_t carry = 0;
        for (int j = 0; j < 8; j++) {
            unsigned __int128 t = (unsigned __int128)a->w[i] * b->w[j]
                + acc[i + j] + carry;
            acc[i + j] = (uint64_t)(t & 0xFFFFFFFFULL);
            carry = (uint64_t)(t >> 32);
        }
        acc[i + 8] += carry;
    }
    bn_zero(r);
    uint64_t carry = 0;
    for (int i = 0; i < 24; i++) {
        uint64_t t = acc[i] + carry;
        r->w[i] = (uint32_t)t;
        carry = t >> 32;
    }
}

static void bn_add(bn* r, const bn* a, const bn* b) {
    uint64_t carry = 0;
    for (int i = 0; i < 24; i++) {
        uint64_t t = (uint64_t)a->w[i] + b->w[i] + carry;
        r->w[i] = (uint32_t)t;
        carry = t >> 32;
    }
}

// --------------------------------------------------------------------
// ECVRF protocol (mirrors core/signing.py byte for byte)
// --------------------------------------------------------------------

static const uint8_t SUITE = 0x03;

static void expand_key(const uint8_t seed[32], uint8_t x_clamped[32],
                       uint8_t nonce_key[32]) {
    Sha512 h;
    uint8_t d[64];
    h.update(seed, 32);
    h.final(d);
    memcpy(x_clamped, d, 32);
    x_clamped[0] &= 248;
    x_clamped[31] &= 63;
    x_clamped[31] |= 64;
    memcpy(nonce_key, d + 32, 32);
}

static int hash_to_curve_tai(ge* out, const uint8_t pk[32],
                             const uint8_t* alpha, size_t alen) {
    for (int ctr = 0; ctr < 256; ctr++) {
        Sha512 h;
        uint8_t prefix[2] = {SUITE, 0x01};
        uint8_t tail[2] = {(uint8_t)ctr, 0x00};
        uint8_t d[64];
        h.update(prefix, 2);
        h.update(pk, 32);
        h.update(alpha, alen);
        h.update(tail, 2);
        h.final(d);
        ge pt;
        if (ge_frombytes(&pt, d)) {
            uint8_t eight = 8;
            ge_scalarmult(out, &eight, 1, &pt);  // clear cofactor
            return 1;
        }
    }
    return 0;
}

// encode 5 points with ONE field inversion (Montgomery's trick) — a
// fe_invert is ~380 fe_muls, comparable to a whole scalarmult, and the
// challenge hash needs five encodings
static void ge_tobytes_batch5(uint8_t enc[5][32], const ge* pts[5]) {
    fe prefix[5], inv;
    prefix[0] = pts[0]->Z;
    for (int i = 1; i < 5; i++) fe_mul(&prefix[i], &prefix[i - 1],
                                       &pts[i]->Z);
    fe_invert(&inv, &prefix[4]);
    for (int i = 4; i >= 0; i--) {
        fe zi;
        if (i == 0) {
            zi = inv;
        } else {
            fe_mul(&zi, &inv, &prefix[i - 1]);
            fe_mul(&inv, &inv, &pts[i]->Z);
        }
        ge_encode_affine(enc[i], pts[i], &zi);
    }
}

static void challenge16(uint8_t c16[16], const ge* pts[5]) {
    Sha512 h;
    uint8_t prefix[2] = {SUITE, 0x02};
    uint8_t zero = 0x00;
    uint8_t d[64];
    uint8_t enc[5][32];
    ge_tobytes_batch5(enc, pts);
    h.update(prefix, 2);
    for (int i = 0; i < 5; i++) h.update(enc[i], 32);
    h.update(&zero, 1);
    h.final(d);
    memcpy(c16, d, 16);
}

extern "C" {

int smtpu_vrf_public_key(const uint8_t seed[32], uint8_t pk[32]) {
    uint8_t x[32], nk[32];
    expand_key(seed, x, nk);
    ge B, Y;
    if (!ge_frombytes(&B, B_BYTES)) return -1;
    ge_scalarmult(&Y, x, 32, &B);
    ge_tobytes(pk, &Y);
    return 0;
}

int smtpu_vrf_prove(const uint8_t seed[32], const uint8_t* alpha,
                    size_t alen, uint8_t proof[80]) {
    uint8_t x[32], nk[32];
    expand_key(seed, x, nk);
    ge B, Y;
    if (!ge_frombytes(&B, B_BYTES)) return -1;
    ge_scalarmult(&Y, x, 32, &B);
    uint8_t pk[32];
    ge_tobytes(pk, &Y);

    ge H;
    if (!hash_to_curve_tai(&H, pk, alpha, alen)) return -1;
    uint8_t h_bytes[32];
    ge_tobytes(h_bytes, &H);

    ge Gamma;
    ge_scalarmult(&Gamma, x, 32, &H);

    // k = SHA512(nonce_key || h_bytes) mod q
    Sha512 hk;
    uint8_t kd[64];
    hk.update(nk, 32);
    hk.update(h_bytes, 32);
    hk.final(kd);
    bn kbig, k;
    bn_from_le(&kbig, kd, 64);
    bn_mod_q(&k, &kbig);
    uint8_t k_le[32];
    bn_to_le32(k_le, &k);

    ge kB, kH;
    ge_scalarmult(&kB, k_le, 32, &B);
    ge_scalarmult(&kH, k_le, 32, &H);

    uint8_t c16[16];
    const ge* pts[5] = {&Y, &H, &Gamma, &kB, &kH};
    challenge16(c16, pts);

    // s = (k + c*x) mod q
    bn c, xb, cx, sum, s;
    bn_from_le(&c, c16, 16);
    bn_from_le(&xb, x, 32);
    bn_mul(&cx, &c, &xb);
    bn_add(&sum, &cx, &k);
    bn_mod_q(&s, &sum);

    ge_tobytes(proof, &Gamma);
    memcpy(proof + 32, c16, 16);
    bn_to_le32(proof + 48, &s);
    return 0;
}

int smtpu_vrf_verify(const uint8_t pk[32], const uint8_t* alpha,
                     size_t alen, const uint8_t proof[80]) {
    ge Y, Gamma;
    if (!ge_frombytes(&Y, pk)) return 0;
    if (!ge_frombytes(&Gamma, proof)) return 0;
    const uint8_t* c16 = proof + 32;
    const uint8_t* s_le = proof + 48;
    // s < q
    bn s, q;
    bn_from_le(&s, s_le, 32);
    bn_from_le(&q, Q_LE, 32);
    if (bn_cmp(&s, &q) >= 0) return 0;

    ge H;
    if (!hash_to_curve_tai(&H, pk, alpha, alen)) return 0;

    ge B;
    if (!ge_frombytes(&B, B_BYTES)) return 0;
    ge negY = Y, negGamma = Gamma;
    fe_neg(&negY.X, &Y.X);
    fe_neg(&negY.T, &Y.T);
    fe_neg(&negGamma.X, &Gamma.X);
    fe_neg(&negGamma.T, &Gamma.T);

    ge U, V;
    ge_double_scalarmult(&U, s_le, 32, &B, c16, 16, &negY);
    ge_double_scalarmult(&V, s_le, 32, &H, c16, 16, &negGamma);

    uint8_t c_check[16];
    const ge* pts[5] = {&Y, &H, &Gamma, &U, &V};
    challenge16(c_check, pts);
    return memcmp(c_check, c16, 16) == 0 ? 1 : 0;
}

int smtpu_vrf_output(const uint8_t proof[80], uint8_t out[64]) {
    ge Gamma;
    if (!ge_frombytes(&Gamma, proof)) return -1;
    ge cg;
    uint8_t eight = 8;
    ge_scalarmult(&cg, &eight, 1, &Gamma);
    uint8_t enc[32];
    ge_tobytes(enc, &cg);
    Sha512 h;
    uint8_t prefix[2] = {SUITE, 0x03};
    uint8_t zero = 0x00;
    h.update(prefix, 2);
    h.update(enc, 32);
    h.update(&zero, 1);
    h.final(out);
    return 0;
}

}  // extern "C"
