// BLAKE3 one-shot hashing — the node runtime's hottest CPU path.
//
// Native twin of core/hashing.py (same from-spec algorithm, same tree
// rules); compiled by native/build.py into libsmtpu_blake3.so and loaded
// via ctypes with the Python implementation as fallback + test oracle.
// Every gossip message id, codec content id, address and merkle node
// rides this (reference hash/hash.go uses the native BLAKE3 crate the
// same way).
//
// Build: g++ -O3 -shared -fPIC -o libsmtpu_blake3.so blake3.cpp

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t IV[8] = {
    0x6A09E667u, 0xBB67AE85u, 0x3C6EF372u, 0xA54FF53Au,
    0x510E527Fu, 0x9B05688Cu, 0x1F83D9ABu, 0x5BE0CD19u};

constexpr int MSG_PERM[16] = {2, 6,  3,  10, 7, 0,  4,  13,
                              1, 11, 12, 5,  9, 14, 15, 8};

constexpr uint32_t CHUNK_START = 1;
constexpr uint32_t CHUNK_END = 2;
constexpr uint32_t PARENT = 4;
constexpr uint32_t ROOT = 8;
constexpr uint32_t KEYED_HASH = 16;

constexpr size_t BLOCK_LEN = 64;
constexpr size_t CHUNK_LEN = 1024;

static inline uint32_t rotr(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static inline void g(uint32_t *st, int a, int b, int c, int d, uint32_t mx,
                     uint32_t my) {
  st[a] = st[a] + st[b] + mx;
  st[d] = rotr(st[d] ^ st[a], 16);
  st[c] = st[c] + st[d];
  st[b] = rotr(st[b] ^ st[c], 12);
  st[a] = st[a] + st[b] + my;
  st[d] = rotr(st[d] ^ st[a], 8);
  st[c] = st[c] + st[d];
  st[b] = rotr(st[b] ^ st[c], 7);
}

static void compress(const uint32_t cv[8], const uint32_t block[16],
                     uint64_t counter, uint32_t block_len, uint32_t flags,
                     uint32_t out[16]) {
  uint32_t st[16];
  uint32_t m[16];
  std::memcpy(st, cv, 32);
  std::memcpy(st + 8, IV, 16);
  st[12] = static_cast<uint32_t>(counter);
  st[13] = static_cast<uint32_t>(counter >> 32);
  st[14] = block_len;
  st[15] = flags;
  std::memcpy(m, block, 64);
  for (int round = 0;; ++round) {
    g(st, 0, 4, 8, 12, m[0], m[1]);
    g(st, 1, 5, 9, 13, m[2], m[3]);
    g(st, 2, 6, 10, 14, m[4], m[5]);
    g(st, 3, 7, 11, 15, m[6], m[7]);
    g(st, 0, 5, 10, 15, m[8], m[9]);
    g(st, 1, 6, 11, 12, m[10], m[11]);
    g(st, 2, 7, 8, 13, m[12], m[13]);
    g(st, 3, 4, 9, 14, m[14], m[15]);
    if (round == 6) break;
    uint32_t p[16];
    for (int i = 0; i < 16; ++i) p[i] = m[MSG_PERM[i]];
    std::memcpy(m, p, 64);
  }
  for (int i = 0; i < 8; ++i) {
    out[i] = st[i] ^ st[i + 8];
    out[i + 8] = st[i + 8] ^ cv[i];
  }
}

static inline void load_block(const uint8_t *p, size_t len,
                              uint32_t block[16]) {
  uint8_t buf[BLOCK_LEN] = {0};
  std::memcpy(buf, p, len);
  for (int i = 0; i < 16; ++i) {
    block[i] = static_cast<uint32_t>(buf[4 * i]) |
               (static_cast<uint32_t>(buf[4 * i + 1]) << 8) |
               (static_cast<uint32_t>(buf[4 * i + 2]) << 16) |
               (static_cast<uint32_t>(buf[4 * i + 3]) << 24);
  }
}

struct Output {
  uint32_t cv[8];
  uint32_t block[16];
  uint64_t counter;
  uint32_t block_len;
  uint32_t flags;
};

// compress one whole 1024-byte chunk straight to its chaining value
static void chunk_cv(const uint8_t *p, size_t len, uint64_t chunk_counter,
                     const uint32_t key[8], uint32_t base_flags,
                     uint32_t cv_out[8]) {
  uint32_t cv[8];
  std::memcpy(cv, key, 32);
  size_t off = 0;
  int block_idx = 0;
  while (len - off > BLOCK_LEN) {
    uint32_t block[16];
    load_block(p + off, BLOCK_LEN, block);
    uint32_t flags = base_flags | (block_idx == 0 ? CHUNK_START : 0);
    uint32_t out[16];
    compress(cv, block, chunk_counter, BLOCK_LEN, flags, out);
    std::memcpy(cv, out, 32);
    off += BLOCK_LEN;
    ++block_idx;
  }
  uint32_t block[16];
  load_block(p + off, len - off, block);
  uint32_t flags = base_flags | (block_idx == 0 ? CHUNK_START : 0) | CHUNK_END;
  uint32_t out[16];
  compress(cv, block, chunk_counter, static_cast<uint32_t>(len - off), flags,
           out);
  std::memcpy(cv_out, out, 32);
}

// the FINAL (possibly partial) chunk keeps its pre-finalization state so
// the root flag can be applied at output time
static void chunk_output(const uint8_t *p, size_t len, uint64_t chunk_counter,
                         const uint32_t key[8], uint32_t base_flags,
                         Output *out) {
  uint32_t cv[8];
  std::memcpy(cv, key, 32);
  size_t off = 0;
  int block_idx = 0;
  while (len > 0 && len - off > BLOCK_LEN) {
    uint32_t block[16];
    load_block(p + off, BLOCK_LEN, block);
    uint32_t flags = base_flags | (block_idx == 0 ? CHUNK_START : 0);
    uint32_t cout[16];
    compress(cv, block, chunk_counter, BLOCK_LEN, flags, cout);
    std::memcpy(cv, cout, 32);
    off += BLOCK_LEN;
    ++block_idx;
  }
  std::memcpy(out->cv, cv, 32);
  load_block(p + off, len - off, out->block);
  out->counter = chunk_counter;
  out->block_len = static_cast<uint32_t>(len - off);
  out->flags = base_flags | (block_idx == 0 ? CHUNK_START : 0) | CHUNK_END;
}

static void parent_output(const uint32_t left[8], const uint32_t right[8],
                          const uint32_t key[8], uint32_t base_flags,
                          Output *out) {
  std::memcpy(out->cv, key, 32);
  std::memcpy(out->block, left, 32);
  std::memcpy(out->block + 8, right, 32);
  out->counter = 0;
  out->block_len = BLOCK_LEN;
  out->flags = base_flags | PARENT;
}

}  // namespace

extern "C" {

// One-shot BLAKE3. key32 may be null (unkeyed) or point at 32 bytes
// (keyed mode). Writes out_len bytes of root XOF output.
void smtpu_blake3(const uint8_t *data, size_t len, const uint8_t *key32,
                  uint8_t *out, size_t out_len) {
  uint32_t key[8];
  uint32_t base_flags = 0;
  if (key32 != nullptr) {
    for (int i = 0; i < 8; ++i) {
      key[i] = static_cast<uint32_t>(key32[4 * i]) |
               (static_cast<uint32_t>(key32[4 * i + 1]) << 8) |
               (static_cast<uint32_t>(key32[4 * i + 2]) << 16) |
               (static_cast<uint32_t>(key32[4 * i + 3]) << 24);
    }
    base_flags = KEYED_HASH;
  } else {
    std::memcpy(key, IV, 32);
  }

  // tree: full chunks push CVs onto the merge stack; the last (possibly
  // partial/empty) chunk becomes the root candidate (hashing.py Hasher)
  uint32_t stack[54][8];  // 2^54 chunks ≫ any input
  int depth = 0;
  uint64_t total_chunks = 0;

  size_t off = 0;
  while (len - off > CHUNK_LEN) {
    uint32_t cv[8];
    chunk_cv(data + off, CHUNK_LEN, total_chunks, key, base_flags, cv);
    ++total_chunks;
    uint64_t total = total_chunks;
    while ((total & 1) == 0) {
      Output po;
      parent_output(stack[--depth], cv, key, base_flags, &po);
      uint32_t cout[16];
      compress(po.cv, po.block, po.counter, po.block_len, po.flags, cout);
      std::memcpy(cv, cout, 32);
      total >>= 1;
    }
    std::memcpy(stack[depth++], cv, 32);
    off += CHUNK_LEN;
  }

  Output root;
  chunk_output(data + off, len - off, total_chunks, key, base_flags, &root);
  for (int i = depth - 1; i >= 0; --i) {
    uint32_t cout[16];
    compress(root.cv, root.block, root.counter, root.block_len, root.flags,
             cout);
    uint32_t cv[8];
    std::memcpy(cv, cout, 32);
    parent_output(stack[i], cv, key, base_flags, &root);
  }

  uint64_t block_counter = 0;
  size_t produced = 0;
  while (produced < out_len) {
    uint32_t wide[16];
    compress(root.cv, root.block, block_counter, root.block_len,
             root.flags | ROOT, wide);
    uint8_t bytes[64];
    for (int i = 0; i < 16; ++i) {
      bytes[4 * i] = static_cast<uint8_t>(wide[i]);
      bytes[4 * i + 1] = static_cast<uint8_t>(wide[i] >> 8);
      bytes[4 * i + 2] = static_cast<uint8_t>(wide[i] >> 16);
      bytes[4 * i + 3] = static_cast<uint8_t>(wide[i] >> 24);
    }
    size_t take = out_len - produced < 64 ? out_len - produced : 64;
    std::memcpy(out + produced, bytes, take);
    produced += take;
    ++block_counter;
  }
}

}  // extern "C"
