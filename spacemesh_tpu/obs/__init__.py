"""Health & SLO engine: windowed SLIs, watchdogs, flight recorder.

The metrics registry accumulates since boot; the span tracer explains
individual units of work. Neither answers "is this node healthy RIGHT
NOW". This package does:

* ``sli.py``     — rolling-window service-level indicators computed from
                   registry snapshots (counter-rate deltas, interpolated
                   quantiles from histogram bucket deltas) plus runtime
                   collectors (RSS, fds, event-loop lag).
* ``health.py``  — declarative SLOs with burn-rate accounting, a
                   component health registry with progress-counter stall
                   watchdogs, and the HealthEngine tick loop behind
                   ``/healthz`` and ``/readyz``.
* ``flight.py``  — the flight recorder: on a breach or stall, dump a
                   spooled diagnostic bundle (trace export, metrics
                   snapshot, recent events, health report).
* ``remediate.py`` — the layer that ACTS on the verdicts: circuit
                   breakers around the chronic retry-forever sites,
                   declarative recovery policies with budgets and
                   quarantine escalation, and the process-global
                   breaker/action-hook registries behind
                   ``/debug/remediation`` (docs/SELF_HEALING.md).
* ``federate.py`` — the fleet collection plane: per-process metric
                   snapshots re-exposed under ``proc=`` labels with
                   strict cardinality hygiene, trace captures collected
                   for ``tracing.merge_captures()``, crashed-process
                   snapshots retained for forensics
                   (docs/OBSERVABILITY.md § Fleet observability).

docs/OBSERVABILITY.md documents the SLO spec format, the HTTP surface
and the flight-bundle layout.
"""

from . import federate, flight, health, remediate, sli  # noqa: F401

__all__ = ["sli", "health", "flight", "remediate", "federate"]
