"""Flight recorder: spool a diagnostic bundle when something breaks.

An SLO breach or a stalled component is exactly the moment an operator
wishes they had started a trace capture five minutes ago. The flight
recorder makes that retroactively true: the span tracer's ring, the
full metrics exposition, the event bus's recent ring and the health
report are all already in memory — a dump just serializes them into a
timestamped bundle directory under the spool dir:

    flight-<unix_ts>-<pid>-<seq>/
        manifest.json   {reason, unix_ts, pid, health}
        trace.json      tracing.export() (validates via tracing.validate)
        metrics.prom    Registry.expose() text exposition
        events.json     recent EventBus emissions (bounded ring)
        health.json     the engine's readiness report at dump time
        procs/<proc>/   per-federated-process trace.json + metrics.prom
                        (obs/federate.py; only when children federated)

Automatic dumps (engine tick transitions) are rate-limited to one per
``min_interval_s`` so a flapping SLO cannot fill the disk; the manual
``/debug/flight`` trigger bypasses the limit. The spool keeps the
newest ``keep`` bundles and prunes the rest.

``profiler --flight <bundle>`` (tools/profiler.py) digests a bundle:
validates the trace, summarizes it, and prints the unhealthy components
and breached SLOs from the manifest.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import shutil
import time
from pathlib import Path

from ..utils import fsio
from ..utils import logging as slog
from ..utils import metrics, tracing

_log = slog.get("flight")

DEFAULT_MIN_INTERVAL_S = 60.0
DEFAULT_KEEP = 8

MANIFEST = "manifest.json"
TRACE = "trace.json"
METRICS = "metrics.prom"
EVENTS = "events.json"
HEALTH = "health.json"
PROCS = "procs"


def _jsonable(obj):
    """Best-effort JSON projection for event payloads (bytes -> hex)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _jsonable(v)
                for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


class FlightRecorder:
    def __init__(self, spool_dir, *,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 keep: int = DEFAULT_KEEP,
                 registry: metrics.Registry = metrics.REGISTRY,
                 time_source=time.monotonic):
        self.spool = Path(spool_dir)
        self.min_interval_s = float(min_interval_s)
        self.keep = max(int(keep), 1)
        self.registry = registry
        self.time_source = time_source
        self._last_dump: float | None = None
        self._seq = itertools.count()

    def dump(self, reason: str, *, now: float | None = None,
             health: dict | None = None, events=None,
             remediation: dict | None = None,
             force: bool = False) -> Path | None:
        """Write one bundle; returns its path, or None when rate-limited.

        ``now`` is the engine's monotonic clock (rate limiting only —
        bundle names use wall time so operators can correlate them with
        logs)."""
        t = self.time_source() if now is None else float(now)
        if (not force and self._last_dump is not None
                and t - self._last_dump < self.min_interval_s):
            return None
        # pid in the name: a crash-looping node restarting within one
        # wall-clock second resets the seq counter, and colliding with a
        # previous run's bundle would fail os.replace (ENOTEMPTY) and
        # drop the dump at exactly the moment it matters
        name = (f"flight-{int(time.time())}-{os.getpid()}-"
                f"{next(self._seq):03d}")
        path = self.spool / name
        tmp = self.spool / f".{name}.tmp"
        try:
            tmp.mkdir(parents=True, exist_ok=True)
            from ..utils import sanitize

            if remediation is None:
                # breaker states always ride along: a bundle taken at
                # the unhealthy moment must answer "was the node
                # already remediating?" even for loop-less embedders
                from . import remediate as remediate_mod

                remediation = {
                    "breakers": remediate_mod.BREAKERS.snapshot()}
            manifest = {
                "reason": reason,
                "unix_ts": time.time(),
                "pid": os.getpid(),
                "trace_enabled": tracing.is_enabled(),
                "health": health,
                "remediation": remediation,
                # sanitizer findings ride along so a bundle taken at the
                # unhealthy moment carries the race/slow-callback reports
                # (the counters themselves survive via metrics.prom)
                "sanitize_violations": [dataclasses.asdict(v)
                                        for v in sanitize.violations()],
            }
            (tmp / MANIFEST).write_text(
                json.dumps(_jsonable(manifest), indent=1))
            (tmp / TRACE).write_text(json.dumps(tracing.export()))
            (tmp / METRICS).write_text(self.registry.expose())
            (tmp / EVENTS).write_text(json.dumps(
                [{"t": et, "type": etype, "event": _jsonable(ev)}
                 for et, etype, ev in (events or [])]))
            (tmp / HEALTH).write_text(
                json.dumps(_jsonable(health or {}), indent=1))
            # fleet federation: every child process's last trace +
            # proc=-labeled metrics land under procs/ so ONE bundle
            # answers for the whole fleet, crashed workers included
            from .federate import FEDERATION

            for proc, ent in sorted(FEDERATION.flight_procs().items()):
                pdir = tmp / PROCS / proc.replace("/", "_")
                pdir.mkdir(parents=True, exist_ok=True)
                if ent["trace"] is not None:
                    (pdir / TRACE).write_text(json.dumps(ent["trace"]))
                (pdir / METRICS).write_text(ent["metrics"])
                if ent["crashed"]:
                    (pdir / "CRASHED").write_text("retained snapshot\n")
            # durable publish (utils/fsio): fsync + atomic rename +
            # parent-dir fsync — the bundle an operator reaches for
            # after a crash must not itself be a casualty of the crash
            fsio.persist(tmp, path)
        except OSError as exc:
            _log.error("flight dump failed: %r", exc)
            shutil.rmtree(tmp, ignore_errors=True)
            return None
        # rate limit arms only on SUCCESS: a failed write (disk full)
        # must not suppress the next automatic dump once it could work
        self._last_dump = t
        metrics.flight_bundles.inc(trigger=reason.split(":", 1)[0])
        _log.warning("flight bundle written: %s (%s)", path, reason)
        self._prune()
        return path

    def bundles(self) -> list[Path]:
        if not self.spool.is_dir():
            return []
        return sorted(p for p in self.spool.iterdir()
                      if p.is_dir() and p.name.startswith("flight-"))

    def _prune(self) -> None:
        for stale in self.bundles()[:-self.keep]:
            shutil.rmtree(stale, ignore_errors=True)


# --- bundle digestion (profiler --flight) -------------------------------


def read_bundle(path) -> dict:
    """Load + validate one bundle. Raises on a malformed trace or an
    unparseable metrics snapshot — a corrupt bundle must fail loudly."""
    p = Path(path)
    if not (p / MANIFEST).exists():
        raise FileNotFoundError(f"{p}: not a flight bundle (no {MANIFEST})")
    manifest = json.loads((p / MANIFEST).read_text())
    trace = json.loads((p / TRACE).read_text())
    tracing.validate(trace)
    metrics_text = (p / METRICS).read_text()
    samples = 0
    for line in metrics_text.splitlines():
        if line and not line.startswith("#"):
            if " " not in line:
                raise ValueError(f"{p}/{METRICS}: bad sample {line!r}")
            samples += 1
    events = json.loads((p / EVENTS).read_text()) \
        if (p / EVENTS).exists() else []
    health = json.loads((p / HEALTH).read_text()) \
        if (p / HEALTH).exists() else {}
    procs: dict = {}
    procs_dir = p / PROCS
    if procs_dir.is_dir():
        for pdir in sorted(procs_dir.iterdir()):
            if not pdir.is_dir():
                continue
            ptrace = None
            if (pdir / TRACE).exists():
                ptrace = json.loads((pdir / TRACE).read_text())
                tracing.validate(ptrace)
            procs[pdir.name] = {
                "trace": ptrace,
                "metrics": ((pdir / METRICS).read_text()
                            if (pdir / METRICS).exists() else ""),
                "crashed": (pdir / "CRASHED").exists(),
            }
    return {"path": str(p), "manifest": manifest, "trace": trace,
            "metrics_samples": samples, "events": events,
            "health": health, "procs": procs}


def digest(bundle: dict, top: int = 10) -> dict:
    """A render-ready summary of ``read_bundle()``'s output. When the
    bundle carries federated ``procs/``, the trace summary runs over
    the MERGED timeline (parent + every child capture) so per-proc
    self-time and cross-process link counts appear in one table."""
    health = bundle.get("health") or {}
    components = health.get("components", {})
    slos = health.get("slos", {})
    procs = bundle.get("procs") or {}
    child_traces = [ent["trace"] for _, ent in sorted(procs.items())
                    if ent.get("trace") is not None]
    doc = bundle["trace"]
    if child_traces:
        doc = tracing.merge_captures([doc] + child_traces)
    summary = tracing.summarize(doc, top=top)
    return {
        "bundle": bundle["path"],
        "reason": bundle["manifest"].get("reason"),
        "unix_ts": bundle["manifest"].get("unix_ts"),
        "ready": health.get("ready"),
        "unhealthy_components": {
            name: ent.get("reason") for name, ent in components.items()
            if not ent.get("healthy", True)},
        "breached_slos": {
            name: {"value": ent.get("value"), "target": ent.get("target"),
                   "burn": ent.get("burn")}
            for name, ent in slos.items() if ent.get("breached")},
        "slis": health.get("slis", {}),
        "metrics_samples": bundle["metrics_samples"],
        "events": len(bundle["events"]),
        "trace_spans": summary["spans"],
        "trace_top_self_time": summary["top_self_time"][:top],
        "procs": {name: {"crashed": ent.get("crashed", False),
                         "spans": (ent["trace"]["otherData"].get(
                             "captured_spans", 0)
                             if ent.get("trace") else 0)}
                  for name, ent in sorted(procs.items())},
        "proc_self_time": summary.get("procs", []),
        "cross_proc_links": summary.get("cross_proc_links",
                                        {"total": 0, "pairs": {}}),
        "trace_warnings": summary.get("warnings", []),
    }
