"""Windowed service-level indicators over the metrics registry.

Cumulative counters and since-boot histograms answer "how much, ever";
an SLO needs "how is it going NOW". The :class:`SliSampler` snapshots
the whole registry (``Registry.sample()``) on an interval and computes
each SLI from the DELTA between the newest snapshot and the one at the
far edge of a rolling window:

* ``rate``      — counter delta / elapsed seconds (e.g. init labels/s);
* ``quantile``  — p50/p95/p99 linearly interpolated from histogram
                  bucket-count deltas (the standard
                  ``histogram_quantile`` estimator, applied to the
                  window's observations only);
* ``gauge``     — the newest sampled value (loop lag, RSS).

Counter resets (a restarted process re-registering from zero) make a
delta negative; the window is then truncated to "since the reset" by
using the newest cumulative values alone. An empty window (no snapshots
old enough, or zero observations in the delta) yields ``None`` — absence
of data is not a number, and SLO evaluation treats it as unknown rather
than healthy-by-default-zero.

Runtime collectors registered here via the registry's scrape-time hook
(``Registry.add_collector``) keep process RSS and open-fd gauges honest
at observation time; the event-loop lag gauge is fed by the
HealthEngine's heartbeat (obs/health.py), which is the only place a lag
measurement can actually be taken.
"""

from __future__ import annotations

import bisect
import dataclasses
import os
import threading
import time
from collections import deque

from ..utils import metrics

DEFAULT_WINDOW_S = 60.0
DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


# --- quantile interpolation from bucket deltas --------------------------


def quantile_from_buckets(bounds, counts, q: float) -> float | None:
    """``histogram_quantile``: interpolate the q-quantile from cumulative
    bucket ``counts`` at upper ``bounds`` (le semantics, last bound may
    be +Inf). Returns None when the distribution is empty.

    Within a bucket the observations are assumed uniform (linear
    interpolation); a quantile landing in the +Inf bucket clamps to the
    highest finite bound — the estimator cannot know more than the
    layout recorded.
    """
    if not counts or counts[-1] <= 0:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = counts[-1]
    rank = q * total
    # first bucket whose cumulative count reaches the rank
    i = bisect.bisect_left(counts, rank)
    while i < len(counts) and counts[i] <= 0:
        i += 1  # bisect on rank 0.0: skip leading empty buckets
    i = min(i, len(counts) - 1)
    hi = bounds[i]
    if hi == float("inf"):
        # the +Inf bucket has no width to interpolate in; clamp to the
        # highest finite bound (Prometheus does the same)
        return float(bounds[i - 1]) if i > 0 else 0.0
    lo = float(bounds[i - 1]) if i > 0 else 0.0
    below = counts[i - 1] if i > 0 else 0
    in_bucket = counts[i] - below
    if in_bucket <= 0:
        return float(hi)
    frac = (rank - below) / in_bucket
    return lo + (float(hi) - lo) * min(max(frac, 0.0), 1.0)


# --- SLI specifications -------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SliSpec:
    """One indicator: which instrument, how to reduce it, over what.

    ``labels`` is a labelset filter as a sorted item tuple
    (``(("kind", "sig"),)``): an exact labelset matches directly, and
    otherwise every series CONTAINING those items aggregates (bucket
    deltas sum, counter deltas sum) — so ``(("client", "a"),)`` covers
    all of one verifyd client's ``{client=a, kind=...}`` series.
    ``None`` aggregates across every labelset of the instrument.
    """

    name: str
    metric: str
    kind: str                      # "quantile" | "rate" | "gauge"
    q: float = 0.99
    labels: tuple | None = None


def quantile_slis(metric: str, prefix: str,
                  quantiles=DEFAULT_QUANTILES,
                  labels: tuple | None = None) -> list[SliSpec]:
    """p50/p95/p99 spec triple for one histogram."""
    return [SliSpec(name=f"{prefix}_p{int(q * 100)}", metric=metric,
                    kind="quantile", q=q, labels=labels)
            for q in quantiles]


def default_slis() -> list[SliSpec]:
    """The node-wide indicator set (ISSUE 7): layer apply, farm queue
    wait + dispatch (aggregate and per hot kind), prove window time,
    gossip handler latency, init labels/s, plus the runtime gauges."""
    specs: list[SliSpec] = []
    specs += quantile_slis("layer_apply_seconds", "layer_apply")
    specs += quantile_slis("verify_farm_queue_wait_seconds",
                           "farm_queue_wait")
    specs += quantile_slis("verify_farm_dispatch_seconds", "farm_dispatch")
    for kind in ("sig", "post"):
        key = (("kind", kind),)
        specs.append(SliSpec(name=f"farm_dispatch_{kind}_p95",
                             metric="verify_farm_dispatch_seconds",
                             kind="quantile", q=0.95, labels=key))
        specs.append(SliSpec(name=f"farm_queue_wait_{kind}_p95",
                             metric="verify_farm_queue_wait_seconds",
                             kind="quantile", q=0.95, labels=key))
    specs += quantile_slis("post_prove_window_seconds", "prove_window")
    specs += quantile_slis("gossip_handler_seconds", "gossip_handler")
    specs.append(SliSpec(name="init_labels_per_sec",
                         metric="post_pipeline_labels_total", kind="rate"))
    specs.append(SliSpec(name="event_loop_lag",
                         metric="runtime_event_loop_lag_seconds",
                         kind="gauge"))
    specs.append(SliSpec(name="process_rss_bytes",
                         metric="process_resident_memory_bytes",
                         kind="gauge"))
    return specs


def verifyd_slis() -> list[SliSpec]:
    """The verification service's indicator set (docs/VERIFYD.md):
    admitted-request latency quantiles per lane (the overload SLO
    constrains the BLOCK lane), admission/shed rates, pending depth."""
    specs: list[SliSpec] = []
    specs += quantile_slis("verifyd_request_seconds", "verifyd_request")
    for lane in ("block", "gossip", "sync"):
        specs.append(SliSpec(name=f"verifyd_request_{lane}_p99",
                             metric="verifyd_request_seconds",
                             kind="quantile", q=0.99,
                             labels=(("lane", lane),)))
    specs.append(SliSpec(name="verifyd_items_per_sec",
                         metric="verifyd_items_total", kind="rate"))
    specs.append(SliSpec(name="verifyd_shed_per_sec",
                         metric="verifyd_shed_total", kind="rate"))
    specs.append(SliSpec(name="verifyd_pending_items",
                         metric="verifyd_pending_items", kind="gauge"))
    return specs


def failover_slis() -> list[SliSpec]:
    """The failover verifier's indicator set (verifyd/failover.py): the
    latency the NODE saw regardless of serving path — the signal that
    must stay green straight through a verifyd outage (the BLOCK-lane
    p99 is the verifyd-outage scenario's acceptance SLO) — plus
    per-path request rates that make a failover visible as a rate
    crossover."""
    specs: list[SliSpec] = []
    specs += quantile_slis("failover_verify_seconds", "failover_verify")
    for lane in ("block", "gossip", "sync"):
        specs.append(SliSpec(name=f"failover_{lane}_p99",
                             metric="failover_verify_seconds",
                             kind="quantile", q=0.99,
                             labels=(("lane", lane),)))
    for path in ("remote", "local", "local_fastfail"):
        specs.append(SliSpec(name=f"failover_{path}_per_sec",
                             metric="failover_requests_total",
                             kind="rate", labels=(("path", path),)))
    return specs


def fleet_slis(replicas=()) -> list[SliSpec]:
    """The verifyd fleet's indicator set (verifyd/fleet.py): the
    latency the NODE saw whatever replica (or local path) served it —
    the BLOCK-lane p99 is the fleet sim's acceptance SLO — plus the
    per-replica load signals FleetRouter.update_signals() turns into
    work-steal decisions and the ``fleet_desired_replicas`` autoscaling
    gauge: each replica's queue-wait p99 and shed rate, named exactly
    ``fleet_replica_{name}_queue_p99`` / ``fleet_replica_{name}_
    shed_per_sec`` (the router looks them up by that contract)."""
    specs: list[SliSpec] = []
    specs += quantile_slis("fleet_verify_seconds", "fleet_verify")
    for lane in ("block", "gossip", "sync"):
        specs.append(SliSpec(name=f"fleet_{lane}_p99",
                             metric="fleet_verify_seconds",
                             kind="quantile", q=0.99,
                             labels=(("lane", lane),)))
    for path in ("remote", "local", "local_fastfail"):
        specs.append(SliSpec(name=f"fleet_{path}_per_sec",
                             metric="fleet_requests_total",
                             kind="rate", labels=(("path", path),)))
    for name in replicas:
        key = (("replica", str(name)),)
        specs.append(SliSpec(
            name=f"fleet_replica_{name}_queue_p99",
            metric="fleet_replica_verify_seconds",
            kind="quantile", q=0.99, labels=key))
        specs.append(SliSpec(
            name=f"fleet_replica_{name}_shed_per_sec",
            metric="fleet_replica_sheds_total",
            kind="rate", labels=key))
    specs.append(SliSpec(name="fleet_desired_replicas",
                         metric="fleet_desired_replicas", kind="gauge"))
    return specs


def verifyd_client_slis(clients) -> list[SliSpec]:
    """Per-client indicators for the given client ids — each spec's
    labelset filter aggregates every series carrying that ``client``
    label (admitted items/s, sheds/s, pending depth). The caller scopes
    the list (e.g. the service's registered clients at engine build
    time): specs are static, clients churn."""
    specs: list[SliSpec] = []
    for cid in clients:
        key = (("client", str(cid)),)
        specs.append(SliSpec(name=f"verifyd_client_{cid}_items_per_sec",
                             metric="verifyd_items_total", kind="rate",
                             labels=key))
        specs.append(SliSpec(name=f"verifyd_client_{cid}_shed_per_sec",
                             metric="verifyd_shed_total", kind="rate",
                             labels=key))
        specs.append(SliSpec(name=f"verifyd_client_{cid}_pending",
                             metric="verifyd_client_pending_items",
                             kind="gauge", labels=key))
    return specs


# --- the sampler --------------------------------------------------------


class SliSampler:
    """Rolling snapshots of one registry + windowed SLI computation.

    ``sample(now)`` is called by the HealthEngine tick (or directly by
    tests with an injected clock — nothing here sleeps or schedules).
    Snapshots older than ``window_s`` plus one sampling slack are
    dropped, so memory is bounded by window/interval.
    """

    def __init__(self, registry: metrics.Registry = metrics.REGISTRY,
                 window_s: float = DEFAULT_WINDOW_S,
                 max_samples: int = 256):
        self.registry = registry
        self.window_s = float(window_s)
        self._snaps: deque = deque(maxlen=max(int(max_samples), 2))
        self._lock = threading.Lock()

    def sample(self, now: float | None = None) -> None:
        """Take one registry snapshot stamped ``now`` (monotonic)."""
        t = time.monotonic() if now is None else float(now)
        snap = self.registry.sample()
        with self._lock:
            self._snaps.append((t, snap))
            # keep one snapshot beyond the window edge so a full window
            # is always spannable
            while (len(self._snaps) > 2
                   and self._snaps[1][0] <= t - self.window_s):
                self._snaps.popleft()

    def _edges(self):
        """(old, new) snapshots spanning the window, or None.

        The old edge is the LATEST snapshot at or beyond the window
        start (delta covers a full window); with nothing that old yet,
        the oldest snapshot available (a partial, honest window)."""
        with self._lock:
            if len(self._snaps) < 2:
                return None
            snaps = list(self._snaps)
        new_t, new = snaps[-1]
        edge = new_t - self.window_s
        old_t, old = snaps[0]
        for t, s in snaps[:-1]:
            if t <= edge:
                old_t, old = t, s
            else:
                break
        if old_t >= new_t:
            return None
        return (old_t, old), (new_t, new)

    @staticmethod
    def _sum_counter(data: dict, labels: tuple | None) -> float | None:
        if labels is not None:
            exact = data.get(labels)
            if exact is not None:
                return exact
            # subset semantics: aggregate every series containing the
            # filter items (a per-entity SLI over multi-label series)
            items = set(labels)
            vals = [v for k, v in data.items()
                    if items.issubset(set(k))]
            return sum(vals) if vals else None
        return sum(data.values()) if data else None

    @staticmethod
    def _sum_hist(data: dict, labels: tuple | None):
        """-> (bucket counts, total count) aggregated per the filter
        (exact labelset first, else every series containing it)."""
        series = data["series"]
        if labels is not None:
            s = series.get(labels)
            if s is not None:
                return (list(s[0]), s[2])
            items = set(labels)
            picked = [s for k, s in series.items()
                      if items.issubset(set(k))]
        else:
            picked = list(series.values())
        agg = None
        total = 0
        for counts, _sum, n in picked:
            if agg is None:
                agg = list(counts)
            else:
                agg = [a + c for a, c in zip(agg, counts)]
            total += n
        return (agg, total) if agg is not None else None

    def compute(self, spec: SliSpec) -> float | None:
        """The spec's current windowed value, or None (no data)."""
        if spec.kind == "gauge":
            # gauges are instantaneous: newest snapshot alone suffices
            with self._lock:
                if not self._snaps:
                    return None
                _, snap = self._snaps[-1]
            ent = snap.get(spec.metric)
            if ent is None or ent[0] != "gauge":
                return None
            return self._sum_counter(ent[1], spec.labels)
        edges = self._edges()
        if edges is None:
            return None
        (old_t, old), (new_t, new) = edges
        ent_new = new.get(spec.metric)
        if ent_new is None:
            return None
        kind, data_new = ent_new
        ent_old = old.get(spec.metric)
        data_old = ent_old[1] if ent_old is not None else None
        if spec.kind == "rate":
            # a counter that EXISTS but saw no increments is rate 0.0
            # (an idle pipeline), not unknown — only a missing metric is
            nv = self._sum_counter(data_new, spec.labels) or 0.0
            ov = (self._sum_counter(data_old, spec.labels)
                  if data_old is not None else None) or 0.0
            if nv < ov:
                ov = 0.0  # counter reset: window truncates to the restart
            return (nv - ov) / (new_t - old_t)
        if spec.kind == "quantile":
            if kind != "histogram":
                return None
            hn = self._sum_hist(data_new, spec.labels)
            if hn is None:
                return None
            counts_new, _ = hn
            ho = (self._sum_hist(data_old, spec.labels)
                  if ent_old is not None and ent_old[0] == "histogram"
                  else None)
            if ho is not None and len(ho[0]) == len(counts_new):
                deltas = [n - o for n, o in zip(counts_new, ho[0])]
                if any(d < 0 for d in deltas):
                    deltas = counts_new  # reset: since-restart window
            else:
                deltas = counts_new
            return quantile_from_buckets(data_new["buckets"], deltas,
                                         spec.q)
        raise ValueError(f"unknown SLI kind {spec.kind!r}")

    def values(self, specs) -> dict[str, float | None]:
        return {spec.name: self.compute(spec) for spec in specs}


# --- runtime collectors (scrape-time hooks) -----------------------------

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _collect_rss() -> None:
    try:
        with open("/proc/self/statm", "rb") as f:
            rss_pages = int(f.read().split()[1])
        metrics.process_rss_bytes.set(rss_pages * _PAGE)
    except (OSError, ValueError, IndexError):
        try:  # non-procfs fallback: peak RSS is better than nothing
            import resource

            metrics.process_rss_bytes.set(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
        except Exception:  # noqa: BLE001
            pass


def _collect_fds() -> None:
    try:
        metrics.process_open_fds.set(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass


def register_runtime_collectors(
        registry: metrics.Registry = metrics.REGISTRY) -> None:
    """Attach the process-level collectors to ``registry`` (idempotent
    per registry instance; the marker lives ON the object — an id()-
    keyed set would confuse a new registry reusing a dead one's
    address)."""
    if getattr(registry, "_runtime_collectors_attached", False):
        return
    registry._runtime_collectors_attached = True
    # spacecheck: ok=SC004 idempotence-guarded just above (attribute marker on the registry, PR-7 review fix)
    registry.add_collector(_collect_rss)
    registry.add_collector(_collect_fds)  # spacecheck: ok=SC004 same attribute-marker guard
