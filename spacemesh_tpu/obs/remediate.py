"""Remediation: typed, rate-limited, escalating recovery actions.

PR 7's health engine DETECTS — it flips ``/readyz``, fires
``SloBreach``/``ComponentHealth`` events and spools flight bundles —
and then nothing consumed those verdicts: a dead device backend
re-paid its failing dispatch on every batch forever, a shedding
verifyd service had no node-side failover, a wedged farm lane stayed
wedged until an operator noticed.  This module is the layer that ACTS
(the reference node is built the same way — Tortoise is literally
named "self-healing"):

* :class:`CircuitBreaker` — the generic closed → open → half-open →
  closed (or quarantined) state machine wrapped around the chronic
  retry-forever sites: the runtime engine's device-dispatch path
  (runtime/engine.py ``Pipeline(breaker=...)``), the farm's per-kind
  backends (verify/farm.py), and the verifyd failover client
  (verifyd/failover.py).  Zero sleeps: the clock is injectable and
  every decision is a pure function of ``(state, now)``.
* :func:`backoff_delay` — ONE capped, seeded-jitter backoff shared by
  the breaker's half-open probe timing and the verifyd client's
  ``retry_after_s`` honoring, so the two can never drift apart.
* :data:`BREAKERS` / :data:`ACTIONS` — process-global registries (the
  ``obs.health.HEALTH`` shape: one node per process, last-wins names,
  unregister-by-identity).  Breakers register so ``/debug/remediation``
  and flight-bundle manifests can report every breaker in the process,
  wherever it was constructed; components register their restart hooks
  beside their existing watchdogs so a policy verdict can reach them.
  Unregistering removes every per-component metric series
  (``metrics.remove_matching`` — the PR-12 cardinality pattern).
* :class:`RecoveryPolicy` rules — declarative ``health verdict →
  typed action`` mappings (``restart_component``, ``reset_farm_lanes``,
  ``quarantine_tenant``, ``failover_remote``, ``shed_and_alert``) with
  a per-component action budget: a flapping component exhausts its
  budget and ESCALATES to quarantine instead of restart-looping.
* :class:`RemediationEngine` — subscribes to the health engine's
  event-bus verdicts and executes policy.  Every decision is recorded
  four ways: a ``remediate.action`` span, the
  ``remediation_actions_total{component,action,outcome}`` counter, a
  :class:`~..node.events.RemediationAction` bus event, and the bounded
  action history served by ``/debug/remediation`` and embedded in
  flight-bundle manifests.

docs/SELF_HEALING.md is the operator guide (action vocabulary,
breaker tuning, the verifyd failover runbook).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import random
import time
from collections import deque
from typing import Callable, Optional

from ..utils import logging as slog
from ..utils import metrics, sanitize, tracing

_log = slog.get("remediate")

# --- breaker states (gauge encoding: remediation_breaker_state) ---------

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
QUARANTINED = "quarantined"

STATE_CODES = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0, QUARANTINED: 3.0}

# --- the typed action vocabulary ----------------------------------------

RESTART_COMPONENT = "restart_component"
RESET_FARM_LANES = "reset_farm_lanes"
QUARANTINE_TENANT = "quarantine_tenant"
FAILOVER_REMOTE = "failover_remote"
SHED_AND_ALERT = "shed_and_alert"
QUARANTINE_COMPONENT = "quarantine_component"

ACTION_KINDS = (RESTART_COMPONENT, RESET_FARM_LANES, QUARANTINE_TENANT,
                FAILOVER_REMOTE, SHED_AND_ALERT, QUARANTINE_COMPONENT)


class BreakerOpen(RuntimeError):
    """A call was refused because its circuit breaker is open.

    Call sites that have a fallback route there without paying the
    failing attempt; call sites without one surface this typed error
    instead of the underlying (long-dead) failure."""

    def __init__(self, component: str, retry_in_s: float | None = None):
        detail = (f"breaker {component!r} open"
                  + (f", retry in {retry_in_s:.3f}s"
                     if retry_in_s is not None else ""))
        super().__init__(detail)
        self.component = component
        self.retry_in_s = retry_in_s


def backoff_delay(attempt: int, *, base_s: float, cap_s: float,
                  retry_after_s: float | None = None,
                  seed: int = 0) -> float:
    """Capped exponential backoff with deterministic seeded jitter.

    The ONE timing rule shared by the verifyd client's shed retries and
    the breaker's reopen cooldown, so the two cannot drift: attempt
    ``k`` waits ``base * 2^k`` jittered into ``[0.5, 1.0)`` of itself,
    floored at the server's ``retry_after_s`` hint (retrying sooner
    than the server said is a wasted round trip), and capped at
    ``cap_s`` (a hint beyond the caller's patience is the caller's cue
    to give up BEFORE sleeping — see VerifydClient).  Deterministic:
    ``f(attempt, seed)`` — no wall clock, no global RNG.
    """
    raw = min(float(base_s) * (2.0 ** max(int(attempt), 0)), float(cap_s))
    jitter = random.Random((int(seed) << 20) ^ (attempt + 1)).random()
    delay = raw * (0.5 + 0.5 * jitter)
    if retry_after_s is not None:
        delay = max(delay, float(retry_after_s))
    return min(delay, float(cap_s))


class CircuitBreaker:
    """closed → open after ``failure_budget`` typed failures within
    ``window_s`` → half-open single probe after a cooldown → closed on
    probe success (or re-open with an escalated cooldown on failure);
    ``quarantine_after`` consecutive opens without a stable close
    escalate to QUARANTINED, which only :meth:`reset` leaves.

    Zero sleeps: ``time_source`` injects the clock and every transition
    happens inside :meth:`allow` / :meth:`record_failure` /
    :meth:`record_success`.  Thread-safe — the runtime engine consults
    it from pipeline threads while the event loop reads state docs.

    The reopen cooldown is :func:`backoff_delay` over the consecutive
    open count, floored at the peer's ``retry_after_s`` when the
    failure carried one (a shedding verifyd's hint drives exactly when
    the half-open probe goes out).
    """

    def __init__(self, component: str, *,
                 failure_budget: int = 5,
                 window_s: float = 30.0,
                 cooldown_s: float = 5.0,
                 cooldown_cap_s: float = 120.0,
                 quarantine_after: int = 0,
                 seed: int = 0,
                 time_source: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        self.component = str(component)
        self.failure_budget = max(int(failure_budget), 1)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_cap_s = float(cooldown_cap_s)
        self.quarantine_after = max(int(quarantine_after), 0)
        self.seed = int(seed)
        self._now = time_source
        self._on_transition = on_transition
        self.state = CLOSED
        self._failures: deque[float] = deque()
        self._opened_at: float | None = None
        self._retry_at: float | None = None
        self._open_streak = 0        # consecutive opens, reset on close
        self._probing = False
        self.opens = 0               # lifetime transitions into OPEN
        self.probes = 0              # half-open probes granted
        self._registered = False
        self._lock = sanitize.lock(f"remediate.breaker.{self.component}")

    # -- state machine --------------------------------------------------

    def _transition(self, to: str) -> None:
        # guarded by: self._lock — every caller holds it
        if to == self.state:
            return
        frm, self.state = self.state, to
        if self._registered:
            metrics.remediation_breaker_state.set(
                STATE_CODES[to], component=self.component)
            metrics.remediation_breaker_transitions.inc(
                component=self.component, to=to)
        if self._on_transition is not None:
            self._on_transition(frm, to)

    def allow(self, now: float | None = None) -> bool:
        """May an attempt go out right now?  CLOSED: yes.  OPEN: no
        until the cooldown elapses, then exactly ONE half-open probe.
        HALF_OPEN: no while that probe is unresolved.  QUARANTINED:
        never (manual :meth:`reset` only)."""
        t = self._now() if now is None else float(now)
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == QUARANTINED:
                return False
            if self.state == OPEN:
                if self._retry_at is not None and t >= self._retry_at:
                    self._transition(HALF_OPEN)
                    self._probing = True
                    self.probes += 1
                    return True
                return False
            # HALF_OPEN: the single probe is out; a second caller waits
            if not self._probing:
                self._probing = True
                self.probes += 1
                return True
            return False

    def record_success(self, now: float | None = None) -> None:
        with self._lock:
            if self.state in (HALF_OPEN, OPEN):
                _log.info("breaker %s: probe ok, closing", self.component)
            self._probing = False
            self._failures.clear()
            self._open_streak = 0
            self._retry_at = None
            self._transition(CLOSED)

    def record_failure(self, now: float | None = None,
                       retry_after_s: float | None = None) -> None:
        t = self._now() if now is None else float(now)
        with self._lock:
            if self.state == QUARANTINED:
                return
            if self.state in (HALF_OPEN, OPEN):
                # failed probe (or a straggler failing while open):
                # reopen with an ESCALATED cooldown
                self._probing = False
                self._open(t, retry_after_s)
                return
            self._failures.append(t)
            while self._failures and self._failures[0] < t - self.window_s:
                self._failures.popleft()
            if len(self._failures) >= self.failure_budget:
                self._open(t, retry_after_s)

    def _open(self, t: float, retry_after_s: float | None) -> None:
        # guarded by: self._lock — record_failure is the only caller
        self.opens += 1
        self._open_streak += 1
        if (self.quarantine_after
                and self._open_streak >= self.quarantine_after):
            _log.warning("breaker %s: %d consecutive opens, quarantining",
                         self.component, self._open_streak)
            self._transition(QUARANTINED)
            self._retry_at = None
            return
        cooldown = backoff_delay(self._open_streak - 1,
                                 base_s=self.cooldown_s,
                                 cap_s=self.cooldown_cap_s,
                                 retry_after_s=retry_after_s,
                                 seed=self.seed)
        self._opened_at = t
        self._retry_at = t + cooldown
        self._failures.clear()
        _log.warning("breaker %s: open (streak %d), half-open probe in "
                     "%.3fs", self.component, self._open_streak, cooldown)
        self._transition(OPEN)

    def abort_probe(self) -> None:
        """Release a granted probe slot WITHOUT a verdict — the attempt
        resolved in a way that says nothing about the peer's health (a
        config-class shed, a cancelled caller).  Every ``allow() ==
        True`` in HALF_OPEN must reach exactly one of
        record_success/record_failure/abort_probe, or the breaker wedges
        with the probe slot held and fast-fails forever."""
        with self._lock:
            self._probing = False

    def quarantine(self) -> None:
        """Force QUARANTINED (the engine's budget-exhausted escalation)."""
        with self._lock:
            self._retry_at = None
            self._probing = False
            self._transition(QUARANTINED)

    def reset(self) -> None:
        """Manual all-clear: back to CLOSED with a clean window."""
        with self._lock:
            self._failures.clear()
            self._open_streak = 0
            self._probing = False
            self._retry_at = None
            self._transition(CLOSED)

    # -- introspection --------------------------------------------------

    def retry_in(self, now: float | None = None) -> float | None:
        t = self._now() if now is None else float(now)
        with self._lock:
            if self.state != OPEN or self._retry_at is None:
                return None
            return max(self._retry_at - t, 0.0)

    def state_doc(self, now: float | None = None) -> dict:
        t = self._now() if now is None else float(now)
        with self._lock:
            return {
                "component": self.component,
                "state": self.state,
                "failures_in_window": len(self._failures),
                "failure_budget": self.failure_budget,
                "window_s": self.window_s,
                "open_streak": self._open_streak,
                "opens": self.opens,
                "probes": self.probes,
                "retry_in_s": (round(max(self._retry_at - t, 0.0), 6)
                               if self.state == OPEN
                               and self._retry_at is not None else None),
            }


class BreakerRegistry:
    """Every live breaker in the process, by component name (the
    ``HEALTH`` registry shape: last-wins names, unregister only removes
    the exact object, one node per process).  Registration owns the
    per-component ``/metrics`` series: ``unregister`` drops them via
    ``remove``/``remove_matching`` so a churn of short-lived components
    cannot grow the registry without bound."""

    def __init__(self) -> None:
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = sanitize.lock("remediate.breakers")
        self._shared = sanitize.SharedField("remediate.breakers.map")

    def register(self, breaker: CircuitBreaker) -> CircuitBreaker:
        with self._lock:
            self._shared.touch()
            prev = self._breakers.get(breaker.component)
            if prev is not None and prev is not breaker:
                # last-wins, like HEALTH: the DISPLACED breaker must
                # stop writing the (shared, name-keyed) metric series,
                # or two same-named breakers flap one gauge between
                # two unrelated components' states
                prev._registered = False
            self._breakers[breaker.component] = breaker
        breaker._registered = True
        metrics.remediation_breaker_state.set(
            STATE_CODES[breaker.state], component=breaker.component)
        return breaker

    def unregister(self, breaker: CircuitBreaker) -> None:
        """Stop ``breaker`` writing its series, and — only while its
        name still maps to it (a finished component must not evict its
        successor) — drop the per-component metric series too."""
        breaker._registered = False  # always: a gone breaker is silent
        with self._lock:
            self._shared.touch()
            if self._breakers.get(breaker.component) is not breaker:
                return  # displaced earlier: the successor owns the series
            del self._breakers[breaker.component]
        metrics.remediation_breaker_state.remove(
            component=breaker.component)
        metrics.remediation_breaker_transitions.remove_matching(
            component=breaker.component)

    def get(self, component: str) -> CircuitBreaker | None:
        with self._lock:
            self._shared.touch(write=False)
            return self._breakers.get(component)

    def names(self) -> list[str]:
        with self._lock:
            self._shared.touch(write=False)
            return sorted(self._breakers)

    def states(self) -> dict[str, str]:
        with self._lock:
            self._shared.touch(write=False)
            items = list(self._breakers.items())
        return {name: br.state for name, br in sorted(items)}

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            self._shared.touch(write=False)
            items = list(self._breakers.items())
        return {name: br.state_doc() for name, br in sorted(items)}


BREAKERS = BreakerRegistry()


class HookRegistry:
    """Per-component recovery hooks, registered beside the component's
    watchdog (post pipelines, the farm, the syncer, verifyd) and
    consumed by the engine when a policy rule fires.  ``register`` /
    ``unregister`` pair like health probes — spacecheck SC004 enforces
    it on package code."""

    def __init__(self) -> None:
        self._hooks: dict[tuple[str, str], Callable[[], object]] = {}
        self._lock = sanitize.lock("remediate.actions")
        self._shared = sanitize.SharedField("remediate.actions.map")

    def register(self, component: str, action: str,
                 hook: Callable[[], object]) -> None:
        with self._lock:
            self._shared.touch()
            self._hooks[(str(component), str(action))] = hook

    def unregister(self, component: str, action: str,
                   hook: Callable[[], object] | None = None) -> None:
        """Remove the hook — only if it still maps to ``hook`` when one
        is given (equality, not identity: bound methods rebuild)."""
        with self._lock:
            self._shared.touch()
            key = (str(component), str(action))
            if hook is None or self._hooks.get(key) == hook:
                self._hooks.pop(key, None)

    def get(self, component: str,
            action: str) -> Callable[[], object] | None:
        with self._lock:
            self._shared.touch(write=False)
            return self._hooks.get((str(component), str(action)))

    def names(self) -> list[tuple[str, str]]:
        with self._lock:
            self._shared.touch(write=False)
            return sorted(self._hooks)


ACTIONS = HookRegistry()


# --- declarative policy -------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RecoveryRule:
    """health verdict → action, with a budget and an escalation.

    ``component`` is an fnmatch pattern over component names (for
    ``trigger="unhealthy"``) or SLO names (``trigger="slo_breach"``).
    ``cooldown_s`` rate-limits the action per component; ``budget``
    bounds actions within ``window_s`` — the budget-exhausting verdict
    executes ``escalation`` instead (once), so a flapping component
    lands in quarantine rather than a restart storm.
    """

    component: str
    action: str
    trigger: str = "unhealthy"           # "unhealthy" | "slo_breach"
    budget: int = 3
    window_s: float = 600.0
    cooldown_s: float = 30.0
    escalation: str = QUARANTINE_COMPONENT

    def matches(self, name: str, trigger: str) -> bool:
        return (self.trigger == trigger
                and fnmatch.fnmatchcase(name, self.component))


def default_policy() -> list[RecoveryRule]:
    """The node's rule set (docs/SELF_HEALING.md documents each): wedged
    farm lanes reset, verifyd's drain path resets its farm lanes, a
    fleet replica that keeps tripping its breaker restarts then lands
    in quarantine (the router stops routing to it), a stalled syncer
    restarts, stalled POST pipelines restart, and any SLO breach
    sheds-and-alerts (flight bundle + event, no mutation).  Rule order
    matters (first match wins): ``verifyd.replica.*`` must precede the
    ``verifyd.*`` shard rule it would otherwise fall through to."""
    return [
        RecoveryRule(component="verify.farm", action=RESET_FARM_LANES,
                     budget=3, window_s=600.0, cooldown_s=60.0),
        # a fleet replica breaker (verifyd/fleet.py registers one per
        # replica as verifyd.replica.<name>): restart it; a flapper
        # that exhausts the budget gets quarantined, which the fleet
        # router treats as "never route here" until an operator acts
        RecoveryRule(component="verifyd.replica.*",
                     action=RESTART_COMPONENT, budget=3,
                     window_s=600.0, cooldown_s=60.0,
                     escalation=QUARANTINE_COMPONENT),
        RecoveryRule(component="verifyd", action=RESET_FARM_LANES,
                     budget=3, window_s=600.0, cooldown_s=60.0),
        # sharded in-process services (verifyd.<shard> — the fleet sim
        # and multi-replica single-host layouts) heal like verifyd
        RecoveryRule(component="verifyd.*", action=RESET_FARM_LANES,
                     budget=3, window_s=600.0, cooldown_s=60.0),
        RecoveryRule(component="sync", action=RESTART_COMPONENT,
                     budget=3, window_s=900.0, cooldown_s=120.0),
        RecoveryRule(component="post.*", action=RESTART_COMPONENT,
                     budget=2, window_s=600.0, cooldown_s=60.0),
        RecoveryRule(component="*", trigger="slo_breach",
                     action=SHED_AND_ALERT, budget=6, window_s=600.0,
                     cooldown_s=30.0, escalation=SHED_AND_ALERT),
    ]


# --- the engine ---------------------------------------------------------


class RemediationEngine:
    """Consume health verdicts, execute policy, record everything.

    Lifecycle: construct → :meth:`start` (subscribes to the event bus
    on the running loop) → :meth:`close` (SC004 pairs them).  The
    deterministic core is :meth:`handle_component` /
    :meth:`handle_slo` — tests and the sim drive those directly with an
    injected ``now``; the bus subscription is a thin production
    scheduler around them, exactly like HealthEngine.tick vs run.
    """

    def __init__(self, *, bus=None,
                 policy: list[RecoveryRule] | None = None,
                 hooks: HookRegistry = ACTIONS,
                 breakers: BreakerRegistry = BREAKERS,
                 history: int = 256,
                 time_source: Callable[[], float] = time.monotonic):
        self.bus = bus
        self.policy = list(policy) if policy is not None \
            else default_policy()
        self.hooks = hooks
        self.breakers = breakers
        self._now = time_source
        self.history: deque[dict] = deque(maxlen=max(int(history), 1))
        # per-component execution record: [(t, action), ...] pruned to
        # the widest rule window; quarantined components stop acting
        self._executed: dict[str, deque] = {}
        self._last_action: dict[str, float] = {}
        self._quarantined: set[str] = set()
        self._sub = None
        self._task = None
        self._closed = False
        self._lock = sanitize.lock("remediate.engine")

    # -- production scheduling ------------------------------------------

    def start(self) -> None:
        """Subscribe to ``ComponentHealth``/``SloBreach`` on the running
        loop (idempotent)."""
        if self._closed or self.bus is None or self._sub is not None:
            return
        import asyncio

        from ..node import events as events_mod

        self._sub = self.bus.subscribe(events_mod.ComponentHealth,
                                       events_mod.SloBreach, size=256)
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        import asyncio

        from ..node import events as events_mod

        try:
            while not self._closed:
                ev = await self._sub.next()
                if isinstance(ev, events_mod.ComponentHealth):
                    if ev.healthy:
                        self.note_recovered(ev.component)
                    else:
                        self.handle_component(ev.component, ev.reason)
                elif isinstance(ev, events_mod.SloBreach):
                    self.handle_slo(ev.slo, f"{ev.sli}={ev.value} "
                                            f"burn={ev.burn:.3f}")
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            try:
                self._task.cancel()
            except RuntimeError:  # loop already torn down
                pass
            self._task = None
        if self._sub is not None:
            self._sub.close()
            self._sub = None

    # -- the deterministic core -----------------------------------------

    def handle_component(self, component: str, reason: str = "",
                         now: float | None = None) -> dict | None:
        """An unhealthy component verdict: find the first matching rule
        and execute (or escalate/ratelimit).  Returns the action record
        (None when no rule matches)."""
        t = self._now() if now is None else float(now)
        for rule in self.policy:
            if rule.matches(component, "unhealthy"):
                return self._execute(component, rule, reason, t)
        return None

    def handle_slo(self, slo: str, reason: str = "",
                   now: float | None = None) -> dict | None:
        t = self._now() if now is None else float(now)
        for rule in self.policy:
            if rule.matches(slo, "slo_breach"):
                return self._execute(slo, rule, reason, t)
        return None

    def note_recovered(self, component: str) -> None:
        """A healthy verdict clears the action cooldown (a component
        that RECOVERED and broke again deserves a fresh action sooner
        than the rate limit), but not the windowed budget — flapping
        must still exhaust it and escalate."""
        with self._lock:
            self._last_action.pop(component, None)

    def _execute(self, component: str, rule: RecoveryRule, reason: str,
                 t: float) -> dict:
        # decide under the lock (budget/cooldown state), act and record
        # OUTSIDE it — a recovery hook may take arbitrarily long (or
        # raise), and must never serialize against snapshot readers
        with self._lock:
            if component in self._quarantined:
                return self._record(component, rule.action, "quarantined",
                                    reason, t, ran=False)
            last = self._last_action.get(component)
            if last is not None and t - last < rule.cooldown_s:
                return self._record(component, rule.action, "rate_limited",
                                    reason, t, ran=False)
            executed = self._executed.setdefault(component, deque())
            while executed and executed[0] < t - rule.window_s:
                executed.popleft()
            if len(executed) >= rule.budget:
                # budget exhausted: escalate ONCE instead of the action
                self._last_action[component] = t
                if rule.escalation == QUARANTINE_COMPONENT:
                    self._quarantined.add(component)
                    escalate = QUARANTINE_COMPONENT
                else:
                    escalate = rule.escalation
            else:
                executed.append(t)
                self._last_action[component] = t
                escalate = None
        if escalate == QUARANTINE_COMPONENT:
            br = self.breakers.get(component)
            if br is not None:
                br.quarantine()
            _log.warning(
                "remediation: %s exhausted its %s budget (%d/%.0fs), "
                "quarantined", component, rule.action, rule.budget,
                rule.window_s)
            return self._record(component, QUARANTINE_COMPONENT,
                                "escalated", reason, t, ran=True)
        if escalate is not None:
            return self._run_hook(component, escalate, "escalated",
                                  reason, t)
        return self._run_hook(component, rule.action, None, reason, t)

    def _run_hook(self, component: str, action: str,
                  forced_outcome: str | None, reason: str,
                  t: float) -> dict:
        hook = self.hooks.get(component, action)
        with tracing.span("remediate.action",
                          {"component": component, "action": action}
                          if tracing.is_enabled() else None):
            if hook is None:
                outcome = forced_outcome or "no_hook"
                ran = False
            else:
                try:
                    hook()
                    outcome = forced_outcome or "ok"
                    ran = True
                except Exception as exc:  # noqa: BLE001 — recorded, never propagates
                    _log.error("remediation hook %s/%s raised: %r",
                               component, action, exc)
                    outcome = "error"
                    ran = False
        return self._record(component, action, outcome, reason, t,
                            ran=ran)

    def _record(self, component: str, action: str, outcome: str,
                reason: str, t: float, *, ran: bool) -> dict:
        # lock-free: deque.append is atomic, the instruments and the
        # bus carry their own synchronization
        rec = {"t": round(t, 6), "component": component, "action": action,
               "outcome": outcome, "reason": reason, "ran": ran}
        self.history.append(rec)
        metrics.remediation_actions.inc(component=component,
                                        action=action, outcome=outcome)
        if outcome not in ("rate_limited",):
            _log.info("remediation: %s %s -> %s (%s)", component, action,
                      outcome, reason)
        if self.bus is not None:
            from ..node import events as events_mod

            self.bus.emit(events_mod.RemediationAction(
                component=component, action=action, outcome=outcome,
                detail=reason))
        return rec

    # -- introspection (/debug/remediation, flight manifests) ------------

    def budgets(self, now: float | None = None) -> dict:
        t = self._now() if now is None else float(now)
        out: dict[str, dict] = {}
        with self._lock:
            for component, executed in self._executed.items():
                rule = next((r for r in self.policy
                             if fnmatch.fnmatchcase(component,
                                                    r.component)), None)
                window = rule.window_s if rule is not None else 600.0
                used = sum(1 for ts in executed if ts >= t - window)
                out[component] = {
                    "used": used,
                    "budget": rule.budget if rule is not None else None,
                    "window_s": window,
                    "quarantined": component in self._quarantined,
                }
        return out

    def snapshot(self, now: float | None = None) -> dict:
        with self._lock:
            quarantined = sorted(self._quarantined)
        return {
            "breakers": self.breakers.snapshot(),
            "hooks": [list(k) for k in self.hooks.names()],
            "quarantined": quarantined,
            "budgets": self.budgets(now),
            "actions": list(self.history),
            "policy": [dataclasses.asdict(r) for r in self.policy],
        }
