"""Component health registry, stall watchdogs, declarative SLOs, and
the HealthEngine tick loop.

Liveness here is **progress, not heartbeats**: every pipeline already
exposes a monotonically advancing counter (the init fetch frontier, the
LabelWriter durable cursor, the prover's labels swept, the farm's
dispatched-item count, the syncer's processed layer). A
:class:`Watchdog` wraps one such counter with an activity predicate and
a deadline — "while there is work outstanding, the counter must advance
within N seconds" — which detects a wedged pipeline without a single
sleep and stays silent while a component is legitimately idle.

Probes register on the process-global :data:`HEALTH` registry (the same
shape as ``metrics.REGISTRY``): transient pipelines register on entry
and unregister on exit, long-lived components (the verify farm, the
syncer) register for their lifetime. ``unregister`` only removes the
exact probe object that was registered, so a closing component can
never evict its successor under the same name. Names are fixed and
registration is last-wins — like the metrics registry, the global
health registry models ONE node per process; a multi-App test cluster
blends into shared names (the last constructed farm owns
``verify.farm``), exactly as its /metrics series already blend.

The :class:`HealthEngine` ties it together: each ``tick(now)`` samples
the SLI window (obs/sli.py), evaluates every :class:`Slo` with
burn-rate accounting, runs every probe, publishes the verdicts as
metrics, emits EventBus events on transitions, logs breaches with the
current trace span id (utils/logging.py JSON mode), and hands
transitions to the flight recorder (obs/flight.py). ``tick`` is pure
with respect to time — ``now`` is injectable — so the whole engine is
testable (and CI-assertable) without one wall-clock sleep.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Callable, Optional

from ..utils import logging as slog
from ..utils import metrics, sanitize, tracing
from . import sli as sli_mod

_log = slog.get("health")

# Probe protocol: fn(now: float) -> (healthy: bool, reason: str)
Probe = Callable[[float], tuple[bool, str]]

DEFAULT_INTERVAL_S = 5.0
DEFAULT_STALL_DEADLINE_S = 30.0


# --- stall watchdogs ----------------------------------------------------


class Watchdog:
    """Progress-counter-not-advancing detection.

    ``progress()`` returns any value that changes while the component
    makes progress (usually a monotonically increasing count).
    ``active()`` gates the deadline: an idle component (no outstanding
    work) is healthy by definition. The first check after becoming
    active re-baselines, so a long-idle component is never accused of a
    stall it had no work to progress through.
    """

    def __init__(self, name: str, progress: Callable[[], object],
                 deadline_s: float = DEFAULT_STALL_DEADLINE_S,
                 active: Callable[[], bool] | None = None):
        self.name = name
        self.progress = progress
        self.deadline_s = float(deadline_s)
        self.active = active
        self._last_value: object = object()  # sentinel != any progress
        self._last_advance: float | None = None

    def check(self, now: float) -> tuple[bool, str]:
        try:
            if self.active is not None and not self.active():
                self._last_advance = None  # re-baseline on next activity
                return True, "idle"
            value = self.progress()
        except Exception as exc:  # noqa: BLE001 — a dead probe IS unhealthy
            return False, f"probe raised: {exc!r}"
        if value != self._last_value or self._last_advance is None:
            self._last_value = value
            self._last_advance = now
            return True, f"progress={value}"
        stalled_for = now - self._last_advance
        if stalled_for > self.deadline_s:
            return False, (f"stalled: progress={value} unchanged for "
                           f"{stalled_for:.1f}s (deadline "
                           f"{self.deadline_s:.1f}s)")
        return True, (f"progress={value} "
                      f"(quiet {stalled_for:.1f}s/{self.deadline_s:.1f}s)")


def writer_watchdog(writer, deadline_s: float = DEFAULT_STALL_DEADLINE_S
                    ) -> Watchdog:
    """The LabelWriter liveness contract: while writes are queued or in
    flight, bytes must keep moving — the FLUSHED cursor (contiguous
    bytes handed to the OS) advances per completed write, the DURABLE
    cursor (contiguous bytes *fsynced*) at checkpoint boundaries; either
    advancing counts as progress, so the interval between metadata
    checkpoints never reads as a stall, while a wedged disk shows up
    here before the bounded queue backpressures the whole init pipeline
    to a halt. A writer parked in the ENOSPC retry loop is DEGRADED,
    not stalled — that is ``store_probe``'s verdict, not this one's —
    so the watchdog stays quiet while the pool waits out a full disk.
    (Older writers without ``flushed()`` fall back to the durable
    cursor alone.)"""
    flushed = getattr(writer, "flushed", writer.durable)

    def progress():
        return (flushed(), writer.durable())

    def active():
        if getattr(writer, "degraded", lambda: None)():
            return False  # ENOSPC park: degraded is store_probe's call
        return writer.pending() > 0

    return Watchdog("post.writer", progress=progress,
                    deadline_s=deadline_s, active=active)


def store_probe(writer) -> Probe:
    """The ``post.store`` readiness probe: healthy while the label
    writer is not parked in ENOSPC degradation. Flipping /readyz (and
    never the process) is the whole point — a full disk pauses init,
    the operator frees space, init resumes (docs/CRASH_SAFETY.md)."""

    def probe(now: float) -> tuple[bool, str]:
        reason = writer.degraded()
        if reason:
            return False, f"degraded: {reason}"
        return True, "ok"

    return probe


# --- the component health registry --------------------------------------


class HealthRegistry:
    """Named liveness probes, reported together (``/readyz``)."""

    def __init__(self) -> None:
        self._probes: dict[str, Probe] = {}
        # probe map declared shared to the lockset sanitizer: pipelines
        # register/unregister from worker threads, the engine ticks
        # from the loop — every access must hold this lock
        self._lock = sanitize.lock("health.registry")
        self._shared = sanitize.SharedField("health.registry.probes")

    def register(self, name: str, probe: Probe) -> None:
        """Register (or replace) a component probe."""
        with self._lock:
            self._shared.touch()
            self._probes[name] = probe

    def unregister(self, name: str, probe: Probe | None = None) -> None:
        """Remove ``name`` — only if it still maps to ``probe`` when one
        is given (a finished pipeline must not evict its successor).
        Equality, not identity: bound methods are rebuilt per access."""
        with self._lock:
            self._shared.touch()
            if probe is None or self._probes.get(name) == probe:
                self._probes.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            self._shared.touch(write=False)
            return sorted(self._probes)

    def report(self, now: float | None = None) -> dict[str, dict]:
        """{component: {"healthy": bool, "reason": str}} for every
        registered probe. A raising probe reports unhealthy, never
        propagates."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            self._shared.touch(write=False)
            probes = list(self._probes.items())
        out: dict[str, dict] = {}
        for name, probe in probes:
            try:
                healthy, reason = probe(t)
            except Exception as exc:  # noqa: BLE001
                healthy, reason = False, f"probe raised: {exc!r}"
            out[name] = {"healthy": bool(healthy), "reason": reason}
        return out


HEALTH = HealthRegistry()


# --- declarative SLOs ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Slo:
    """target + window + burn budget over one SLI.

    The SLO is met while ``sli_value op target`` holds ("<=" for
    latency/lag ceilings, ">=" for throughput floors). Each engine tick
    marks the instant as violating or not; ``burn`` is the violating
    fraction of the trailing ``window_s``. The SLO **breaches** when
    burn exceeds ``budget`` (budget 0.0: the first violating tick
    breaches). An SLI with no data is *unknown*, which neither violates
    nor repairs — the burn window simply doesn't advance on it.
    """

    name: str
    sli: str                      # SliSpec.name this SLO constrains
    target: float
    op: str = "<="                # "<=" or ">="
    window_s: float = 300.0
    budget: float = 0.0           # allowed violating fraction, 0..1

    def violated(self, value: float) -> bool:
        if self.op == "<=":
            return value > self.target
        if self.op == ">=":
            return value < self.target
        raise ValueError(f"unknown SLO op {self.op!r}")


def default_slos() -> list[Slo]:
    return [
        Slo(name="layer_apply_latency", sli="layer_apply_p99",
            target=2.0, window_s=300.0, budget=0.1),
        Slo(name="farm_queue_wait", sli="farm_queue_wait_p99",
            target=0.25, window_s=120.0, budget=0.2),
        Slo(name="farm_dispatch_latency", sli="farm_dispatch_p99",
            target=5.0, window_s=300.0, budget=0.1),
        Slo(name="gossip_handler_latency", sli="gossip_handler_p99",
            target=1.0, window_s=300.0, budget=0.1),
        Slo(name="event_loop_lag", sli="event_loop_lag",
            target=0.5, window_s=120.0, budget=0.2),
    ]


def verifyd_slos() -> list[Slo]:
    """The verification service's SLO set (docs/VERIFYD.md): under
    overload the service SHEDS rather than queueing — so admitted
    BLOCK-lane work keeps a tight latency ceiling, and the aggregate
    p99 a looser one (tests/test_verifyd.py asserts the BLOCK SLO from
    windowed SLIs with injected time)."""
    return [
        Slo(name="verifyd_block_latency", sli="verifyd_request_block_p99",
            target=0.5, window_s=60.0, budget=0.1),
        Slo(name="verifyd_request_latency", sli="verifyd_request_p99",
            target=2.0, window_s=120.0, budget=0.2),
    ]


def fleet_slos() -> list[Slo]:
    """The verifyd fleet's SLO set (verifyd/fleet.py): what the NODE
    experienced end-to-end, whichever replica (or the local farm)
    served it.  The BLOCK-lane p99 mirrors the failover scenario's
    acceptance bar — a replica kill mid-load must NOT show up here
    (the sim's fleet scenario asserts this SLO green on the virtual
    clock); the aggregate p99 keeps a looser ceiling on the gossip and
    sync lanes' tail."""
    return [
        Slo(name="fleet_block_latency", sli="fleet_block_p99",
            target=0.25, window_s=60.0, budget=0.1),
        Slo(name="fleet_verify_latency", sli="fleet_verify_p99",
            target=2.0, window_s=120.0, budget=0.2),
    ]


class _SloState:
    __slots__ = ("marks", "breached", "burn")

    def __init__(self) -> None:
        self.marks: list[tuple[float, bool]] = []  # (t, violating)
        self.breached = False
        self.burn = 0.0


# --- the engine ---------------------------------------------------------


class HealthEngine:
    """One tick loop: SLIs -> SLOs -> probes -> metrics/events/flight.

    Everything time-dependent takes an explicit ``now`` so tests and the
    CI health-smoke job drive the engine deterministically; the async
    ``run()`` loop is a thin production scheduler around ``tick()`` that
    doubles as the event-loop-lag measurement point.
    """

    def __init__(self, *,
                 registry: metrics.Registry = metrics.REGISTRY,
                 health: HealthRegistry = HEALTH,
                 bus=None,
                 slis=None,
                 slos=None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 window_s: float = sli_mod.DEFAULT_WINDOW_S,
                 spool_dir=None,
                 time_source: Callable[[], float] = time.monotonic):
        from . import flight as flight_mod

        self.health = health
        self.bus = bus
        self.interval_s = float(interval_s)
        self.time_source = time_source
        self.slis = list(slis) if slis is not None \
            else sli_mod.default_slis()
        self.slos = list(slos) if slos is not None else default_slos()
        self.sampler = sli_mod.SliSampler(registry, window_s=window_s)
        sli_mod.register_runtime_collectors(registry)
        self.recorder = (flight_mod.FlightRecorder(
            spool_dir, registry=registry, time_source=time_source)
            if spool_dir is not None else None)
        self._slo_state = {s.name: _SloState() for s in self.slos}
        # an attached RemediationEngine (obs/remediate.py): its snapshot
        # rides into every flight-bundle manifest so the bundle records
        # what the node was already doing about the breach
        self.remediation = None
        self._component_state: dict[str, bool] = {}
        self._last_tick: float | None = None
        self._last_loop_tick: float | None = None
        self._loop_started_at: float | None = None
        self._last_report: dict = {}
        self._pending_dump: tuple | None = None
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        self._lock = sanitize.lock("health.engine")
        self._shared_dump = sanitize.SharedField("health.engine.pending_dump")

    # --- one evaluation ------------------------------------------------

    def tick(self, now: float | None = None, *,
             defer_dump: bool = False) -> dict:
        """Sample, evaluate, publish. Returns the readiness report the
        HTTP surface serves (see docs/OBSERVABILITY.md for the shape).

        A breach/stall transition queues a flight dump; by default it is
        written before returning. Async callers (the run loop, the HTTP
        handlers) pass ``defer_dump=True`` and flush via
        ``asyncio.to_thread(self.flush_dump)`` so serializing a 64k-span
        trace ring never blocks the event loop at exactly the moment the
        node is unhealthy."""
        t = self.time_source() if now is None else float(now)
        with self._lock:
            report = self._tick_locked(t)
        if not defer_dump:
            self.flush_dump()
        return report

    def flush_dump(self) -> None:
        """Write the dump queued by the last tick, if any. Touches only
        the recorder/registry/tracer (all thread-safe) — safe from a
        worker thread. The handoff swap happens under the engine lock:
        a tick queueing a new dump must never race a flusher into
        overwriting it with None unwritten."""
        with self._lock:
            self._shared_dump.touch()
            pending, self._pending_dump = self._pending_dump, None
        if pending is None or self.recorder is None:
            return
        reason, t, report, events = pending
        self.recorder.dump(reason, now=t, health=report, events=events,
                           remediation=self._remediation_doc())

    # guarded by: self._lock — tick() is the only caller and enters with the engine lock held
    def _tick_locked(self, t: float) -> dict:
        with tracing.span("health.tick"):
            self.sampler.sample(t)
            values = self.sampler.values(self.slis)
            new_breaches: list[str] = []
            slo_doc: dict[str, dict] = {}
            for slo in self.slos:
                state = self._slo_state[slo.name]
                value = values.get(slo.sli)
                # an unknown SLI (None) neither violates nor repairs —
                # but it must TERMINATE the previous mark's interval, or
                # one violating tick followed by idleness would keep
                # accruing burn with zero observations
                state.marks.append(
                    (t, slo.violated(value) if value is not None
                     else None))
                state.marks = [(mt, v) for mt, v in state.marks
                               if mt >= t - slo.window_s]
                state.burn = self._burn(state.marks, slo.window_s, t)
                breached = (state.burn > slo.budget
                            or (slo.budget == 0.0 and bool(state.marks)
                                and state.marks[-1][1] is True))
                if breached and not state.breached:
                    new_breaches.append(slo.name)
                    metrics.slo_breaches.inc(slo=slo.name)
                    _log.warning(
                        "SLO breach: %s (%s=%s, target %s %s, burn "
                        "%.3f > budget %.3f)", slo.name, slo.sli, value,
                        slo.op, slo.target, state.burn, slo.budget)
                    if self.bus is not None:
                        from ..node import events as events_mod

                        self.bus.emit(events_mod.SloBreach(
                            slo=slo.name, sli=slo.sli,
                            value=value if value is not None else -1.0,
                            target=slo.target, burn=state.burn))
                elif not breached and state.breached:
                    _log.info("SLO recovered: %s (burn %.3f)", slo.name,
                              state.burn)
                state.breached = breached
                metrics.slo_healthy.set(0.0 if breached else 1.0,
                                        slo=slo.name)
                metrics.slo_burn.set(state.burn, slo=slo.name)
                slo_doc[slo.name] = {
                    "sli": slo.sli, "value": value, "target": slo.target,
                    "op": slo.op, "window_s": slo.window_s,
                    "budget": slo.budget, "burn": round(state.burn, 4),
                    "breached": breached,
                }
            components = self.health.report(t)
            new_stalls: list[str] = []
            for name, ent in components.items():
                was = self._component_state.get(name, True)
                metrics.component_healthy.set(
                    1.0 if ent["healthy"] else 0.0, component=name)
                if was and not ent["healthy"]:
                    new_stalls.append(name)
                    metrics.component_stalls.inc(component=name)
                    _log.warning("component unhealthy: %s — %s", name,
                                 ent["reason"])
                elif ent["healthy"] and not was:
                    _log.info("component recovered: %s", name)
                if ent["healthy"] != was and self.bus is not None:
                    from ..node import events as events_mod

                    self.bus.emit(events_mod.ComponentHealth(
                        component=name, healthy=ent["healthy"],
                        reason=ent["reason"]))
                self._component_state[name] = ent["healthy"]
            # probes that unregistered since the last tick must not pin
            # a stale verdict — in the report OR the /metrics series
            for gone in set(self._component_state) - set(components):
                del self._component_state[gone]
                metrics.component_healthy.remove(component=gone)
            self._last_tick = t
            report = {
                "ready": all(e["healthy"] for e in components.values()),
                "components": components,
                "slos": slo_doc,
                "slis": {k: v for k, v in values.items()
                         if v is not None},
            }
            self._last_report = report
            if self.recorder is not None and (new_breaches or new_stalls):
                reason = ";".join([f"slo:{n}" for n in new_breaches]
                                  + [f"stall:{n}" for n in new_stalls])
                self._shared_dump.touch()
                self._pending_dump = (reason, t, report,
                                      self._recent_events())
            return report

    @staticmethod
    def _burn(marks, window_s: float, now: float) -> float:
        """Violating fraction of the window: each mark owns the interval
        until the next mark (the last one until ``now``). Marks with an
        unknown verdict (None) own their interval without charging it."""
        if not marks:
            return 0.0
        violating = 0.0
        for (t0, v), (t1, _) in zip(marks, marks[1:]):
            if v is True:
                violating += t1 - t0
        if marks[-1][1] is True:
            violating += max(now - marks[-1][0], 0.0)
        return min(violating / window_s, 1.0)

    def _recent_events(self):
        bus = self.bus
        if bus is None or not hasattr(bus, "recent"):
            return []
        return list(bus.recent)

    # --- serving state -------------------------------------------------

    def report(self, now: float | None = None, *,
               defer_dump: bool = False) -> dict:
        """A fresh evaluation."""
        return self.tick(now, defer_dump=defer_dump)

    def current_report(self, now: float | None = None) -> dict:
        """What ``/readyz`` serves: the background loop's latest report
        while the loop is alive and recent — a 1 Hz readiness prober
        must not grow the sampler window by one full-registry snapshot
        per poll. Loop-less embedders (and a stale loop) evaluate fresh
        (dump deferred; the HTTP handler flushes it off-loop)."""
        t = self.time_source() if now is None else float(now)
        if (self._last_loop_tick is not None and self._last_report
                and t - self._last_loop_tick < 2 * self.interval_s):
            return self._last_report
        return self.tick(t, defer_dump=True)

    def live(self, now: float | None = None) -> bool:
        """Liveness: the tick loop is not wedged. Once ``run()`` has
        started, only the LOOP's own ticks count — request-driven
        ``/readyz`` evaluations must not mask a dead background task.
        Embedders that never start the loop fall back to any-tick
        recency (manual-tick test drivers), and True before the first
        tick."""
        t = self.time_source() if now is None else float(now)
        budget = 3 * self.interval_s + 1.0
        if self._loop_started_at is not None:
            if (self._task is not None and self._task.done()
                    and not self._closed):
                return False  # the run() task died
            ref = (self._last_loop_tick
                   if self._last_loop_tick is not None
                   else self._loop_started_at)
            return t - ref < budget
        if self._last_tick is None:
            return True
        return t - self._last_tick < budget

    def dump_flight(self, reason: str = "manual") -> Optional[str]:
        """Write a flight bundle NOW, bypassing the rate limit (the
        ``/debug/flight`` handler). None when no spool dir is set."""
        if self.recorder is None:
            return None
        path = self.recorder.dump(reason, now=self.time_source(),
                                  health=self._last_report or None,
                                  events=self._recent_events(),
                                  remediation=self._remediation_doc(),
                                  force=True)
        return str(path) if path is not None else None

    def _remediation_doc(self) -> dict | None:
        """The attached remediation engine's snapshot (None lets the
        recorder fall back to the global breaker registry alone)."""
        if self.remediation is None:
            return None
        try:
            return self.remediation.snapshot()
        except Exception:  # noqa: BLE001 — a bundle beats a perfect bundle
            return None

    # --- production scheduling ----------------------------------------

    def ensure_running(self, interval_s: float | None = None) -> None:
        """Start the tick loop on the current running event loop
        (idempotent; a dead task is replaced)."""
        if self._closed:
            return
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self.run(interval_s), )

    async def run(self, interval_s: float | None = None) -> None:
        """Tick every ``interval_s``, measuring asyncio scheduling lag
        as the drift between the requested and actual wake-up — the
        only honest place to observe event-loop health from."""
        interval = float(interval_s or self.interval_s)
        loop = asyncio.get_running_loop()
        self._loop_started_at = self.time_source()
        try:
            while not self._closed:
                # spacecheck: ok=SC001 measuring the LOOP's own scheduling lag is the point; the loop clock is the only honest reference
                target = loop.time() + interval
                await asyncio.sleep(interval)
                lag = max(loop.time() - target, 0.0)  # spacecheck: ok=SC001 same loop-lag measurement
                metrics.event_loop_lag.set(lag)
                self.tick(defer_dump=True)
                self._last_loop_tick = self.time_source()
                # bundle serialization (64k-span ring + full exposition)
                # happens off the loop
                await asyncio.to_thread(self.flush_dump)
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        self._closed = True
        if self._task is not None:
            try:
                self._task.cancel()
            except RuntimeError:  # loop already torn down
                pass
            self._task = None
