"""Fleet-wide observability: the cross-process collection plane.

PRs 17–19 made the system multi-process — verifyd replica fleets,
sharded sim workers, subprocess bench probes — and left each process
with its own span ring and metrics registry. This module is the parent
side of the federation (docs/OBSERVABILITY.md § Fleet observability):

* **Metrics**: children ship full registry snapshots
  (``Registry.sample()`` over a pipe, or Prometheus exposition text
  over HTTP) and the parent re-exposes every series under a ``proc=``
  label with strict cardinality hygiene — ``FEDERATION.drop(proc)``
  removes a process's entire snapshot the moment it exits or
  unregisters (the PR-12 ``remove_matching`` discipline at the
  federation layer), while a CRASHED process's last snapshot is
  retained and flagged so its final counters survive for forensics.
* **Traces**: capture documents collected here feed
  ``tracing.merge_captures()`` into one validated timeline; the
  per-proc trace+metrics pairs also land in flight bundles' ``procs/``
  subdir (obs/flight.py).

The exposition parser is the STRICT escape-aware one: label values in
the wild carry quotes, backslashes and newlines (peer ids, error
reasons), and a sloppy regex split corrupts exactly the scrape you
need during an incident. It was born in tests/test_http_debug.py and
is promoted here because federation makes it production input.
"""

from __future__ import annotations

import re
import threading

from ..utils.metrics import _escape, federated_procs

# metric line: name, optional {labels}, value. Labels are parsed
# separately because escaped quotes make a single regex fragile.
_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"      # metric name
    r"(?:\{(.*)\})?"                    # optional label block
    r" (-?(?:[0-9.eE+-]+|inf|nan))$")   # value


def _parse_labels(s: str) -> dict:
    """Parse a Prometheus label block honoring ``\\\\``, ``\\"`` and
    ``\\n`` escapes inside quoted values. Raises ValueError on any
    malformed input — federation must not guess at a corrupt scrape."""
    labels: dict[str, str] = {}
    i, n = 0, len(s)
    while i < n:
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', s[i:])
        if not m:
            raise ValueError(f"bad label at {s[i:]!r}")
        name = m.group(1)
        i += m.end()
        out = []
        while i < n:
            c = s[i]
            if c == "\\":
                if i + 1 >= n:
                    raise ValueError("dangling escape")
                nxt = s[i + 1]
                out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                out.append(c)
                i += 1
        else:
            raise ValueError("unterminated label value")
        labels[name] = "".join(out)
        if i < n and s[i] == ",":
            i += 1
    return labels


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Parse Prometheus text exposition into (name, labels, value)
    triples. Strict: any non-comment line that does not parse raises
    (a silent skip would hide exactly the series being tested)."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if not m:
            raise ValueError(f"unparseable metric line: {line!r}")
        name, labelblock, value = m.groups()
        labels = _parse_labels(labelblock) if labelblock else {}
        out.append((name, labels, float(value)))
    return out


def flatten_samples(samples: dict) -> list[tuple[str, dict, float]]:
    """Flatten a ``Registry.sample()`` document into exposition-shaped
    (name, labels, value) triples — histograms expand to their
    ``_bucket``/``_sum``/``_count`` series, exactly what ``expose()``
    would have printed, so pipe-shipped (pickled sample) and
    HTTP-shipped (parsed exposition) snapshots federate identically."""
    out: list[tuple[str, dict, float]] = []
    for name, (kind, data) in sorted(samples.items()):
        if kind == "histogram":
            buckets = data["buckets"]
            for labelset, (counts, sum_, count) in sorted(
                    data["series"].items()):
                labels = dict(labelset)
                for b, c in zip(buckets, counts):
                    le = "+Inf" if b == float("inf") else str(b)
                    out.append((f"{name}_bucket",
                                {**labels, "le": le}, float(c)))
                out.append((f"{name}_sum", labels, float(sum_)))
                out.append((f"{name}_count", labels, float(count)))
        else:
            for labelset, v in sorted(data.items()):
                out.append((name, dict(labelset), float(v)))
    return out


class Federation:
    """Per-process metric snapshots + trace captures, re-exposed with
    ``proc=`` provenance. One module instance (``FEDERATION``) serves
    the parent process; tests may build private ones."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # proc -> {"series": [(name, labels, value)], "crashed": bool,
        #          "trace": export doc | None}
        self._procs: dict[str, dict] = {}

    def _gauge(self) -> None:
        # caller holds self._lock
        crashed = sum(1 for e in self._procs.values() if e["crashed"])
        federated_procs.set(float(len(self._procs) - crashed),
                            state="live")
        federated_procs.set(float(crashed), state="crashed")

    # --- ingestion ----------------------------------------------------

    def update(self, proc: str, series, trace: dict | None = None) -> None:
        """Replace ``proc``'s snapshot with (name, labels, value)
        triples (and optionally its latest trace capture). A re-update
        clears any crash flag — the process is evidently alive."""
        series = [(str(n), dict(l), float(v)) for n, l, v in series]
        with self._lock:
            ent = self._procs.setdefault(
                proc, {"series": [], "crashed": False, "trace": None})
            ent["series"] = series
            ent["crashed"] = False
            if trace is not None:
                ent["trace"] = trace
            self._gauge()

    def update_from_samples(self, proc: str, samples: dict,
                            trace: dict | None = None) -> None:
        """Ingest a ``Registry.sample()`` document (the pipe-shipped
        form the sim shard workers send at finalize)."""
        self.update(proc, flatten_samples(samples), trace=trace)

    def parse_and_update(self, proc: str, text: str,
                         trace: dict | None = None) -> int:
        """Ingest Prometheus exposition text (the HTTP-pulled form from
        verifyd replicas). Returns the number of series ingested."""
        series = parse_exposition(text)
        self.update(proc, series, trace=trace)
        return len(series)

    # --- lifecycle / cardinality hygiene ------------------------------

    def drop(self, proc: str) -> bool:
        """Remove EVERYTHING federated for ``proc`` — called when a
        worker exits cleanly or a replica unregisters. This is the
        federation-layer remove_matching: after drop, zero ``proc=``
        series for that process survive on any scrape."""
        with self._lock:
            gone = self._procs.pop(proc, None) is not None
            self._gauge()
            return gone

    def mark_crashed(self, proc: str) -> None:
        """Flag ``proc`` crashed but RETAIN its last snapshot: the dead
        worker's final counters and spans are exactly the forensics a
        ShardWorkerCrash report needs."""
        with self._lock:
            ent = self._procs.get(proc)
            if ent is not None:
                ent["crashed"] = True
            self._gauge()

    def clear(self) -> None:
        with self._lock:
            self._procs.clear()
            self._gauge()

    # --- read side ----------------------------------------------------

    def procs(self) -> dict[str, dict]:
        """{proc: {"crashed", "series"(count), "trace"(bool)}} summary."""
        with self._lock:
            return {p: {"crashed": e["crashed"],
                        "series": len(e["series"]),
                        "trace": e["trace"] is not None}
                    for p, e in self._procs.items()}

    def series(self, proc: str) -> list[tuple[str, dict, float]]:
        with self._lock:
            ent = self._procs.get(proc)
            return list(ent["series"]) if ent else []

    def trace(self, proc: str) -> dict | None:
        with self._lock:
            ent = self._procs.get(proc)
            return ent["trace"] if ent else None

    def captures(self) -> dict[str, dict]:
        """{proc: trace export doc} for every proc that shipped one —
        the input half of ``tracing.merge_captures()``."""
        with self._lock:
            return {p: e["trace"] for p, e in self._procs.items()
                    if e["trace"] is not None}

    def flight_procs(self) -> dict[str, dict]:
        """Per-proc payloads for a flight bundle's ``procs/`` subdir:
        {proc: {"trace": doc|None, "metrics": exposition text,
        "crashed": bool}}."""
        with self._lock:
            items = [(p, dict(e)) for p, e in self._procs.items()]
        return {p: {"trace": e["trace"],
                    "metrics": self._expose_proc(p, e),
                    "crashed": e["crashed"]}
                for p, e in items}

    @staticmethod
    def _expose_proc(proc: str, ent: dict) -> str:
        lines = []
        for name, labels, value in ent["series"]:
            merged = {"proc": proc, **labels}
            lbl = ",".join(f'{k}="{_escape(v)}"'
                           for k, v in sorted(merged.items()))
            lines.append(f"{name}{{{lbl}}} {value}")
        return "\n".join(lines) + ("\n" if lines else "")

    def expose(self) -> str:
        """Every federated series as exposition text, each line under
        its origin's ``proc=`` label, deterministically ordered; a
        ``federated_proc_crashed`` marker series flags retained
        snapshots of dead processes. The HTTP ``/metrics`` handlers
        append this after the local registry's exposition."""
        with self._lock:
            items = sorted(self._procs.items())
        lines: list[str] = []
        for proc, ent in items:
            if ent["crashed"]:
                lines.append(
                    f'federated_proc_crashed{{proc="{_escape(proc)}"}} 1')
            chunk = self._expose_proc(proc, ent)
            if chunk:
                lines.append(chunk.rstrip("\n"))
        return "\n".join(lines) + ("\n" if lines else "")

    def merged_capture(self, parent: dict | None = None) -> dict | None:
        """Merge the parent's capture (if given) with every federated
        child capture into one timeline; None when nothing federated
        and no parent given."""
        from ..utils import tracing

        captures = [] if parent is None else [parent]
        captures.extend(doc for _, doc in sorted(self.captures().items()))
        if not captures:
            return None
        return tracing.merge_captures(captures)


FEDERATION = Federation()
