"""spacemesh_tpu — a TPU-native proof-of-space-time framework.

A brand-new framework with the capabilities of spacemeshos/go-spacemesh
(reference at /root/reference): layered-mesh blockchain node with Hare and
Tortoise consensus, randomness beacon, gossip/sync networking, deterministic
account-template VM, and a POST (proof of space-time) compute plane that runs
on TPUs via JAX/XLA/Pallas instead of the reference's CGo/OpenCL/RandomX
native stack.

Package map (mirrors SURVEY.md §2's component inventory):

- ``ops/``        TPU compute kernels: scrypt labeler (SHA-256, Salsa20/8,
                  ROMix in JAX + Pallas), ChaCha-based proving hash, k2pow,
                  batch verification primitives.
- ``models/``     POST pipeline compositions: the labeler (init), prover
                  (nonce search) and verifier as jittable "models".
- ``parallel/``   Device-mesh sharding helpers (jax.sharding / shard_map),
                  multi-identity data-parallel init.
- ``post/``       The POST worker: disk streaming with resume, the
                  PostService contract (node <-> worker seam).
- ``core/``       Primitives: domain types, canonical codec, hashing
                  (blake3), ed25519 + VRF signing.
- ``storage/``    SQLite persistence (statesql/localsql split, migrations),
                  cached DB and in-RAM ATX cache.
- ``consensus/``  Beacon, Hare, Tortoise, block certifier, malfeasance.
- ``vm/``         Deterministic account-template VM (wallet, multisig,
                  vesting, vault).
- ``txs/``        Conservative state / mempool.
- ``p2p/``        Gossip + request/response networking, fetch, sync.
- ``node/``       Composition root: config, presets, clock, events, app.
- ``api/``        gRPC-style API services and event streams.
- ``utils/``      Small shared helpers.
"""

__version__ = "0.1.0"
