"""Accelerator liveness probing + CPU fallback, shared by every
operator entry point (bench.py, tools/profiler.py).

A wedged TPU tunnel hangs ``jax.devices()`` forever, and the container's
sitecustomize imports jax at interpreter start — so by the time any main()
runs, setting JAX_PLATFORMS in the environment alone is too late: the
config update is what actually takes effect in-process, the env var only
covers subprocesses. One helper owns that whole sequence so tunnel
handling cannot drift between tools (code-review r5: bench.py and
profiler.py had diverging copies, one missing the config update)."""

from __future__ import annotations

import os
import subprocess
import sys

PROBE_TIMEOUT_S = 120


def accelerator_reachable(timeout_s: int = PROBE_TIMEOUT_S) -> bool:
    """``jax.devices()`` in a SUBPROCESS with a hard timeout."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def force_cpu_platform() -> None:
    """Pin this process (config update) AND its children (env var) to
    the CPU platform."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_usable_platform(timeout_s: int = PROBE_TIMEOUT_S) -> bool:
    """Probe the accelerator; fall back to CPU when it is unreachable.
    Returns True when the accelerator answered (no fallback)."""
    if accelerator_reachable(timeout_s):
        return True
    force_cpu_platform()
    return False
