"""Accelerator liveness probing + CPU fallback, shared by every
operator entry point (bench.py, tools/profiler.py).

A wedged TPU tunnel hangs ``jax.devices()`` forever, and the container's
sitecustomize imports jax at interpreter start — so by the time any main()
runs, setting JAX_PLATFORMS in the environment alone is too late: the
config update is what actually takes effect in-process, the env var only
covers subprocesses. One helper owns that whole sequence so tunnel
handling cannot drift between tools (code-review r5: bench.py and
profiler.py had diverging copies, one missing the config update)."""

from __future__ import annotations

import os
import subprocess
import sys

PROBE_TIMEOUT_S = 120

# Per-machine cache root: the XLA compile cache lives here, and the ROMix
# kernel autotuner (ops/autotune.py) persists its raced winners beside it
# (romix_autotune.json) so one SPACEMESH_JAX_CACHE override moves both.
DEFAULT_CACHE_DIR = "~/.cache/spacemesh_tpu/jax_cache"
_cache_enabled: str | None = None


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a per-machine directory.

    The labeler pays 17-26s of XLA compile per (batch, N) shape; the cache
    makes that a once-per-machine cost — a second bench/init run on the
    same host deserializes the executable in well under a second. Knob:
    ``SPACEMESH_JAX_CACHE`` (a directory, or ``off``/``0`` to disable);
    an explicit ``path`` argument wins. Idempotent; returns the directory
    in effect (None when disabled)."""
    global _cache_enabled
    env = os.environ.get("SPACEMESH_JAX_CACHE")
    if path is None and env in ("0", "off", "none"):
        return None
    dir_ = os.path.expanduser(path or env or DEFAULT_CACHE_DIR)
    if _cache_enabled == dir_:
        return dir_
    try:
        os.makedirs(dir_, exist_ok=True)
    except OSError as e:
        # the cache is an optimization: an unwritable HOME (read-only
        # container, sandboxed CI) must not break init/bench/tests
        print(f"persistent compile cache disabled ({e})", file=sys.stderr)
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", dir_)
    # the tiny per-test compiles are worth caching too — loading beats
    # recompiling well below the 1s default threshold
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    _cache_enabled = dir_
    return dir_


DEFAULT_HOST_DEVICES = 8  # the autotuner's raced mesh grid is {1,2,4,8}


def ensure_host_devices(count: int | None = None) -> int:
    """Expose ``count`` virtual CPU devices (XLA_FLAGS, this process AND
    children) so the CPU fallback can lane-shard label batches across
    them (parallel/mesh.py; the autotuner races whether/how many win —
    ops/autotune.py mesh dimension).

    Must run BEFORE the first backend use — the flag is read when the
    CPU client is instantiated; afterwards it is inert (harmless). A
    pre-existing ``xla_force_host_platform_device_count`` flag (tests'
    conftest, the driver entry) is respected, as is
    ``SPACEMESH_HOST_DEVICES`` (0/off disables). Oversubscription is
    deliberate: more virtual devices than cores still wins on the
    op-dispatch-bound label kernel (sequential per-device streams beat
    one device's intra-op parallelism), and the race decides per host
    how many to actually use. Returns the count in effect."""
    env = os.environ.get("SPACEMESH_HOST_DEVICES")
    if env is not None and env.lower() in ("0", "off", "none"):
        return 1
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        for part in flags.split():
            if "xla_force_host_platform_device_count" in part:
                try:
                    return int(part.split("=", 1)[1])
                except (IndexError, ValueError):
                    return 1
        return 1
    try:
        n = count if count is not None else int(env or DEFAULT_HOST_DEVICES)
    except ValueError:
        raise ValueError(
            f"SPACEMESH_HOST_DEVICES={env!r}: expected a device count "
            "or 0/off")
    if n <= 1:
        return 1
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()
    return n


def accelerator_reachable(timeout_s: int = PROBE_TIMEOUT_S) -> bool:
    """``jax.devices()`` in a SUBPROCESS with a hard timeout."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def force_cpu_platform() -> None:
    """Pin this process (config update) AND its children (env var) to
    the CPU platform."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_usable_platform(timeout_s: int = PROBE_TIMEOUT_S) -> bool:
    """Probe the accelerator; fall back to CPU when it is unreachable.
    Returns True when the accelerator answered (no fallback)."""
    if accelerator_reachable(timeout_s):
        return True
    force_cpu_platform()
    return False
