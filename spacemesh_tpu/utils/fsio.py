"""Crash-safe persistence primitives: tmp + fsync + rename + dir-fsync.

Every "write a file that must survive a power cut" site in the tree
routes through here (spacecheck rule SC009 enforces it).  The naive
idiom — write a tmp file, ``os.replace`` it over the destination — is
atomic against concurrent *readers* but not against power loss: the
rename can reach the directory before the tmp file's bytes reach the
platter, leaving a correctly-named file full of zeros (or a truncated
tail) after reboot.  Worse, most callers treat an unparseable cache as
"empty, re-derive" — so the corruption is silently *absorbed* and days
of autotune/batchtune measurements or POST resume state vanish without
a log line.  The durable sequence is:

    1. write the payload to ``<dst>.tmp.<pid>``;
    2. ``fsync`` the tmp file (bytes durable under the tmp name);
    3. ``os.replace`` tmp -> dst (atomic name swap);
    4. ``fsync`` the parent directory (the name swap durable).

Every function takes an optional ``fs`` — an object with the os-shaped
primitive methods of :class:`RealFS` — so the deterministic disk-fault
shim (post/faultfs.py) can inject EIO/ENOSPC/torn-write/power-cut
faults at exact operation counts underneath unmodified callers.

Stdlib-only on purpose: the spacecheck analyzer persists its findings
cache through this module and must run before dependency install.
"""

from __future__ import annotations

import os
from pathlib import Path

TMP_MARK = ".tmp."


class RealFS:
    """The os-backed primitive set. One method per syscall so a shim
    can intercept, count, and fault each operation individually."""

    def open(self, path, flags: int, mode: int = 0o644) -> int:
        return os.open(str(path), flags, mode)

    def close(self, fd: int) -> None:
        os.close(fd)

    def pread(self, fd: int, n: int, offset: int) -> bytes:
        return os.pread(fd, n, offset)

    def pwrite(self, fd: int, data, offset: int) -> int:
        return os.pwrite(fd, data, offset)

    def fsync(self, fd: int) -> None:
        os.fsync(fd)

    def replace(self, src, dst) -> None:
        os.replace(str(src), str(dst))  # spacecheck: ok=SC009 this IS the fsync-bracketed primitive every other site routes through

    def truncate(self, path, length: int) -> None:
        os.truncate(str(path), length)

    def unlink(self, path) -> None:
        os.unlink(str(path))

    def fsync_dir(self, path) -> None:
        fd = os.open(str(path), os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    # convenience passthroughs (never faulted: metadata queries only)

    def exists(self, path) -> bool:
        return os.path.exists(str(path))

    def getsize(self, path) -> int:
        return os.path.getsize(str(path))


REAL = RealFS()


def _resolve(fs) -> RealFS:
    return REAL if fs is None else fs


def tmp_path(path) -> Path:
    """The tmp sibling a durable write of ``path`` stages through."""
    p = Path(path)
    return p.with_name(f"{p.name}{TMP_MARK}{os.getpid()}")


def fsync_dir(path, fs=None) -> None:
    """Durably commit ``path``'s directory entries (renames/unlinks)."""
    _resolve(fs).fsync_dir(path)


def atomic_write_bytes(path, data: bytes, fs=None) -> None:
    """Durably replace ``path`` with ``data``: the full tmp + fsync +
    rename + dir-fsync sequence. Raises OSError on any step — callers
    for whom persistence is an optimization catch it themselves."""
    fs = _resolve(fs)
    p = Path(path)
    tmp = tmp_path(p)
    fd = fs.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC)
    try:
        try:
            view = memoryview(data)
            off = 0
            while off < len(view):
                n = fs.pwrite(fd, view[off:], off)
                if n <= 0:
                    raise OSError(f"zero-length write to {tmp}")
                off += n
            fs.fsync(fd)
        finally:
            fs.close(fd)
        fs.replace(tmp, p)
        fs.fsync_dir(p.parent)
    except BaseException:
        # stage failed (or a simulated power cut): drop the tmp if the
        # rename did not happen; the destination is untouched
        try:
            if fs.exists(tmp):
                fs.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path, text: str, fs=None) -> None:
    atomic_write_bytes(path, text.encode("utf-8"), fs=fs)


def _fsync_file(path, fs) -> None:
    fd = fs.open(path, os.O_RDONLY)
    try:
        fs.fsync(fd)
    finally:
        fs.close(fd)


def persist(tmp, dst, fs=None) -> None:
    """Durably publish an already-written ``tmp`` (file or directory)
    at ``dst``: fsync the tmp, atomic rename, fsync the parent. For
    payloads produced by an external writer (a compiler emitting a .so,
    a spooled bundle directory) that cannot go through
    :func:`atomic_write_bytes`.

    A directory payload fsyncs every regular file inside it before the
    rename — fsyncing only the directory inode makes the NAMES durable
    while the file data can still be lost, which for a flight bundle
    means a correctly-named spool full of empty files after a crash."""
    fs = _resolve(fs)
    tmp, dst = Path(tmp), Path(dst)
    if tmp.is_dir():
        for sub in sorted(tmp.rglob("*")):
            if sub.is_dir():
                fs.fsync_dir(sub)
            elif sub.is_file():
                _fsync_file(sub, fs)
        fs.fsync_dir(tmp)
    else:
        _fsync_file(tmp, fs)
    fs.replace(tmp, dst)
    fs.fsync_dir(dst.parent)


def stale_tmps(path) -> list[Path]:
    """Tmp siblings a crashed earlier save of ``path`` may have left:
    the ``<name>.tmp.<pid>`` staging names plus the legacy
    ``<stem>.tmp`` spelling older metadata writers used."""
    p = Path(path)
    if not p.parent.is_dir():
        return []
    out = [c for c in p.parent.iterdir()
           if c.name.startswith(p.name + TMP_MARK)]
    legacy = p.with_suffix(".tmp")
    if legacy != p and legacy.exists():
        out.append(legacy)
    return sorted(out)


def cleanup_stale_tmps(path, fs=None) -> int:
    """Delete crash-leftover tmp files beside ``path``; returns the
    count removed. A tmp that survived a crash between write and rename
    holds a payload that was never published — the durable content is
    whatever ``path`` itself says."""
    fs = _resolve(fs)
    n = 0
    for tmp in stale_tmps(path):
        try:
            fs.unlink(tmp)
            n += 1
        except OSError:
            pass
    return n
