"""Virtual-clock asyncio event loop for deterministic time-driven tests.

The reference injects fake clocks everywhere (clockwork in
timesync/clock_test.go and throughout the Go test suite — SURVEY.md
§4.3) so consensus tests are machine-load independent. asyncio needs the
equivalent at the LOOP level: every `asyncio.sleep`, `wait_for`, and
`call_later` resolves against `loop.time()`, so virtualizing that one
clock virtualizes the whole timing model.

Mechanics: `loop.time()` returns virtual time, and the selector is
wrapped so that whenever the loop would block waiting for a timer with
no ready IO, the virtual clock JUMPS to the timer's deadline instead of
sleeping. Logical ordering of every callback is exactly preserved; wall
time spent is proportional to work done, not to configured durations.
A 14-layer consensus scenario with 2 s layers runs in however long the
hashing takes, identically on an idle or a loaded machine.

Two interactions with external reality:
- Executor threads (`asyncio.to_thread`, `run_in_executor`): virtual
  time FREEZES while any executor future is outstanding — otherwise the
  clock would leap over consensus deadlines (or a wait_for timeout)
  while a POST init is still crunching in a worker thread. The loop
  polls real IO briefly instead; the thread's completion callback wakes
  it via the self-pipe.
- No timers at all: the loop is waiting on pure external IO (a
  subprocess pipe, a real socket) — fall back to a short real wait
  instead of spinning.
- With a `time_governor` attached (sharded scenario fabric), executor
  completions are additionally SEQUENCED: each future completes at a
  loop-idle point, in submission order, one per idle. Raw completion
  order is an OS-scheduling race, and the governor's real-time pipe
  round-trips make that race actually flip between runs; since virtual
  time is frozen anyway, picking the deterministic schedule is always
  legal and makes sharded replay byte-identical.

Components must read time from the loop for this to work: `App`
accepts `time_source` and wires it through to LayerClock, hare, and
beacon, so tests pass `time_source=loop.time`.
"""

from __future__ import annotations

import asyncio

START = 1_700_000_000.0  # arbitrary fixed epoch so layer math looks real


class VirtualClockLoop(asyncio.SelectorEventLoop):
    """SelectorEventLoop whose clock jumps over idle waits."""

    def __init__(self, start: float = START):
        super().__init__()
        self._vtime = start
        self._busy_threads = 0
        self._io_streak = 0
        # Sequenced executor releases (governor mode only): real threads
        # finish in OS-scheduling order, and WHICH ready batch their
        # wake-up lands in is a wall-clock race. Single-process sims are
        # stable because nothing else perturbs real timing, but a shard
        # governor blocks the loop on worker pipes for real milliseconds,
        # so completions bunch and the race starts flipping replay runs.
        # Under a governor every executor future is therefore completed
        # at a loop-idle point, in submission order, one per idle — a
        # deterministic schedule that is always legal because virtual
        # time freezes while any thread is outstanding.
        self._exec_seq = 0              # next submission id
        self._exec_next = 0             # next id allowed to complete
        self._exec_results: dict[int, tuple] = {}   # id -> (result, exc)
        self._exec_futs: dict[int, asyncio.Future] = {}
        # Optional conservative-window governor (sim/shard.py): called as
        # governor(now, proposed) -> target before any idle time jump.
        # Returning a target < proposed holds the clock at a barrier (a
        # cross-shard window edge); returning None falls back to a short
        # real wait (external IO pending). The hook lives HERE so
        # ChaosClockLoop's extra select wrapper composes with it.
        self.time_governor = None
        # CRITICAL: asyncio fires a timer when `when < time() + resolution`.
        # The default resolution (1 ns) is BELOW one float64 ulp at
        # unix-epoch magnitudes (~4.8e-7 at 1.7e9), so `time() + 1e-9`
        # rounds back to time() and a timer scheduled exactly AT the
        # current virtual instant never fires — the loop spins forever
        # with timeout=0. Resolution must exceed the clock's ulp.
        self._clock_resolution = 1e-6
        orig_select = self._selector.select

        def select(timeout):
            events = orig_select(0)
            if not events:
                self._io_streak = 0
                if self._exec_next < self._exec_seq:
                    # sequenced executor work in flight: time stays
                    # frozen, and the next completion (in submission
                    # order) is released only at a true idle point —
                    # never while ready callbacks are pending
                    if not self._ready:
                        entry = self._exec_results.pop(
                            self._exec_next, None)
                        if entry is not None:
                            fut = self._exec_futs.pop(self._exec_next)
                            self._exec_next += 1
                            result, exc = entry
                            if not fut.done():
                                if exc is not None:
                                    fut.set_exception(exc)
                                else:
                                    fut.set_result(result)
                        else:
                            events = orig_select(0.002)
                    return events
                if self._busy_threads > 0:
                    # real work in flight: do NOT advance virtual time —
                    # wait for the thread's wake-up on the self-pipe
                    events = orig_select(0.002)
                elif timeout is None:
                    # no timers scheduled at all: waiting on external IO —
                    # but a governor may install fresh timers (cross-shard
                    # frames arriving at a window barrier)
                    if self.time_governor is not None:
                        target = self.time_governor(self._vtime, None)
                        if target is not None and target > self._vtime:
                            self._vtime = target + 1e-6
                            return events
                    events = orig_select(0.005)
                elif timeout > 0:
                    # the 1 µs overshoot matters: _run_once fires timers
                    # strictly below time()+clock_resolution (~1 ns), and
                    # at unix-epoch magnitudes (1.7e9) one float64 ulp is
                    # ~4.8e-7 — landing EXACTLY on the deadline rounds the
                    # comparison into a never-firing busy spin
                    proposed = self._vtime + timeout + 1e-6
                    if self.time_governor is not None:
                        target = self.time_governor(self._vtime, proposed)
                        if target is not None:
                            proposed = max(
                                self._vtime, min(target + 1e-6, proposed))
                    self._vtime = proposed
            else:
                # timer-starvation guard: an fd that stays ready without
                # its callback making progress (e.g. a half-closed
                # socket) would freeze virtual time forever — after a
                # long all-IO streak, trickle time forward so timers
                # can't starve. 1 ms/iteration bounds the skew a LEGIT
                # burst (a large transfer) can accumulate.
                self._io_streak += 1
                if self._io_streak > 256 and timeout is not None \
                        and timeout > 0:
                    self._vtime += 0.001
            return events

        self._selector.select = select

    def time(self) -> float:
        return self._vtime

    def advance(self, dt: float) -> None:
        """Manual jump (rarely needed: idle waits auto-advance)."""
        self._vtime += dt

    def run_in_executor(self, executor, func, *args):
        if self.time_governor is None:
            fut = super().run_in_executor(executor, func, *args)
            self._busy_threads += 1

            def _done(_):
                self._busy_threads -= 1

            fut.add_done_callback(_done)
            return fut
        # governor mode: park the raw completion and let select()
        # release it at an idle point, in submission order
        seq = self._exec_seq
        self._exec_seq += 1
        fut = self.create_future()
        self._exec_futs[seq] = fut

        def _job():
            try:
                entry = (func(*args), None)
            except BaseException as exc:   # delivered via the future
                entry = (None, exc)
            self._exec_results[seq] = entry
            self.call_soon_threadsafe(self._exec_wake)

        super().run_in_executor(executor, _job)
        return fut

    def _exec_wake(self) -> None:
        """No-op loop wake so a parked completion is noticed promptly
        even while select() is in a real 2 ms poll."""


class ChaosClockLoop(VirtualClockLoop):
    """VirtualClockLoop that PERTURBS ready-callback ordering with a
    seeded RNG — the asyncio analogue of the reference's race detector
    plus schedule fuzzing (`go test -race` over randomized goroutine
    interleavings, SURVEY §5.2).

    asyncio's cooperative model rules out data races inside one loop,
    but ORDERING bugs survive: code that accidentally depends on two
    tasks resuming in FIFO order (who observes a shared dict first, a
    publish racing a subscribe) behaves identically on every normal run
    and breaks only under real-world timing. Shuffling the ready queue
    each iteration (timers still respect their deadlines — only
    already-runnable callbacks are reordered, so time causality is
    preserved) surfaces those dependencies deterministically: any
    failure replays exactly from its seed."""

    def __init__(self, seed: int, start: float = START):
        super().__init__(start=start)
        import random

        self._chaos_rng = random.Random(seed)
        # VirtualClockLoop already wrapped select for the time-jump; we
        # wrap once more so the shuffle runs every loop iteration,
        # before the loop drains self._ready
        inner = self._selector.select

        def chaotic_select(timeout):
            if len(self._ready) > 1:
                ready = list(self._ready)
                self._chaos_rng.shuffle(ready)
                self._ready.clear()
                self._ready.extend(ready)
            return inner(timeout)

        self._selector.select = chaotic_select


async def cancel_all_tasks() -> None:
    """Cancel every task but the caller and await them (teardown helper —
    must run INSIDE the loop so gather binds to it)."""
    tasks = [t for t in asyncio.all_tasks()
             if t is not asyncio.current_task()]
    for t in tasks:
        t.cancel()
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)


def run_virtual(coro, *, start: float = START, timeout: float | None = None):
    """asyncio.run() on a VirtualClockLoop. ``timeout`` is VIRTUAL time."""
    loop = VirtualClockLoop(start=start)
    try:
        if timeout is not None:
            coro = asyncio.wait_for(coro, timeout)
        return loop.run_until_complete(coro)
    finally:
        try:
            loop.run_until_complete(cancel_all_tasks())
            loop.run_until_complete(loop.shutdown_asyncgens())
            loop.run_until_complete(loop.shutdown_default_executor())
        finally:
            asyncio.set_event_loop(None)
            loop.close()
