"""Shared utilities: metrics registry, logging setup."""
