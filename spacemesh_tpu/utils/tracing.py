"""Node-wide span tracing: causal timelines from gossip to TPU dispatch.

The metrics registry (utils/metrics.py) answers *how much* — seconds per
pipeline stage, batches per second. This module answers *which one and
why then*: each unit of work (a gossip delivery, a verify-farm batch, a
prove window, a ROMix kernel enqueue) records a **span** — name, wall
interval, attributes, parent — into a bounded in-memory ring, and the
whole capture exports as Chrome trace-event / Perfetto-compatible JSON
so one init+prove+verify run reads as a single causal timeline in
https://ui.perfetto.dev.

Design constraints, in order:

1. **Free when off.** Tracing is always compiled in but disabled by
   default; the disabled ``span()`` call is one attribute load, one
   branch, and the return of a module-singleton no-op context manager —
   no dict, no object allocation, no clock read (asserted by a test).
   Hot paths therefore call it unconditionally.
2. **Fixed memory when on.** Completed spans land in a preallocated
   ring of ``capacity`` slots; the writer index is an
   ``itertools.count`` (atomic under the GIL — the "lock-free-ish"
   part), so recording from pool threads takes no lock and a capture
   can run for hours overwriting its own tail. Overwritten spans are
   counted, not silently lost.
3. **Causality across tasks and threads.** The current span travels
   through ``contextvars`` — awaits, ``asyncio.to_thread`` and task
   creation all inherit it. Long-lived worker threads (the label
   writer/reader pools) cannot inherit a context, so ``current_id()``
   lets the submitting side capture the parent explicitly and pass it
   with the work item.

Controls:

* ``start(capacity=..)`` / ``stop()`` / ``export()`` — embedder API;
  the HTTP server maps them to ``/debug/trace/start|stop|export``
  (api/http.py).
* ``SPACEMESH_TRACE`` — capture from boot: ``1``/``on`` starts the
  tracer at import with the default ring; an integer value sets the
  ring capacity.
* ``SPACEMESH_TRACE_JAX`` — bridge each span into a
  ``jax.profiler.TraceAnnotation`` so host spans line up with XLA
  device traces inside a ``jax.profiler.trace()`` capture on TPU.

Span linkage in the export: every event's ``args`` carries its ``id``
and its ``parent`` id; cross-cutting links that are not parent/child
(a verify-farm batch and its member requests) are recorded as explicit
``args`` references (``batch``/``members``) — see docs/OBSERVABILITY.md
for how to follow them in Perfetto.

Fleet federation (docs/OBSERVABILITY.md § Fleet observability): each
process declares an identity with ``set_process_identity(role)`` —
exports then carry ``otherData["proc"]`` (role, pid, clock domain) and
a Perfetto ``process_name`` metadata event. ``merge_captures()``
combines N such exports into one ``validate()``-clean timeline: span
ids are rewritten per capture so rings that each started counting at 1
cannot collide, and a span recorded with a ``link`` arg holding a
``"<role>/<id>"`` token (built by ``link_token()`` on the sending side
and shipped with the cross-process request) gets its ``parent``
resolved to the merged id of the remote span — the cross-process
parent edges the single-process tracer could never draw.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time

DEFAULT_CAPACITY = 65536

# the current span id, inherited by child tasks/coroutines/to_thread
_current: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "spacemesh_trace_span", default=None)


def current_id() -> int | None:
    """The enclosing span's id (None when untraced/disabled) — for
    handing to long-lived worker threads as an explicit parent."""
    return _current.get()


# --- process identity (fleet federation provenance) ---------------------
#
# One role per process: the sharded sim fabric stamps its workers
# "shard-<k>", verifyd fleet replicas are "replica-<name>", the parent
# defaults to "pid-<pid>". merge_captures() keys cross-process link
# tokens and per-proc provenance on this role.

_proc_identity = {"role": None, "clock_domain": "wall"}


def set_process_identity(role: str, clock_domain: str = "wall") -> None:
    """Declare this process's role label (``shard-3``, ``replica-r1``)
    and clock domain (``wall`` perf_counter µs, or ``virtual`` for sim
    wheels that timestamp spans in virtual time). Carried in every
    export's ``otherData["proc"]`` and as a Perfetto ``process_name``."""
    _proc_identity["role"] = str(role)
    _proc_identity["clock_domain"] = str(clock_domain)


def process_identity() -> dict:
    """This process's federation identity (role defaults to pid-N)."""
    return {
        "role": _proc_identity["role"] or f"pid-{os.getpid()}",
        "pid": os.getpid(),
        "clock_domain": _proc_identity["clock_domain"],
    }


def link_token(span_id: int | None = None) -> str | None:
    """A globally-unique token naming a span of THIS process —
    ``"<role>/<id>"`` — for shipping with a cross-process request.
    The receiving side records it as a ``link`` attr on its own span;
    ``merge_captures()`` resolves it into a real parent edge. None when
    untraced (callers ship nothing)."""
    sid = span_id if span_id is not None else _current.get()
    if sid is None:
        return None
    return f"{process_identity()['role']}/{sid}"


class _NopSpan:
    """The disabled-path singleton: every operation is a no-op."""

    __slots__ = ()
    id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOP = _NopSpan()


class _Span:
    """A live span: a context manager that records itself on exit."""

    __slots__ = ("_tracer", "name", "cat", "attrs", "parent", "id",
                 "_t0", "_token", "_ann")

    def __init__(self, tracer: "Tracer", name: str, attrs, parent, cat):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.parent = parent if parent is not None else _current.get()
        self.id = next(tracer._ids)
        self._ann = None

    def set(self, **attrs):
        """Attach/overwrite attributes on a live span."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self):
        self._token = _current.set(self.id)
        tracer = self._tracer
        if tracer.jax_bridge:
            try:
                from jax import profiler as _jprof

                self._ann = _jprof.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 — bridge is best-effort
                tracer.jax_bridge = False
                self._ann = None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001
                pass
        _current.reset(self._token)
        self._tracer._record(self.name, self.cat, self._t0 // 1000,
                             (t1 - self._t0) // 1000, self.id, self.parent,
                             self.attrs, "X")
        return False

    # spans bracket awaits too; the sync protocol does the work
    async def __aenter__(self):
        return self.__enter__()

    async def __aexit__(self, exc_type, exc, tb):
        return self.__exit__(exc_type, exc, tb)


class Tracer:
    """A bounded-ring span recorder. One module-level instance (TRACER)
    serves the whole process; tests may build private ones."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.enabled = False
        self.capacity = max(int(capacity), 16)
        self.jax_bridge = False
        self._ids = itertools.count(1)
        self._buf: list = []
        self._slots = itertools.count()
        self._recorded = 0  # approximate under thread races; display only
        self._tid_names: dict[int, str] = {}
        self._started_at: float | None = None

    # --- lifecycle ----------------------------------------------------

    def start(self, capacity: int | None = None,
              jax_bridge: bool | None = None) -> None:
        """(Re)start a capture with a fresh ring. Idempotent-ish: a
        second start resets the buffer (a new capture window)."""
        if capacity is not None:
            self.capacity = max(int(capacity), 16)
        if jax_bridge is None:
            jax_bridge = os.environ.get(
                "SPACEMESH_TRACE_JAX", "") not in ("", "0", "off")
        self.jax_bridge = bool(jax_bridge)
        self._buf = [None] * self.capacity
        self._slots = itertools.count()
        self._recorded = 0
        self._tid_names = {}
        self._started_at = time.time()
        self.enabled = True

    def stop(self) -> int:
        """Stop recording; the ring stays exportable. Returns the number
        of spans retained."""
        self.enabled = False
        return min(self._recorded, self.capacity)

    def recorded(self) -> int:
        """Spans recorded since start (including overwritten ones)."""
        return self._recorded

    # --- recording ----------------------------------------------------

    def _record(self, name, cat, ts_us, dur_us, span_id, parent,
                attrs, ph) -> None:
        if not self.enabled:
            return  # stopped while the span was open
        tid = threading.get_ident()
        if tid not in self._tid_names:
            self._tid_names[tid] = threading.current_thread().name
        slot = next(self._slots)
        # ring write: a racing slot under heavy thread contention can
        # momentarily resurrect an older record — acceptable for a
        # diagnostic ring, and the GIL makes the list store atomic.
        # Snapshot the buffer and mod by ITS length: a concurrent
        # start() swapping in a different-capacity ring must never
        # index a pool thread out of bounds mid-record
        buf = self._buf
        if not buf:
            return
        buf[slot % len(buf)] = (
            name, cat, ts_us, dur_us, tid, span_id, parent, attrs, ph)
        self._recorded += 1

    def instant(self, name: str, attrs=None, cat: str = "host") -> None:
        """A zero-duration marker event (decision points, state flips)."""
        if not self.enabled:
            return
        self._record(name, cat, time.perf_counter_ns() // 1000, 0,
                     next(self._ids), _current.get(), attrs, "i")

    def span(self, name: str, attrs=None, parent=None, cat: str = "host"):
        if not self.enabled:
            return _NOP
        return _Span(self, name, attrs, parent, cat)

    # --- export -------------------------------------------------------

    def export(self) -> dict:
        """The capture as a Chrome trace-event / Perfetto JSON object."""
        total = self._recorded
        pid = os.getpid()
        proc = process_identity()
        events = [{"name": "process_name", "ph": "M", "pid": pid,
                   "tid": 0, "args": {"name": proc["role"]}}]
        for tid, tname in sorted(self._tid_names.items()):
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        recs = [r for r in self._buf if r is not None]
        recs.sort(key=lambda r: r[2])  # ring order != time order
        for (name, cat, ts, dur, tid, span_id, parent, attrs, ph) in recs:
            args = {"id": span_id}
            if parent is not None:
                args["parent"] = parent
            if attrs:
                args.update(attrs)
            ev = {"name": name, "cat": cat, "ph": ph, "ts": ts,
                  "pid": pid, "tid": tid, "args": args}
            if ph == "X":
                ev["dur"] = dur
            elif ph == "i":
                ev["s"] = "t"
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "tool": "spacemesh_tpu.utils.tracing",
                "captured_spans": len(recs),
                "dropped_spans": max(0, total - len(recs)),
                "capacity": self.capacity,
                "started_at_unix": self._started_at,
                "proc": proc,
            },
        }


TRACER = Tracer()


# --- module-level convenience API (what instrumented code calls) --------


def is_enabled() -> bool:
    return TRACER.enabled


def span(name: str, attrs=None, parent=None, cat: str = "host"):
    """A span context manager, or the no-op singleton when disabled.

    ``attrs`` is an optional dict the caller builds (kept positional so
    the disabled path never materializes a kwargs dict). ``parent``
    overrides the contextvar parent — for work crossing into long-lived
    pool threads, pair with ``current_id()``.
    """
    if not TRACER.enabled:
        return _NOP
    return _Span(TRACER, name, attrs, parent, cat)


def instant(name: str, attrs=None, cat: str = "host") -> None:
    if TRACER.enabled:
        TRACER.instant(name, attrs, cat)


def start(capacity: int | None = None, jax_bridge: bool | None = None) -> None:
    TRACER.start(capacity, jax_bridge)


def stop() -> int:
    return TRACER.stop()


def export() -> dict:
    return TRACER.export()


def export_json(path: str) -> dict:
    """Export and write to ``path``; returns the document."""
    doc = TRACER.export()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return doc


# --- federation: merge N process captures into one timeline -------------

# per-capture span-id offset: every process's ring counts ids from 1, so
# without rewriting, shard-0's span 17 and replica-r2's span 17 would
# alias in the merged args graph
_MERGE_ID_STRIDE = 1 << 32


def merge_captures(captures) -> dict:
    """Combine N ``export()`` documents into ONE ``validate()``-clean
    timeline with per-process provenance.

    * Each capture gets a distinct merged ``pid`` (1..N) and a Perfetto
      ``process_name`` metadata event naming its role, so the merged
      file renders as per-process tracks and ``summarize()`` can build
      per-proc columns.
    * Span ``id``/``parent`` (and ``batch`` references) are rewritten
      with a per-capture offset — rings that each count from 1 must not
      collide in the merged graph.
    * A span whose args carry a ``link`` token (``"<role>/<id>"``, see
      ``link_token()``) gets its ``parent`` resolved to the merged id
      of the remote span; resolved/unresolved counts land in
      ``otherData["links"]`` — "zero unresolved" is the scenario-level
      assertion that no cross-process edge dangled.
    * Timed events are globally re-sorted by ``ts`` (validate requires
      one monotonic stream; metadata events are emitted first).
    """
    meta_events: list[dict] = []
    timed: list[tuple] = []  # (ts, seq, event) — seq keeps sort stable
    procs: list[dict] = []
    token_map: dict[str, int] = {}
    captured = dropped = 0
    seq = 0
    for idx, doc in enumerate(captures):
        off = (idx + 1) * _MERGE_ID_STRIDE
        mpid = idx + 1
        other = dict(doc.get("otherData") or {})
        proc = dict(other.get("proc") or {})
        role = str(proc.get("role") or f"proc-{idx}")
        proc_entry = {
            "role": role,
            "pid": proc.get("pid"),
            "merged_pid": mpid,
            "clock_domain": proc.get("clock_domain", "wall"),
            "captured_spans": int(other.get("captured_spans", 0)),
            "dropped_spans": int(other.get("dropped_spans", 0)),
        }
        procs.append(proc_entry)
        captured += proc_entry["captured_spans"]
        dropped += proc_entry["dropped_spans"]
        meta_events.append({"name": "process_name", "ph": "M",
                            "pid": mpid, "tid": 0, "args": {"name": role}})
        for ev in doc.get("traceEvents", ()):
            ev = dict(ev)
            ev["pid"] = mpid
            args = ev.get("args")
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    continue  # replaced by the role-named one above
                meta_events.append(ev)
                continue
            if args:
                args = dict(args)
                sid = args.get("id")
                if sid is not None:
                    token_map.setdefault(f"{role}/{sid}", sid + off)
                    args["id"] = sid + off
                for ref in ("parent", "batch"):
                    if args.get(ref) is not None:
                        args[ref] = args[ref] + off
                if args.get("members"):
                    args["members"] = [m + off for m in args["members"]]
                ev["args"] = args
            timed.append((ev.get("ts", 0), seq, ev))
            seq += 1
    resolved = unresolved = 0
    for _, _, ev in timed:
        args = ev.get("args")
        tok = args.get("link") if args else None
        if tok is None:
            continue
        target = token_map.get(tok)
        if target is not None:
            args["parent"] = target
            resolved += 1
        else:
            unresolved += 1
    timed.sort(key=lambda t: (t[0], t[1]))
    return {
        "traceEvents": meta_events + [ev for _, _, ev in timed],
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "spacemesh_tpu.utils.tracing",
            "merged": True,
            "captured_spans": captured,
            "dropped_spans": dropped,
            "procs": procs,
            "links": {"resolved": resolved, "unresolved": unresolved},
        },
    }


def span_multiset_digest(doc) -> str:
    """sha256 over the merged capture's ``(proc role, span name, count)``
    multiset — the replay-stable identity of a capture. Timestamps, span
    ids and durations are wall/ordering artifacts and stay out; under
    the sim's deterministic virtual clock the multiset is a pure
    function of (seed, W), so same seed ⇒ byte-identical digest."""
    import hashlib

    roles = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            roles[ev["pid"]] = ev["args"]["name"]
    counts: dict[tuple, int] = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") in ("X", "i"):
            key = (roles.get(ev["pid"], str(ev["pid"])), ev["name"])
            counts[key] = counts.get(key, 0) + 1
    h = hashlib.sha256()
    for (role, name), n in sorted(counts.items()):
        h.update(f"{role}\x00{name}\x00{n}\n".encode())
    return h.hexdigest()


# --- validation (tests + the CI trace-smoke job) ------------------------

_PHASES = {"X", "B", "E", "i", "M", "s", "f"}
_REQUIRED = ("name", "ph", "pid", "tid")


def validate(doc) -> list[str]:
    """Raise ValueError unless ``doc`` is structurally valid trace-event
    JSON: required keys present, known phases, non-negative monotonic
    ``ts`` within the stream, ``dur`` on complete (X) events, and
    matched B/E pairs per (pid, tid) if any are used.

    Returns a list of non-fatal WARNINGS — today, ring-drop accounting:
    a capture whose ring evicted spans is structurally fine but
    analytically lossy (the storm-1024 silent-eviction class), so every
    caller that prints gets told to raise ``trace_capacity`` /
    ``SPACEMESH_TRACE=<N>`` / ``?capacity=``."""
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace document must be {'traceEvents': [...]}")
    last_ts = None
    stacks: dict[tuple, list] = {}
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"event {i}: not an object")
        for k in _REQUIRED:
            if k not in ev:
                raise ValueError(f"event {i}: missing key {k!r}")
        ph = ev["ph"]
        if ph not in _PHASES:
            raise ValueError(f"event {i}: unknown phase {ph!r}")
        if ph == "M":
            continue  # metadata events carry no timestamp contract
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            raise ValueError(f"event {i}: bad ts {ts!r}")
        if last_ts is not None and ts < last_ts:
            raise ValueError(f"event {i}: ts went backwards "
                             f"({ts} < {last_ts})")
        last_ts = ts
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(f"event {i}: X event with bad dur {dur!r}")
        elif ph == "B":
            stacks.setdefault((ev["pid"], ev["tid"]), []).append(ev["name"])
        elif ph == "E":
            stack = stacks.setdefault((ev["pid"], ev["tid"]), [])
            if not stack:
                raise ValueError(f"event {i}: E without matching B")
            stack.pop()
    for key, stack in stacks.items():
        if stack:
            raise ValueError(f"unclosed B events on {key}: {stack}")
    return drop_warnings(doc)


def drop_warnings(doc) -> list[str]:
    """Ring-eviction warnings for a capture (or each proc of a merged
    capture): non-empty means the timeline is missing spans and any
    span-count assertion on it is suspect."""
    other = doc.get("otherData") or {}
    warnings = []
    procs = other.get("procs")
    if procs:
        for p in procs:
            if p.get("dropped_spans"):
                warnings.append(
                    f"proc {p.get('role')}: ring dropped "
                    f"{p['dropped_spans']} spans — raise trace_capacity "
                    f"(script) / SPACEMESH_TRACE=<capacity> / "
                    f"?capacity= on /debug/trace/start")
    elif other.get("dropped_spans"):
        cap = other.get("capacity")
        warnings.append(
            f"ring dropped {other['dropped_spans']} spans"
            f"{f' (capacity {cap})' if cap else ''} — raise "
            f"trace_capacity (script) / SPACEMESH_TRACE=<capacity> / "
            f"?capacity= on /debug/trace/start")
    return warnings


# --- text flame summary (tools/profiler.py --timeline) ------------------

_WAIT_MARKERS = ("wait", "stall", "queue", "idle", "block")


def summarize(doc, top: int = 20) -> dict:
    """Digest an exported trace: top spans by self-time (duration minus
    nested child spans on the same thread) and a per-stage queue-wait vs
    work split. The stage is the span name's dotted prefix ("prove" for
    "prove.read_wait"); wait spans are named with one of
    {wait, stall, queue, idle, block}.

    Merged captures additionally digest per-PROCESS: a ``procs`` table
    (spans + self-time per role — the SZKP "is every worker saturated"
    column) and ``cross_proc_links`` counting parent edges that cross a
    process boundary, keyed "parent_span->child_span" (e.g. the
    ``farm.request->verifyd.request`` edges the fleet federation
    resolves). ``warnings`` carries ring-drop accounting."""
    proc_names: dict[int, str] = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            proc_names[ev["pid"]] = ev["args"]["name"]
    per_tid: dict[tuple, list] = {}
    id_home: dict[int, tuple[int, str]] = {}  # span id -> (pid, name)
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") == "X":
            per_tid.setdefault((ev["pid"], ev["tid"]), []).append(ev)
            sid = (ev.get("args") or {}).get("id")
            if sid is not None:
                id_home[sid] = (ev["pid"], ev["name"])
    totals: dict[str, dict] = {}
    stages: dict[str, dict] = {}
    procs: dict[int, dict] = {}
    link_pairs: dict[str, int] = {}
    for evs in per_tid.values():
        for ev in evs:
            parent = (ev.get("args") or {}).get("parent")
            home = id_home.get(parent)
            if home is not None and home[0] != ev["pid"]:
                pair = f"{home[1]}->{ev['name']}"
                link_pairs[pair] = link_pairs.get(pair, 0) + 1
    for evs in per_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack: list = []  # (end_ts, name, child_dur_acc as 1-item list)
        for ev in evs:
            ts, dur = ev["ts"], ev.get("dur", 0)
            while stack and stack[-1][0] <= ts:
                stack.pop()
            if stack:
                stack[-1][2][0] += dur
            stack.append((ts + dur, ev["name"], [0]))
            # self time settles when the span pops; accumulate eagerly
            # by recording the entry and fixing it up below
            ev["_children"] = stack[-1][2]
    for evs in per_tid.values():
        for ev in evs:
            name = ev["name"]
            dur = ev.get("dur", 0)
            self_us = max(dur - ev.pop("_children")[0], 0)
            t = totals.setdefault(name, {"count": 0, "total_us": 0,
                                         "self_us": 0})
            t["count"] += 1
            t["total_us"] += dur
            t["self_us"] += self_us
            p = procs.setdefault(ev["pid"], {"spans": 0, "self_us": 0})
            p["spans"] += 1
            p["self_us"] += self_us
            stage = name.split(".", 1)[0]
            s = stages.setdefault(stage, {"wait_us": 0, "work_us": 0})
            leaf = name.rsplit(".", 1)[-1]
            if any(m in leaf for m in _WAIT_MARKERS):
                s["wait_us"] += self_us
            else:
                s["work_us"] += self_us
    ranked = sorted(totals.items(), key=lambda kv: -kv[1]["self_us"])
    proc_rows = [
        {"proc": proc_names.get(pid, str(pid)), **v}
        for pid, v in sorted(procs.items())]
    return {
        "spans": len([1 for evs in per_tid.values() for _ in evs]),
        "top_self_time": [{"name": k, **v} for k, v in ranked[:top]],
        "stages": {k: {**v,
                       "wait_frac": round(v["wait_us"]
                                          / max(v["wait_us"] + v["work_us"],
                                                1), 3)}
                   for k, v in sorted(stages.items())},
        "procs": proc_rows,
        "cross_proc_links": {
            "total": sum(link_pairs.values()),
            "pairs": dict(sorted(link_pairs.items())),
        },
        "warnings": drop_warnings(doc),
    }


def render_summary(summary: dict) -> str:
    """A terminal-friendly flame digest of ``summarize()``'s output."""
    lines = [f"{'span':<36} {'count':>7} {'total ms':>10} {'self ms':>10}"]
    for row in summary["top_self_time"]:
        lines.append(f"{row['name']:<36} {row['count']:>7} "
                     f"{row['total_us'] / 1000:>10.2f} "
                     f"{row['self_us'] / 1000:>10.2f}")
    lines.append("")
    lines.append(f"{'stage':<12} {'work ms':>10} {'wait ms':>10} "
                 f"{'wait %':>7}")
    for stage, s in summary["stages"].items():
        lines.append(f"{stage:<12} {s['work_us'] / 1000:>10.2f} "
                     f"{s['wait_us'] / 1000:>10.2f} "
                     f"{100 * s['wait_frac']:>6.1f}%")
    proc_rows = summary.get("procs") or []
    if len(proc_rows) > 1:
        lines.append("")
        lines.append(f"{'proc':<24} {'spans':>8} {'self ms':>10}")
        for row in proc_rows:
            lines.append(f"{row['proc']:<24} {row['spans']:>8} "
                         f"{row['self_us'] / 1000:>10.2f}")
        links = summary.get("cross_proc_links") or {}
        lines.append("")
        lines.append(f"cross-process parent links: {links.get('total', 0)}")
        for pair, n in (links.get("pairs") or {}).items():
            lines.append(f"  {pair}: {n}")
    for warn in summary.get("warnings") or ():
        lines.append("")
        lines.append(f"WARNING: {warn}")
    return "\n".join(lines)


# --- capture-from-boot (SPACEMESH_TRACE) --------------------------------

_boot = os.environ.get("SPACEMESH_TRACE", "")
if _boot and _boot.lower() not in ("0", "off", "false", "none"):
    start(capacity=int(_boot) if _boot.isdigit() and int(_boot) > 1
          else None)
