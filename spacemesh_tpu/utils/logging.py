"""Named hierarchical loggers with per-module levels.

Mirrors the reference log package (reference log/: zap wrapper with named
loggers and per-module level overrides, node/node.go:557 addLogger).
Thin stdlib wrapper: ``get(name)`` returns a child of the "smtpu" root;
``configure(levels={"hare": "DEBUG"})`` sets per-module levels.
"""

from __future__ import annotations

import logging
import sys

ROOT = "smtpu"


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"{ROOT}.{name}")


def configure(level: str = "INFO", levels: dict[str, str] | None = None,
              stream=None) -> None:
    root = logging.getLogger(ROOT)
    root.setLevel(level.upper())
    if not root.handlers:
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
        root.addHandler(h)
    for module, lvl in (levels or {}).items():
        get(module).setLevel(lvl.upper())
