"""Named hierarchical loggers with per-module levels.

Mirrors the reference log package (reference log/: zap wrapper with named
loggers and per-module level overrides, node/node.go:557 addLogger).
Thin stdlib wrapper: ``get(name)`` returns a child of the "smtpu" root;
``configure(levels={"hare": "DEBUG"})`` sets per-module levels.

Structured mode: ``SPACEMESH_LOG_JSON=1`` (or ``configure(json_lines=
True)``) switches the handler to one JSON object per line carrying the
current span id from the tracer's contextvars (utils/tracing.py). A
health-engine breach line logged inside a ``health.tick`` span then
carries ``"span": <id>`` — paste that id into Perfetto's args search
over a ``/debug/trace/export`` capture and the log line lands on its
exact spot in the timeline (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

ROOT = "smtpu"


def get(name: str) -> logging.Logger:
    return logging.getLogger(f"{ROOT}.{name}")


class JsonFormatter(logging.Formatter):
    """One JSON object per line; stable keys, span-id correlated."""

    def format(self, record: logging.LogRecord) -> str:
        from . import tracing

        doc = {
            "ts": round(record.created, 6),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                 time.gmtime(record.created))
                   + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        span = tracing.current_id()
        if span is not None:
            doc["span"] = span
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc, ensure_ascii=False)


def json_mode_enabled() -> bool:
    return os.environ.get("SPACEMESH_LOG_JSON", "").lower() not in (
        "", "0", "off", "false")


def configure(level: str = "INFO", levels: dict[str, str] | None = None,
              stream=None, json_lines: bool | None = None) -> None:
    """``json_lines=None`` defers to ``SPACEMESH_LOG_JSON``; an explicit
    value wins. Re-calling reformats the existing handler, so flipping
    modes mid-process (tests) works."""
    if json_lines is None:
        json_lines = json_mode_enabled()
    root = logging.getLogger(ROOT)
    root.setLevel(level.upper())
    if not root.handlers:
        root.addHandler(logging.StreamHandler(stream or sys.stderr))
    fmt = (JsonFormatter() if json_lines else logging.Formatter(
        "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
    for h in root.handlers:
        h.setFormatter(fmt)
    for module, lvl in (levels or {}).items():
        get(module).setLevel(lvl.upper())
