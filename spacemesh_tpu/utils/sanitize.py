"""Runtime sanitizers: what the static pass (tools/spacecheck) can't see.

``SPACEMESH_SANITIZE=1`` arms three cheap, always-compiled-in checks
that catch the *dynamic* halves of the recurring defect classes:

1. **Slow-callback detection** (the SC002 complement): every asyncio
   callback/task step is timed; one that holds the loop longer than
   the threshold (``SPACEMESH_SANITIZE_SLOW_MS``, default 250) records
   a violation attributed to the tracing span that was current *inside*
   the callback's context — so the report says "farm.batch blocked the
   loop for 800ms", not just "something was slow". PR 7's flight-dump
   fix (trace-ring serialization on the loop at the exact moment the
   node was unhealthy) is the originating bug. Violations are recorded
   and counted (``sanitize_violations_total``), never raised — raising
   inside ``Handle._run`` would take down an unrelated task.

2. **Registry thread-affinity** (the SC005 complement): metrics
   instruments must be created on the thread that built their Registry
   (module import, in practice). A worker thread minting an instrument
   mid-run is exactly how PR 7's silent wrong-bucket histogram
   happened — two creation sites racing get-or-create with different
   layouts. Creation off-thread raises :class:`SanitizeError`.

3. **Compile-explosion guard** (the PR 6 compile-cost contract,
   enforced instead of hoped): the fused label pipelines may only be
   dispatched at power-of-two lane buckets — the grid the autotuner
   races and ``tools/warmcache.py`` pre-compiles. An off-bucket shape
   means some caller bypassed the pad-and-trim wrappers and is about
   to pay a 17–26s XLA compile per ragged size; the guard raises
   :class:`SanitizeError` at the dispatch boundary with the offending
   lane count.

The hooks live at three choke points (``asyncio.events.Handle._run``,
``metrics.Registry._get``'s create branch, ``ops/scrypt.py`` dispatch)
and cost one flag check each when the sanitizer is off.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time

from . import logging as slog
from . import tracing

_log = slog.get("sanitize")

ENV = "SPACEMESH_SANITIZE"
ENV_SLOW_MS = "SPACEMESH_SANITIZE_SLOW_MS"

_OFF = ("", "0", "off", "false", "none")

DEFAULT_SLOW_S = 0.25
MAX_VIOLATIONS = 256


class SanitizeError(RuntimeError):
    """A sanitizer contract was violated (raising kinds only)."""


@dataclasses.dataclass
class Violation:
    kind: str              # "slow-callback" | "registry-thread" | "jit-shape"
    detail: str
    span: int | None       # tracing span id current at the violation
    seconds: float | None = None


_enabled = False
_slow_threshold_s = DEFAULT_SLOW_S
_violations: list[Violation] = []
_lock = threading.Lock()
_handle_patched = False
_orig_handle_run = None


def enabled() -> bool:
    return _enabled


def violations() -> list[Violation]:
    with _lock:
        return list(_violations)


def clear_violations() -> None:
    with _lock:
        _violations.clear()


def _record(kind: str, detail: str, *, span: int | None = None,
            seconds: float | None = None) -> Violation:
    v = Violation(kind, detail, span, seconds)
    with _lock:
        if len(_violations) < MAX_VIOLATIONS:
            _violations.append(v)
    try:
        from . import metrics

        metrics.sanitize_violations.inc(kind=kind)
    except Exception:  # noqa: BLE001 — the sanitizer must never take
        pass           # down the code it watches
    _log.warning("sanitize[%s]: %s%s%s", kind, detail,
                 f" ({seconds * 1000:.0f}ms)" if seconds is not None else "",
                 f" [span {span}]" if span is not None else "")
    return v


# --- 1. slow asyncio callbacks ------------------------------------------


def _patch_handle() -> None:
    """Wrap ``asyncio.events.Handle._run`` once per process; the wrapper
    is a single flag check when the sanitizer is disabled."""
    global _handle_patched, _orig_handle_run
    if _handle_patched:
        return
    import asyncio.events as aev

    _orig_handle_run = aev.Handle._run

    def _run(self):  # noqa: ANN001 — signature fixed by asyncio
        if not _enabled:
            return _orig_handle_run(self)
        t0 = time.perf_counter()
        try:
            return _orig_handle_run(self)
        finally:
            dt = time.perf_counter() - t0
            if dt >= _slow_threshold_s:
                # the span current INSIDE the callback's context — the
                # contextvars Context the loop ran it under — names the
                # work that held the loop
                span = None
                ctx = getattr(self, "_context", None)
                if ctx is not None:
                    try:
                        span = ctx.get(tracing._current)
                    except Exception:  # noqa: BLE001
                        span = None
                try:
                    what = repr(getattr(self, "_callback", self))
                except Exception:  # noqa: BLE001
                    what = "<unprintable callback>"
                _record("slow-callback",
                        f"event-loop callback held the loop for "
                        f"{dt * 1000:.0f}ms (threshold "
                        f"{_slow_threshold_s * 1000:.0f}ms): {what:.200}",
                        span=span, seconds=dt)

    aev.Handle._run = _run
    _handle_patched = True


# --- 2. registry thread-affinity ----------------------------------------


def on_instrument_create(name: str, registry) -> None:
    """Called from ``metrics.Registry._get`` when a NEW instrument is
    about to be created. Raises off the registry's owning thread."""
    if not _enabled:
        return
    owner = getattr(registry, "_created_thread", None)
    if owner is None or owner == threading.get_ident():
        return
    _record("registry-thread",
            f"instrument {name!r} created on thread "
            f"{threading.current_thread().name!r}, but its registry "
            "belongs to another thread: create instruments at module "
            "import, record from anywhere",
            span=tracing.current_id())
    raise SanitizeError(
        f"metrics instrument {name!r} created off the registry's owning "
        "thread (SPACEMESH_SANITIZE)")


# --- 3. compile-explosion guard -----------------------------------------


def on_jit_shape(fn_name: str, lanes: int) -> None:
    """Called at the fused-label dispatch boundary with the lane count
    entering the jit. Off-bucket (non-power-of-two) shapes raise: they
    bypass the warmed executable population and mint a fresh compile."""
    if not _enabled:
        return
    try:
        lanes = int(lanes)
    except (TypeError, ValueError):
        return  # symbolic/traced dim: not a host dispatch
    if lanes >= 1 and lanes & (lanes - 1) == 0:
        return
    _record("jit-shape",
            f"{fn_name} dispatched {lanes} lanes — outside the "
            "power-of-two bucket grid the autotuner warms; some caller "
            "bypassed the pad-and-trim wrappers (shape_bucket)",
            span=tracing.current_id())
    raise SanitizeError(
        f"{fn_name}: off-bucket jit shape {lanes} (SPACEMESH_SANITIZE; "
        "see docs/STATIC_ANALYSIS.md)")


# --- lifecycle ----------------------------------------------------------


def enable(slow_threshold_s: float | None = None) -> None:
    global _enabled, _slow_threshold_s
    if slow_threshold_s is not None:
        _slow_threshold_s = float(slow_threshold_s)
    _patch_handle()
    _enabled = True


def disable() -> None:
    """Disarm (the Handle patch stays installed but inert)."""
    global _enabled
    _enabled = False


def _boot() -> None:
    raw = (os.environ.get(ENV) or "").strip().lower()
    if raw in _OFF:
        return
    ms = os.environ.get(ENV_SLOW_MS)
    try:
        threshold = float(ms) / 1000.0 if ms else None
    except ValueError:
        threshold = None
    enable(threshold)


_boot()
