"""Runtime sanitizers: what the static pass (tools/spacecheck) can't see.

``SPACEMESH_SANITIZE`` arms cheap, always-compiled-in checks that catch
the *dynamic* halves of the recurring defect classes.  The value is
either ``1``/``on``/``all`` (everything) or a comma-separated subset of
kinds — ``race``, ``slow-callback`` (alias ``slow``),
``registry-thread`` (``registry``), ``jit-shape`` (``shape``):

1. **Slow-callback detection** (the SC002 complement): every asyncio
   callback/task step is timed; one that holds the loop longer than
   the threshold (``SPACEMESH_SANITIZE_SLOW_MS``, default 250) records
   a violation attributed to the tracing span that was current *inside*
   the callback's context — so the report says "farm.batch blocked the
   loop for 800ms", not just "something was slow". PR 7's flight-dump
   fix (trace-ring serialization on the loop at the exact moment the
   node was unhealthy) is the originating bug. Violations are recorded
   and counted (``sanitize_violations_total``), never raised — raising
   inside ``Handle._run`` would take down an unrelated task.

2. **Registry thread-affinity** (the SC005 complement): metrics
   instruments must be created on the thread that built their Registry
   (module import, in practice). A worker thread minting an instrument
   mid-run is exactly how PR 7's silent wrong-bucket histogram
   happened — two creation sites racing get-or-create with different
   layouts. Creation off-thread raises :class:`SanitizeError`.

3. **Compile-explosion guard** (the PR 6 compile-cost contract,
   enforced instead of hoped): the fused label pipelines may only be
   dispatched at power-of-two lane buckets — the grid the autotuner
   races and ``tools/warmcache.py`` pre-compiles. An off-bucket shape
   means some caller bypassed the pad-and-trim wrappers and is about
   to pay a 17–26s XLA compile per ragged size; the guard raises
   :class:`SanitizeError` at the dispatch boundary with the offending
   lane count.

4. **Eraser-style lockset race detection** (the SC007/SC008
   complement; ISSUE 12).  Locks created through :func:`lock` /
   :func:`condition` maintain a per-thread held-lockset; objects
   declared shared through :class:`SharedField` (the scheduler's
   tenant tables, the ``LabelWriter`` cursor, the metrics registry's
   series maps, the HEALTH probe map, EventBus subscriber lists)
   shrink a per-field candidate lockset on each access — an empty
   intersection once a second thread is involved reports a race with
   BOTH threads' stacks, the current tracing span, and
   ``sanitize_violations_total{kind="race"}``.  ``mode="owner-write"``
   is the runtime twin of the static ``# spacecheck: loop-only``
   annotation: any thread may read (the GIL-snapshot pattern), only
   the first writing thread may write.  Three side-checks ride along:
   a **lock-order watcher** records the acquisition graph as it
   happens and reports inversions the static SC008 graph can't see;
   ``Handle._run`` reports a callback that RETURNS TO THE LOOP with a
   tracked ``threading`` lock still held (``with lock: await ...`` —
   the event-loop-wedge class, detected at the first suspension); all
   are recorded, never raised.  Note: :func:`lock` / :func:`condition`
   decide at CONSTRUCTION time — arm the sanitizer before building
   the objects you want watched (the env var arms it at import).

The hooks cost one flag check each when the sanitizer is off, and
:func:`lock`/:func:`condition` hand back raw ``threading`` primitives
when race mode is off at construction.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time

from . import logging as slog
from . import tracing

_log = slog.get("sanitize")

ENV = "SPACEMESH_SANITIZE"
ENV_SLOW_MS = "SPACEMESH_SANITIZE_SLOW_MS"

_OFF = ("", "0", "off", "false", "none", "no")
_ALL = ("1", "on", "true", "all", "yes")

KIND_SLOW = "slow-callback"
KIND_REGISTRY = "registry-thread"
KIND_SHAPE = "jit-shape"
KIND_RACE = "race"
KINDS = (KIND_SLOW, KIND_REGISTRY, KIND_SHAPE, KIND_RACE)

# the race subsystem's sibling report kinds (armed together by the
# "race" mode token; distinct in violations() and the metrics label)
KIND_ORDER = "lock-order"
KIND_AWAIT = "lock-across-await"

_MODE_ALIASES = {
    "slow": KIND_SLOW, KIND_SLOW: KIND_SLOW,
    "registry": KIND_REGISTRY, KIND_REGISTRY: KIND_REGISTRY,
    "shape": KIND_SHAPE, KIND_SHAPE: KIND_SHAPE,
    "race": KIND_RACE, "lockset": KIND_RACE,
}

DEFAULT_SLOW_S = 0.25
MAX_VIOLATIONS = 256
_STACK_DEPTH = 8


class SanitizeError(RuntimeError):
    """A sanitizer contract was violated (raising kinds only)."""


@dataclasses.dataclass
class Violation:
    kind: str              # KINDS member, or KIND_ORDER / KIND_AWAIT
    detail: str
    span: int | None       # tracing span id current at the violation
    seconds: float | None = None
    thread: str | None = None        # reporting thread
    stack: str | None = None         # reporting thread's stack
    other_thread: str | None = None  # the racing peer, when known
    other_stack: str | None = None


def parse_modes(raw: str | None) -> frozenset[str]:
    """``SPACEMESH_SANITIZE`` value -> armed kind set (empty = off).
    Unknown tokens are logged and ignored, they never silently arm or
    disarm everything."""
    raw = (raw or "").strip().lower()
    if raw in _OFF:
        return frozenset()
    if raw in _ALL:
        return frozenset(KINDS)
    modes: set[str] = set()
    for tok in raw.split(","):
        tok = tok.strip()
        if not tok:
            continue
        kind = _MODE_ALIASES.get(tok)
        if kind is None:
            _log.warning("sanitize: unknown %s kind %r ignored "
                         "(known: %s, or 1/on/all)", ENV, tok,
                         ",".join(KINDS))
            continue
        modes.add(kind)
    return frozenset(modes)


def parse_slow_threshold(raw: str | None) -> float | None:
    """``SPACEMESH_SANITIZE_SLOW_MS`` -> seconds. Garbage and
    non-positive values fall back to the default (None): a typo'd
    threshold must not silence — or spam — the slow-callback check."""
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        _log.warning("sanitize: bad %s=%r, using default %.0fms",
                     ENV_SLOW_MS, raw, DEFAULT_SLOW_S * 1000)
        return None
    if ms <= 0:
        _log.warning("sanitize: non-positive %s=%r, using default "
                     "%.0fms", ENV_SLOW_MS, raw, DEFAULT_SLOW_S * 1000)
        return None
    return ms / 1000.0


_enabled = False
_modes: frozenset[str] = frozenset()
_race = False
_slow_threshold_s = DEFAULT_SLOW_S
_violations: list[Violation] = []
_lock = threading.Lock()
_handle_patched = False
_orig_handle_run = None


def enabled(kind: str | None = None) -> bool:
    if kind is None:
        return _enabled
    return kind in _modes


def race_enabled() -> bool:
    return _race


def violations() -> list[Violation]:
    with _lock:
        return list(_violations)


def clear_violations() -> None:
    """Forget recorded violations AND the lock-order watcher's edge
    memory (tests isolate order-graph scenarios per case)."""
    with _lock:
        _violations.clear()
    with _order_lock:
        _order_edges.clear()


def _caller_stack(skip: int = 2) -> str:
    """A compact ``file:line fn`` stack of the caller, cheap enough to
    take on every sanitized access (no source-line loading)."""
    frames = []
    try:
        f = sys._getframe(skip)
    except ValueError:
        return ""
    while f is not None and len(frames) < _STACK_DEPTH:
        code = f.f_code
        if "/utils/sanitize" not in code.co_filename:
            frames.append(f"{code.co_filename}:{f.f_lineno} "
                          f"{code.co_name}")
        f = f.f_back
    return " <- ".join(frames)


def _record(kind: str, detail: str, *, span: int | None = None,
            seconds: float | None = None, stack: str | None = None,
            other_thread: str | None = None,
            other_stack: str | None = None) -> Violation:
    v = Violation(kind, detail, span, seconds,
                  thread=threading.current_thread().name, stack=stack,
                  other_thread=other_thread, other_stack=other_stack)
    with _lock:
        if len(_violations) < MAX_VIOLATIONS:
            _violations.append(v)
    try:
        from . import metrics

        metrics.sanitize_violations.inc(kind=kind)
    except Exception:  # noqa: BLE001 — the sanitizer must never take
        pass           # down the code it watches
    _log.warning("sanitize[%s]: %s%s%s%s", kind, detail,
                 f" ({seconds * 1000:.0f}ms)" if seconds is not None else "",
                 f" [span {span}]" if span is not None else "",
                 f"\n  this thread ({v.thread}): {stack}"
                 + (f"\n  other thread ({other_thread}): {other_stack}"
                    if other_stack else "") if stack else "")
    return v


# --- 1. slow asyncio callbacks (+ lock-held-across-await) ---------------


def _patch_handle() -> None:
    """Wrap ``asyncio.events.Handle._run`` once per process; the wrapper
    is a single flag check when the sanitizer is disabled."""
    global _handle_patched, _orig_handle_run
    if _handle_patched:
        return
    import asyncio.events as aev

    _orig_handle_run = aev.Handle._run

    def _run(self):  # noqa: ANN001 — signature fixed by asyncio
        if not _enabled:
            return _orig_handle_run(self)
        # a callback step that ACQUIRES a tracked threading lock and
        # then returns control to the loop still holding it is a
        # coroutine suspended inside `with lock:` — every other
        # acquirer (loop callbacks included) parks until it resumes
        entry_held = frozenset(_held()) if _race else None
        t0 = time.perf_counter()
        try:
            return _orig_handle_run(self)
        finally:
            dt = time.perf_counter() - t0
            if entry_held is not None:
                leaked = [k for k in _held() if k not in entry_held]
                if leaked:
                    names = ", ".join(sorted(k[0] for k in leaked))
                    _record(KIND_AWAIT,
                            f"threading lock(s) {names} held across an "
                            "await: the callback returned to the event "
                            "loop still holding them",
                            span=tracing.current_id(),
                            stack=_caller_stack(1))
            if dt >= _slow_threshold_s and KIND_SLOW in _modes:
                # the span current INSIDE the callback's context — the
                # contextvars Context the loop ran it under — names the
                # work that held the loop
                span = None
                ctx = getattr(self, "_context", None)
                if ctx is not None:
                    try:
                        span = ctx.get(tracing._current)
                    except Exception:  # noqa: BLE001
                        span = None
                try:
                    what = repr(getattr(self, "_callback", self))
                except Exception:  # noqa: BLE001
                    what = "<unprintable callback>"
                _record(KIND_SLOW,
                        f"event-loop callback held the loop for "
                        f"{dt * 1000:.0f}ms (threshold "
                        f"{_slow_threshold_s * 1000:.0f}ms): {what:.200}",
                        span=span, seconds=dt)

    aev.Handle._run = _run
    _handle_patched = True


# --- 2. registry thread-affinity ----------------------------------------


def on_instrument_create(name: str, registry) -> None:
    """Called from ``metrics.Registry._get`` when a NEW instrument is
    about to be created. Raises off the registry's owning thread."""
    if KIND_REGISTRY not in _modes:
        return
    owner = getattr(registry, "_created_thread", None)
    if owner is None or owner == threading.get_ident():
        return
    _record(KIND_REGISTRY,
            f"instrument {name!r} created on thread "
            f"{threading.current_thread().name!r}, but its registry "
            "belongs to another thread: create instruments at module "
            "import, record from anywhere",
            span=tracing.current_id())
    raise SanitizeError(
        f"metrics instrument {name!r} created off the registry's owning "
        "thread (SPACEMESH_SANITIZE)")


# --- 3. compile-explosion guard -----------------------------------------


def on_jit_shape(fn_name: str, lanes: int) -> None:
    """Called at the fused-label dispatch boundary with the lane count
    entering the jit. Off-bucket (non-power-of-two) shapes raise: they
    bypass the warmed executable population and mint a fresh compile."""
    if KIND_SHAPE not in _modes:
        return
    try:
        lanes = int(lanes)
    except (TypeError, ValueError):
        return  # symbolic/traced dim: not a host dispatch
    if lanes >= 1 and lanes & (lanes - 1) == 0:
        return
    _record(KIND_SHAPE,
            f"{fn_name} dispatched {lanes} lanes — outside the "
            "power-of-two bucket grid the autotuner warms; some caller "
            "bypassed the pad-and-trim wrappers (shape_bucket)",
            span=tracing.current_id())
    raise SanitizeError(
        f"{fn_name}: off-bucket jit shape {lanes} (SPACEMESH_SANITIZE; "
        "see docs/STATIC_ANALYSIS.md)")


# --- 4. lockset race detection ------------------------------------------
#
# Held-lockset entries are ``(name, id(raw lock))``: the ORDER watcher
# reasons over names (every LabelWriter's ``_lock`` is one node), the
# CANDIDATE locksets intersect over instances (another writer's lock
# does not protect this writer's cursor).

_tls = threading.local()


def _held() -> set:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = set()
    return held


_order_lock = threading.Lock()
# (held-name, acquired-name) -> stack text at first observation
_order_edges: dict[tuple[str, str], str] = {}
_in_report = threading.local()


def _order_reaches(src: str, dst: str) -> bool:
    seen: set[str] = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(b for (a, b) in _order_edges if a == n)
    return False


def _note_acquire(key: tuple) -> None:
    """Order check + held-set insert for a tracked lock acquisition."""
    held = _held()
    if held and not getattr(_in_report, "on", False):
        bn = key[0]
        stack = None
        for hk in held:
            an = hk[0]
            if an == bn:
                continue
            with _order_lock:
                known = (an, bn) in _order_edges
                if not known:
                    inversion = _order_reaches(bn, an)
                    other = _order_edges.get((bn, an))
                    if stack is None:
                        stack = _caller_stack(3)
                    _order_edges[(an, bn)] = stack
            if not known and inversion:
                _in_report.on = True
                try:
                    _record(KIND_ORDER,
                            f"lock-order inversion: {bn} acquired while "
                            f"holding {an}, but the opposite order was "
                            "observed earlier — two threads taking the "
                            "two paths deadlock",
                            span=tracing.current_id(), stack=stack,
                            other_stack=other)
                finally:
                    _in_report.on = False
    held.add(key)


class TrackedLock:
    """``threading.Lock`` twin feeding the per-thread held-lockset."""

    __slots__ = ("_raw", "name", "_key")

    def __init__(self, name: str, raw=None):
        self._raw = raw if raw is not None else threading.Lock()
        self.name = name
        self._key = (name, id(self._raw))

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._raw.acquire(blocking, timeout)
        if ok and _race:
            _note_acquire(self._key)
        return ok

    def release(self) -> None:
        _held().discard(self._key)
        self._raw.release()

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class TrackedCondition:
    """``threading.Condition`` twin; shares its root lock's held-set
    key, so ``with cond:`` counts as holding the lock it wraps (the
    ``Condition(self._lock)`` aliasing the static SC007 rule models)."""

    __slots__ = ("_cond", "name", "_key")

    def __init__(self, name: str, lock=None):
        if isinstance(lock, TrackedLock):
            self._cond = threading.Condition(lock._raw)
            self._key = lock._key
        else:
            self._cond = threading.Condition(lock)
            self._key = (name, id(self._cond._lock))
        self.name = name

    def acquire(self, *a) -> bool:
        ok = self._cond.acquire(*a)
        if ok and _race:
            _note_acquire(self._key)
        return ok

    def release(self) -> None:
        _held().discard(self._key)
        self._cond.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: float | None = None) -> bool:
        # wait() drops the lock while parked and reacquires before
        # returning; the held-set must mirror that or every waiter
        # looks like it holds the lock across the whole wait
        held = _held()
        held.discard(self._key)
        try:
            return self._cond.wait(timeout)
        finally:
            if _race:
                held.add(self._key)

    def wait_for(self, predicate, timeout: float | None = None):
        held = _held()
        held.discard(self._key)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            if _race:
                held.add(self._key)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


def lock(name: str):
    """A lock for sanitizer-aware modules: tracked when race mode is
    armed at CONSTRUCTION, a raw ``threading.Lock`` (zero overhead)
    otherwise."""
    return TrackedLock(name) if _race else threading.Lock()


def condition(name: str, lock=None):
    """Condition twin of :func:`lock`; pass the owning tracked lock to
    share its critical-section identity."""
    if _race or isinstance(lock, TrackedLock):
        return TrackedCondition(name, lock)
    return threading.Condition(lock)


class SharedField:
    """One declared-shared object (a cursor, a table, a subscriber
    list).  ``touch(write=...)`` is the access hook — one module-level
    flag check when race mode is off.

    ``mode="lockset"``  Eraser: candidates := held at the first access
    after a second thread joins, then intersect on every access; an
    empty candidate set with a cross-thread write in play reports.
    ``mode="owner-write"``  the loop-affinity contract: any thread may
    read, only the first writing thread may write (the runtime twin of
    ``# spacecheck: loop-only``).
    """

    __slots__ = ("name", "mode", "_armed", "_threads", "_writer",
                 "_candidates", "_shared", "_written_shared",
                 "_last_by_thread", "_last_tid", "_reported",
                 "_state_lock")

    def __init__(self, name: str, mode: str = "lockset"):
        if mode not in ("lockset", "owner-write"):
            raise ValueError(f"unknown SharedField mode {mode!r}")
        self.name = name
        self.mode = mode
        # armed at CONSTRUCTION, like lock()/condition(): a field built
        # while race mode was off pairs with RAW locks the held-set
        # never sees — refining it later would only manufacture false
        # races (arm via the env var to watch import-time singletons)
        self._armed = _race
        self._threads: set[int] = set()
        self._writer: int | None = None
        self._candidates: set | None = None   # None = exclusive phase
        self._shared = False
        self._written_shared = False
        self._last_by_thread: dict[int, tuple[str, int | None]] = {}
        self._last_tid: int | None = None
        self._reported = False
        self._state_lock = threading.Lock()

    def reset(self) -> None:
        """Forget ownership/lockset history — for owners whose state is
        legitimately recreated (LaneGroup.bind() to a fresh event loop:
        the new loop may live on a different thread, and the dead
        loop's thread must not be remembered as the owner)."""
        with self._state_lock:
            self._threads = set()
            self._writer = None
            self._candidates = None
            self._shared = False
            self._written_shared = False
            self._last_by_thread = {}
            self._last_tid = None
            self._reported = False

    def touch(self, write: bool = True) -> None:
        if not _race or not self._armed:
            return
        tid = threading.get_ident()
        held = frozenset(_held())
        stack = _caller_stack(2)
        span = tracing.current_id()
        report = None
        with self._state_lock:
            self._threads.add(tid)
            if self.mode == "owner-write":
                if write:
                    if self._writer is None:
                        self._writer = tid
                    elif self._writer != tid and not self._reported:
                        self._reported = True
                        report = self._report_args(
                            tid, f"{self.name}: write from thread "
                            f"{threading.current_thread().name!r} but "
                            "the field is owner-write (loop-only): "
                            "first writer owns mutation")
            else:
                if len(self._threads) > 1:
                    if not self._shared:
                        self._shared = True
                        self._candidates = set(held)
                    else:
                        self._candidates &= held
                    if write:
                        self._written_shared = True
                    if (not self._candidates and self._written_shared
                            and not self._reported):
                        self._reported = True
                        report = self._report_args(
                            tid, f"{self.name}: no common lock protects "
                            "this field across its accessing threads "
                            "(candidate lockset is empty)")
            self._last_by_thread[tid] = (stack, span)
            self._last_tid = tid
        if report is not None:
            detail, other_thread, other_stack = report
            _record(KIND_RACE, detail, span=span, stack=stack,
                    other_thread=other_thread, other_stack=other_stack)

    # guarded by: self._state_lock — touch() is the only caller and holds it
    def _report_args(self, tid: int, detail: str):
        other_thread = other_stack = None
        for otid, (ostack, _ospan) in self._last_by_thread.items():
            if otid != tid:
                other_thread, other_stack = str(otid), ostack
        return detail, other_thread, other_stack


# --- lifecycle ----------------------------------------------------------


def enable(slow_threshold_s: float | None = None,
           modes=None) -> None:
    """Arm the sanitizer (``modes`` None = every kind).  Note that
    :func:`lock`/:func:`condition` decide at construction: objects
    built before ``enable()`` stay untracked."""
    global _enabled, _modes, _race, _slow_threshold_s
    if slow_threshold_s is not None:
        _slow_threshold_s = float(slow_threshold_s)
    if modes is None:
        _modes = frozenset(KINDS)
    else:
        kept: set[str] = set()
        for m in modes:
            kind = _MODE_ALIASES.get(m)
            if kind is None:
                # same contract as parse_modes: a typo'd token must
                # never SILENTLY disarm a check the caller believes on
                _log.warning("sanitize: unknown enable() mode %r "
                             "ignored (known: %s)", m, ",".join(KINDS))
                continue
            kept.add(kind)
        _modes = frozenset(kept)
    _race = KIND_RACE in _modes
    _patch_handle()
    _enabled = bool(_modes)


def disable() -> None:
    """Disarm (the Handle patch stays installed but inert)."""
    global _enabled, _modes, _race
    _enabled = False
    _race = False
    _modes = frozenset()


def _boot() -> None:
    modes = parse_modes(os.environ.get(ENV))
    if not modes:
        return
    enable(parse_slow_threshold(os.environ.get(ENV_SLOW_MS)), modes)


_boot()
