"""Metrics: counters/gauges/histograms with Prometheus text exposition.

Mirrors the reference's metrics layer (reference metrics/: per-package
prometheus counters + a scrape server; curated public metrics
metrics/public/public.go). Subsystems register instruments on the global
registry; the API serves /metrics in exposition format.
"""

from __future__ import annotations

import threading
from collections import defaultdict

from . import sanitize


def _escape(value) -> str:
    """Escape a label VALUE per the Prometheus text exposition format:
    backslash, double-quote and newline must be escaped inside the
    quoted value, or one peer id / reason string containing a quote
    corrupts the entire /metrics scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labelstr(labels) -> str:
    return ",".join(f'{k}="{_escape(v)}"' for k, v in labels)


class _Instrument:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        # series maps are DECLARED SHARED to the lockset sanitizer
        # (SPACEMESH_SANITIZE=race): every access must hold this lock,
        # which the tracked twin feeds into the per-thread held-lockset
        self._lock = sanitize.lock(f"metrics.{name}")
        self._shared = sanitize.SharedField(f"metrics.{name}.series")

    def _series_map(self) -> dict:
        return self._values  # Histogram overrides (its map is _series)

    def remove_matching(self, **labels) -> int:
        """Drop every labelset CONTAINING these label items — the
        per-entity series-removal pattern (PR 10's
        ``runtime_tenant_queued.remove``) extended to instruments whose
        entity label rides with others (``{client=..., kind=...}``):
        when the entity goes away, all of its series must leave the
        scrape, or a churn of short-lived clients grows the registry
        without bound. Returns the number of series removed."""
        items = set(labels.items())
        with self._lock:
            self._shared.touch()
            m = self._series_map()
            gone = [k for k in m if items.issubset(set(k))]
            for k in gone:
                del m[k]
        return len(gone)


class Counter(_Instrument):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, value: float = 1.0, **labels) -> None:
        with self._lock:
            self._shared.touch()
            self._values[tuple(sorted(labels.items()))] += value

    def sample(self) -> dict[tuple, float]:
        """Point-in-time {labelset: value} snapshot (obs/sli.py sampler)."""
        with self._lock:
            self._shared.touch(write=False)
            return dict(self._values)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            for labels, v in self._values.items():
                lbl = _labelstr(labels)
                out.append(f"{self.name}{{{lbl}}} {v}" if lbl
                           else f"{self.name} {v}")
        return out


class Gauge(_Instrument):
    def __init__(self, name, help_=""):
        super().__init__(name, help_)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._shared.touch()
            self._values[tuple(sorted(labels.items()))] = value

    def remove(self, **labels) -> None:
        """Drop one labelset's series — a gauge describing something
        that no longer exists (an unregistered health component) must
        disappear from the scrape, not pin its last value forever."""
        with self._lock:
            self._shared.touch()
            self._values.pop(tuple(sorted(labels.items())), None)

    def sample(self) -> dict[tuple, float]:
        with self._lock:
            self._shared.touch(write=False)
            return dict(self._values)

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            for labels, v in self._values.items():
                lbl = _labelstr(labels)
                out.append(f"{self.name}{{{lbl}}} {v}" if lbl
                           else f"{self.name} {v}")
        return out


class Histogram(_Instrument):
    """Bucketed distribution with label support: each distinct labelset
    carries its own buckets/sum/count series (like Counter/Gauge), so
    e.g. verify-farm dispatch timings split per request kind instead of
    blending signatures and POST proofs into one histogram."""

    DEFAULT_BUCKETS = (0.005, 0.05, 0.5, 5.0, 50.0, float("inf"))

    def __init__(self, name, help_="", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = tuple(buckets)
        # labelset -> [per-bucket counts, sum, count]
        self._series: dict[tuple, list] = {}

    def _series_map(self) -> dict:
        return self._series

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._shared.touch()
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [[0] * len(self.buckets), 0.0, 0]
            s[1] += value
            s[2] += 1
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s[0][i] += 1

    def sample(self) -> dict[tuple, tuple[list, float, int]]:
        """{labelset: (cumulative bucket counts, sum, count)} snapshot."""
        with self._lock:
            self._shared.touch(write=False)
            return {k: (list(s[0]), s[1], s[2])
                    for k, s in self._series.items()}

    def expose(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            series = [(k, [list(s[0]), s[1], s[2]])
                      for k, s in self._series.items()]
        for labels, (counts, sum_, n) in series:
            base = _labelstr(labels)
            sep = "," if base else ""
            for b, c in zip(self.buckets, counts):
                le = "+Inf" if b == float("inf") else b
                out.append(f'{self.name}_bucket{{{base}{sep}le="{le}"}} {c}')
            out.append(f"{self.name}_sum{{{base}}} {sum_}" if base
                       else f"{self.name}_sum {sum_}")
            out.append(f"{self.name}_count{{{base}}} {n}" if base
                       else f"{self.name}_count {n}")
        return out


class Registry:
    def __init__(self) -> None:
        self._instruments: dict[str, _Instrument] = {}
        self._collectors: list = []
        self._lock = sanitize.lock("metrics.registry")
        self._shared = sanitize.SharedField("metrics.registry.instruments")
        # the owning thread: instrument CREATION belongs at module
        # import on this thread; recording is thread-safe from anywhere.
        # The runtime sanitizer (utils/sanitize.py, SPACEMESH_SANITIZE)
        # asserts this affinity on the create branch of _get.
        self._created_thread = threading.get_ident()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "", buckets=None) -> Histogram:
        inst = self._get(
            name, lambda: Histogram(name, help_,
                                    buckets or Histogram.DEFAULT_BUCKETS),
            Histogram)
        # re-registering with DIFFERENT buckets used to silently return
        # the original instrument — the caller would then record into a
        # bucket layout it never asked for and every quantile computed
        # from the deltas would be wrong without a trace
        if buckets is not None and tuple(buckets) != inst.buckets:
            raise ValueError(
                f"histogram {name} already registered with buckets "
                f"{inst.buckets}, re-registration asked for "
                f"{tuple(buckets)}")
        return inst

    def _get(self, name, factory, cls):
        with self._lock:
            self._shared.touch()
            inst = self._instruments.get(name)
            if inst is None:
                sanitize.on_instrument_create(name, self)
                inst = self._instruments[name] = factory()
            elif not isinstance(inst, cls):
                raise TypeError(f"{name} already registered as "
                                f"{type(inst).__name__}")
            return inst

    # --- scrape-time collectors ---------------------------------------

    def add_collector(self, fn) -> None:
        """Register a zero-arg hook run before every scrape/sample.

        Collectors recompute gauges whose truth lives elsewhere (event
        queue depths, process RSS, open fds) at OBSERVATION time instead
        of trusting the last write — a gauge set on emit and never
        decayed lies to every later scrape."""
        with self._lock:
            self._shared.touch()
            self._collectors.append(fn)

    def run_collectors(self) -> None:
        with self._lock:
            self._shared.touch(write=False)
            fns = list(self._collectors)
        for fn in fns:
            try:
                fn()
            except Exception:  # noqa: BLE001 — one bad hook ≠ dead scrape
                pass

    def sample(self) -> dict[str, tuple[str, object]]:
        """Run collectors, then snapshot every instrument:
        {name: (kind, data)} where kind is counter/gauge/histogram and
        data is the instrument's ``sample()`` (histograms additionally
        carry their bucket bounds). The SLI sampler diffs two of these."""
        self.run_collectors()
        with self._lock:
            self._shared.touch(write=False)
            instruments = list(self._instruments.items())
        out: dict[str, tuple[str, object]] = {}
        for name, inst in instruments:
            if isinstance(inst, Histogram):
                out[name] = ("histogram", {"buckets": inst.buckets,
                                           "series": inst.sample()})
            elif isinstance(inst, Counter):
                out[name] = ("counter", inst.sample())
            else:
                out[name] = ("gauge", inst.sample())
        return out

    def expose(self) -> str:
        self.run_collectors()
        with self._lock:
            self._shared.touch(write=False)
            instruments = list(self._instruments.values())
        lines: list[str] = []
        for inst in instruments:
            lines.extend(inst.expose())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# curated "public" metrics (reference metrics/public/public.go)
layer_gauge = REGISTRY.gauge("node_current_layer", "wall-clock layer")
verified_gauge = REGISTRY.gauge("tortoise_verified_layer", "verified frontier")
post_init_seconds = REGISTRY.histogram("post_init_seconds",
                                       "POST init session duration")
proofs_generated = REGISTRY.counter("post_proofs_generated", "proofs made")
proofs_verified = REGISTRY.counter("post_proofs_verified",
                                   "proofs verified (label=result)")
peers_gauge = REGISTRY.gauge("p2p_connected_peers", "connected peers")
sync_state_gauge = REGISTRY.gauge(
    "sync_state", "0 notSynced, 1 gossipSync, 2 synced")
tortoise_mode_gauge = REGISTRY.gauge(
    "tortoise_mode", "0 verifying, 1 full (reference tortoise/metrics.go)")
applied_gauge = REGISTRY.gauge("mesh_last_applied_layer", "applied frontier")

# POST init streaming pipeline (post/initializer.py). Stage seconds carry a
# stage label (dispatch/fetch/write/stall) so an operator can see where a
# slow init is actually spending its time without a full profile.
post_pipeline_dispatched = REGISTRY.counter(
    "post_pipeline_batches_dispatched_total",
    "label batches enqueued on the accelerator")
post_pipeline_inflight = REGISTRY.gauge(
    "post_pipeline_inflight_batches", "device batches currently in flight")
post_pipeline_queue_depth = REGISTRY.gauge(
    "post_pipeline_write_queue_depth", "label writes queued for disk")
post_pipeline_stall_seconds = REGISTRY.counter(
    "post_pipeline_stall_seconds_total",
    "dispatch-loop seconds blocked on writer backpressure")
post_pipeline_stage_seconds = REGISTRY.counter(
    "post_pipeline_stage_seconds_total",
    "host seconds per pipeline stage (label=stage)")
post_pipeline_meta_saves = REGISTRY.counter(
    "post_pipeline_meta_saves_total", "interval resume-metadata rewrites")
post_pipeline_labels_per_sec = REGISTRY.gauge(
    "post_pipeline_labels_per_sec", "labels/s of the last init session")

# autotuned device mesh (ops/autotune.py mesh dimension, consumed by
# post/initializer.py + post/prover.py). Shard fetch seconds include the
# first shard's wait for the sharded program to retire; the imbalance
# gauge is (max-min)/max over the last batch's per-shard fetch seconds,
# so a straggling device (or an unevenly split host thread pool) is
# visible without a trace capture.
post_mesh_devices = REGISTRY.gauge(
    "post_mesh_devices",
    "device count label batches are sharded over (1 = single device)")
post_mesh_shard_labels_per_sec = REGISTRY.gauge(
    "post_mesh_shard_labels_per_sec",
    "mean per-shard label fetch throughput of the last sharded batch")
post_mesh_shard_imbalance = REGISTRY.gauge(
    "post_mesh_shard_imbalance",
    "(max-min)/max per-shard fetch seconds of the last sharded batch")

# ROMix label kernel (ops/scrypt.py dispatch + ops/autotune.py). The
# fallback counter makes a Pallas selection that silently degraded to the
# XLA path visible (an explicit SPACEMESH_ROMIX=pallas request raises
# instead of counting here).
post_romix_fallback = REGISTRY.counter(
    "post_romix_fallback_total",
    "Pallas ROMix selections that fell back to the XLA path "
    "(label=reason)")
post_romix_autotune_races = REGISTRY.counter(
    "post_romix_autotune_races_total",
    "ROMix kernel autotune races run (persisted-winner cache misses)")

# POST label-store reads (post/data.py LabelStore.read_labels — the serial
# prover and the prefetching LabelReader pool both land here). The prove
# pipeline's disk-frugality contract ("at most one pass over the store per
# scanned nonce window") is asserted against these counters in tests.
post_store_read_calls = REGISTRY.counter(
    "post_store_read_calls_total", "label-store read_labels invocations")
post_store_read_bytes = REGISTRY.counter(
    "post_store_read_bytes_total", "label bytes read back from disk")
post_store_read_retries = REGISTRY.counter(
    "post_store_read_retries_total",
    "transient-EIO label reads retried with backoff (post/data.py)")

# POST store crash safety (post/data.py recover_store + LabelWriter
# fsync discipline, post/faultfs.py injection — docs/CRASH_SAFETY.md)
post_store_fsyncs = REGISTRY.counter(
    "post_store_fsyncs_total",
    "label-file fsyncs at checkpoint/drain boundaries")
post_store_fault_injections = REGISTRY.counter(
    "post_store_fault_injections_total",
    "disk faults fired by a faultfs plan (label=kind)")
post_store_recovery_runs = REGISTRY.counter(
    "post_store_recovery_runs_total",
    "reopens where recovery repaired files or rolled the cursor back")
post_store_recovery_truncated_bytes = REGISTRY.counter(
    "post_store_recovery_truncated_bytes_total",
    "torn/un-fsynced label bytes truncated on reopen")
post_store_recovery_intervals_dropped = REGISTRY.counter(
    "post_store_recovery_intervals_dropped_total",
    "checkpoint intervals that failed CRC verification on reopen")
post_store_degraded = REGISTRY.gauge(
    "post_store_degraded",
    "1 while the label writer is parked waiting out ENOSPC")
post_store_enospc_waits = REGISTRY.counter(
    "post_store_enospc_waits_total",
    "ENOSPC retry waits entered by the label writer pool")

# POST proving streaming pipeline (post/prover.py). Stage seconds carry a
# stage label (read/dispatch/retire) mirroring the init pipeline's.
post_prove_windows = REGISTRY.counter(
    "post_prove_windows_total", "nonce windows swept over the label store")
post_prove_batches = REGISTRY.counter(
    "post_prove_batches_total", "label batches dispatched by the prover")
post_prove_early_exits = REGISTRY.counter(
    "post_prove_early_exits_total",
    "prove passes cut short once the winning nonce was decided")
post_prove_stage_seconds = REGISTRY.counter(
    "post_prove_stage_seconds_total",
    "host seconds per prove pipeline stage (label=stage)")
post_prove_d2h_bytes = REGISTRY.counter(
    "post_prove_d2h_bytes_total",
    "bytes copied device->host by the prover (compacted hits, not masks)")
post_prove_labels_per_sec = REGISTRY.gauge(
    "post_prove_labels_per_sec",
    "store labels covered per second by the last prove call")
post_prove_inflight = REGISTRY.gauge(
    "post_prove_inflight", "proving sessions currently running (grpc worker)")

# device-job runtime (spacemesh_tpu/runtime/): the shared
# submit->batch->dispatch->retire engine all four device pipelines run
# on, plus the multi-tenant scheduler on top. Every series carries the
# workload `kind`; per-identity series carry `tenant` ("-" when the
# embedder is single-tenant).
runtime_dispatched = REGISTRY.counter(
    "runtime_batches_dispatched_total",
    "device batches dispatched through the runtime engine "
    "(labels: kind, tenant)")
runtime_retired = REGISTRY.counter(
    "runtime_batches_retired_total",
    "device batches retired (results consumed) (labels: kind, tenant)")
runtime_inflight = REGISTRY.gauge(
    "runtime_inflight_batches",
    "device batches currently in flight (label: kind)")
runtime_stage_seconds = REGISTRY.counter(
    "runtime_stage_seconds_total",
    "host seconds per engine stage (labels: kind, stage)")
runtime_fallbacks = REGISTRY.counter(
    "runtime_fallbacks_total",
    "dispatch failures absorbed by a workload's device-failure "
    "fallback (label: kind)")
runtime_tenant_jobs = REGISTRY.counter(
    "runtime_tenant_jobs_total",
    "scheduler jobs by outcome (labels: tenant, kind, state)")
runtime_tenant_queued = REGISTRY.gauge(
    "runtime_tenant_queued_jobs",
    "jobs queued per tenant in the scheduler (label: tenant)")
runtime_tenant_labels = REGISTRY.counter(
    "runtime_tenant_labels_total",
    "init labels computed+written through the scheduler (label: tenant)")
runtime_pack_occupancy = REGISTRY.histogram(
    "runtime_pack_occupancy_lanes",
    "lanes per packed multi-tenant init dispatch",
    buckets=(64, 128, 256, 512, 1024, 2048, 4096, 8192, float("inf")))
runtime_pack_tenants = REGISTRY.histogram(
    "runtime_pack_tenants",
    "distinct tenants per packed init dispatch",
    buckets=(1, 2, 4, 8, 16, 32, float("inf")))
runtime_quantum_seconds = REGISTRY.counter(
    "runtime_quantum_seconds_total",
    "worker seconds per scheduler quantum (labels: kind, tenant)")
runtime_deadline_boosts = REGISTRY.counter(
    "runtime_deadline_boosts_total",
    "quanta admitted by deadline (EDF) ahead of fair-share order")

# verification farm (verify/farm.py): the micro-batching admission
# service for signatures / VRFs / POST proofs / poet membership.
verify_farm_requests = REGISTRY.counter(
    "verify_farm_requests_total",
    "verification requests submitted (labels: kind, lane)")
verify_farm_dedup_hits = REGISTRY.counter(
    "verify_farm_dedup_hits_total",
    "requests coalesced onto an identical in-flight request")
verify_farm_batches = REGISTRY.counter(
    "verify_farm_batches_total", "batches dispatched (label: kind)")
verify_farm_batch_occupancy = REGISTRY.histogram(
    "verify_farm_batch_occupancy", "requests per dispatched batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, float("inf")))
verify_farm_dispatch_seconds = REGISTRY.histogram(
    "verify_farm_dispatch_seconds",
    "backend seconds per batch (label: kind)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, float("inf")))
verify_farm_queue_depth = REGISTRY.gauge(
    "verify_farm_queue_depth", "pending requests (label: lane)")

# verifyd — verification-as-a-service (spacemesh_tpu/verifyd/). Every
# per-client series is REMOVED on unregister (remove_matching above) and
# the client population is bounded by the service's max_clients knob, so
# a connect-flood cannot grow the registry without bound.
verifyd_clients = REGISTRY.gauge(
    "verifyd_clients", "registered verifyd clients")
verifyd_client_pending = REGISTRY.gauge(
    "verifyd_client_pending_items",
    "admitted items in flight per client (label: client)")
verifyd_pending = REGISTRY.gauge(
    "verifyd_pending_items", "admitted items in flight, all clients")
verifyd_requests = REGISTRY.counter(
    "verifyd_requests_total",
    "verification requests by outcome (labels: client, outcome)")
verifyd_items = REGISTRY.counter(
    "verifyd_items_total",
    "verification items admitted (labels: client, kind)")
verifyd_shed = REGISTRY.counter(
    "verifyd_shed_total",
    "requests shed with a typed reason (labels: client, reason)")
verifyd_request_seconds = REGISTRY.histogram(
    "verifyd_request_seconds",
    "admitted request latency, admission to verdicts (label: lane)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, float("inf")))
verifyd_batchtune_races = REGISTRY.counter(
    "verifyd_batchtune_races_total",
    "batch-size calibration races run (persisted-rates cache misses)")

# pubsub delivery hardening (p2p/pubsub.py): a raising handler is
# counted + logged, never allowed to abort delivery to the remaining
# subscribers.
pubsub_handler_drops = REGISTRY.counter(
    "pubsub_handler_drops_total",
    "handler exceptions swallowed during delivery (label: topic)")

# event bus (node/events.py): subscription overflow used to be a silent
# per-subscription boolean — lossy API event streams were invisible until
# a consumer noticed a sequence gap. The counter fires per dropped event
# (label=type); the gauge tracks the DEEPEST subscription queue on each
# emit, so a consumer falling behind shows up before it overflows.
events_overflows = REGISTRY.counter(
    "events_subscription_overflows_total",
    "events dropped on full subscription queues (label: type)")
events_queue_depth = REGISTRY.gauge(
    "events_queue_depth",
    "deepest subscription queue, recomputed at scrape time")

# span tracer (utils/tracing.py): capture state for operators reading
# /metrics while a /debug/trace capture runs.
trace_enabled_gauge = REGISTRY.gauge(
    "trace_capture_enabled", "1 while the span tracer is recording")
trace_spans_gauge = REGISTRY.gauge(
    "trace_spans_recorded",
    "spans recorded by the current capture (incl. ring overwrites)")

# --- health & SLO engine substrate (spacemesh_tpu/obs/) -----------------
#
# The windowed-SLI sampler (obs/sli.py) interpolates p50/p95/p99 from
# BUCKET DELTAS of these histograms over a rolling window, so bucket
# layouts are chosen to straddle each signal's healthy range (a quantile
# is only as sharp as the bucket it lands in).

layer_apply_seconds = REGISTRY.histogram(
    "layer_apply_seconds",
    "mesh.process_layer wall seconds (tortoise tally + apply)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, float("inf")))
gossip_handler_seconds = REGISTRY.histogram(
    "gossip_handler_seconds",
    "per-handler gossip validation seconds (label: topic)",
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0, float("inf")))
verify_farm_queue_wait_seconds = REGISTRY.histogram(
    "verify_farm_queue_wait_seconds",
    "submit -> batch-take queue wait seconds (label: kind)",
    buckets=(0.001, 0.003, 0.01, 0.05, 0.25, 1.0, 10.0, float("inf")))
post_prove_window_seconds = REGISTRY.histogram(
    "post_prove_window_seconds",
    "wall seconds per prove nonce-window disk pass",
    buckets=(0.05, 0.25, 1.0, 5.0, 30.0, 120.0, 600.0, float("inf")))
post_pipeline_labels = REGISTRY.counter(
    "post_pipeline_labels_total",
    "labels fetched to host by the init pipeline (rate = init labels/s)")

# runtime collectors (obs/sli.py register_runtime_collectors): recomputed
# by scrape-time hooks, not trusted last writes
process_rss_bytes = REGISTRY.gauge(
    "process_resident_memory_bytes", "resident set size")
process_open_fds = REGISTRY.gauge(
    "process_open_fds", "open file descriptors")
event_loop_lag = REGISTRY.gauge(
    "runtime_event_loop_lag_seconds",
    "asyncio scheduling lag measured by the health engine heartbeat")

# SLO evaluation (obs/health.py HealthEngine)
slo_healthy = REGISTRY.gauge(
    "slo_healthy", "1 while the SLO is met (label: slo)")
slo_burn = REGISTRY.gauge(
    "slo_burn_rate",
    "violating fraction of the SLO window, 0..1 (label: slo)")
slo_breaches = REGISTRY.counter(
    "slo_breaches_total", "healthy->breach transitions (label: slo)")

# component health + stall watchdogs (obs/health.py HealthRegistry)
component_healthy = REGISTRY.gauge(
    "component_healthy", "1 while the liveness probe passes "
    "(label: component)")
component_stalls = REGISTRY.counter(
    "component_stalls_total",
    "healthy->unhealthy probe transitions (label: component)")

# flight recorder (obs/flight.py)
flight_bundles = REGISTRY.counter(
    "flight_bundles_total", "diagnostic bundles written (label: trigger)")

# remediation engine + circuit breakers (obs/remediate.py). Per-component
# breaker series are REMOVED when the breaker unregisters
# (remove/remove_matching — the PR-12 cardinality pattern), so pipeline
# churn cannot grow the registry without bound.
remediation_actions = REGISTRY.counter(
    "remediation_actions_total",
    "recovery actions decided by the remediation engine "
    "(labels: component, action, outcome)")
remediation_breaker_state = REGISTRY.gauge(
    "remediation_breaker_state",
    "0 closed, 1 open, 2 half-open, 3 quarantined (label: component)")
remediation_breaker_transitions = REGISTRY.counter(
    "remediation_breaker_transitions_total",
    "breaker state transitions (labels: component, to)")

# verifyd failover client (verifyd/failover.py): requests by serving
# path, and the latency the node actually saw regardless of path — the
# signal that proves a verifyd outage never dented the BLOCK lane.
failover_requests = REGISTRY.counter(
    "failover_requests_total",
    "failover verifier batches by serving path "
    "(labels: path=remote|local|local_fastfail, lane)")
failover_verify_seconds = REGISTRY.histogram(
    "failover_verify_seconds",
    "failover verifier batch latency by serving path "
    "(labels: path, lane)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, float("inf")))

# verifyd fleet (verifyd/fleet.py + routing.py): the replica-sharded
# service plane.  Per-replica series are REMOVED when the replica
# unregisters from the router (remove/remove_matching — the PR-12
# cardinality pattern), so fleet membership churn cannot grow the
# registry without bound.
fleet_replicas = REGISTRY.gauge(
    "fleet_replicas", "verifyd replicas registered on the router")
fleet_desired_replicas = REGISTRY.gauge(
    "fleet_desired_replicas",
    "autoscaling signal: replicas the fleet's windowed load wants")
fleet_replica_load = REGISTRY.gauge(
    "fleet_replica_load_score",
    "windowed load score per replica, ~1.0 = at target (label: replica)")
fleet_clients = REGISTRY.gauge(
    "fleet_clients", "clients placed by the fleet router")
fleet_requests = REGISTRY.counter(
    "fleet_requests_total",
    "fleet verifier batches by serving path "
    "(labels: path=<replica>|local|local_fastfail, lane)")
fleet_verify_seconds = REGISTRY.histogram(
    "fleet_verify_seconds",
    "fleet verifier batch latency by origin "
    "(labels: path=remote|local|local_fastfail, lane)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, float("inf")))
fleet_replica_verify_seconds = REGISTRY.histogram(
    "fleet_replica_verify_seconds",
    "per-replica remote verify latency — the steal/autoscale queue-wait "
    "signal (labels: replica, lane)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, float("inf")))
fleet_replica_sheds = REGISTRY.counter(
    "fleet_replica_sheds_total",
    "typed sheds seen per replica — the steal/autoscale pressure "
    "signal (labels: replica, reason)")
fleet_reroutes = REGISTRY.counter(
    "fleet_reroutes_total",
    "clients moved between replicas (labels: reason)")
fleet_steals = REGISTRY.counter(
    "fleet_steals_total",
    "batches stolen from a hot replica (labels: src, dst)")
fleet_audit_divergence = REGISTRY.counter(
    "fleet_audit_divergence_total",
    "remote batches whose spot-checked verdicts diverged from the "
    "local farm — byzantine replica detections (label: replica)")

# sim fabric (sim/net.py EventMeshHub): the O(edges-that-matter) claim
# made observable.  Hot paths bump plain ints; the hub flushes deltas
# once per heartbeat so a million-frame storm costs the registry ~one
# inc per virtual second, not per frame.
sim_fabric_events = REGISTRY.counter(
    "sim_fabric_events_total",
    "event-wheel calendar entries (labels: kind=scheduled|fired)")
sim_fabric_dirty = REGISTRY.gauge(
    "sim_fabric_heartbeat_dirty_nodes",
    "mesh nodes with pending control-plane work after the last beat "
    "(idle nodes cost zero — this staying << population is the win)")
sim_fabric_cache = REGISTRY.counter(
    "sim_fabric_cache_total",
    "fault-epoch cache lookups on reachable()/neighbors() "
    "(labels: result=hit|miss)")

# sharded fabric (sim/shard.py): the multi-process event wheel's
# conservative-window exchange plane
sim_shard_events = REGISTRY.counter(
    "sim_shard_events_total",
    "per-shard event-wheel activity merged at finalize "
    "(labels: shard, kind=fired)")
sim_shard_barrier_waits = REGISTRY.counter(
    "sim_shard_barrier_waits_total",
    "cross-shard exchange rounds (settlements + window grants) — the "
    "synchronization cost of the conservative protocol")
sim_shard_imbalance = REGISTRY.gauge(
    "sim_shard_imbalance_ratio",
    "(max - min) / max of events fired across shards at finalize — "
    "0 is a perfectly balanced partition")
sim_shard_worker_stats = REGISTRY.gauge(
    "sim_shard_worker_stat",
    "WORKER-side event-wheel stats set in the worker's own registry "
    "just before each federated snapshot ships (labels: shard, stat); "
    "the parent re-exposes them under proc=shard-<k> via obs.federate")
federated_procs = REGISTRY.gauge(
    "federated_procs",
    "processes with a live federated snapshot in obs.federate "
    "(labels: state=live|crashed); crashed snapshots are retained "
    "for forensics until explicitly dropped")

# runtime sanitizers (utils/sanitize.py, SPACEMESH_SANITIZE=1): each
# recorded violation — a slow event-loop callback, an off-thread
# instrument creation, an off-bucket jit dispatch — counts here so a
# sanitized soak run surfaces its findings on /metrics too
sanitize_violations = REGISTRY.counter(
    "sanitize_violations_total",
    "runtime sanitizer violations (label: kind)")
