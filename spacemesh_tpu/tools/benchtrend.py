"""Bench-trend gate: fail CI when a headline bench line regresses >10%.

The repo commits one ``BENCH_r<N>.json`` per growth round — the driver's
record of that round's ``python bench.py`` run, with the stderr log and
the emitted JSON metric lines in ``tail`` (the last line also parsed
into ``parsed``).  Those files are a free regression baseline that
nothing was diffing (ROADMAP #2 residual): a PR could halve the packer's
advantage and CI would stay green as long as the line still printed.

This tool diffs the CURRENT run's metric lines against the LATEST
committed ``BENCH_*.json`` and exits non-zero on any >10% drop.

What is compared — RATIO fields, not absolute rates, by default:
``vs_sequential``, ``vs_single``, ``vs_serial``, ``vs_baseline``,
``vs_legacy``, ``vs_single_process`` and ``speedup``.  Absolute labels/s are a property of the machine (a CI
runner generation swap would trip an absolute gate with no code
change), while the ratios are self-calibrated — both sides of each
ratio are measured in the same process on the same host, so a drop
means the RELATIVE win this repo exists to deliver shrank.
``--absolute`` additionally gates raw ``value`` fields for same-machine
workflows.  Lines whose identity gate failed (``bit_identical`` /
``verified`` false) are rejected outright — belt to bench.py's
exit-1 braces.

Metric names carry shape suffixes (``post_init_labels_per_sec_n8192_
b1024_cpufallback``); lines are matched by FAMILY — the name with the
``_n<N>_b<B>``/platform suffix stripped — so a baseline recorded at one
sweep shape still gates a run at another (the ratios are the
comparable part; shapes only move absolutes).  Families present on one
side only are reported, never failed: new metrics must be landable
without a baseline, and a skipped sub-bench (BENCH_TENANTS=0) must not
fail the gate.

Usage (CI: .github/workflows/tier1.yml mesh-smoke / runtime-smoke):
  python bench.py | tee bench_out.txt
  python -m spacemesh_tpu.tools.benchtrend --current bench_out.txt
Options: ``--baseline <file>`` (default: latest BENCH_*.json in the
repo root), ``--drop 0.10``, ``--absolute``, ``--require <family>``
(fail if the family is absent from the current run; repeatable).

``--history`` renders the FULL committed trajectory instead of gating:
every BENCH_r01..rNN in round order, one table per metric family with
the absolute rate and each ratio column, a ``v`` marker on any
round-over-round drop beyond ``--drop``. The gate only ever compares
against the latest round, so a slow leak (-5% per round for five
rounds) is invisible to it — the history view is where that trend
shows up. Report-only: always exits 0.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

RATIO_FIELDS = ("vs_sequential", "vs_single", "vs_serial", "vs_baseline",
                "vs_legacy", "vs_single_process", "speedup")
GATE_FLAGS = ("bit_identical", "verified")

_SUFFIX = re.compile(r"(_n\d+)?(_b\d+)?(_cpufallback)?$")


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def family(metric: str) -> str:
    """The metric name with its shape/platform suffix stripped."""
    return _SUFFIX.sub("", metric)


def metric_lines(text: str) -> dict[str, dict]:
    """{family: line-doc} for every JSON metric line in ``text``; the
    LAST line of a family wins (bench prints one per family per run)."""
    out: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            doc = json.loads(line)
        except ValueError:
            continue
        if isinstance(doc, dict) and isinstance(doc.get("metric"), str):
            out[family(doc["metric"])] = doc
    return out


def latest_baseline(root: str) -> str | None:
    """The committed BENCH_r<N>.json with the highest round number."""
    best, best_n = None, -1
    for path in glob.glob(os.path.join(root, "BENCH_*.json")):
        try:
            n = int(json.load(open(path, encoding="utf-8")).get("n", -1))
        except (OSError, ValueError):
            continue
        if n > best_n:
            best, best_n = path, n
    return best


def baseline_lines(path: str) -> dict[str, dict]:
    """Metric lines recorded in one committed BENCH_*.json (its ``tail``
    carries the run's stdout JSON lines; ``parsed`` the last of them)."""
    doc = json.load(open(path, encoding="utf-8"))
    lines = metric_lines(doc.get("tail") or "")
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("metric"), str):
        lines.setdefault(family(parsed["metric"]), parsed)
    return lines


def all_baselines(root: str) -> list[tuple[int, str]]:
    """Every committed BENCH_*.json as (round, path), round order."""
    rounds = []
    for path in glob.glob(os.path.join(root, "BENCH_*.json")):
        try:
            n = int(json.load(open(path, encoding="utf-8")).get("n", -1))
        except (OSError, ValueError):
            continue
        if n >= 0:
            rounds.append((n, path))
    return sorted(rounds)


def history(root: str, *, drop: float = 0.10) -> dict:
    """Per-family trajectory across ALL committed rounds.

    -> {"rounds": [n, ...], "families": {family: [row, ...]}} where each
    row carries the round, the absolute ``value``, every ratio field the
    line had, and ``regressed``: the fields that fell more than ``drop``
    vs the PREVIOUS round the family appeared in."""
    fams: dict[str, list[dict]] = {}
    rounds = all_baselines(root)
    for n, path in rounds:
        try:
            lines = baseline_lines(path)
        except (OSError, ValueError):
            continue
        for fam, doc in lines.items():
            row: dict = {"round": n}
            for f in ("value",) + RATIO_FIELDS:
                if isinstance(doc.get(f), (int, float)):
                    row[f] = float(doc[f])
            fams.setdefault(fam, []).append(row)
    for rows in fams.values():
        prev: dict | None = None
        for row in rows:
            row["regressed"] = [
                f for f, v in row.items()
                if f != "round" and isinstance(v, float) and prev
                and isinstance(prev.get(f), float) and prev[f] > 0
                and v < prev[f] * (1.0 - drop)]
            prev = row
    return {"rounds": [n for n, _ in rounds],
            "families": dict(sorted(fams.items()))}


def render_history(doc: dict) -> str:
    """Text tables (stderr view) for ``history()``'s output."""
    out = []
    for fam, rows in doc["families"].items():
        fields = [f for f in ("value",) + RATIO_FIELDS
                  if any(f in r for r in rows)]
        out.append(f"{fam}:")
        out.append("  round" + "".join(f"{f:>20}" for f in fields))
        for r in rows:
            cells = []
            for f in fields:
                v = r.get(f)
                cell = "-" if v is None else f"{v:,.4g}"
                if f in r["regressed"]:
                    cell += " v"
                cells.append(f"{cell:>20}")
            out.append(f"  r{r['round']:<4}" + "".join(cells))
    return "\n".join(out) if out else "(no BENCH_*.json rounds found)"


def compare(base: dict[str, dict], cur: dict[str, dict], *,
            drop: float = 0.10, absolute: bool = False) -> dict:
    """-> {"failures": [...], "compared": [...], "only_*": [...]}."""
    failures, compared = [], []
    for fam in sorted(set(base) & set(cur)):
        b, c = base[fam], cur[fam]
        for flag in GATE_FLAGS:
            if c.get(flag) is False:
                failures.append({"family": fam, "field": flag,
                                 "baseline": True, "current": False,
                                 "reason": "identity gate failed"})
        fields = [f for f in RATIO_FIELDS
                  if isinstance(b.get(f), (int, float))
                  and isinstance(c.get(f), (int, float))]
        if absolute and isinstance(b.get("value"), (int, float)) \
                and isinstance(c.get("value"), (int, float)):
            fields.append("value")
        for f in fields:
            bv, cv = float(b[f]), float(c[f])
            ok = bv <= 0 or cv >= bv * (1.0 - drop)
            compared.append({"family": fam, "field": f,
                             "baseline": bv, "current": cv, "ok": ok})
            if not ok:
                failures.append({
                    "family": fam, "field": f, "baseline": bv,
                    "current": cv,
                    "reason": f"dropped {(1 - cv / bv) * 100:.0f}% "
                              f"(gate: {drop * 100:.0f}%)"})
    return {"failures": failures, "compared": compared,
            "only_baseline": sorted(set(base) - set(cur)),
            "only_current": sorted(set(cur) - set(base))}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchtrend",
        description="fail on >10%% drops vs the last committed "
                    "BENCH_*.json (ratio fields; see module docstring)")
    ap.add_argument("--current", default=None,
                    help="file of bench.py stdout (JSON metric lines); "
                    "'-' reads stdin (required unless --history)")
    ap.add_argument("--history", action="store_true",
                    help="render the full BENCH_r01..rNN trajectory per "
                    "metric family (rate + ratio columns, 'v' regression "
                    "markers) instead of gating; always exits 0")
    ap.add_argument("--baseline", default=None,
                    help="baseline BENCH_*.json (default: highest-round "
                    "BENCH_*.json under --root)")
    ap.add_argument("--root", default=".",
                    help="repo root to search for BENCH_*.json")
    ap.add_argument("--drop", type=float, default=0.10,
                    help="max tolerated fractional drop (default 0.10)")
    ap.add_argument("--absolute", action="store_true",
                    help="also gate absolute 'value' fields "
                    "(same-machine baselines only)")
    ap.add_argument("--require", action="append", default=[],
                    help="metric family that must be present in the "
                    "current run (repeatable)")
    a = ap.parse_args(argv)

    if a.history:
        doc = history(a.root, drop=a.drop)
        _log(render_history(doc))
        print(json.dumps(doc, indent=1))
        return 0
    if not a.current:
        ap.error("--current is required unless --history")

    base_path = a.baseline or latest_baseline(a.root)
    if base_path is None:
        _log("benchtrend: no BENCH_*.json baseline found; nothing to gate")
        print(json.dumps({"baseline": None, "failures": []}))
        return 0
    try:
        base = baseline_lines(base_path)
    except (OSError, ValueError) as e:
        _log(f"benchtrend: unreadable baseline {base_path} ({e})")
        return 2
    cur_text = sys.stdin.read() if a.current == "-" else open(
        a.current, encoding="utf-8").read()
    cur = metric_lines(cur_text)

    result = compare(base, cur, drop=a.drop, absolute=a.absolute)
    result["baseline"] = base_path
    for fam in a.require:
        if family(fam) not in cur:
            result["failures"].append({
                "family": family(fam), "field": None,
                "reason": "required family missing from current run"})
    for row in result["compared"]:
        _log(f"benchtrend: {row['family']}.{row['field']}: "
             f"{row['baseline']} -> {row['current']} "
             f"{'ok' if row['ok'] else 'REGRESSED'}")
    for fam in result["only_baseline"]:
        _log(f"benchtrend: {fam}: baseline only (not in current run)")
    for fam in result["only_current"]:
        _log(f"benchtrend: {fam}: new metric (no baseline; not gated)")
    print(json.dumps(result, indent=1))
    if result["failures"]:
        _log(f"benchtrend: FAILED — {len(result['failures'])} "
             f"regression(s) vs {os.path.basename(base_path)}")
        return 1
    _log(f"benchtrend: ok vs {os.path.basename(base_path)} "
         f"({len(result['compared'])} comparisons)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
