"""Operator benchmark / tuning tool (VERDICT r3 missing item 6).

The reference exposes provider enumeration + benchmarking so an operator
can pick the POST compute device and batch size before committing to a
multi-day init (reference activation/post_supervisor.go:105-127
Providers()/Benchmark(); post-rs ships a standalone `profiler` binary).
The TPU-native equivalents:

- ``providers`` — every JAX device visible from this process (the TPU
  chip under axon, CPU otherwise) plus the OpenSSL scrypt paths
  (single-core and all-cores), which are the reference CPU provider's
  exact labeling function;
- ``benchmark`` — labels/second per provider across batch sizes, with a
  recommendation (provider + batch) an operator can paste into the
  smeshing config.

Usage:
  python -m spacemesh_tpu.tools.profiler --providers
  python -m spacemesh_tpu.tools.profiler --n 8192 --batches 1024,2048
  python -m spacemesh_tpu.tools.profiler --pipeline --n 8192   # per-stage
  python -m spacemesh_tpu.tools.profiler --prove               # prove view
  python -m spacemesh_tpu.tools.profiler --verify-farm         # farm view
  python -m spacemesh_tpu.tools.profiler --romix --n 8192      # kernel view
  python -m spacemesh_tpu.tools.profiler --timeline trace.json # flame view
Prints ONE JSON document on stdout; progress goes to stderr. --pipeline
runs a real (tiny) init through the streaming pipeline and dumps per-stage
host seconds (dispatch/fetch/write/stall) so stalls are visible without a
full profile (docs/POST_PIPELINE.md). --timeline digests a span-trace
export (``/debug/trace/export`` or utils/tracing.export_json): top spans
by self-time plus a per-stage queue-wait vs work split, with the text
flame summary on stderr (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import functools
import hashlib
import json
import os
import sys
import time


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def providers(probe: bool = True) -> list[dict]:
    """Enumerate label-compute providers (post_supervisor.go:105
    Providers()). The XLA pipeline runs on the DEFAULT device — one row
    represents it (with the device count), since benchmarking the same
    default-device computation once per visible device would report N
    identical rows for N compiles' worth of wall time."""
    from ..utils import accel

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; JAX restricted to CPU")
    import jax

    devs = jax.devices()
    out = [{
        "id": f"jax:{devs[0].id}",
        "kind": getattr(devs[0], "device_kind", "?"),
        "platform": devs[0].platform,
        "devices": len(devs),
        "impl": "xla-scrypt",
    }]
    out.append({"id": "cpu:openssl", "kind": "single core",
                "platform": "cpu", "impl": "hashlib.scrypt"})
    out.append({"id": "cpu:openssl-mt",
                "kind": f"{os.cpu_count()} threads",
                "platform": "cpu", "impl": "hashlib.scrypt"})
    return out


def _cpu_rate(commitment: bytes, n: int, count: int,
              threads: int = 1) -> float:
    def burst(start: int, m: int) -> None:
        for i in range(start, start + m):
            hashlib.scrypt(commitment, salt=i.to_bytes(8, "little"),
                           n=n, r=1, p=1, maxmem=256 * 1024 * 1024,
                           dklen=16)

    t0 = time.perf_counter()
    if threads <= 1:
        burst(0, count)
    else:
        per = max(count // threads, 1)
        with concurrent.futures.ThreadPoolExecutor(threads) as pool:
            # hashlib.scrypt releases the GIL: real parallelism
            futs = [pool.submit(burst, k * per, per)
                    for k in range(threads)]
            for f in futs:
                f.result()
        count = per * threads
    return count / (time.perf_counter() - t0)


def _jax_rate(commitment: bytes, n: int, batch: int, reps: int) -> float:
    import jax.numpy as jnp
    import numpy as np

    from ..ops import scrypt

    cw = jnp.asarray(scrypt.commitment_to_words(commitment))
    lo_, hi_ = scrypt.split_indices(np.arange(batch, dtype=np.uint64))
    lo, hi = jnp.asarray(lo_), jnp.asarray(hi_)
    t0 = time.perf_counter()
    scrypt.scrypt_labels_jit(cw, lo, hi, n=n).block_until_ready()
    _log(f"  batch={batch}: compile+first {time.perf_counter() - t0:.1f}s")
    rate = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        scrypt.scrypt_labels_jit(cw, lo, hi, n=n).block_until_ready()
        rate = max(rate, batch / (time.perf_counter() - t0))
    return rate


def benchmark(n: int, batches: list[int], reps: int,
              cpu_labels: int, probe: bool = True) -> dict:
    """Per-provider labels/s + a tuning recommendation
    (post_supervisor.go:117 Benchmark())."""
    commitment = hashlib.sha256(b"profiler-commitment").digest()
    provs = providers(probe=probe)
    results = []
    for p in provs:
        if p["id"].startswith("jax:"):
            best, best_batch = 0.0, 0
            for batch in batches:
                try:
                    rate = _jax_rate(commitment, n, batch, reps)
                except Exception as e:  # noqa: BLE001 — e.g. HBM OOM
                    _log(f"  batch={batch}: failed "
                         f"({type(e).__name__}: {e})")
                    continue
                _log(f"{p['id']} batch={batch}: {rate:,.0f} labels/s")
                if rate > best:
                    best, best_batch = rate, batch
            results.append({**p, "labels_per_sec": round(best, 1),
                            "best_batch": best_batch})
        else:
            threads = os.cpu_count() if p["id"].endswith("-mt") else 1
            rate = _cpu_rate(commitment, n, cpu_labels, threads)
            _log(f"{p['id']}: {rate:,.1f} labels/s")
            results.append({**p, "labels_per_sec": round(rate, 1),
                            "best_batch": None})
    results.sort(key=lambda r: -r["labels_per_sec"])
    winner = results[0]
    recommendation = {
        "provider": winner["id"],
        "labels_per_sec": winner["labels_per_sec"],
    }
    if winner["best_batch"]:
        recommendation["init_batch"] = winner["best_batch"]
    su = 1 << 32  # labels per space unit (mainnet.go:186)
    if winner["labels_per_sec"] > 0:
        recommendation["hours_per_space_unit"] = round(
            su / winner["labels_per_sec"] / 3600, 1)
    return {"scrypt_n": n, "providers": results,
            "recommendation": recommendation}


def pipeline_benchmark(n: int, labels: int, batch: int,
                       inflight: int | None = None,
                       writers: int | None = None,
                       probe: bool = True) -> dict:
    """Per-stage timings of the streaming init pipeline (dispatch/fetch/
    write/stall), so an operator can see where a slow init spends its time
    without a full profile. Runs a real (tiny) init through
    post/initializer.py and dumps its PipelineStats."""
    import tempfile

    from ..post import initializer
    from ..utils import accel

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; JAX restricted to CPU")
    node = hashlib.sha256(b"profiler-pipe-node").digest()
    commit = hashlib.sha256(b"profiler-pipe-commit").digest()
    with tempfile.TemporaryDirectory() as d:
        _, res = initializer.initialize(
            d, node_id=node, commitment=commit, num_units=1,
            labels_per_unit=labels, scrypt_n=n,
            max_file_size=64 * 1024 * 1024, batch_size=batch,
            inflight=inflight, writers=writers)
    stats = res.stats.as_dict() if res.stats else {}
    doc = {
        "scrypt_n": n, "labels": labels, "batch": batch,
        "labels_per_sec": round(res.labels_per_s, 1),
        "elapsed_s": round(res.elapsed_s, 2),
        "stages": {k: (round(v, 4) if isinstance(v, float) else v)
                   for k, v in stats.items()},
    }
    busiest = max(("dispatch_s", "fetch_s", "write_stall_s"),
                  key=lambda k: stats.get(k, 0.0))
    doc["bottleneck"] = busiest
    return doc


def prove_benchmark(labels: int, batch: int,
                    window_groups: int | None = None,
                    inflight: int | None = None,
                    probe: bool = True) -> dict:
    """Per-stage timings of the streaming prove pipeline (read/dispatch/
    retire) against the legacy serial scan over the same tiny store, so an
    operator can see where prove time goes — and whether the sound early
    exit fired — before pointing the prover at a multi-TiB label store
    (docs/POST_PROVING.md). The deterministic fixture is shared with
    bench.py (spacemesh_tpu/post/workload.py)."""
    import tempfile

    from ..post import workload
    from ..utils import accel

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; JAX restricted to CPU")
    with tempfile.TemporaryDirectory() as d:
        prover = workload.build(d, labels, batch,
                                window_groups=window_groups,
                                inflight=inflight)
        res = workload.compare_serial_vs_pipelined(prover, reps=1)
    stats = res["stats"]
    doc = {
        "labels": labels, "batch": batch,
        "proof_nonce": res["proof"].nonce,
        "serial_s": round(res["serial_s"], 4),
        "pipelined_s": round(res["pipelined_s"], 4),
        "speedup": round(res["speedup"], 2) if res["speedup"] else None,
        "stages": {k: (round(v, 4) if isinstance(v, float) else v)
                   for k, v in stats.items()},
    }
    busiest = max(("read_wait_s", "dispatch_s", "retire_s"),
                  key=lambda k: stats.get(k, 0.0))
    doc["bottleneck"] = busiest
    return doc


def romix_roofline(n: int, r: int = 1, p: int = 1,
                   labels_per_sec: float | None = None,
                   gbps: float | None = None) -> dict:
    """Analytic memory-traffic roofline for one scrypt label.

    ROMix moves the V scratch exactly twice per label: the fill phase
    writes all N blocks of 128*r bytes, the mix phase reads N blocks
    back in data-dependent order — 2*128*r*N bytes per label per
    parallel chunk (p). Compute cost is 2 BlockMix passes of 2*r
    Salsa20/8 cores each: 4*N*r*p Salsa20/8 calls per label. Both
    follow from N/r/p alone, so a measured labels/s converts directly
    into achieved DRAM/HBM bandwidth and (given a peak, via ``gbps``
    or ``SPACEMESH_ROOFLINE_GBPS``) a utilization fraction — the
    number that says whether the kernel is bandwidth-bound or leaving
    the memory system idle."""
    n, r, p = int(n), int(r), int(p)
    bytes_per_label = 2 * 128 * r * n * p
    out = {
        "bytes_per_label": bytes_per_label,
        "salsa20_8_per_label": 4 * n * r * p,
    }
    if gbps is None:
        gbps = float(os.environ.get("SPACEMESH_ROOFLINE_GBPS", "0") or 0)
    if labels_per_sec:
        out["achieved_gbps"] = round(
            bytes_per_label * float(labels_per_sec) / 1e9, 3)
    if gbps > 0:
        out["roofline_gbps"] = gbps
        out["roofline_labels_per_sec"] = round(gbps * 1e9
                                               / bytes_per_label, 1)
        if labels_per_sec:
            out["utilization"] = round(out["achieved_gbps"] / gbps, 4)
    return out


def romix_benchmark(n: int, batch: int, reps: int = 2,
                    include_pallas: bool | None = None,
                    probe: bool = True) -> dict:
    """Per-stage timings of the label kernel — expand (PBKDF2 first),
    fill (ROMix phase 1), mix (ROMix phase 2), finish (PBKDF2 second) —
    for the tuned XLA variant and, on TPU (or with --romix-pallas), the
    Pallas DMA kernel, on the SAME calibration workload the autotuner
    races (ops/autotune.py). The fill/mix split runs the kernel once
    with the mix phase compiled out and subtracts."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import autotune, scrypt
    from ..utils import accel

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; JAX restricted to CPU")
    platform = jax.default_backend()
    decision = autotune.decide(n, batch, platform=platform)

    commitment = hashlib.sha256(b"profiler-romix").digest()
    cw = jnp.asarray(scrypt.commitment_to_words(commitment))
    lo_, hi_ = scrypt.split_indices(np.arange(batch, dtype=np.uint64))
    lo, hi = jnp.asarray(lo_), jnp.asarray(hi_)
    x = jnp.asarray(autotune.calibration_block(batch))

    def best_of(fn):
        fn().block_until_ready()  # compile + warm
        t = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn().block_until_ready()
            t = min(t, time.perf_counter() - t0)
        return t

    # the PBKDF2 envelope stages are implementation-independent
    expand_s = best_of(lambda: scrypt._stage_expand(cw, lo, hi)[2])
    inner, outer, blk0 = scrypt._stage_expand(cw, lo, hi)
    finish_s = best_of(lambda: scrypt._stage_finish(inner, outer, blk0))

    if include_pallas is None:
        include_pallas = platform == "tpu"
    rows = []
    variants = [(decision.impl if decision.impl != "pallas" else "xla",
                 decision.chunk)]
    if include_pallas:
        variants.append(("pallas", None))
    for impl, chunk in variants:
        interpret = impl == "pallas" and platform != "tpu"
        if interpret:
            _log("pallas timings run in INTERPRET mode (every DMA "
                 "executes in Python) — correctness-grade, not perf")
        try:
            kw = dict(n=n, impl=impl, chunk=chunk, interpret=interpret)
            fill_s = best_of(functools.partial(
                scrypt.romix_tuned, x, mix_phase=False, **kw))
            romix_s = best_of(functools.partial(scrypt.romix_tuned, x, **kw))
        except Exception as e:  # noqa: BLE001 — e.g. pallas on hosts
            # without Mosaic; the operator still gets the other rows
            _log(f"{impl}: failed ({type(e).__name__}: {e})")
            continue
        total = expand_s + romix_s + finish_s
        rate = round(batch / total, 1)
        # roofline against the ROMix phase alone (the only stage that
        # touches V): the PBKDF2 envelope would dilute the bandwidth
        # number with compute that moves no scratch memory
        roof = romix_roofline(n, labels_per_sec=batch / romix_s)
        line = (f"{impl}: {roof['bytes_per_label']:,} B/label, "
                f"{roof['salsa20_8_per_label']:,} salsa20/8 calls/label")
        if "achieved_gbps" in roof:
            line += f", {roof['achieved_gbps']} GB/s achieved"
        if "utilization" in roof:
            line += (f" = {roof['utilization'] * 100:.1f}% of "
                     f"{roof['roofline_gbps']} GB/s roofline")
        elif "achieved_gbps" in roof:
            line += (" (set SPACEMESH_ROOFLINE_GBPS=<peak> for a "
                     "utilization fraction)")
        _log(line)
        rows.append({
            "impl": impl, "chunk": chunk, "interpret": interpret,
            "stages": {"expand_s": round(expand_s, 4),
                       "fill_s": round(fill_s, 4),
                       "mix_s": round(max(romix_s - fill_s, 0.0), 4),
                       "finish_s": round(finish_s, 4)},
            "romix_s": round(romix_s, 4),
            "labels_per_sec": rate,
            "roofline": roof,
        })
    return {"scrypt_n": n, "batch": batch,
            "decision": decision.as_json(), "impls": rows}


def verify_benchmark(counts: list[int], reps: int = 2,
                     probe: bool = True) -> dict:
    """Proof-verification throughput (BASELINE config 3: batch of NIPoST
    proofs through the vmapped verifier vs the reference's CPU worker
    pool). Builds one tiny real unit + proof (scrypt N=2), then measures
    verify_many over batches of that proof — proofs are LANES in the
    batched pass, so duplicates exercise the same compute path as
    distinct proofs."""
    import tempfile

    from ..post import initializer, verifier
    from ..post.prover import ProofParams, Prover
    from ..utils import accel

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; JAX restricted to CPU")
    node = hashlib.sha256(b"profiler-node").digest()
    commit = hashlib.sha256(b"profiler-commit").digest()
    challenge = hashlib.sha256(b"profiler-challenge").digest()
    params = ProofParams(k1=64, k2=16, k3=8,
                         pow_difficulty=bytes([32]) + bytes([255]) * 31)
    rates = []
    with tempfile.TemporaryDirectory() as d:
        meta, _ = initializer.initialize(
            d, node_id=node, commitment=commit, num_units=2,
            labels_per_unit=512, scrypt_n=2, max_file_size=4096,
            batch_size=256)
        proof = Prover(d, params, batch_labels=512).prove(challenge)
        item = verifier.VerifyItem(
            proof=proof, challenge=challenge, node_id=node,
            commitment=commit, scrypt_n=meta.scrypt_n,
            total_labels=meta.total_labels)
        for count in counts:
            batch = [item] * count
            best = 0.0
            for _ in range(reps + 1):  # first rep pays the compile
                t0 = time.perf_counter()
                ok = verifier.verify_many(batch, params)
                best = max(best, count / (time.perf_counter() - t0))
                if not all(ok):
                    # a throughput number for proofs that FAILED would
                    # be worse than no number (and `assert` vanishes
                    # under python -O)
                    raise RuntimeError("verifier rejected a valid proof")
            _log(f"verify batch={count}: {best:,.0f} proofs/s")
            rates.append({"batch": count, "proofs_per_sec": round(best, 1)})
    return {"verify": rates}


def verify_farm_benchmark(items: int = 256, probe: bool = True) -> dict:
    """The verification farm (spacemesh_tpu/verify/) against the inline
    serial path on one mixed workload, with the farm's own telemetry
    (batch occupancy, per-lane queue peaks, dispatch seconds, dedup
    hits) so an operator can see the coalescing behavior, not just the
    end-to-end ratio."""
    import tempfile

    from ..utils import accel
    from ..verify import workload

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; JAX restricted to CPU")
    posts = max(items // 8, 4)
    vrfs = max(items // 16, 4)
    mems = max(items // 16, 4)
    sigs = max(items - posts - vrfs - mems, 8)
    with tempfile.TemporaryDirectory() as d:
        w = workload.build(d, sigs=sigs, vrfs=vrfs, posts=posts,
                           memberships=mems, post_challenges=4)
        doc = workload.compare_serial_vs_farm(w)
    return {
        "items": doc["items"],
        "rejected": doc["rejected"],
        "decisions_match": True,  # compare_serial_vs_farm raises otherwise
        "serial_s": round(doc["serial_s"], 3),
        "batched_s": round(doc["batched_s"], 3),
        "speedup": doc["speedup"],
        "farm": {k: (round(v, 4) if isinstance(v, float) else v)
                 for k, v in doc["stats"].items()},
    }


def _drop_hint(warnings: list[str]) -> str | None:
    """A loud, actionable capacity hint when any capture dropped spans.

    Drops are the one failure mode that silently corrupts every number
    in a timeline (self-time, queue-wait splits, link counts all become
    lower bounds), so the hint has to be impossible to miss."""
    if not warnings:
        return None
    lines = ["!" * 66]
    lines += [f"!!! {w}" for w in warnings]
    lines += [
        "!!! Self-time, wait/work splits and link counts below are",
        "!!! LOWER BOUNDS. Re-capture with a larger span ring:",
        "!!!   scenario scripts:  \"trace_capacity\": <spans>",
        "!!!   capture-from-boot: SPACEMESH_TRACE=<spans>",
        "!!!   verifyd replicas:  /debug/trace/start?capacity=<spans>",
        "!" * 66,
    ]
    return "\n".join(lines)


def timeline_view(path: str, top: int = 20) -> dict:
    """Digest one or more captured span traces (tools view over
    utils/tracing.summarize): validates the trace-event JSON first, so a
    truncated or hand-edited capture fails loudly, not with a nonsense
    flame summary. A comma-separated list of captures (one per process)
    is merged into a single federated timeline via
    tracing.merge_captures before summarizing."""
    from ..utils import tracing

    docs = []
    for one in str(path).split(","):
        one = one.strip()
        if not one:
            continue
        with open(one, encoding="utf-8") as f:
            docs.append(json.load(f))
    doc = docs[0] if len(docs) == 1 else tracing.merge_captures(docs)
    warnings = tracing.validate(doc)
    summary = tracing.summarize(doc, top=top)
    _log(tracing.render_summary(summary))
    hint = _drop_hint(warnings)
    if hint:
        _log(hint)
    other = doc.get("otherData", {})
    return {
        "trace": path,
        "merged": len(docs) > 1,
        "captured_spans": other.get("captured_spans"),
        "dropped_spans": other.get("dropped_spans"),
        **summary,
    }


def flight_view(path: str, top: int = 10) -> dict:
    """Digest a flight-recorder bundle (obs/flight.py): validates the
    trace export and the metrics snapshot while loading, then summarizes
    what was unhealthy and where the captured time went."""
    from ..obs import flight as flight_mod
    from ..utils import tracing

    bundle = flight_mod.read_bundle(path)
    doc = flight_mod.digest(bundle, top=top)
    lines = [f"flight bundle: {doc['bundle']}",
             f"  reason: {doc['reason']}  ready: {doc['ready']}"]
    for name, reason in (doc["unhealthy_components"] or {}).items():
        lines.append(f"  unhealthy {name}: {reason}")
    for name, ent in (doc["breached_slos"] or {}).items():
        lines.append(f"  breached SLO {name}: value={ent['value']} "
                     f"target={ent['target']} burn={ent['burn']}")
    for name, ent in (doc["procs"] or {}).items():
        lines.append(f"  proc {name}: {ent['spans']} spans"
                     + ("  [CRASHED — retained snapshot]"
                        if ent["crashed"] else ""))
    _log("\n".join(lines))
    # render over the MERGED timeline (parent + every procs/ child),
    # the same doc digest() summarized — not the parent capture alone
    procs = bundle.get("procs") or {}
    child = [ent["trace"] for _, ent in sorted(procs.items())
             if ent.get("trace") is not None]
    merged = bundle["trace"] if not child else \
        tracing.merge_captures([bundle["trace"]] + child)
    _log(tracing.render_summary(tracing.summarize(merged, top=top)))
    hint = _drop_hint(doc.get("trace_warnings") or [])
    if hint:
        _log(hint)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="profiler",
        description="POST provider enumeration + label benchmark")
    ap.add_argument("--providers", action="store_true",
                    help="list providers only, no benchmark")
    ap.add_argument("--verify", action="store_true",
                    help="benchmark proof verification instead of labels")
    ap.add_argument("--verify-batches", default="100,1000",
                    help="comma-separated proof batch sizes for --verify")
    ap.add_argument("--verify-farm", action="store_true",
                    help="serial vs farm-batched mixed verification + "
                    "farm telemetry (occupancy, lanes, dedup)")
    ap.add_argument("--verify-items", type=int, default=256,
                    help="workload size for --verify-farm")
    ap.add_argument("--pipeline", action="store_true",
                    help="profile the streaming init pipeline per stage "
                    "(dispatch/fetch/write/stall)")
    ap.add_argument("--pipeline-labels", type=int, default=4096,
                    help="labels for the --pipeline run")
    ap.add_argument("--pipeline-batch", type=int, default=1024)
    ap.add_argument("--inflight", type=int, default=None,
                    help="in-flight device batches for --pipeline/--prove")
    ap.add_argument("--writers", type=int, default=None,
                    help="writer threads for --pipeline")
    ap.add_argument("--prove", action="store_true",
                    help="profile the streaming prove pipeline per stage "
                    "(read/dispatch/retire) vs the legacy serial scan")
    ap.add_argument("--romix", action="store_true",
                    help="profile the label kernel per stage (expand/fill/"
                    "mix/finish) under the autotuned decision "
                    "(docs/ROMIX_KERNEL.md)")
    ap.add_argument("--romix-batch", type=int, default=None,
                    help="label lanes for --romix (default: the autotune "
                    "calibration batch)")
    ap.add_argument("--romix-pallas", action="store_true",
                    help="include the Pallas kernel in --romix even off-"
                    "TPU (interpret mode: minutes-slow, correctness-grade)")
    ap.add_argument("--prove-labels", type=int, default=16384,
                    help="store size for the --prove run")
    ap.add_argument("--prove-batch", type=int, default=2048)
    ap.add_argument("--window-groups", type=int, default=None,
                    help="nonce groups per disk pass for --prove")
    ap.add_argument("--n", type=int, default=8192, help="scrypt N")
    ap.add_argument("--batches", default="1024,2048,4096",
                    help="comma-separated label lanes per program")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--cpu-labels", type=int, default=16,
                    help="labels for the OpenSSL reference measurement")
    ap.add_argument("--warm", action="store_true",
                    help="pre-compile the autotuned winner shapes into "
                    "the persistent XLA cache (tools/warmcache.py)")
    ap.add_argument("--warm-batches", default="8192,4096,2048,1024,512",
                    help="batch sizes for --warm")
    ap.add_argument("--warm-prove", action="store_true",
                    help="--warm also compiles the prover's scan step")
    ap.add_argument("--timeline", metavar="TRACE_JSON[,TRACE_JSON...]",
                    default=None,
                    help="summarize a span-trace export (top spans by "
                    "self-time, per-stage wait-vs-work split) instead of "
                    "benchmarking; a comma-separated list merges one "
                    "capture per process into a federated timeline")
    ap.add_argument("--timeline-top", type=int, default=20,
                    help="rows in the --timeline self-time ranking")
    ap.add_argument("--flight", metavar="BUNDLE_DIR", default=None,
                    help="digest a flight-recorder bundle "
                    "(obs/flight.py spool entry): validates the trace "
                    "+ metrics snapshot, prints unhealthy components, "
                    "breached SLOs and a trace summary")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the accelerator liveness probe (tests)")
    a = ap.parse_args(argv)

    if a.timeline:
        # pure file digestion: no accelerator probe, no jax import
        print(json.dumps(timeline_view(a.timeline, top=a.timeline_top),
                         indent=2))
        return 0

    if a.flight:
        # pure file digestion too
        print(json.dumps(flight_view(a.flight, top=a.timeline_top),
                         indent=2))
        return 0

    if a.warm:
        from . import warmcache

        doc = warmcache.warm(
            a.n, [int(b) for b in a.warm_batches.split(",") if b],
            prove=a.warm_prove, probe=not a.no_probe)
        print(json.dumps(doc, indent=2))
        return 0

    from ..utils import accel

    # every benchmark below JITs; the persistent cache makes repeat runs
    # measure steady state instead of XLA compile time
    accel.enable_persistent_cache()

    if a.providers:
        print(json.dumps({"providers": providers(probe=not a.no_probe)},
                         indent=2))
        return 0
    if a.pipeline:
        doc = pipeline_benchmark(
            a.n, a.pipeline_labels, a.pipeline_batch,
            inflight=a.inflight, writers=a.writers, probe=not a.no_probe)
        print(json.dumps(doc, indent=2))
        return 0
    if a.romix:
        from ..ops import autotune

        doc = romix_benchmark(
            a.n, a.romix_batch or autotune.CAL_BATCH, reps=a.reps,
            include_pallas=True if a.romix_pallas else None,
            probe=not a.no_probe)
        print(json.dumps(doc, indent=2))
        return 0
    if a.prove:
        doc = prove_benchmark(
            a.prove_labels, a.prove_batch,
            window_groups=a.window_groups, inflight=a.inflight,
            probe=not a.no_probe)
        print(json.dumps(doc, indent=2))
        return 0
    if a.verify:
        doc = verify_benchmark(
            [int(b) for b in a.verify_batches.split(",")],
            reps=a.reps, probe=not a.no_probe)
        print(json.dumps(doc, indent=2))
        return 0
    if a.verify_farm:
        doc = verify_farm_benchmark(a.verify_items, probe=not a.no_probe)
        print(json.dumps(doc, indent=2))
        return 0
    doc = benchmark(a.n, [int(b) for b in a.batches.split(",")],
                    a.reps, a.cpu_labels, probe=not a.no_probe)
    print(json.dumps(doc, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
