"""Operator alias for the scenario engine CLI.

The engine lives in ``spacemesh_tpu/sim`` (docs/SCENARIOS.md); this
alias keeps it discoverable beside the other operator tools:

    python -m spacemesh_tpu.tools.simrun --scenario partition-heal \
        --light 64 --seed 7 --repeat 2

is exactly ``python -m spacemesh_tpu.sim ...``.
"""

from __future__ import annotations

import sys

from ..sim.__main__ import main

if __name__ == "__main__":
    sys.exit(main())
