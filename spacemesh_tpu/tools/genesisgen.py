"""genesisgen: mint genesis identities for a new network.

Mirrors the reference tool (reference cmd/genesisgen/main.go): given a
genesis time (RFC3339) and extra data, validates the genesis config,
derives the network's genesis id, and prints N freshly generated smesher
identities as JSON lines — private key, node id, and the initial POST
commitment (commitment_of(node_id, golden_atx), what `post init` needs).

  python -m spacemesh_tpu.tools.genesisgen \
      --time 2026-01-01T00:00:00Z --extra my-testnet -n 4
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="spacemesh_tpu.tools.genesisgen")
    p.add_argument("--time", required=True,
                   help="genesis time, RFC3339 (e.g. 2026-01-01T00:00:00Z)")
    p.add_argument("--extra", default="tpu-mainnet",
                   help="genesis extra data, 1..255 chars")
    p.add_argument("-n", type=int, default=10, help="number of identities")
    a = p.parse_args(argv)

    try:
        dt = datetime.datetime.fromisoformat(a.time.replace("Z", "+00:00"))
    except ValueError as e:
        print(f"invalid genesis time: {e}", file=sys.stderr)
        return 1
    if not 1 <= len(a.extra) <= 255:
        print("extra data must be 1..255 chars", file=sys.stderr)
        return 1

    from ..consensus.activation import commitment_of
    from ..core.hashing import sum256
    from ..core.signing import EdSigner
    from ..node.config import GenesisConfig

    genesis = GenesisConfig(time=dt.timestamp(), extra_data=a.extra)
    prefix = genesis.genesis_id
    golden = sum256(b"golden", prefix)
    print(json.dumps({"genesis_id": prefix.hex(),
                      "genesis_time": dt.isoformat(),
                      "extra_data": a.extra,
                      "golden_atx": golden.hex()}))
    for i in range(a.n):
        s = EdSigner(prefix=prefix)
        print(json.dumps({
            "n": i,
            "private": s.private_bytes().hex(),
            "id": s.node_id.hex(),
            "commitment": commitment_of(s.node_id, golden).hex(),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
