"""Pre-warm the persistent XLA compile cache for the autotuned shapes.

The label pipeline pays 17-26s of XLA compile per (N, batch) executable
on a cold host — a cost that dominates every short session (bench runs,
CI jobs, a node's first init batch after an upgrade). The persistent
compile cache (utils/accel.py) already makes that once-per-machine;
this tool makes it once-per-NOBODY by compiling exactly the shapes the
autotuned winners will run — ahead of time, so tier-1/bench/operator
sessions start warm (ISSUE 6; the CI warm-cache job publishes the
resulting cache directory and every other job restores it).

What gets compiled per (N, bucketed batch):

* the fused single-device label programs (``_labels_fused`` and the
  min-scan variant) under the autotuned single-device decision — the
  executables bench.py's sweep and the verifier's recomputes hit;
* when the mesh race says ``devices > 1``: the GSPMD-sharded twins via
  parallel/mesh.py — the executables the streaming initializer and the
  bench mesh headline hit;
* with ``--prove``: the streaming prover's scan step at its default
  (bucketed) batch.

Because decisions are taken through ops/autotune.py, a cold host races
first (and persists the winners beside the cache), so one warmcache run
leaves BOTH caches — executables and winners — ready. Shapes already in
the cache deserialize in well under a second; the per-shape ``compile_s``
in the output tells you which were actually cold.

Beyond the label shapes, every workload kind registered with the device
runtime (runtime/workloads.py: fused init, packed multi-tenant init,
prove scan step, verify batch, k2pow) warms its own executables at the
primary shape — so a cold 16-tenant start pays ZERO serialized compiles
across kinds (the runtime scheduler's first mixed admission hits a warm
cache for every kind it can dispatch).  ``--no-runtime`` skips that.

Usage:
  python -m spacemesh_tpu.tools.warmcache [--n 8192]
      [--batches 8192,4096,2048,1024,512] [--prove] [--no-mesh]
      [--no-probe] [--cached-shapes] [--no-runtime]
      [--pack-lanes 4096]
  python -m spacemesh_tpu.tools.profiler --warm      # same, via profiler

``--cached-shapes`` additionally warms every shape that already has a
persisted autotune winner for this platform (a machine that has run real
workloads re-warms what those workloads used).
"""

from __future__ import annotations

import argparse
import hashlib
import json
import sys
import time


def _log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _cached_shapes(platform: str) -> list[tuple[int, int]]:
    """(n, batch) pairs with a persisted autotune winner on this host."""
    from ..ops import autotune

    out = set()
    prefix = f"v{autotune.SCHEMA}:{platform}:"
    for key in autotune._load_cache():
        if not key.startswith(prefix):
            continue
        try:
            n_part, b_part = key[len(prefix):].split(":")[:2]
            out.add((int(n_part[1:]), int(b_part[1:])))
        except (ValueError, IndexError):
            continue
    return sorted(out)


def _warm_shape(n: int, batch: int, mesh_ok: bool) -> dict:
    """Compile (or cache-deserialize) every executable one (n, batch)
    shape runs at; returns per-program seconds."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import autotune, scrypt

    commitment = hashlib.sha256(b"warmcache").digest()
    cw = scrypt.commitment_to_words(commitment)
    idx = np.arange(batch, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    jcw, jlo, jhi = jnp.asarray(cw), jnp.asarray(lo), jnp.asarray(hi)

    doc: dict = {"n": n, "batch": batch, "programs": {}}

    def timed(name, fn):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        doc["programs"][name] = round(time.perf_counter() - t0, 2)
        _log(f"  {name}: {doc['programs'][name]}s")

    # single-device decision + fused programs (bench sweep, verifier)
    d1 = autotune.decide(n, batch)
    doc["impl"] = d1.impl
    doc["chunk"] = d1.chunk
    timed("labels_fused", lambda: scrypt.scrypt_labels_jit(
        jcw, jlo, jhi, n=n))
    timed("labels_min_fused", lambda: scrypt.scrypt_labels_with_min(
        jcw, jlo, jhi, jnp.asarray(scrypt.vrf_carry_init()), n=n)[0])

    if not mesh_ok:
        return doc
    dm = autotune.decide(n, batch, max_devices=None)
    doc["devices"] = dm.devices
    if dm.devices <= 1 or batch % dm.devices:
        return doc
    from ..parallel import mesh as pmesh

    mesh = pmesh.data_mesh(jax.devices()[:dm.devices])
    timed(f"labels_sharded_d{dm.devices}",
          lambda: pmesh.scrypt_labels_sharded(mesh, cw, lo, hi, n=n,
                                              impl=dm.impl))
    timed(f"labels_min_sharded_d{dm.devices}",
          lambda: pmesh.labels_with_min_sharded(
              mesh, cw, lo, hi, scrypt.vrf_carry_init(), n=n,
              impl=dm.impl)[0])
    # BOTH persisted mesh-shape winners (lane-sharded and V-sharded),
    # not just the routed one: a later re-race or SPACEMESH_ROMIX flip
    # that lands on the other layout must hit the compile cache, not pay
    # a cold GSPMD compile mid-session
    doc["mesh_shapes"] = {}
    for shape in autotune.MESH_SHAPES:
        sw = autotune.shape_winner(n, batch, shape, max_devices=None)
        if sw is None or sw.devices <= 1 or batch % sw.devices:
            continue
        doc["mesh_shapes"][shape] = {"impl": sw.impl,
                                     "devices": sw.devices}
        if (sw.impl, sw.devices) == (dm.impl, dm.devices):
            continue  # the routed winner above already compiled it
        smesh = pmesh.data_mesh(jax.devices()[:sw.devices])
        timed(f"labels_sharded_{shape}_d{sw.devices}",
              lambda sm=smesh, si=sw.impl: pmesh.scrypt_labels_sharded(
                  sm, cw, lo, hi, n=n, impl=si))
    return doc


def _warm_prove(batch: int) -> dict:
    """Compile the streaming prover's scan step at its bucketed batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import proving, scrypt

    b = scrypt.shape_bucket(-(-batch // proving.HIT_SEGMENT)
                            * proving.HIT_SEGMENT)
    ng, cap = 16, 37  # prover defaults (nonce_group, k2)
    cw = jnp.asarray(proving.challenge_words(bytes(32)))
    idx = np.arange(b, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    lw = jnp.zeros((4, b), jnp.uint32)
    counts, carry = proving.init_hit_state(ng, cap)
    t0 = time.perf_counter()
    out = proving.prove_scan_step_jit(
        cw, jnp.uint32(0), jnp.asarray(lo), jnp.asarray(hi), lw,
        jnp.uint32(1 << 30), counts, carry, jnp.uint32(b),
        jnp.uint32(0), jnp.uint32(0), n_nonces=ng, max_hits=cap)
    jax.block_until_ready(out)
    dt = round(time.perf_counter() - t0, 2)
    _log(f"  prove_scan_step b={b}: {dt}s")
    return {"batch": b, "nonce_group": ng, "compile_s": dt}


def _warm_runtime_kinds(n: int, batch: int, pack_lanes: int) -> dict:
    """Warm every registered runtime workload kind's executables.

    The packed init / verify kinds warm at the PACK bucket (the shape
    the multi-tenant scheduler composes), the rest at the session
    ``batch``; each kind's recipe lives beside the kind itself
    (runtime/workloads.py), so a new workload registered there is
    automatically covered here and by the CI warm-cache job.
    """
    from ..ops import scrypt
    from ..runtime import workloads

    pack = scrypt.shape_bucket(pack_lanes)
    out: dict = {}
    for kind in workloads.registered():
        b = pack if kind.name in ("init_pack", "verify") else batch
        _log(f"warming runtime kind {kind.name} (n={n} b={b}) ...")
        try:
            out[kind.name] = dict(kind.warm(n, b), batch=b)
        except Exception as e:  # noqa: BLE001 — e.g. OOM at big batches
            _log(f"  {kind.name} failed ({type(e).__name__}: {e})")
            out[kind.name] = {"failed": type(e).__name__}
    return out


def warm(n: int = 8192, batches: list[int] | None = None, *,
         mesh: bool = True, prove: bool = False,
         cached_shapes: bool = False, probe: bool = True,
         runtime_kinds: bool = True, pack_lanes: int = 4096) -> dict:
    """Warm the persistent caches; returns a JSON-able report."""
    import os

    from ..utils import accel

    if probe and not accel.ensure_usable_platform():
        _log("accelerator unreachable; warming the CPU fallback")
    if mesh and os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        # BEFORE any backend use (jax.default_backend below instantiates
        # it): expose the virtual host devices the mesh winners run on
        accel.ensure_host_devices()
    import jax

    platform = jax.default_backend()
    cache_dir = accel.enable_persistent_cache()
    _log(f"persistent compile cache: {cache_dir or 'DISABLED'}")

    from ..ops import autotune, scrypt

    shapes = {(n, scrypt.shape_bucket(b))
              for b in (batches or [8192, 4096, 2048, 1024, 512])}
    if cached_shapes:
        shapes.update(_cached_shapes(platform))
    t0 = time.perf_counter()
    done = []
    for sn, sb in sorted(shapes):
        _log(f"warming n={sn} b={sb} ...")
        try:
            done.append(_warm_shape(sn, sb, mesh))
        except Exception as e:  # noqa: BLE001 — e.g. OOM at big batches
            _log(f"  n={sn} b={sb} failed ({type(e).__name__}: {e})")
            done.append({"n": sn, "batch": sb,
                         "failed": type(e).__name__})
    doc = {
        "platform": platform,
        "devices_visible": jax.device_count(),
        "cache_dir": cache_dir,
        "autotune_cache": autotune.cache_path(),
        "shapes": done,
        "elapsed_s": round(time.perf_counter() - t0, 1),
    }
    if prove:
        doc["prove"] = _warm_prove(1 << 14)
    if runtime_kinds:
        primary = scrypt.shape_bucket(
            (batches or [8192])[0]) if batches else 8192
        doc["runtime_kinds"] = _warm_runtime_kinds(n, primary, pack_lanes)
        doc["elapsed_s"] = round(time.perf_counter() - t0, 1)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="warmcache",
        description="pre-compile the autotuned winner shapes into the "
                    "persistent XLA cache (docs/ROMIX_KERNEL.md)")
    ap.add_argument("--n", type=int, default=8192, help="scrypt N")
    ap.add_argument("--batches", default="8192,4096,2048,1024,512",
                    help="comma-separated label batch sizes (bucketed)")
    ap.add_argument("--no-mesh", action="store_true",
                    help="skip the sharded (multi-device) programs")
    ap.add_argument("--prove", action="store_true",
                    help="also warm the streaming prover's scan step")
    ap.add_argument("--cached-shapes", action="store_true",
                    help="also warm every shape with a persisted "
                    "autotune winner on this host")
    ap.add_argument("--no-probe", action="store_true",
                    help="skip the accelerator liveness probe (tests)")
    ap.add_argument("--no-runtime", action="store_true",
                    help="skip the registered runtime workload kinds "
                    "(fused/packed init, prove scan, verify, k2pow)")
    ap.add_argument("--pack-lanes", type=int, default=4096,
                    help="pack bucket for the multi-tenant init/verify "
                    "kind warms (runtime/scheduler.py pack_lanes)")
    a = ap.parse_args(argv)
    doc = warm(a.n, [int(b) for b in a.batches.split(",") if b],
               mesh=not a.no_mesh, prove=a.prove,
               cached_shapes=a.cached_shapes, probe=not a.no_probe,
               runtime_kinds=not a.no_runtime, pack_lanes=a.pack_lanes)
    print(json.dumps(doc, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
