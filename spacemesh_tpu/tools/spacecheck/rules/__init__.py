"""Rule registry: one module per rule, each exporting RULE + check()."""

from . import (sc001_clock, sc002_async_blocking, sc003_donation,
               sc004_pairing, sc005_metrics, sc006_excepts,
               sc007_lock_discipline, sc008_lock_order, sc009_durability,
               sc010_sharding)

ALL_RULES = (sc001_clock, sc002_async_blocking, sc003_donation,
             sc004_pairing, sc005_metrics, sc006_excepts,
             sc007_lock_discipline, sc008_lock_order, sc009_durability,
             sc010_sharding)

__all__ = ["ALL_RULES"]
