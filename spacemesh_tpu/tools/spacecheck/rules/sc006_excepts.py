"""SC006 bare-except / swallowed-error in consensus-critical packages.

Originating bugs: the PR 2 farm-vs-inline verification divergence hid
for a while behind broadly-caught handler paths, and PR 6's fuzz rider
(core/codec OverflowError crash) existed precisely because an untyped
stream-decode error escaped the intended except clause. In
``consensus/``, ``verify/`` and ``p2p/`` a silently swallowed error is
a consensus-split or a wedged sync in waiting — every broad catch must
either be justified in a comment or narrow its type and surface the
error (log, counter, re-raise).

Flags, in ``spacemesh_tpu/consensus/``, ``spacemesh_tpu/verify/``,
``spacemesh_tpu/p2p/``:

* bare ``except:`` — always (it catches CancelledError/SystemExit on
  py3.7-; even on 3.10 it hides KeyboardInterrupt-adjacent teardown);
* ``except Exception``/``BaseException`` (alone or in a tuple) whose
  handler body only ``pass``/``continue``/``...`` — a swallow with no
  trace.

A handler is accepted when its ``except`` line (or the first body
line) carries a *justified* suppression: ``# spacecheck: ok=SC006
<why>`` or an existing ``# noqa: ... — <why>`` comment with a real
reason (the codebase's established convention); the flake8 code alone
does not count.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo

RULE = "SC006"

SCOPE_PREFIXES = (
    "spacemesh_tpu/consensus/",
    "spacemesh_tpu/verify/",
    "spacemesh_tpu/p2p/",
)

_BROAD = {"Exception", "BaseException"}


def _broad_type(node: ast.expr | None) -> bool:
    if node is None:
        return True
    if isinstance(node, ast.Name):
        return node.id in _BROAD
    if isinstance(node, ast.Attribute):
        return node.attr in _BROAD
    if isinstance(node, ast.Tuple):
        return any(_broad_type(e) for e in node.elts)
    return False


def _swallows(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring / ellipsis
        return False
    return True


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not ctx.rel.startswith(SCOPE_PREFIXES):
        return []
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        justified = any(
            ctx.noqa_comment(ln) is not None
            for ln in (node.lineno, node.body[0].lineno
                       if node.body else node.lineno))
        if node.type is None:
            if not justified:
                findings.append(ctx.finding(
                    RULE, node,
                    "bare except: in a consensus-critical package — "
                    "name the exception types (and surface the error) "
                    "or justify the suppression"))
            continue
        if _broad_type(node.type) and _swallows(node.body) \
                and not justified:
            findings.append(ctx.finding(
                RULE, node,
                "broad except swallowing the error with no log/counter/"
                "re-raise in a consensus-critical package: narrow the "
                "type or surface the failure, or justify with a "
                "comment"))
    return findings
