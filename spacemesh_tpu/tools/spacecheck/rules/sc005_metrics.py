"""SC005 metrics-hygiene: registration and label discipline.

Originating bug: PR 7's silent wrong-bucket histogram —
``Registry.histogram`` re-registered under the same name with different
explicit buckets silently returned the original layout, so every
quantile computed from the deltas was wrong without a trace (the
registry now raises; this rule keeps the *callers* honest before
runtime). The SLI sampler (obs/sli.py) diffs whole-registry snapshots,
so instrument identity and label cardinality are correctness inputs,
not style.

Flags:

* **creation outside module scope** — ``<registry>.counter/gauge/
  histogram(...)`` inside a function/method: per-instance creation is
  where duplicate-name and bucket-mismatch registrations come from;
  create at import, record at runtime.
* **duplicate metric names** — the same name literal registered at
  module scope in two different places: both sites silently share one
  instrument, and the second's help text/buckets are discarded.
* **non-literal label names** — ``inc/set/observe(**labels)`` splat on
  a known instrument: the label *schema* becomes data-dependent, and
  one unexpected key forks a new series family.
* **f-string label values** — ``inc(reason=f"...")``: interpolated
  values are unbounded (peer ids, exception strings) and each distinct
  value mints a series — the classic cardinality bomb. Use a bounded
  enum (``type(e).__name__``-style) instead.

Suppress with ``# spacecheck: ok=SC005 <why>`` (e.g. a per-process
registry in a tool that never coexists with a second instance).
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name

RULE = "SC005"

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_RECORD_METHODS = ("inc", "set", "observe")


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    findings: list[Finding] = []
    in_package = ctx.rel.startswith("spacemesh_tpu/")

    # duplicate names: only report sites in THIS file (the runner visits
    # every file, so each duplicate site reports once); module-scope
    # creations only — runtime lookups of an existing instrument are the
    # registry's documented get-or-create behavior
    if in_package:
        for name, sites in project.metric_creations.items():
            module_sites = [s for s in sites if s[2]]
            if len(module_sites) > 1:
                for rel, lineno, _ in module_sites[1:]:
                    if rel == ctx.rel:
                        first = module_sites[0]
                        findings.append(Finding(
                            rule=RULE, path=ctx.rel, line=lineno, col=0,
                            message=(
                                f"metric {name!r} already registered at "
                                f"{first[0]}:{first[1]}; this site "
                                "silently shares that instrument and "
                                "its help/buckets win"),
                            snippet=(ctx.lines[lineno - 1].strip()
                                     if lineno <= len(ctx.lines) else "")))

    fn_depth = 0

    def visit(node: ast.AST) -> None:
        nonlocal fn_depth
        is_fn = isinstance(node, _FUNCS)
        if is_fn:
            fn_depth += 1
        if isinstance(node, ast.Call):
            check_call(node)
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_fn:
            fn_depth -= 1

    def check_call(node: ast.Call) -> None:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        if in_package and fn_depth > 0 \
                and ProjectInfo._is_registry_create(node):
            findings.append(ctx.finding(
                RULE, node,
                f"instrument created inside a function "
                f"(.{func.attr}(...)): create at module scope so "
                "duplicate names and bucket mismatches fail at import, "
                "not mid-run"))
            return
        if func.attr not in _RECORD_METHODS:
            return
        recv = dotted_name(func.value)
        if recv is None \
                or recv.rsplit(".", 1)[-1] not in project.instrument_vars:
            return
        for kw in node.keywords:
            if kw.arg is None:
                findings.append(ctx.finding(
                    RULE, node,
                    f"**splat label names on instrument {recv}: the "
                    "label schema must be literal keywords so the "
                    "series family is fixed at the call site"))
            elif isinstance(kw.value, ast.JoinedStr):
                findings.append(ctx.finding(
                    RULE, kw.value,
                    f"f-string label value for {kw.arg!r} on {recv}: "
                    "interpolated values are unbounded and each "
                    "distinct one mints a series (cardinality bomb); "
                    "use a bounded enum"))

    visit(ctx.tree)
    return findings
