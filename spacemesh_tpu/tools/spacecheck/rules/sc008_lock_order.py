"""SC008 lock-order: no acquisition cycles, no ``await`` under a lock.

Originating bugs: the PR 10 scheduler grew three conditions over one
lock plus a worker pool, the farm and the runtime queue share admission
state across the loop and backend threads — one nested ``with`` in the
wrong order away from a deadlock no test ever hits (lock inversions
need the losing interleaving; the graph doesn't). And the event-loop
twin: a ``threading.Lock`` held across an ``await`` parks every other
acquirer for as long as the coroutine stays suspended — the whole loop,
when the other acquirer IS the loop (the PR 7 flight-dump class, with a
lock attached).

Two checks (``spacemesh_tpu/`` package code only):

* **lock-order cycles** — the pre-pass collects every lock attribute
  (``rules/_locks.py``: ``threading.Lock/RLock/Condition`` and the
  sanitize-tracked twins, Conditions aliased to their root lock) and
  module-level locks, then builds the project-wide acquisition graph:
  a ``with self.B:`` lexically inside ``with self.A:`` adds edge A→B,
  and a call to a same-class method that acquires B while A is held
  adds A→B too (one call level). Any edge on a cycle flags at its
  acquisition site. The runtime lock-order watcher
  (``utils/sanitize.py``) catches the orders the AST can't see.
* **await under a held threading lock** — inside ``async def``, an
  ``await`` lexically inside ``with <known threading lock>:`` flags
  (nested ``def``s excluded). Use ``asyncio.Lock`` + ``async with``
  for loop-side mutual exclusion, or move the locked section to
  ``asyncio.to_thread``.

Suppress a deliberate site with ``# spacecheck: ok=SC008 <why>``.
"""

from __future__ import annotations

import ast
import dataclasses

from ..engine import FileContext, Finding, ProjectInfo
from . import _locks

RULE = "SC008"

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class _Edge:
    src: str
    dst: str
    rel: str
    node: ast.AST           # acquisition (or call) site of ``dst``
    via_call: str | None    # method name when the edge is call-through


class _Graph:
    def __init__(self) -> None:
        self.edges: list[_Edge] = []
        self.adj: dict[str, set[str]] = {}

    def add(self, edge: _Edge) -> None:
        self.edges.append(edge)
        self.adj.setdefault(edge.src, set()).add(edge.dst)

    def reaches(self, src: str, dst: str) -> bool:
        seen: set[str] = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self.adj.get(n, ()))
        return False


def _lock_node(expr: ast.AST, cls: ast.ClassDef | None,
               locks: _locks.ClassLocks | None,
               mod_locks: set[str], rel: str) -> str | None:
    """The graph node id a ``with`` context expression acquires."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and locks is not None:
        root = locks.root(expr.attr)
        if root is not None and cls is not None:
            return f"{cls.name}.{root}"
    elif isinstance(expr, ast.Name) and expr.id in mod_locks:
        return f"{rel}:{expr.id}"
    return None


def _method_acquires(method: ast.AST, cls: ast.ClassDef,
                     locks: _locks.ClassLocks, mod_locks: set[str],
                     rel: str) -> set[str]:
    """Locks ``method`` acquires anywhere in its own body."""
    out: set[str] = set()

    def visit(node: ast.AST) -> None:
        if isinstance(node, _FUNCS + (ast.Lambda,)) and node is not method:
            return
        if isinstance(node, ast.With):
            for item in node.items:
                n = _lock_node(item.context_expr, cls, locks, mod_locks,
                               rel)
                if n is not None:
                    out.add(n)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(method)
    return out


def _build_graph(project: ProjectInfo) -> _Graph:
    graph = project.cache.get("sc008_graph")
    if graph is not None:
        return graph
    graph = _Graph()
    for ctx in project.contexts:
        if not ctx.rel.startswith("spacemesh_tpu/"):
            continue
        mod_locks = _locks.module_locks(ctx.tree)
        classes = [n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.ClassDef)]
        cls_locks = {id(c): _locks.collect_class_locks(c) for c in classes}
        cls_methods = {id(c): {m.name: m for m in c.body
                               if isinstance(m, _FUNCS)} for c in classes}

        def scan(fn: ast.AST, cls: ast.ClassDef | None) -> None:
            locks = cls_locks.get(id(cls)) if cls is not None else None
            methods = cls_methods.get(id(cls), {}) if cls is not None \
                else {}

            def visit(node: ast.AST, held: tuple[str, ...]) -> None:
                if isinstance(node, _FUNCS + (ast.Lambda,)) \
                        and node is not fn:
                    return  # its own scan() starts a fresh held stack
                if isinstance(node, ast.With):
                    inner = held
                    for item in node.items:
                        n = _lock_node(item.context_expr, cls, locks,
                                       mod_locks, ctx.rel)
                        if n is not None:
                            for h in inner:
                                if h != n:
                                    graph.add(_Edge(h, n, ctx.rel,
                                                    node, None))
                            inner = inner + (n,)
                    for child in node.body:
                        visit(child, inner)
                    return
                if held and isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in methods \
                        and methods[node.func.attr] is not fn:
                    callee = methods[node.func.attr]
                    for n in _method_acquires(callee, cls, locks,
                                              mod_locks, ctx.rel):
                        for h in held:
                            if h != n:
                                graph.add(_Edge(h, n, ctx.rel, node,
                                                node.func.attr))
                for child in ast.iter_child_nodes(node):
                    visit(child, held)

            visit(fn, ())

        def walk(node: ast.AST, cls: ast.ClassDef | None) -> None:
            if isinstance(node, ast.ClassDef):
                cls = node
            elif isinstance(node, _FUNCS):
                scan(node, cls)
            for child in ast.iter_child_nodes(node):
                walk(child, cls)

        walk(ctx.tree, None)
    project.cache["sc008_graph"] = graph
    return graph


def _check_await_under_lock(ctx: FileContext,
                            findings: list[Finding]) -> None:
    mod_locks = _locks.module_locks(ctx.tree)
    classes = [n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)]
    cls_locks = {id(c): _locks.collect_class_locks(c) for c in classes}

    def scan_async(fn: ast.AsyncFunctionDef,
                   cls: ast.ClassDef | None) -> None:
        locks = cls_locks.get(id(cls)) if cls is not None else None

        def visit(node: ast.AST, lock: str | None) -> None:
            if isinstance(node, _FUNCS + (ast.Lambda,)) and node is not fn:
                return
            if isinstance(node, ast.With):
                inner = lock
                for item in node.items:
                    n = _lock_node(item.context_expr, cls, locks,
                                   mod_locks, ctx.rel)
                    if n is not None:
                        inner = n
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Await) and lock is not None:
                findings.append(ctx.finding(
                    RULE, node,
                    f"await inside `with {lock.split('.')[-1]}` in async "
                    f"def {fn.name}(): a threading lock held across a "
                    "suspension parks every other acquirer (and wedges "
                    "the event loop when the loop is one of them) — use "
                    "asyncio.Lock/async with, or move the locked "
                    "section off the loop"))
            for child in ast.iter_child_nodes(node):
                visit(child, lock)

        for stmt in fn.body:
            visit(stmt, None)

    def walk(node: ast.AST, cls: ast.ClassDef | None) -> None:
        if isinstance(node, ast.ClassDef):
            cls = node
        if isinstance(node, ast.AsyncFunctionDef):
            scan_async(node, cls)
        for child in ast.iter_child_nodes(node):
            walk(child, cls)

    walk(ctx.tree, None)


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not ctx.rel.startswith("spacemesh_tpu/"):
        return []
    findings: list[Finding] = []
    graph = _build_graph(project)
    seen: set[int] = set()
    for edge in graph.edges:
        if edge.rel != ctx.rel or id(edge.node) in seen:
            continue
        if graph.reaches(edge.dst, edge.src):
            seen.add(id(edge.node))
            via = (f" (via self.{edge.via_call}())"
                   if edge.via_call else "")
            findings.append(ctx.finding(
                RULE, edge.node,
                f"lock-order cycle: {edge.dst} acquired while holding "
                f"{edge.src}{via}, but the project also acquires them "
                "in the opposite order — two threads taking the two "
                "paths deadlock; pick one global order"))
    _check_await_under_lock(ctx, findings)
    return findings
