"""Shared lock/thread facts for the concurrency rules (SC007/SC008).

Both rules need the same two project-wide facts, collected once per run
through the ``ProjectInfo.cache`` handoff (the SC003 pattern):

* **lock attributes** — per class: ``self.X = threading.Lock()`` /
  ``RLock()`` / ``Condition(...)`` (and the sanitize-instrumented twins
  ``sanitize.lock(...)`` / ``sanitize.condition(...)``), with Condition
  aliasing resolved to the root lock (``self._idle =
  threading.Condition(self._lock)`` guards the SAME critical sections
  as ``self._lock``). Module-level ``X = threading.Lock()`` is tracked
  too (SC008's graph).
* **threaded classes** — classes whose methods run off the constructing
  thread: a ``threading.Thread(target=self.m)``, ``executor.submit``,
  ``loop.run_in_executor``, ``call_soon_threadsafe`` or
  ``asyncio.to_thread`` call targeting one of the class's methods (or a
  lambda/local closure over ``self``), anywhere in the project.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, ProjectInfo, dotted_name

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

# call attrs that hand their callable argument(s) to another thread
_SPAWNERS = {"submit", "run_in_executor", "call_soon_threadsafe",
             "to_thread", "start_soon", "run_coroutine_threadsafe"}

_LOCK_FACTORIES = {"Lock", "RLock"}
_COND_FACTORIES = {"Condition"}


def _is_sanitize_recv(recv: str | None) -> bool:
    return bool(recv) and recv.rsplit(".", 1)[-1] == "sanitize"


def _lock_factory_kind(call: ast.Call) -> str | None:
    """"lock" / "cond" when ``call`` constructs a (tracked) lock."""
    name = dotted_name(call.func)
    if name is None:
        return None
    last = name.rsplit(".", 1)[-1]
    recv = name.rsplit(".", 1)[0] if "." in name else None
    if last in _LOCK_FACTORIES:
        return "lock"
    if last in _COND_FACTORIES:
        return "cond"
    if _is_sanitize_recv(recv) and last in ("lock", "tracked_lock"):
        return "lock"
    if _is_sanitize_recv(recv) and last in ("condition",
                                            "tracked_condition"):
        return "cond"
    return None


class ClassLocks:
    """Lock attributes of one class, with Condition aliases resolved."""

    def __init__(self) -> None:
        self.roots: dict[str, str] = {}  # attr -> root lock attr

    def add(self, attr: str, kind: str, alias_of: str | None) -> None:
        if kind == "cond" and alias_of is not None:
            self.roots[attr] = self.roots.get(alias_of, alias_of)
        else:
            self.roots[attr] = attr

    def root(self, attr: str) -> str | None:
        return self.roots.get(attr)


def collect_class_locks(cls: ast.ClassDef) -> ClassLocks:
    locks = ClassLocks()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        kind = _lock_factory_kind(node.value)
        if kind is None:
            continue
        alias = None
        for arg in node.value.args:
            if isinstance(arg, ast.Attribute) \
                    and isinstance(arg.value, ast.Name) \
                    and arg.value.id == "self":
                alias = arg.attr
                break
        for tgt in node.targets:
            if isinstance(tgt, ast.Attribute) \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                locks.add(tgt.attr, kind, alias)
    return locks


def module_locks(tree: ast.Module) -> set[str]:
    """Module-level names bound to a lock factory."""
    out: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and _lock_factory_kind(node.value) is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _spawn_targets(call: ast.Call) -> list[ast.AST] | None:
    """The callable-ish arguments of a thread-spawning call, or None
    when ``call`` is not a spawn site."""
    func = call.func
    name = dotted_name(func)
    last = name.rsplit(".", 1)[-1] if name else None
    if last == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                return [kw.value]
        return list(call.args[:1])
    if isinstance(func, ast.Attribute) and func.attr in _SPAWNERS:
        return list(call.args) + [kw.value for kw in call.keywords]
    return None


class ThreadFacts:
    """Which classes have methods running on more than one thread."""

    def __init__(self) -> None:
        self.threaded_classes: set[str] = set()
        # method names spawned through a non-self receiver anywhere
        # (``threading.Thread(target=writer._worker)``): any class
        # defining one of these is conservatively treated as threaded
        self.spawned_method_names: set[str] = set()

    def is_threaded(self, cls: ast.ClassDef) -> bool:
        if cls.name in self.threaded_classes:
            return True
        return any(isinstance(n, _FUNCS)
                   and n.name in self.spawned_method_names
                   for n in cls.body)


def _collect_threads(ctx: FileContext, facts: ThreadFacts) -> None:
    def visit(node: ast.AST, cls: str | None) -> None:
        if isinstance(node, ast.ClassDef):
            cls = node.name
        elif isinstance(node, ast.Call):
            targets = _spawn_targets(node)
            if targets is not None:
                for t in targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name):
                        if t.value.id == "self" and cls is not None:
                            facts.threaded_classes.add(cls)
                        else:
                            facts.spawned_method_names.add(t.attr)
                    elif isinstance(t, (ast.Lambda, ast.Name)) \
                            and cls is not None:
                        # a lambda/local closure handed to a pool still
                        # drags self onto the worker thread
                        facts.threaded_classes.add(cls)
        for child in ast.iter_child_nodes(node):
            visit(child, cls)

    visit(ctx.tree, None)


def thread_facts(project: ProjectInfo) -> ThreadFacts:
    cached = project.cache.get("concurrency_threads")
    if cached is None:
        cached = ThreadFacts()
        for ctx in project.contexts:
            _collect_threads(ctx, cached)
        project.cache["concurrency_threads"] = cached
    return cached


# --- annotations ---------------------------------------------------------

GUARDED_BY = "guarded by:"
LOOP_ONLY = "loop-only"


def _comment_annotation(text: str | None) -> str | None:
    """"guarded" / "loop-only" when the comment carries one of the two
    exemption annotations (each must name a lock / carry a why)."""
    if not text:
        return None
    low = text.lower()
    i = low.find(GUARDED_BY)
    if i >= 0 and len(text[i + len(GUARDED_BY):].strip()) >= 4:
        return "guarded"
    i = low.find(LOOP_ONLY)
    if i >= 0 and "spacecheck" in low:
        return "loop-only"
    return None


def line_annotation(ctx: FileContext, lineno: int) -> str | None:
    """Annotation covering ``lineno``: on the line itself, or on a
    standalone comment line directly above it."""
    ann = _comment_annotation(ctx.comments.get(lineno))
    if ann:
        return ann
    above = ctx.comments.get(lineno - 1)
    if above and lineno - 2 < len(ctx.lines):
        own = ctx.lines[lineno - 2].lstrip()
        if own.startswith("#"):
            return _comment_annotation(above)
    return None


def function_annotation(ctx: FileContext, fn: ast.AST) -> str | None:
    """A ``# guarded by: <lock>`` on (or directly above) the ``def``
    line declares the whole function runs with that lock held — the
    caller-holds-the-lock idiom (``_pick_job``, ``_tick_locked``)."""
    return line_annotation(ctx, fn.lineno)
