"""SC004 pairing: acquire/release lifecycles must pair on all paths.

Originating bugs: PR 7's name-only watchdog eviction (``App.close``
unregistered health probes by name and evicted a successor node's
probes — the fix unregisters by equality, and registration/cleanup now
pair explicitly), and the PR 3 review fix closing the prover's cached
read fds per session. The shared shape: an acquire with a release that
is missing, or present but skipped on the exception path.

Checked pairings (package code only — ``tests/`` is exempt, test
teardown runs through fixtures):

* **health probes** — a function calling ``HEALTH.register(...)``
  (any receiver whose dotted name ends in ``HEALTH``/``health``) must
  either unregister in a ``finally`` in the same function, or belong
  to a class that unregisters in another method (the long-lived
  component split lifecycle). An unregister that exists in the same
  function but NOT under ``finally`` flags: the exception path leaks
  the probe.
* **manual span brackets** — ``x.__enter__()`` requires
  ``x.__exit__(...)`` under a ``finally`` in the same function (the
  autotune race uses exactly this shape; an unguarded exit loses the
  span AND the contextvar reset on error).
* **collectors** — ``<registry>.add_collector(...)`` has no remove;
  calling it anywhere a second construction can reach (i.e. inside a
  function) re-adds the hook forever. PR 7 keyed idempotence on a
  registry attribute; such guarded sites carry a pragma.
* **executors/fds** — a ``ThreadPoolExecutor(...)``/``open(...)``/
  ``os.open(...)`` result bound to a *local* name must be closed in a
  ``finally`` or managed by ``with``; escaping the function (returned,
  stored on an attribute, passed to another call) hands the lifecycle
  elsewhere and is accepted.
* **runtime job handles** — a ``<scheduler>.submit_init/submit_prove/
  submit_verify/submit_pow/submit_call/submit_proof(...)`` JobHandle
  bound to a local must be CONSUMED (``.result()``/``.wait()``
  anywhere) or ``.cancel()``ed under ``finally``, or escape — the
  defect class the runtime deleted from four pipelines must not
  re-enter through its own submission API (an orphaned handle is a job
  whose failure nobody observes and whose tenant quota slot pins until
  resolution).
* **tenant registration** — ``<scheduler>.register_tenant(...)``
  pairs with ``unregister_tenant`` exactly like the HEALTH probes: in
  a ``finally`` in the same function, or in a sibling method of the
  same class (the long-lived component split); a gone identity must
  not pin its per-tenant gauge series and fair-share state forever.
* **verifyd client registration** — ``<service>.register_client(...)``
  pairs with ``unregister_client`` under the same rules as tenants: a
  disconnected client that is never unregistered pins its token
  bucket, scheduler tenant, and every per-client metric series (the
  cardinality bound the verifyd max_clients knob exists to keep).
* **verifyd server lifecycle** — a local bound to a
  ``VerifydServer(...)``/``VerifydService(...)`` construction that is
  ``start()``ed must ``close()``/``aclose()``/``stop()`` under a
  ``finally`` in the same function, or escape (returned/stored/passed
  — the lifecycle is handed elsewhere); a server leaked on the error
  path strands its scheduler worker threads, farm tasks, and bound
  sockets.
* **remediation lifecycles (ISSUE 15)** — ``RemediationEngine(...)``
  and ``FailoverVerifier(...)`` locals that are ``start()``ed follow
  the same started-must-close rule (a leaked engine keeps consuming
  bus verdicts; a leaked failover verifier pins its breaker series);
  and breaker/hook registrations —
  ``<...>BREAKERS.register(...)`` / ``<...>ACTIONS.register(...)``
  (obs/remediate.py's global registries) — pair with ``unregister``
  exactly like HEALTH probes: in a ``finally`` in the same function,
  or in a sibling method (the long-lived component split).  An
  unpaired breaker pins its ``remediation_breaker_*`` series forever;
  an unpaired hook lets a dead component keep receiving recovery
  actions.
* **fleet lifecycles (ISSUE 17)** — ``FleetRouter(...)`` and
  ``FleetVerifier(...)`` locals that are ``start()``ed follow the
  started-must-close rule too (a leaked router pins every replica's
  breaker and ``fleet_replica_*`` series); and
  ``<router>.register_replica(...)`` pairs with
  ``unregister_replica`` exactly like tenants/clients — a replica
  that left the fleet without unregistering keeps its breaker on the
  global registry, its per-replica series in the exposition, and its
  clients pinned to a ghost.

Suppress a deliberate unpaired site with ``# spacecheck: ok=SC004 <why>``.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name

RULE = "SC004"

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_ACQUIRE_FACTORIES = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_SUBMITS = {"submit_init", "submit_prove", "submit_verify", "submit_pow",
            "submit_call", "submit_proof"}
_HANDLE_CONSUME = {"result", "wait"}


def _is_health_recv(recv: str | None) -> bool:
    if not recv:
        return False
    last = recv.rsplit(".", 1)[-1]
    return last in ("HEALTH", "health") or last.endswith("HEALTH")


def _is_remediation_recv(recv: str | None) -> bool:
    """The obs/remediate.py global registries: breaker registrations
    (``BREAKERS``) and recovery-action hooks (``ACTIONS``)."""
    if not recv:
        return False
    last = recv.rsplit(".", 1)[-1]
    return last.endswith("BREAKERS") or last.endswith("ACTIONS")


def _finally_linenos(fn: ast.AST) -> list[tuple[int, int, int]]:
    """(try lineno, finally-body first lineno, finally-body last lineno)
    for every try/finally lexically inside ``fn`` (nested defs skipped)."""
    spans: list[tuple[int, int, int]] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, _FUNCS + (ast.Lambda,)) and node is not fn:
            return
        if isinstance(node, ast.Try) and node.finalbody:
            first = node.finalbody[0].lineno
            last = max(getattr(n, "end_lineno", first) or first
                       for n in node.finalbody)
            spans.append((node.lineno, first, last))
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(fn)
    return spans


def _in_finally(spans, lineno: int) -> bool:
    return any(first <= lineno <= last for _, first, last in spans)


def _scoped(fn: ast.AST) -> list[ast.AST]:
    """Every node lexically in ``fn``'s own scope (nested defs and
    lambdas excluded — they are analyzed as their own scopes)."""
    out: list[ast.AST] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, _FUNCS + (ast.Lambda,)) and node is not fn:
            return
        out.append(node)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(fn)
    return out


def _calls_in(fn: ast.AST) -> list[ast.Call]:
    return [n for n in _scoped(fn) if isinstance(n, ast.Call)]


def _class_methods(tree: ast.Module) -> dict[int, list[ast.AST]]:
    """id(method node) -> sibling method list (same class)."""
    out: dict[int, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            methods = [n for n in node.body if isinstance(n, _FUNCS)]
            for m in methods:
                out[id(m)] = methods
    return out


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not ctx.rel.startswith("spacemesh_tpu/"):
        return []
    findings: list[Finding] = []
    siblings = _class_methods(ctx.tree)

    _CM_DUNDERS = ("__enter__", "__aenter__", "__exit__", "__aexit__")

    def check_function(fn) -> None:
        spans = _finally_linenos(fn)
        calls = _calls_in(fn)
        # a context manager's own dunders acquire/release across the
        # enter/exit METHOD pair (and __aenter__ delegates to
        # self.__enter__()): pairing there is the class's protocol
        # contract, not a per-function leak
        cm_method = fn.name in _CM_DUNDERS
        registers: list[ast.Call] = []
        unregisters: list[ast.Call] = []
        t_registers: list[ast.Call] = []
        t_unregisters: list[ast.Call] = []
        c_registers: list[ast.Call] = []
        c_unregisters: list[ast.Call] = []
        r_registers: list[ast.Call] = []
        r_unregisters: list[ast.Call] = []
        f_registers: list[ast.Call] = []
        f_unregisters: list[ast.Call] = []
        enters: dict[str, ast.Call] = {}
        exits: dict[str, list[int]] = {}
        for call in calls:
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            recv = dotted_name(func.value)
            if func.attr == "register" and _is_health_recv(recv):
                registers.append(call)
            elif func.attr == "unregister" and _is_health_recv(recv):
                unregisters.append(call)
            elif func.attr == "register" and _is_remediation_recv(recv):
                r_registers.append(call)
            elif func.attr == "unregister" \
                    and _is_remediation_recv(recv):
                r_unregisters.append(call)
            elif func.attr == "register_tenant":
                t_registers.append(call)
            elif func.attr == "unregister_tenant":
                t_unregisters.append(call)
            elif func.attr == "register_client":
                c_registers.append(call)
            elif func.attr == "unregister_client":
                c_unregisters.append(call)
            elif func.attr == "register_replica":
                f_registers.append(call)
            elif func.attr == "unregister_replica":
                f_unregisters.append(call)
            elif func.attr == "__enter__" and recv and not cm_method:
                enters[recv] = call
            elif func.attr == "__exit__" and recv:
                exits.setdefault(recv, []).append(call.lineno)
            elif func.attr == "add_collector":
                findings.append(ctx.finding(
                    RULE, call,
                    "add_collector() inside a function: collectors have "
                    "no remove, so any re-reachable construction re-adds "
                    "the hook forever; attach at module scope or guard "
                    "idempotently and pragma"))
        for call in registers:
            if any(_in_finally(spans, u.lineno) for u in unregisters):
                continue
            if unregisters:
                findings.append(ctx.finding(
                    RULE, call,
                    "HEALTH.register here but the unregister in this "
                    "function is not under finally: the exception path "
                    "leaks the probe"))
                continue
            sib = siblings.get(id(fn), [])
            paired = any(
                isinstance(c.func, ast.Attribute)
                and c.func.attr == "unregister"
                and _is_health_recv(dotted_name(c.func.value))
                for m in sib for c in _calls_in(m) if m is not fn)
            if not paired:
                findings.append(ctx.finding(
                    RULE, call,
                    "HEALTH.register without any unregister in this "
                    "function or its class: a finished component pins "
                    "its probe (and its component_healthy series) "
                    "forever"))
        for call in r_registers:
            if any(_in_finally(spans, u.lineno) for u in r_unregisters):
                continue
            if r_unregisters:
                findings.append(ctx.finding(
                    RULE, call,
                    "BREAKERS/ACTIONS register here but the unregister "
                    "in this function is not under finally: the "
                    "exception path pins the breaker's per-component "
                    "series (or leaves a dead component's recovery "
                    "hook live)"))
                continue
            sib = siblings.get(id(fn), [])
            paired = any(
                isinstance(c.func, ast.Attribute)
                and c.func.attr == "unregister"
                and _is_remediation_recv(dotted_name(c.func.value))
                for m in sib for c in _calls_in(m) if m is not fn)
            if not paired:
                findings.append(ctx.finding(
                    RULE, call,
                    "BREAKERS/ACTIONS register without any unregister "
                    "in this function or its class: a finished "
                    "component pins its remediation_breaker_* series "
                    "(or keeps receiving recovery actions) forever"))
        for call in t_registers:
            if any(_in_finally(spans, u.lineno) for u in t_unregisters):
                continue
            if t_unregisters:
                findings.append(ctx.finding(
                    RULE, call,
                    "register_tenant here but the unregister_tenant in "
                    "this function is not under finally: the exception "
                    "path pins the tenant's fair-share state and gauge "
                    "series"))
                continue
            sib = siblings.get(id(fn), [])
            paired = any(
                isinstance(c.func, ast.Attribute)
                and c.func.attr == "unregister_tenant"
                for m in sib for c in _calls_in(m) if m is not fn)
            if not paired:
                findings.append(ctx.finding(
                    RULE, call,
                    "register_tenant without any unregister_tenant in "
                    "this function or its class: a gone identity pins "
                    "its per-tenant series and scheduler state forever"))
        for call in c_registers:
            if any(_in_finally(spans, u.lineno) for u in c_unregisters):
                continue
            if c_unregisters:
                findings.append(ctx.finding(
                    RULE, call,
                    "register_client here but the unregister_client in "
                    "this function is not under finally: the exception "
                    "path pins the client's token bucket, tenant, and "
                    "per-client metric series"))
                continue
            sib = siblings.get(id(fn), [])
            paired = any(
                isinstance(c.func, ast.Attribute)
                and c.func.attr == "unregister_client"
                for m in sib for c in _calls_in(m) if m is not fn)
            if not paired:
                findings.append(ctx.finding(
                    RULE, call,
                    "register_client without any unregister_client in "
                    "this function or its class: a disconnected client "
                    "pins its per-client series and admission state "
                    "forever"))
        for call in f_registers:
            if any(_in_finally(spans, u.lineno) for u in f_unregisters):
                continue
            if f_unregisters:
                findings.append(ctx.finding(
                    RULE, call,
                    "register_replica here but the unregister_replica "
                    "in this function is not under finally: the "
                    "exception path pins the replica's breaker and "
                    "per-replica fleet series"))
                continue
            sib = siblings.get(id(fn), [])
            paired = any(
                isinstance(c.func, ast.Attribute)
                and c.func.attr == "unregister_replica"
                for m in sib for c in _calls_in(m) if m is not fn)
            if not paired:
                findings.append(ctx.finding(
                    RULE, call,
                    "register_replica without any unregister_replica "
                    "in this function or its class: a replica that "
                    "left the fleet pins its breaker registration and "
                    "fleet_replica_* series, and its clients stay "
                    "routed to a ghost"))
        for recv, call in enters.items():
            ok = any(_in_finally(spans, ln) and ln > call.lineno
                     for ln in exits.get(recv, []))
            if not ok:
                findings.append(ctx.finding(
                    RULE, call,
                    f"{recv}.__enter__() without a matching "
                    f"{recv}.__exit__() under finally: the error path "
                    "leaks the span/context"))
        _check_job_handles(fn, spans)
        _check_local_resources(fn, spans)
        _check_verifyd_servers(fn, spans)

    def _check_verifyd_servers(fn, spans) -> None:
        """A locally-constructed VerifydServer/VerifydService/
        RemediationEngine/FailoverVerifier that is start()ed must
        close/aclose/stop under finally, or escape."""
        nodes = _scoped(fn)
        owners: dict[str, ast.Assign] = {}
        for node in nodes:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                cname = dotted_name(node.value.func)
                if cname and cname.rsplit(".", 1)[-1] in (
                        "VerifydServer", "VerifydService",
                        "RemediationEngine", "FailoverVerifier",
                        "FleetRouter", "FleetVerifier"):
                    owners[node.targets[0].id] = node
        if not owners:
            return
        started: dict[str, ast.Call] = {}
        closed: set[str] = set()
        escapes: set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in owners:
                    if f.attr == "start":
                        started.setdefault(f.value.id, node)
                    elif f.attr in ("close", "aclose", "stop") \
                            and _in_finally(spans, node.lineno):
                        closed.add(f.value.id)
                    continue
                for arg in list(node.args) + [k.value
                                              for k in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in owners:
                        escapes.add(arg.id)
            elif isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in owners:
                escapes.add(node.value.id)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in owners:
                escapes.add(node.value.id)
        for name, call in started.items():
            if name in closed or name in escapes:
                continue
            findings.append(ctx.finding(
                RULE, call,
                f"started component {name!r} has no finally-paired "
                "close/aclose/stop and never escapes: the error path "
                "strands its workers/subscriptions and pins its "
                "breaker/metric series"))

    def _check_job_handles(fn, spans) -> None:
        """Runtime scheduler submits: a JobHandle bound to a local must
        be consumed (.result()/.wait() anywhere), cancelled under
        finally, or escape the function."""
        handles: dict[str, ast.Assign] = {}
        nodes = _scoped(fn)
        for node in nodes:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in _SUBMITS \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                handles[node.targets[0].id] = node
        if not handles:
            return
        resolved: set[str] = set()
        escapes: set[str] = set()
        callfuncs = {id(n.func) for n in nodes if isinstance(n, ast.Call)}
        for node in nodes:
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in handles:
                    if f.attr in _HANDLE_CONSUME:
                        resolved.add(f.value.id)
                    elif f.attr == "cancel" \
                            and _in_finally(spans, node.lineno):
                        resolved.add(f.value.id)
                    continue
                for arg in list(node.args) + [k.value
                                              for k in node.keywords]:
                    if isinstance(arg, ast.Name) and arg.id in handles:
                        escapes.add(arg.id)
            elif isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in handles:
                escapes.add(node.value.id)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in handles:
                escapes.add(node.value.id)
            elif isinstance(node, ast.Attribute) \
                    and id(node) not in callfuncs \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in handles \
                    and isinstance(node.ctx, ast.Load) \
                    and node.attr not in ("id", "tenant", "kind"):
                # reading .future hands the lifecycle elsewhere
                # (asyncio.wrap_future, job tables)
                escapes.add(node.value.id)
        for name, stmt in handles.items():
            if name in resolved or name in escapes:
                continue
            findings.append(ctx.finding(
                RULE, stmt,
                f"runtime job handle {name!r} is never consumed "
                "(.result()/.wait()), never cancelled under finally, "
                "and never escapes: an orphaned job's failure is "
                "unobserved and its tenant quota slot pins until it "
                "resolves"))

    def _check_local_resources(fn, spans) -> None:
        assigned: dict[str, ast.Assign] = {}  # local name -> acquire stmt

        def acquire_kind(call: ast.Call) -> str | None:
            func = call.func
            if isinstance(func, ast.Name) and func.id == "open":
                return "open()"
            name = dotted_name(func)
            if name is None:
                return None
            last = name.rsplit(".", 1)[-1]
            if last in _ACQUIRE_FACTORIES:
                return f"{last}()"
            if name == "os.open":
                return "os.open()"
            return None

        nodes = _scoped(fn)
        for node in nodes:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                kind = acquire_kind(node.value)
                if kind and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    assigned[node.targets[0].id] = (node, kind)
        if not assigned:
            return
        closed_in_finally: set[str] = set()
        escapes: set[str] = set()
        for node in nodes:
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("close", "shutdown") \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in assigned \
                        and _in_finally(spans, node.lineno):
                    closed_in_finally.add(f.value.id)
                else:
                    for arg in list(node.args) + [k.value
                                                  for k in node.keywords]:
                        if isinstance(arg, ast.Name) and arg.id in assigned:
                            escapes.add(arg.id)
            elif isinstance(node, ast.Return) and isinstance(node.value,
                                                             ast.Name):
                if node.value.id in assigned:
                    escapes.add(node.value.id)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in assigned:
                escapes.add(node.value.id)  # handed to another binding
            elif isinstance(node, ast.withitem):
                name = dotted_name(node.context_expr)
                if name in assigned:
                    escapes.add(name)  # managed by with
        for name, (stmt, kind) in assigned.items():
            if name in closed_in_finally or name in escapes:
                continue
            findings.append(ctx.finding(
                RULE, stmt,
                f"{kind} bound to local {name!r} is never closed under "
                "finally and never escapes this function: the error "
                "path leaks the handle; use `with` or try/finally"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNCS):
            check_function(node)
    return findings
