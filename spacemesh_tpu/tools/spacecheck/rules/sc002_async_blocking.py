"""SC002 async-blocking: no blocking calls lexically inside ``async def``.

Originating bug: PR 7's flight-dump fix — serializing a 64k-span trace
ring directly from a ``/readyz`` handler blocked the event loop at
exactly the moment the node was unhealthy; the fix moved it behind
``asyncio.to_thread``. The same class (a blocking disk/subprocess/
device call on the loop) stalls gossip delivery, farm dispatch, and
every timeout on the node at once, and reviews keep re-finding it.

Flags, in every scanned file: calls that block the calling thread when
they appear in the *direct* body of an ``async def`` (nested ``def``s
are excluded — they typically run via ``to_thread``/executors):

* ``time.sleep(...)`` (any import alias of ``time``)
* ``subprocess.run/call/check_call/check_output/Popen``
* builtin ``open(...)`` / ``os.open`` / ``os.replace`` / ``os.unlink``
  (sync file IO — unlinking a large file can take hundreds of ms in
  the kernel)
* ``jax.device_get(...)`` and ``<x>.block_until_ready()`` — device
  syncs that stall the loop for a whole dispatch
* ``<x>.result()`` / ``<x>.future.result()`` — ``concurrent.futures``
  waits, including the runtime scheduler's thread-based ``JobHandle``
  (PR 10 made blocking on a device job from a handler an easy new way
  to wedge the loop); ``asyncio``-side results arrive via ``await``,
  never ``.result()``, so any lexical ``.result()`` in an ``async
  def`` is a blocking wait
* ``<q>.get(...)`` / ``<q>.put(...)`` on a ``queue.Queue`` — the
  blocking thread-handoff primitive (names bound to a
  ``queue.Queue(...)``-family constructor in the same file);
  ``get_nowait``/``put_nowait`` stay legal

Allowlist a deliberate site (tiny reads at startup, etc.) with
``# spacecheck: ok=SC002 <why>``.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name, \
    time_module_aliases

RULE = "SC002"

_SUBPROCESS = {"run", "call", "check_call", "check_output", "Popen"}
_OS_SYNC_IO = {"open", "replace", "rename", "fsync", "unlink", "remove"}
_QUEUE_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}


def _queue_vars(tree: ast.Module) -> set[str]:
    """Last-component names bound to a stdlib ``queue.*`` constructor in
    THIS file (``self._q = queue.Queue(...)``). Per-file on purpose: a
    project-wide name set would let one module's queue attribute flag a
    same-named dict in another (``storage/db.py`` ``_readers`` vs
    ``p2p/fetch.py`` ``_readers``)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign):
            # the codebase's own idiom: `self._q: queue.Queue = ...`
            value, targets = node.value, [node.target]
        else:
            continue
        if not isinstance(value, ast.Call):
            continue
        name = dotted_name(value.func)
        if not name:
            continue
        head, _, last = name.rpartition(".")
        if last in _QUEUE_FACTORIES \
                and head.rsplit(".", 1)[-1] == "queue":
            for tgt in targets:
                tname = dotted_name(tgt)
                if tname:
                    out.add(tname.rsplit(".", 1)[-1])
    return out


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    time_aliases = time_module_aliases(ctx.tree)
    queue_vars = _queue_vars(ctx.tree)
    findings: list[Finding] = []

    def blocking(node: ast.Call) -> str | None:
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "sync file IO (open) on the event loop"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv = dotted_name(func.value)
        attr = func.attr
        if attr == "sleep" and recv in time_aliases:
            return f"{recv}.sleep() blocks the event loop"
        if recv == "subprocess" and attr in _SUBPROCESS:
            return (f"subprocess.{attr}() blocks the event loop; use "
                    "asyncio.create_subprocess_* or to_thread")
        if recv == "os" and attr in _OS_SYNC_IO:
            return f"os.{attr}() is sync file IO on the event loop"
        if recv == "jax" and attr == "device_get":
            return ("jax.device_get() synchronously waits for the "
                    "device; fetch via to_thread or async dispatch")
        if attr == "block_until_ready":
            return (".block_until_ready() stalls the loop for a whole "
                    "device dispatch; wrap in to_thread")
        if attr == "result" and not node.args:
            # zero positional args: the Future/JobHandle shape (an
            # argful .result(state, id) is a plain module function)
            return (f"{recv}.result() is a blocking concurrent-futures "
                    "wait (JobHandle/Future); await "
                    "asyncio.wrap_future(...) or move it to to_thread")
        if attr in ("get", "put") and recv \
                and recv.rsplit(".", 1)[-1] in queue_vars:
            return (f"{recv}.{attr}() blocks on a queue.Queue; use "
                    f"{attr}_nowait with loop-side signalling, or "
                    "to_thread")
        return None

    def scan_async_body(fn: ast.AsyncFunctionDef) -> None:
        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.Lambda)):
                return  # nested sync defs run elsewhere (to_thread etc.)
            if isinstance(node, ast.AsyncFunctionDef):
                scan_async_body(node)
                return
            if isinstance(node, ast.Call):
                why = blocking(node)
                if why is not None:
                    findings.append(ctx.finding(
                        RULE, node,
                        f"blocking call inside async def "
                        f"{fn.name}(): {why}"))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fn.body:
            visit(stmt)

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.AsyncFunctionDef):
            scan_async_body(node)
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(ctx.tree)
    return findings
