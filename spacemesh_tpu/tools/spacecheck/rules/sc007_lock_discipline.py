"""SC007 lock-discipline: no mixed locked/bare access to shared state.

Originating bugs: the PR 7 EventBus ``deepest_queue`` iteration race (a
collector thread iterated subscriber lists the loop thread was
resizing) and the registry thread-affinity bug fixed in the same
review; PR 10 then added a packer + worker-pool runtime where every new
attribute is one forgotten ``with self._lock`` away from the same
class. This is the static half of the Eraser-style lockset sanitizer
(``utils/sanitize.py``, ``SPACEMESH_SANITIZE=race``).

Detection (``spacemesh_tpu/`` package code only):

* A class is **threaded** when one of its methods runs off the
  constructing thread anywhere in the project — a
  ``threading.Thread(target=self.m)``, ``executor.submit``,
  ``run_in_executor``, ``call_soon_threadsafe`` or ``asyncio.to_thread``
  call (the ProjectInfo cross-file pre-pass; ``rules/_locks.py``).
* Within a threaded class that owns locks (``threading.Lock`` /
  ``RLock`` / ``Condition`` — Conditions alias to their root lock, so
  ``with self._idle:`` over ``Condition(self._lock)`` counts as holding
  ``self._lock``): an instance attribute accessed under a held lock in
  one place but read/written **bare** elsewhere flags. Only attributes
  written outside ``__init__`` participate (read-only state is
  race-free); construction-time accesses are exempt (happens-before
  thread start); accesses inside nested ``def``/``lambda`` bodies are
  bare even when the def lexically sits inside a ``with`` (the closure
  runs later, without the lock).

Exemption vocabulary (each must carry a lock name / a why):

* ``# guarded by: <lock>`` on the access line (or alone on the line
  above) — the lock is held by the caller in a way the AST can't see;
  on the ``def`` line it declares the WHOLE function runs locked (the
  ``_pick_job``-style "caller holds ``self._lock``" idiom). Annotated
  functions are exempt, and deliberately do NOT establish guardedness
  for the attributes they touch.
* ``# spacecheck: loop-only <why>`` — the access happens only on the
  event-loop thread (single-threaded by construction).
* ``# spacecheck: ok=SC007 <why>`` — anything else deliberate (e.g. a
  monotonic flag read that tolerates staleness).
"""

from __future__ import annotations

import ast
import dataclasses

from ..engine import FileContext, Finding, ProjectInfo
from . import _locks

RULE = "SC007"

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)
_INIT_METHODS = {"__init__", "__del__", "__post_init__"}

# in-place container mutations count as writes to the attribute —
# ``self._tenants[tid] = t`` and ``self._subs[t].append(sub)`` are the
# shapes the PR 7 deepest_queue race was made of
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "remove", "clear", "update", "setdefault", "add", "discard",
             "put", "put_nowait"}


def _mutated_self_attr(node: ast.AST) -> ast.Attribute | None:
    """The ``self.X`` whose CONTENTS this node mutates, if any:
    ``self.X[k] = v`` / ``del self.X[k]`` (Subscript store) and
    ``self.X.pop(...)``-style in-place mutator calls."""
    if isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, (ast.Store, ast.Del)) \
            and isinstance(node.value, ast.Attribute) \
            and isinstance(node.value.value, ast.Name) \
            and node.value.value.id == "self":
        return node.value
    if isinstance(node, ast.Call) \
            and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS \
            and isinstance(node.func.value, ast.Attribute) \
            and isinstance(node.func.value.value, ast.Name) \
            and node.func.value.value.id == "self":
        return node.func.value
    return None


@dataclasses.dataclass
class _Access:
    attr: str
    node: ast.Attribute
    method: str
    write: bool
    locked: bool          # under a held self-lock (lexically)
    exempt: bool          # init method / annotated function or line
    lock_root: str | None


def _class_accesses(ctx: FileContext, cls: ast.ClassDef,
                    locks: _locks.ClassLocks) -> list[_Access]:
    accesses: list[_Access] = []

    def method_scan(method: ast.AST) -> None:
        m_exempt = (method.name in _INIT_METHODS
                    or _locks.function_annotation(ctx, method) is not None)

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, _FUNCS + (ast.Lambda,)) \
                    and node is not method:
                # the closure body runs later, without the with-block's
                # lock — but still on behalf of this method
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for child in body:
                    visit(child, ())
                return
            if isinstance(node, ast.With):
                add = []
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Attribute) \
                            and isinstance(expr.value, ast.Name) \
                            and expr.value.id == "self":
                        root = locks.root(expr.attr)
                        if root is not None:
                            add.append(root)
                    visit(expr, held)
                inner = held + tuple(add)
                for child in node.body:
                    visit(child, inner)
                return
            target = _mutated_self_attr(node)
            if target is not None and locks.root(target.attr) is None:
                accesses.append(_Access(
                    attr=target.attr, node=target, method=method.name,
                    write=True, locked=bool(held),
                    exempt=(m_exempt or _locks.line_annotation(
                        ctx, target.lineno) is not None),
                    lock_root=held[-1] if held else None))
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self" \
                    and locks.root(node.attr) is None:
                write = isinstance(node.ctx, (ast.Store, ast.Del))
                accesses.append(_Access(
                    attr=node.attr, node=node, method=method.name,
                    write=write, locked=bool(held),
                    exempt=(m_exempt or _locks.line_annotation(
                        ctx, node.lineno) is not None),
                    lock_root=held[-1] if held else None))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in method.body:
            visit(stmt, ())

    for node in cls.body:
        if isinstance(node, _FUNCS):
            method_scan(node)
    return accesses


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not ctx.rel.startswith("spacemesh_tpu/"):
        return []
    facts = _locks.thread_facts(project)
    findings: list[Finding] = []

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not facts.is_threaded(node):
            continue
        locks = _locks.collect_class_locks(node)
        if not locks.roots:
            continue
        accesses = _class_accesses(ctx, node, locks)
        by_attr: dict[str, list[_Access]] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)
        for attr, accs in sorted(by_attr.items()):
            # annotated-function accesses are exempt AND do not
            # establish guardedness (the discipline is the caller's)
            locked = [a for a in accs if a.locked and not a.exempt]
            if not locked:
                continue
            written = any(a.write for a in accs if not a.exempt)
            if not written:
                continue  # read-only outside __init__: race-free
            guard = locked[0].lock_root
            reported: set[tuple[str, str]] = set()
            for a in accs:
                if a.locked or a.exempt:
                    continue
                key = (a.method, attr)
                if key in reported:
                    continue  # one finding per (method, attribute)
                reported.add(key)
                what = "written" if a.write else "read"
                findings.append(ctx.finding(
                    RULE, a.node,
                    f"self.{attr} is accessed under self.{guard} in "
                    f"{locked[0].method}() but {what} bare in "
                    f"{a.method}() — {node.name} runs on multiple "
                    "threads; hold the lock, or annotate the site "
                    "(`# guarded by: <lock>` / "
                    "`# spacecheck: loop-only <why>`)"))
    return findings
