"""SC003 donation-safety: no reads of a donated buffer after the call.

Originating bug: PR 4's pre-pallas carry copy — ``scrypt_labels_with_min``
donated its device carry to a Pallas attempt; when the dispatch failed
*after* compile, the XLA fallback retried with the same (now invalid)
reference. The fix keeps an independent copy alive before any call that
may donate. ``donate_argnums`` invalidates the Python reference on the
caller's side: any later read of the same name in the same scope is a
use-after-free that JAX only sometimes reports (and on TPU can silently
alias).

Detection: the rule collects every callable built with
``donate_argnums=`` / ``donate_argnames=`` (``jax.jit(f, donate_...)``
assignments and ``@functools.partial(jax.jit, donate_...)`` decorators)
across the whole tree, then walks each function in source order: an
argument name passed in a donated position marks that name consumed;
any later load of the name before it is rebound flags. Rebinding
(``carry = step(carry, ...)`` — the standard rotate) clears the mark,
so the idiomatic donated-carry loop is clean.

Suppress a deliberate post-donation read (e.g. a shape/dtype attribute
that never touches the buffer) with ``# spacecheck: ok=SC003 <why>``.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name

RULE = "SC003"

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _donation_keywords(call: ast.Call):
    """-> (positions, keyword names) declared by donate_argnums/names."""
    positions: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    positions.add(e.value)
        elif kw.arg == "donate_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return positions, names


def _collect_file(tree: ast.Module) -> dict[str, tuple[set[int], set[str]]]:
    """{callable name: (donated positions, donated kw names)}."""
    out: dict[str, tuple[set[int], set[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos, names = _donation_keywords(node.value)
            if pos or names:
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name:
                        out[name.rsplit(".", 1)[-1]] = (pos, names)
        elif isinstance(node, _FUNCS):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos, names = _donation_keywords(dec)
                    if pos or names:
                        out[node.name] = (pos, names)
    return out


def _donated_map(project: ProjectInfo) -> dict[str, tuple[set[int], set[str]]]:
    cached = project.cache.get("sc003_donated")
    if cached is None:
        cached = {}
        for ctx in project.contexts:
            cached.update(_collect_file(ctx.tree))
        project.cache["sc003_donated"] = cached
    return cached


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    donated = _donated_map(project)
    if not donated:
        return []
    findings: list[Finding] = []

    def scan_scope(body: list[ast.stmt]) -> None:
        # dotted name -> (donating call lineno, callee name)
        consumed: dict[str, tuple[int, str]] = {}

        def mark_store(node: ast.AST) -> None:
            name = dotted_name(node)
            if name is not None:
                consumed.pop(name, None)
            for child in ast.iter_child_nodes(node):
                mark_store(child)

        def visit(node: ast.AST, in_load: bool = True) -> None:
            if isinstance(node, _FUNCS + (ast.Lambda,)):
                return  # nested scopes analyzed separately
            # evaluation order, not AST field order: an Assign's value
            # runs BEFORE its targets bind, so `carry = step(carry)` is
            # donate-then-rebind (clean), never read-after-donate
            if isinstance(node, ast.Assign):
                visit(node.value)
                for tgt in node.targets:
                    visit(tgt)
                return
            if isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    visit(node.value)
                visit(node.target)
                return
            if isinstance(node, ast.AugAssign):
                visit(node.value)
                # aug-assign READS the target before rebinding it
                name = dotted_name(node.target)
                hit = consumed.get(name) if name else None
                if hit is not None:
                    findings.append(ctx.finding(
                        RULE, node,
                        f"{name} was donated to {hit[1]}() on line "
                        f"{hit[0]} and aug-assigned here: the read half "
                        "touches the invalidated buffer"))
                mark_store(node.target)
                return
            if isinstance(node, (ast.For, ast.AsyncFor)):
                visit(node.iter)
                visit(node.target)
                for stmt in node.body + node.orelse:
                    visit(stmt)
                return
            if isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(getattr(node, "ctx", None),
                                   (ast.Store, ast.Del)):
                mark_store(node)
                return
            if isinstance(node, (ast.Name, ast.Attribute)) and in_load:
                name = dotted_name(node)
                # reading carry.sum (or carry.shape[0]) reads carry:
                # check every dotted prefix against the consumed set
                hit, hit_name = None, name
                while name:
                    hit = consumed.get(name)
                    if hit is not None:
                        hit_name = name
                        break
                    name = name.rpartition(".")[0]
                name = hit_name
                if hit is not None:
                    line, callee = hit
                    findings.append(ctx.finding(
                        RULE, node,
                        f"{name} was donated to {callee}() on line "
                        f"{line} and read again here: the buffer may be "
                        "invalidated/aliased — copy before the donating "
                        "call or rebind the name from its result"))
                    consumed.pop(name, None)  # one finding per donation
                if isinstance(node, ast.Attribute):
                    # the receiver chain is covered by the dotted check
                    return
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                short = callee.rsplit(".", 1)[-1] if callee else None
                # evaluate args first (reads of already-donated refs at
                # the call site still flag), then mark this call's
                # donations
                for child in ast.iter_child_nodes(node):
                    visit(child)
                if short in donated:
                    pos, kwnames = donated[short]
                    for idx, arg in enumerate(node.args):
                        if idx in pos:
                            name = dotted_name(arg)
                            if name:
                                consumed[name] = (node.lineno, short)
                    for kw in node.keywords:
                        if kw.arg in kwnames:
                            name = dotted_name(kw.value)
                            if name:
                                consumed[name] = (node.lineno, short)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in body:
            visit(stmt)

    def walk_scopes(node: ast.AST) -> None:
        if isinstance(node, _FUNCS):
            scan_scope(node.body)
        for child in ast.iter_child_nodes(node):
            walk_scopes(child)

    scan_scope(ctx.tree.body)
    walk_scopes(ctx.tree)
    return findings
