"""SC001 clock-discipline: no wall-clock reads in virtual-time modules.

Originating bugs: PR 8 had to chase ``time.time()`` out of p2p/fetch.py
and node/peersync.py so chaos timeskew and the sim scenario engine could
skew them (CHANGES.md PR 8: "loop-clock-based => virtual-aware"), and
the PR 8 satellite audit left 45 wall-clock call sites across 17 files
un-audited. A wall-clock read inside a virtual-time-aware module is
invisible to every deterministic scenario: penalty windows, cert
expiries, and heartbeats silently run on real time while the rest of
the node runs on the virtual clock.

Flags, inside the virtual-time-aware packages (``sim/``, ``obs/``,
``node/``, ``p2p/``, ``consensus/``):

* calls to ``time.time()`` / ``time.monotonic()`` (any import alias);
* calls to ``<something named *loop*>.time()`` — the event-loop clock
  is only virtual under a VirtualClockLoop, so using it as a time
  source is a per-site decision that must be justified with a pragma;
* ``asyncio.sleep(<nonzero literal>)`` — sleep-and-hope delays that a
  scenario cannot compress (``asyncio.sleep(0)`` yields are fine).

Compliant instead: take an injected time source. A call is exempt when
an enclosing function has a parameter named ``now`` / ``time_source`` /
``wall`` / ``clock`` / ``time_fn`` (the module "takes an injected
time_source" and the wall call is its declared default), when the line
carries ``# spacecheck: ok=SC001 <why>``, or when the module header
declares ``# spacecheck: wall-clock-ok <why>``.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name, \
    time_module_aliases

RULE = "SC001"

SCOPE_PREFIXES = (
    "spacemesh_tpu/sim/",
    "spacemesh_tpu/obs/",
    "spacemesh_tpu/node/",
    "spacemesh_tpu/p2p/",
    "spacemesh_tpu/consensus/",
)

INJECTED_PARAMS = {"now", "time_source", "wall", "clock", "time_fn"}

_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _param_names(fn) -> set[str]:
    a = fn.args
    params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    if a.vararg:
        params.append(a.vararg)
    if a.kwarg:
        params.append(a.kwarg)
    return {p.arg for p in params}


def in_scope(rel: str) -> bool:
    return rel.startswith(SCOPE_PREFIXES)


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not in_scope(ctx.rel):
        return []
    if RULE in ctx.module_pragmas:
        return []
    time_aliases = time_module_aliases(ctx.tree)
    findings: list[Finding] = []
    fn_stack: list[set[str]] = []  # parameter-name sets of enclosing defs

    def injected() -> bool:
        return any(params & INJECTED_PARAMS for params in fn_stack)

    def check_call(node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            recv = dotted_name(func.value)
            if func.attr in ("time", "monotonic") and recv in time_aliases:
                if not injected():
                    findings.append(ctx.finding(
                        RULE, node,
                        f"wall-clock read {recv}.{func.attr}() in a "
                        "virtual-time-aware module; inject a time_source "
                        "or pragma with a justification"))
                return
            if func.attr == "time" and recv is not None \
                    and "loop" in recv.rsplit(".", 1)[-1].lower():
                if not injected():
                    findings.append(ctx.finding(
                        RULE, node,
                        f"{recv}.time() is only virtual under a "
                        "VirtualClockLoop; justify the loop clock as this "
                        "site's time source with a pragma or inject one"))
                return
            name = dotted_name(func)
            if name in ("asyncio.sleep",):
                _check_sleep(node)
        elif isinstance(func, ast.Name) and func.id == "sleep":
            # `from asyncio import sleep` — rare, treat as asyncio.sleep
            _check_sleep(node)

    def _check_sleep(node: ast.Call) -> None:
        if not node.args:
            return
        arg = node.args[0]
        neg = (isinstance(arg, ast.UnaryOp)
               and isinstance(arg.op, ast.USub)
               and isinstance(arg.operand, ast.Constant))
        if isinstance(arg, ast.Constant) or neg:
            value = arg.operand.value if neg else arg.value
            if isinstance(value, (int, float)) and value > 0:
                findings.append(ctx.finding(
                    RULE, node,
                    f"literal asyncio.sleep({value}) in a virtual-time-"
                    "aware module: scenarios cannot compress fixed "
                    "delays; derive the delay from config/clock state or "
                    "pragma with a justification"))

    def visit(node: ast.AST) -> None:
        is_fn = isinstance(node, _FUNCS)
        if is_fn:
            fn_stack.append(_param_names(node))
        elif isinstance(node, ast.Lambda):
            fn_stack.append({a.arg for a in node.args.args})
            is_fn = True
        if isinstance(node, ast.Call):
            check_call(node)
        for child in ast.iter_child_nodes(node):
            visit(child)
        if is_fn:
            fn_stack.pop()

    visit(ctx.tree)
    return findings
