"""SC009 durability: rename-based persistence must be fsync-bracketed.

Originating bug: ISSUE 14's power-cut audit — ``PostMetadata.save``
renamed a tmp file over the resume metadata without fsyncing the file
or its directory, so a power cut could publish a correctly-named file
full of zeros; every winners/rates/findings cache in the tree had the
same ``tmp + os.replace`` idiom, and every one of them treats an
unparseable file as "empty, silently re-derive" — corruption absorbed,
days of measurements gone, no log line.  utils/fsio.py owns the full
durable sequence (write tmp, fsync tmp, rename, fsync parent dir);
this rule keeps new persistence sites from re-growing the naked form.

Flags, in ``spacemesh_tpu/`` (minus utils/fsio.py and post/faultfs.py,
which implement the discipline):

* ``os.replace(...)`` / ``os.rename(...)`` calls — the naked
  publish-by-rename idiom;
* single-argument ``.replace(x)`` / ``.rename(x)`` attribute calls —
  the ``pathlib.Path`` spelling of the same thing (``str.replace``
  takes two+ arguments, so string munging never matches).

Route the write through ``fsio.atomic_write_text``/``atomic_write_bytes``
(payloads built in memory) or ``fsio.persist`` (tmp produced by an
external writer: a compiler, a spooled directory).  A rename that is
genuinely not a persistence point (an archival move of an already-
durable file) suppresses with ``# spacecheck: ok=SC009 <why>``.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name

RULE = "SC009"

_EXEMPT = ("spacemesh_tpu/utils/fsio.py", "spacemesh_tpu/post/faultfs.py")
_RENAMERS = ("replace", "rename")


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not ctx.rel.startswith("spacemesh_tpu/") or ctx.rel in _EXEMPT:
        return []
    findings: list[Finding] = []

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) \
                or func.attr not in _RENAMERS:
            continue
        recv = dotted_name(func.value)
        if recv is not None and recv.rsplit(".", 1)[-1] == "os":
            findings.append(ctx.finding(
                RULE, node,
                f"os.{func.attr}(...) publishes by rename without an "
                "fsync bracket: a power cut can land the name swap "
                "before the payload bytes. Route through utils/fsio "
                "(atomic_write_text/atomic_write_bytes, or persist() "
                "for externally-written tmps)"))
            continue
        # pathlib spelling: Path.rename/Path.replace take exactly one
        # positional argument; str.replace takes two or more, so plain
        # string munging never matches this shape (a string-constant
        # target is still a rename — `tmp.replace("cache.json")` is
        # exactly the naked publish the rule exists for)
        if len(node.args) == 1 and not node.keywords:
            findings.append(ctx.finding(
                RULE, node,
                f".{func.attr}(target) on a path publishes by rename "
                "without an fsync bracket; route the write through "
                "utils/fsio, or justify the move with a pragma if the "
                "payload is already durable"))
    return findings
