"""SC010 sharding: no per-call Mesh/NamedSharding construction in hot paths.

Originating defect: ISSUE 16's data-plane audit — parallel/mesh.py
re-derived ``NamedSharding(mesh, P(...))`` on EVERY sharded dispatch
(and re-``device_put`` loop-invariant replicated carries per batch,
evicting donated buffers that were already resident).  Each sharding
object is cheap alone, but jit caches key on them and steady-state
dispatch should allocate none; worse, a hand-built ``Mesh`` per call
defeats executable reuse outright — two meshes over the same devices
are different cache keys, so every dispatch site that minted its own
paid its own GSPMD compile.  parallel/topology.py now owns the ONE
process-wide mesh and its persistent layout catalog; every entry point
consumes it.

Flags, inside function bodies of the hot-path packages
(``spacemesh_tpu/{ops,runtime,post,verify,parallel}/``): calls whose
callee's last dotted segment is ``Mesh`` or ``NamedSharding`` — the
per-call construction idiom this rule exists to keep deleted.
Module-level constants are not flagged (construction at import time is
once-per-process by definition).  The topology module itself is the
exemption — its catalog constructors carry
``# spacecheck: ok=SC010 <why>`` pragmas, which keeps the exemption
visible at the construction site instead of buried in a config list.

Fix: take layouts from ``parallel.topology.get()`` (``layouts()``,
``layouts_for_devices()``, ``layouts_for(mesh)``) or go through the
``parallel/mesh.py`` entry points, which already do.
"""

from __future__ import annotations

import ast

from ..engine import FileContext, Finding, ProjectInfo, dotted_name

RULE = "SC010"

_HOT = tuple(f"spacemesh_tpu/{p}/"
             for p in ("ops", "runtime", "post", "verify", "parallel"))
_CONSTRUCTORS = ("Mesh", "NamedSharding")


class _Visitor(ast.NodeVisitor):
    def __init__(self, ctx: FileContext) -> None:
        self.ctx = ctx
        self.findings: list[Finding] = []
        self._fn_depth = 0

    def _visit_fn(self, node) -> None:
        self._fn_depth += 1
        self.generic_visit(node)
        self._fn_depth -= 1

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn
    visit_Lambda = _visit_fn

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if (self._fn_depth > 0 and name is not None
                and name.rsplit(".", 1)[-1] in _CONSTRUCTORS):
            self.findings.append(self.ctx.finding(
                RULE, node,
                f"per-call {name.rsplit('.', 1)[-1]}(...) construction "
                "in a hot-path module: jit caches key on sharding "
                "objects, so a fresh one per dispatch defeats "
                "executable/layout reuse. Consume the persistent "
                "catalog (parallel/topology.py get().layouts*()) or "
                "the parallel/mesh.py entry points instead"))
        self.generic_visit(node)


def check(ctx: FileContext, project: ProjectInfo) -> list[Finding]:
    if not ctx.rel.startswith(_HOT):
        return []
    v = _Visitor(ctx)
    v.visit(ctx.tree)
    return v.findings
