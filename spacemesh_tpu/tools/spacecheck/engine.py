"""spacecheck engine: file walking, pragmas, fingerprints, rule driving.

The engine parses every target file once, runs a project-wide pre-pass
(cross-file facts some rules need, e.g. which module-level names are
metrics instruments), then hands each file to every selected rule.
Findings carry a **fingerprint** that is stable across unrelated edits —
hash of (rule, path, normalized offending line, occurrence index), not
the line number — so the checked-in baseline survives code motion above
a grandfathered finding.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import os
import re
import tokenize

RULE_IDS = ("SC001", "SC002", "SC003", "SC004", "SC005", "SC006")

# paths (relative, forward-slash) matched against these prefixes are
# skipped entirely
_SKIP_PARTS = {"__pycache__", ".git", ".claude"}

_PRAGMA_RE = re.compile(r"#\s*spacecheck:\s*(?P<body>.+)")
_OK_RE = re.compile(r"ok\s*=\s*(?P<rules>SC\d{3}(?:\s*,\s*SC\d{3})*)"
                    r"(?P<why>.*)", re.IGNORECASE)
_NOQA_RE = re.compile(r"#\s*noqa[:\s]", re.IGNORECASE)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    col: int
    message: str
    snippet: str       # stripped source line
    fingerprint: str = ""

    def key(self) -> tuple:
        return (self.path, self.line, self.rule, self.col)


class FileContext:
    """One parsed file plus its pragma map, shared by every rule."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # lineno -> set of rule ids suppressed on that line
        self.line_pragmas: dict[int, set[str]] = {}
        # comment text per line (SC006 accepts justified noqa comments)
        self.comments: dict[int, str] = {}
        # module-wide suppressions (e.g. "# spacecheck: wall-clock-ok"
        # in the file header)
        self.module_pragmas: set[str] = set()
        self._scan_comments()

    # --- pragmas --------------------------------------------------------

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.start[1], t.string)
                        for t in tokens if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, SyntaxError,
                ValueError):  # the ast parse succeeded; keep going
            comments = []
        for lineno, col, text in comments:
            self.comments[lineno] = text
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            body = m.group("body").strip()
            rules: set[str] = set()
            low = body.lower()
            if low.startswith("wall-clock-ok"):
                rules = {"SC001"}
                why = body[len("wall-clock-ok"):]
            else:
                ok = _OK_RE.match(body)
                if ok:
                    rules = {r.strip().upper()
                             for r in ok.group("rules").split(",")}
                    why = ok.group("why")
            if not rules:
                continue
            # a pragma without a reason is no pragma: suppression must
            # be justified (same contract the baseline enforces) — the
            # finding stays visible until the why is written
            if len(why.strip(" -—:\t")) < 8:
                continue
            own_line = self.lines[lineno - 1] if lineno <= len(self.lines) else ""
            standalone = own_line.lstrip().startswith("#")
            if standalone and col == 0 and lineno <= 25 \
                    and low.startswith("wall-clock-ok"):
                # header pragma: the whole module declares its time source
                self.module_pragmas |= rules
                continue
            self.line_pragmas.setdefault(lineno, set()).update(rules)
            if standalone:
                # a pragma on its own line covers the next line too
                self.line_pragmas.setdefault(lineno + 1, set()).update(rules)

    def suppressed(self, rule: str, lineno: int) -> bool:
        if rule in self.module_pragmas:
            return True
        return rule in self.line_pragmas.get(lineno, set())

    def noqa_comment(self, lineno: int) -> str | None:
        """The line's comment when it is a justified noqa suppression
        (``# noqa: XXX — why``): flake8-style suppressions that already
        carry a human reason double as SC006 pragmas, so the sweep does
        not demand a second comment saying the same thing."""
        text = self.comments.get(lineno)
        if not text or not _NOQA_RE.search(text):
            return None
        # justified = prose beyond the code list ("# noqa: BLE001" alone
        # is not a justification)
        tail = re.sub(r"#\s*noqa[:\s]*[A-Z0-9, ]*", "", text).strip(" -—:\t")
        return text if len(tail) >= 8 else None

    # --- findings -------------------------------------------------------

    def finding(self, rule: str, node, message: str) -> Finding:
        lineno = getattr(node, "lineno", 0) or 0
        col = getattr(node, "col_offset", 0) or 0
        snippet = (self.lines[lineno - 1].strip()
                   if 0 < lineno <= len(self.lines) else "")
        return Finding(rule=rule, path=self.rel, line=lineno, col=col,
                       message=message, snippet=snippet)


# --- shared AST helpers (imported by the rules) -------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def time_module_aliases(tree: ast.Module) -> set[str]:
    """Local names the stdlib ``time`` module is importable under
    (``import time``, ``import time as _time``)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    out.add(alias.asname or "time")
    return out


class ProjectInfo:
    """Cross-file facts collected in one pre-pass over every context."""

    def __init__(self, contexts: list[FileContext]):
        self.contexts = contexts
        # rule-private cross-file caches hang off this dict (SC003's
        # donated-callable map, built lazily on first use)
        self.cache: dict[str, object] = {}
        # names (last dotted component) bound to a registry-created
        # instrument anywhere in the tree: `x = REGISTRY.counter(...)`,
        # `self._latency = _metrics.REGISTRY.histogram(...)`
        self.instrument_vars: set[str] = set()
        # metric name literal -> [(rel, lineno, module_scope)]
        self.metric_creations: dict[str, list[tuple[str, int, bool]]] = {}
        for ctx in contexts:
            self._collect(ctx)

    @staticmethod
    def _is_registry_create(call: ast.Call) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        if call.func.attr not in ("counter", "gauge", "histogram"):
            return False
        recv = dotted_name(call.func.value) or ""
        last = recv.rsplit(".", 1)[-1].lower()
        return last in ("registry", "_registry") or last.endswith("registry")

    def _collect(self, ctx: FileContext) -> None:
        func_depth = 0

        def visit(node: ast.AST) -> None:
            nonlocal func_depth
            is_func = isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda))
            if is_func:
                func_depth += 1
            if isinstance(node, ast.Call) and self._is_registry_create(node):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                    self.metric_creations.setdefault(name, []).append(
                        (ctx.rel, node.lineno, func_depth == 0))
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and self._is_registry_create(node.value):
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name:
                        self.instrument_vars.add(name.rsplit(".", 1)[-1])
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_func:
                func_depth -= 1

        visit(ctx.tree)


# --- walking + running --------------------------------------------------


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_PARTS)
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


def _relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def fingerprint(rule: str, rel: str, snippet: str) -> str:
    norm = " ".join(snippet.split())
    h = hashlib.sha1(f"{rule}|{rel}|{norm}".encode()).hexdigest()
    return h[:16]


def assign_fingerprints(findings: list[Finding]) -> None:
    """Stable ids: hash of (rule, path, normalized offending line) —
    deliberately NOT line numbers and NOT an occurrence index. Two
    textually identical offenses in one file share a fingerprint and
    the baseline matches them as a MULTISET (baseline.split): adding a
    second identical violation above a grandfathered one therefore
    surfaces one new finding, instead of an index shift silently
    suppressing the new line and re-flagging the old one."""
    for f in findings:
        f.fingerprint = fingerprint(f.rule, f.path, f.snippet)


def run_paths(paths: list[str], *, project_root: str | None = None,
              select: set[str] | None = None
              ) -> tuple[list[Finding], list[str]]:
    """Analyze ``paths`` (files or directories). Returns (findings,
    errors); errors are unparseable files — CI treats them as failures
    too (an unparseable file is unanalyzed, not clean)."""
    from . import rules as rules_pkg

    root = os.path.abspath(project_root or os.getcwd())
    contexts: list[FileContext] = []
    errors: list[str] = []
    for path in iter_py_files(paths):
        rel = _relpath(path, root)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            contexts.append(FileContext(path, rel, source))
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
    project = ProjectInfo(contexts)
    findings: list[Finding] = []
    active = [r for r in rules_pkg.ALL_RULES
              if select is None or r.RULE in select]
    for ctx in contexts:
        for rule in active:
            try:
                raw = rule.check(ctx, project)
            except Exception as e:  # noqa: BLE001 — one rule crashing on
                # one file must surface as an analyzer error, not take
                # down the whole run silently
                errors.append(f"{ctx.rel}: rule {rule.RULE} crashed: "
                              f"{type(e).__name__}: {e}")
                continue
            findings.extend(f for f in raw
                            if not ctx.suppressed(f.rule, f.line))
    findings.sort(key=Finding.key)
    assign_fingerprints(findings)
    return findings, errors
