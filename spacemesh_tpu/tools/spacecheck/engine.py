"""spacecheck engine: file walking, pragmas, fingerprints, rule driving.

The engine parses every target file once, runs a project-wide pre-pass
(cross-file facts some rules need, e.g. which module-level names are
metrics instruments), then hands each file to every selected rule.
Findings carry a **fingerprint** that is stable across unrelated edits —
hash of (rule, path, normalized offending line, occurrence index), not
the line number — so the checked-in baseline survives code motion above
a grandfathered finding.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize

RULE_IDS = ("SC001", "SC002", "SC003", "SC004", "SC005", "SC006",
            "SC007", "SC008", "SC009")

# paths (relative, forward-slash) matched against these prefixes are
# skipped entirely
_SKIP_PARTS = {"__pycache__", ".git", ".claude"}

_PRAGMA_RE = re.compile(r"#\s*spacecheck:\s*(?P<body>.+)")
_OK_RE = re.compile(r"ok\s*=\s*(?P<rules>SC\d{3}(?:\s*,\s*SC\d{3})*)"
                    r"(?P<why>.*)", re.IGNORECASE)
_NOQA_RE = re.compile(r"#\s*noqa[:\s]", re.IGNORECASE)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    col: int
    message: str
    snippet: str       # stripped source line
    fingerprint: str = ""

    def key(self) -> tuple:
        return (self.path, self.line, self.rule, self.col)


class FileContext:
    """One parsed file plus its pragma map, shared by every rule."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # lineno -> set of rule ids suppressed on that line
        self.line_pragmas: dict[int, set[str]] = {}
        # comment text per line (SC006 accepts justified noqa comments)
        self.comments: dict[int, str] = {}
        # module-wide suppressions (e.g. "# spacecheck: wall-clock-ok"
        # in the file header)
        self.module_pragmas: set[str] = set()
        self._scan_comments()

    # --- pragmas --------------------------------------------------------

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.start[1], t.string)
                        for t in tokens if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, SyntaxError,
                ValueError):  # the ast parse succeeded; keep going
            comments = []
        for lineno, col, text in comments:
            self.comments[lineno] = text
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            body = m.group("body").strip()
            rules: set[str] = set()
            low = body.lower()
            if low.startswith("wall-clock-ok"):
                rules = {"SC001"}
                why = body[len("wall-clock-ok"):]
            else:
                ok = _OK_RE.match(body)
                if ok:
                    rules = {r.strip().upper()
                             for r in ok.group("rules").split(",")}
                    why = ok.group("why")
            if not rules:
                continue
            # a pragma without a reason is no pragma: suppression must
            # be justified (same contract the baseline enforces) — the
            # finding stays visible until the why is written
            if len(why.strip(" -—:\t")) < 8:
                continue
            own_line = self.lines[lineno - 1] if lineno <= len(self.lines) else ""
            standalone = own_line.lstrip().startswith("#")
            if standalone and col == 0 and lineno <= 25 \
                    and low.startswith("wall-clock-ok"):
                # header pragma: the whole module declares its time source
                self.module_pragmas |= rules
                continue
            self.line_pragmas.setdefault(lineno, set()).update(rules)
            if standalone:
                # a pragma on its own line covers the next line too
                self.line_pragmas.setdefault(lineno + 1, set()).update(rules)

    def suppressed(self, rule: str, lineno: int) -> bool:
        if rule in self.module_pragmas:
            return True
        return rule in self.line_pragmas.get(lineno, set())

    def noqa_comment(self, lineno: int) -> str | None:
        """The line's comment when it is a justified noqa suppression
        (``# noqa: XXX — why``): flake8-style suppressions that already
        carry a human reason double as SC006 pragmas, so the sweep does
        not demand a second comment saying the same thing."""
        text = self.comments.get(lineno)
        if not text or not _NOQA_RE.search(text):
            return None
        # justified = prose beyond the code list ("# noqa: BLE001" alone
        # is not a justification)
        tail = re.sub(r"#\s*noqa[:\s]*[A-Z0-9, ]*", "", text).strip(" -—:\t")
        return text if len(tail) >= 8 else None

    # --- findings -------------------------------------------------------

    def finding(self, rule: str, node, message: str) -> Finding:
        lineno = getattr(node, "lineno", 0) or 0
        col = getattr(node, "col_offset", 0) or 0
        snippet = (self.lines[lineno - 1].strip()
                   if 0 < lineno <= len(self.lines) else "")
        return Finding(rule=rule, path=self.rel, line=lineno, col=col,
                       message=message, snippet=snippet)


# --- shared AST helpers (imported by the rules) -------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def time_module_aliases(tree: ast.Module) -> set[str]:
    """Local names the stdlib ``time`` module is importable under
    (``import time``, ``import time as _time``)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "time":
                    out.add(alias.asname or "time")
    return out


class ProjectInfo:
    """Cross-file facts collected in one pre-pass over every context."""

    def __init__(self, contexts: list[FileContext]):
        self.contexts = contexts
        # rule-private cross-file caches hang off this dict (SC003's
        # donated-callable map, built lazily on first use)
        self.cache: dict[str, object] = {}
        # names (last dotted component) bound to a registry-created
        # instrument anywhere in the tree: `x = REGISTRY.counter(...)`,
        # `self._latency = _metrics.REGISTRY.histogram(...)`
        self.instrument_vars: set[str] = set()
        # metric name literal -> [(rel, lineno, module_scope)]
        self.metric_creations: dict[str, list[tuple[str, int, bool]]] = {}
        for ctx in contexts:
            self._collect(ctx)

    @staticmethod
    def _is_registry_create(call: ast.Call) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        if call.func.attr not in ("counter", "gauge", "histogram"):
            return False
        recv = dotted_name(call.func.value) or ""
        last = recv.rsplit(".", 1)[-1].lower()
        return last in ("registry", "_registry") or last.endswith("registry")

    def _collect(self, ctx: FileContext) -> None:
        func_depth = 0

        def visit(node: ast.AST) -> None:
            nonlocal func_depth
            is_func = isinstance(node, (ast.FunctionDef,
                                        ast.AsyncFunctionDef, ast.Lambda))
            if is_func:
                func_depth += 1
            if isinstance(node, ast.Call) and self._is_registry_create(node):
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    name = node.args[0].value
                    self.metric_creations.setdefault(name, []).append(
                        (ctx.rel, node.lineno, func_depth == 0))
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and self._is_registry_create(node.value):
                for tgt in node.targets:
                    name = dotted_name(tgt)
                    if name:
                        self.instrument_vars.add(name.rsplit(".", 1)[-1])
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_func:
                func_depth -= 1

        visit(ctx.tree)


# --- incremental findings cache -----------------------------------------
#
# A full run is (parse + pre-pass + N rules) x every file; rules keep
# multiplying, and the CI spacecheck job runs BEFORE dependency install
# on every push.  The cache persists per-file findings keyed by
# ``(mtime, sha256)`` beside the autotune winners file, guarded by two
# whole-run digests that keep it SOUND for cross-file rules:
#
# * ``rules_digest`` — hash of engine.py + every rules/*.py source: any
#   analyzer change invalidates everything;
# * ``tree_digest`` — hash of every analyzed file's content hash: rules
#   consume project-wide facts (SC003's donation map, SC005's duplicate
#   names, SC007/SC008's thread/lock graphs), so one changed file can
#   change another file's findings.  A warm run over an identical tree
#   is therefore a pure cache hit (no parse, no rules); any change at
#   all recomputes the whole tree and refreshes the cache.
#
# ``--select`` runs bypass the cache (partial findings must never
# poison a full run's entries).

CACHE_ENV = "SPACEMESH_SPACECHECK_CACHE"
CACHE_VERSION = 1


def default_cache_path() -> str:
    """Beside the autotune winners file (ops/autotune.py cache_path),
    derived without importing any jax-touching module — the analyzer
    must stay runnable before dependency install."""
    explicit = os.environ.get(CACHE_ENV)
    if explicit:
        return os.path.expanduser(explicit)
    jax_cache = os.environ.get("SPACEMESH_JAX_CACHE") \
        or "~/.cache/spacemesh_tpu/jax_cache"
    root = os.path.dirname(os.path.expanduser(jax_cache))
    return os.path.join(root, "spacecheck_cache.json")


def _rules_digest() -> str:
    from . import rules as rules_pkg

    h = hashlib.sha256()
    rules_dir = os.path.dirname(rules_pkg.__file__)
    files = [__file__] + [os.path.join(rules_dir, f)
                          for f in sorted(os.listdir(rules_dir))
                          if f.endswith(".py")]
    for path in files:
        try:
            with open(path, "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(path.encode())
    return h.hexdigest()


def _load_cache_doc(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("version") != CACHE_VERSION \
            or not isinstance(doc.get("files"), dict):
        return None
    return doc


def _file_sha(path: str, cached_entry: dict | None) -> tuple[str, dict]:
    """(sha256 hex, stat info) — reuses the cached hash when the file's
    (mtime, size) are unchanged, so a warm run hashes nothing."""
    st = os.stat(path)
    info = {"mtime": st.st_mtime, "size": st.st_size}
    if cached_entry is not None \
            and cached_entry.get("mtime") == info["mtime"] \
            and cached_entry.get("size") == info["size"] \
            and isinstance(cached_entry.get("sha"), str):
        return cached_entry["sha"], info
    with open(path, "rb") as fh:
        sha = hashlib.sha256(fh.read()).hexdigest()
    return sha, info


def _write_cache(path: str, rules_digest: str, tree_digest: str,
                 per_file: dict[str, dict]) -> None:
    doc = {"version": CACHE_VERSION, "rules_digest": rules_digest,
           "tree_digest": tree_digest, "files": per_file}
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # durable write (utils/fsio is stdlib-only, so the pre-install
        # CI constraint holds): a crash mid-save must not leave a
        # half-written cache the loader silently discards
        from ...utils import fsio

        fsio.atomic_write_text(path, json.dumps(doc))
    except OSError:
        pass  # persistence is an optimization (read-only HOME, CI)


# --- walking + running --------------------------------------------------


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_PARTS)
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return out


def _relpath(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    return rel.replace(os.sep, "/")


def fingerprint(rule: str, rel: str, snippet: str) -> str:
    norm = " ".join(snippet.split())
    h = hashlib.sha1(f"{rule}|{rel}|{norm}".encode()).hexdigest()
    return h[:16]


def assign_fingerprints(findings: list[Finding]) -> None:
    """Stable ids: hash of (rule, path, normalized offending line) —
    deliberately NOT line numbers and NOT an occurrence index. Two
    textually identical offenses in one file share a fingerprint and
    the baseline matches them as a MULTISET (baseline.split): adding a
    second identical violation above a grandfathered one therefore
    surfaces one new finding, instead of an index shift silently
    suppressing the new line and re-flagging the old one."""
    for f in findings:
        f.fingerprint = fingerprint(f.rule, f.path, f.snippet)


def _check_context(ctx: FileContext, project: ProjectInfo,
                   active: list) -> tuple[list[Finding], list[str]]:
    findings: list[Finding] = []
    errors: list[str] = []
    for rule in active:
        try:
            raw = rule.check(ctx, project)
        except Exception as e:  # noqa: BLE001 — one rule crashing on
            # one file must surface as an analyzer error, not take
            # down the whole run silently
            errors.append(f"{ctx.rel}: rule {rule.RULE} crashed: "
                          f"{type(e).__name__}: {e}")
            continue
        findings.extend(f for f in raw
                        if not ctx.suppressed(f.rule, f.line))
    return findings, errors


# fork-inherited handoff for --jobs workers (contexts and the project
# pre-pass are built once in the parent; AST trees cross into children
# for free via fork, only the per-file findings lists come back pickled)
_FORK_STATE: tuple | None = None


def _fork_shard(indices: list[int]) -> list[tuple[list[Finding],
                                                  list[str]]]:
    contexts, project, active = _FORK_STATE
    return [_check_context(contexts[i], project, active)
            for i in indices]


def _run_rules(contexts: list[FileContext], project: ProjectInfo,
               active: list, jobs: int
               ) -> list[tuple[FileContext, list[Finding], list[str]]]:
    jobs = max(int(jobs), 1)
    if jobs > 1 and len(contexts) > 1:
        import multiprocessing

        try:
            mp = multiprocessing.get_context("fork")
        except ValueError:
            mp = None
        if mp is not None:
            import concurrent.futures

            # prime the rules' lazy cross-file caches (SC003's donation
            # map, SC007/SC008's thread/lock facts) in the PARENT by
            # checking one file first — forked children then inherit
            # the populated project.cache instead of each rebuilding it
            out: list = [None] * len(contexts)
            out[0] = (contexts[0],
                      *_check_context(contexts[0], project, active))
            global _FORK_STATE
            _FORK_STATE = (contexts, project, active)
            try:
                shards = [list(range(1 + k, len(contexts), jobs))
                          for k in range(jobs)]
                shards = [s for s in shards if s]
                with concurrent.futures.ProcessPoolExecutor(
                        max_workers=max(len(shards), 1),
                        mp_context=mp) as ex:
                    results = list(ex.map(_fork_shard, shards))
            finally:
                _FORK_STATE = None
            for shard, res in zip(shards, results):
                for i, (fs, errs) in zip(shard, res):
                    out[i] = (contexts[i], fs, errs)
            return out
    return [(ctx, *_check_context(ctx, project, active))
            for ctx in contexts]


def run_paths(paths: list[str], *, project_root: str | None = None,
              select: set[str] | None = None,
              cache: str | bool | None = None,
              jobs: int = 1) -> tuple[list[Finding], list[str]]:
    """Analyze ``paths`` (files or directories). Returns (findings,
    errors); errors are unparseable files — CI treats them as failures
    too (an unparseable file is unanalyzed, not clean).

    ``cache`` — True (default path) or a path: consult/refresh the
    incremental findings cache (full-rule runs only; ``--select`` runs
    always compute).  ``jobs`` — fork-parallel rule execution.
    """
    from . import rules as rules_pkg

    root = os.path.abspath(project_root or os.getcwd())
    files = [(path, _relpath(path, root)) for path in iter_py_files(paths)]

    cache_file = None
    if cache and select is None:
        cache_file = default_cache_path() if cache is True else str(cache)
    cached_doc = _load_cache_doc(cache_file) if cache_file else None
    rules_digest = _rules_digest() if cache_file else ""
    shas: dict[str, tuple[str, dict]] = {}
    tree_digest = ""
    if cache_file:
        cached_files = (cached_doc or {}).get("files", {})
        th = hashlib.sha256()
        try:
            for path, rel in files:
                shas[rel] = _file_sha(path, cached_files.get(rel))
                th.update(f"{rel}:{shas[rel][0]}\n".encode())
            tree_digest = th.hexdigest()
        except OSError:
            cache_file = None  # unreadable file: fall through, the
            # full run reports it as an analyzer error
    if cached_doc is not None and cache_file \
            and cached_doc.get("rules_digest") == rules_digest \
            and cached_doc.get("tree_digest") == tree_digest \
            and all(rel in cached_doc["files"] for _, rel in files):
        findings: list[Finding] = []
        errors: list[str] = []
        for _, rel in files:
            ent = cached_doc["files"][rel]
            findings.extend(Finding(**f) for f in ent.get("findings", []))
            errors.extend(ent.get("errors", []))
        findings.sort(key=Finding.key)
        return findings, errors

    contexts: list[FileContext] = []
    errors = []
    per_file: dict[str, dict] = {}
    for path, rel in files:
        ent: dict = {"findings": [], "errors": []}
        if rel in shas:
            ent.update(sha=shas[rel][0], **shas[rel][1])
        per_file[rel] = ent
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            contexts.append(FileContext(path, rel, source))
        except (OSError, SyntaxError, ValueError) as e:
            msg = f"{rel}: {type(e).__name__}: {e}"
            errors.append(msg)
            ent["errors"].append(msg)
    project = ProjectInfo(contexts)
    active = [r for r in rules_pkg.ALL_RULES
              if select is None or r.RULE in select]
    findings = []
    for ctx, ctx_findings, ctx_errors in _run_rules(contexts, project,
                                                    active, jobs):
        findings.extend(ctx_findings)
        errors.extend(ctx_errors)
        per_file[ctx.rel]["errors"].extend(ctx_errors)
    findings.sort(key=Finding.key)
    assign_fingerprints(findings)
    if cache_file:
        for f in findings:
            if f.path in per_file:
                per_file[f.path]["findings"].append(dataclasses.asdict(f))
        _write_cache(cache_file, rules_digest, tree_digest, per_file)
    return findings, errors
